"""Consumer-side bounded-memory shuffle merge (the MergeManager analog).

Reference parity: tez-runtime-library/.../common/shuffle/orderedgrouped/
MergeManager.java:83 — `reserve()` admission with stall (:404), the
commitMemory >= mergeThreshold mem->disk merge trigger (:387), the on-disk
merge cascade, and a final merge over leftover memory + disk segments —
re-thought for this framework's vectorized data plane:

- Fetched batches are already partition-sorted runs (the producer ships
  sorted slices), so a "mem->disk merge" is one vectorized k-way merge of
  the committed batches written out as a block-chunked sorted file
  (ops.runformat.ChunkedRunWriter), and the DISK admission target just
  streams the oversized batch to its own chunked file — no record-at-a-time
  byte crunching anywhere.
- The final merge is vectorized + in-RAM when everything fits the budget
  (the common case, byte-for-byte the old fast path), and otherwise a
  streaming heap-merge over block-buffered disk runs whose resident set is
  one block per run — a partition far larger than host RAM reduces with
  peak memory ~ budget + num_runs * block_bytes.

Equal keys across different source runs emerge in run-arrival order (the
reference's MergeQueue makes the same arrival-dependent choice; within one
source the producer's sorted order is preserved exactly).
"""
from __future__ import annotations

import logging
import os
import threading
import uuid
from typing import Any, Callable, Iterator, List, Optional, Sequence, Tuple

import numpy as np

from tez_tpu.common.counters import TaskCounter, TezCounters
from tez_tpu.ops.block_merge import iter_merged_blocks
from tez_tpu.ops.runformat import (ChunkedRunWriter, KVBatch, Run,
                                   iter_chunked_run)
from tez_tpu.ops.sorter import merge_sorted_runs, normalize_batch_keys

log = logging.getLogger(__name__)


def _as_run(batch: KVBatch) -> Run:
    return Run(batch, np.array([0, batch.num_records], dtype=np.int64))


class _FileSource:
    """Disk-direct shuffle source: one partition of a producer's
    partition-indexed output file, merged straight off the producer's disk
    (LocalDiskFetchedInput analog) — never copied into this consumer's
    memory budget or spill dir."""

    __slots__ = ("path", "partition", "nbytes")

    def __init__(self, path: str, partition: int, nbytes: int):
        self.path = path
        self.partition = partition
        self.nbytes = nbytes


class ShuffleMergeManager:
    """Admission + background mem->disk merging for one consumer input.

    Thread model: fetch threads call `commit()` (which may stall on the
    memory budget); one background merger thread frees memory by merging
    committed batches to disk; `finish()` joins the merger and hands back
    either a fully-merged in-RAM batch or a streaming plan.
    """

    def __init__(self, counters: TezCounters, budget_bytes: int,
                 spill_dir: str,
                 key_width: int = 16,
                 engine: str = "device",
                 device_min_records: "int | None" = None,
                 merge_factor: int = 64,
                 merge_threshold: float = 0.9,
                 eager_threshold: float = 0.0,
                 max_single_fraction: float = 0.25,
                 key_normalizer: Optional[Callable[[bytes], bytes]] = None,
                 codec: Optional[str] = None,
                 block_records: int = 65536,
                 async_depth: int = 0,
                 instrument: bool = False,
                 breaker: Any = None,
                 watchdog_dispatch_ms: Optional[float] = None,
                 watchdog_readback_ms: Optional[float] = None):
        self.counters = counters
        self.budget = int(budget_bytes)
        self.spill_dir = spill_dir
        self.key_width = key_width
        from tez_tpu.ops.sorter import resolve_engine
        self.engine = resolve_engine(engine)
        from tez_tpu.ops.sorter import DEVICE_SORT_MIN_RECORDS
        self.device_min_records = DEVICE_SORT_MIN_RECORDS \
            if device_min_records is None else device_min_records
        self.merge_factor = max(2, merge_factor)
        self.merge_threshold = merge_threshold
        # push-based shuffle's merge-wave overlap: > 0 lets the background
        # merger start a mem->disk merge once committed memory crosses
        # eager_threshold * budget — well before the admission-pressure
        # threshold above — so merge work runs WHILE the map wave is still
        # pushing spills instead of serializing after it.  0 = historical
        # behavior (merge only under admission pressure).
        self.eager_threshold = max(0.0, float(eager_threshold))
        self.max_single = int(self.budget * max_single_fraction) \
            if self.budget > 0 else 0
        self.key_normalizer = key_normalizer
        self.codec = codec
        self.block_records = block_records

        self.lock = threading.Condition()
        # committed in-memory batches: (slot, seq, batch) — slot-major
        # order keeps the no-spill final merge byte-identical to the
        # historical slot-ordered merge; seq is global arrival order
        self._mem: List[Tuple[int, int, KVBatch]] = []
        self._mem_bytes = 0
        self._seq = 0
        self._disk_runs: List[str] = []          # chunked run paths, by age
        self._disk_slots: set = set()            # slots with data on disk
        # disk-direct sources (producer-owned files; never merged by the
        # background merger — they cost no memory and no consumer disk)
        self._file_sources: List[Tuple[int, int, _FileSource]] = []
        self._merging: List[Tuple[int, int, KVBatch]] = []  # claimed by merger
        self._stalled = 0                        # fetchers waiting in commit
        self._slot_gen: dict = {}                # slot -> reset generation
        self._mem_to_disk = 0
        self._disk_to_disk = 0
        self.peak_mem_bytes = 0
        self._poisoned: Optional[str] = None
        self._closed = False
        self._error: Optional[BaseException] = None
        # --- async merge plane (tez.runtime.merge.async.depth > 0) ---
        # background merges submit through an AsyncSpanPipeline instead of
        # running inline on the merger thread: the chunked-run disk write of
        # merge k (readback stage) overlaps the device dispatch of merge
        # k+1, and in-flight fetch commits overlap both.  async_depth=0 is
        # byte-for-byte the historical synchronous merger.
        self.async_depth = max(0, int(async_depth))
        self._instrument = instrument
        self._pipe_seq = 0              # submission order (= fold order)
        self._pending_out: dict = {}    # seq -> completed, not yet folded
        self._next_out = 0
        self._disk_claim: Optional[List[str]] = None
        self._pipeline = None
        if self.budget > 0 and self.async_depth > 0:
            self._pipeline = self._build_pipeline(
                breaker, watchdog_dispatch_ms, watchdog_readback_ms)
        self._merger: Optional[threading.Thread] = None
        if self.budget > 0:
            self._merger = threading.Thread(target=self._merge_loop,
                                            daemon=True,
                                            name="shuffle-merger")
            self._merger.start()

    def _build_pipeline(self, breaker: Any,
                        watchdog_dispatch_ms: Optional[float],
                        watchdog_readback_ms: Optional[float]):
        """The merge dispatch lane: same AsyncSpanPipeline (and the same
        PR-5 containment ladder — watchdog, circuit breaker, OOM
        span-halving, host failover from raw payloads) that serves the
        producer sort side, pointed at merge work.  Dispatch-wait latency
        lands in the "device.merge" histogram instead of the sort plane's
        device.dispatch_wait."""
        from tez_tpu.ops import async_stage
        from tez_tpu.ops import sorter as _sorter
        return async_stage.AsyncSpanPipeline(
            dispatch_fn=self._pipe_dispatch,
            readback_fn=self._pipe_readback,
            on_complete=self._pipe_complete,
            depth=self.async_depth,
            readback_workers=1,
            counters=self.counters,
            instrument=self._instrument,
            name="merge-pipeline",
            failover_fn=self._pipe_failover,
            oom_retry_fn=self._pipe_oom_retry,
            breaker=breaker,
            watchdog_dispatch_ms=_sorter.DEVICE_WATCHDOG_DISPATCH_MS
            if watchdog_dispatch_ms is None else watchdog_dispatch_ms,
            watchdog_readback_ms=_sorter.DEVICE_WATCHDOG_READBACK_MS
            if watchdog_readback_ms is None else watchdog_readback_ms,
            dispatch_wait_hist="device.merge")

    # ------------------------------------------------------------- admission
    def slot_generation(self, slot: int) -> int:
        """Current reset-generation of a slot.  Fetchers capture this BEFORE
        fetching and pass it to commit(): a commit whose generation is stale
        (the slot reset mid-fetch) is dropped instead of stored, so a new
        producer attempt's data can never be discarded by the old attempt's
        late-arriving fetch."""
        with self.lock:
            return self._slot_gen.get(slot, 0)

    def commit(self, slot: int, batch: KVBatch, generation: int = 0) -> bool:
        """Account a fetched (sorted) batch.  MEM target when it fits the
        budget — stalling while the merger frees memory (reserve():404
        semantics) — DISK target for oversized batches (maxSingleShuffleLimit
        analog): streamed straight to its own chunked run.  Returns False if
        the batch was dropped as stale (slot reset since `generation`)."""
        if self.budget <= 0:
            with self.lock:
                if self._slot_gen.get(slot, 0) != generation:
                    return False
                self._mem.append((slot, self._seq, batch))
                self._seq += 1
                self._mem_bytes += batch.nbytes
                self.peak_mem_bytes = max(self.peak_mem_bytes, self._mem_bytes)
            self.counters.increment(TaskCounter.SHUFFLE_BYTES_TO_MEM,
                                    batch.nbytes)
            return True
        if batch.nbytes > self.max_single:
            path = self._write_chunked([_as_run(batch)])
            with self.lock:
                if self._slot_gen.get(slot, 0) != generation:
                    try:
                        os.remove(path)
                    except OSError:
                        pass
                    return False
                self._disk_runs.append(path)
                self._disk_slots.add(slot)
                if len(self._disk_runs) >= self.merge_factor:
                    # wake the merger the moment the cascade trigger
                    # crosses instead of up to a poll period later
                    self.lock.notify_all()
            self.counters.increment(TaskCounter.SHUFFLE_BYTES_TO_DISK,
                                    batch.nbytes)
            return True
        with self.lock:
            while self._mem_bytes + batch.nbytes > self.budget and \
                    self._error is None and self._poisoned is None:
                if not self._mem and not self._merging:
                    # nothing the merger could free: the batch itself is
                    # what's over budget (many stalled fetchers, tiny
                    # budget).  Fall through and admit anyway — peak memory
                    # then exceeds the budget by at most one sub-max_single
                    # batch, which beats deadlocking the fetch forever.
                    break
                self._stalled += 1           # merger merges on our behalf
                self.lock.notify_all()
                try:
                    self.lock.wait(0.1)
                finally:
                    self._stalled -= 1
            self._raise_if_broken()
            if self._slot_gen.get(slot, 0) != generation:
                return False
            self._mem.append((slot, self._seq, batch))
            self._seq += 1
            self._mem_bytes += batch.nbytes
            self.peak_mem_bytes = max(self.peak_mem_bytes, self._mem_bytes)
            if self._mem_bytes >= self.budget * self._wake_threshold():
                self.lock.notify_all()
        self.counters.increment(TaskCounter.SHUFFLE_BYTES_TO_MEM, batch.nbytes)
        return True

    def commit_local_file(self, slot: int, path: str, partition: int,
                          nbytes: int, generation: int = 0) -> bool:
        """Admit a disk-direct source (same-host producer's partition-
        indexed file).  Costs no memory budget and no consumer disk; the
        blocks stream from the producer's file at merge time.  Returns
        False if dropped as stale (slot reset since `generation`)."""
        with self.lock:
            if self._slot_gen.get(slot, 0) != generation:
                return False
            self._file_sources.append(
                (slot, self._seq, _FileSource(path, partition, nbytes)))
            self._seq += 1
        return True

    def on_slot_reset(self, slot: int) -> List[KVBatch]:
        """A producer is re-running.  The slot's generation bumps (so
        in-flight fetches of the old attempt drop at commit), its in-memory
        batches are discarded (and returned for accounting); if the slot's
        data already merged to disk — or is mid-merge right now — the state
        is unrecoverable in place: poison, so the consumer attempt fails
        loudly and re-runs with fresh fetches (the reference's
        too-many-failures consumer-kill escape hatch)."""
        with self.lock:
            self._slot_gen[slot] = self._slot_gen.get(slot, 0) + 1
            if slot in self._disk_slots or \
                    any(s == slot for s, _, _ in self._merging):
                self._poisoned = (
                    f"slot {slot} re-ran after its data merged to disk; "
                    f"consumer must re-fetch from scratch")
                self.lock.notify_all()
                return []
            dropped = [b for s, _, b in self._mem if s == slot]
            self._mem = [(s, q, b) for s, q, b in self._mem if s != slot]
            self._mem_bytes -= sum(b.nbytes for b in dropped)
            # disk-direct sources are never folded into shared merge files:
            # dropping the slot's entries is a complete undo
            self._file_sources = [t for t in self._file_sources
                                  if t[0] != slot]
            self.lock.notify_all()
            return dropped

    def _raise_if_broken(self) -> None:
        if self._error is not None:
            raise RuntimeError("shuffle merger failed") from self._error
        if self._poisoned is not None:
            raise RuntimeError(f"shuffle merge state lost: {self._poisoned}")

    def quiesce(self, timeout: Optional[float] = None) -> bool:
        """Block until the background merger has nothing runnable and
        nothing in flight (or the manager broke/closed).  Every state
        transition toward idle already notifies the manager Condition,
        so this is a real CV wait, not a poll — tests and drain paths
        that previously slept on private counters use this instead.
        Returns False only on timeout."""
        def _idle() -> bool:
            if self._closed or self._error is not None or \
                    self._poisoned is not None:
                return True
            if self._merging or self._disk_claim is not None:
                return False
            # sync disk cascades claim in place (the run list keeps the
            # merging prefix until the replace), so "due" covers them
            return not self._mem_merge_due() and \
                not self._disk_merge_due_locked()
        with self.lock:
            return bool(self.lock.wait_for(_idle, timeout))

    # ------------------------------------------------------- background merge
    def _wake_threshold(self) -> float:
        """Fraction of the budget at which a commit wakes the merger: the
        eager (push-overlap) threshold when enabled, else the admission-
        pressure threshold."""
        if 0.0 < self.eager_threshold < self.merge_threshold:
            return self.eager_threshold
        return self.merge_threshold

    def _mem_merge_due(self) -> bool:
        """Under lock: committed memory crossed the merge threshold (the
        eager one when push overlap is on), OR a fetcher is stalled on
        admission and there is anything at all to free (without the second
        clause a batch that doesn't fit the remaining budget while memory
        sits below the threshold would stall its fetcher forever)."""
        if not self._mem:
            return False
        return self._mem_bytes >= self.budget * self._wake_threshold() or \
            self._stalled > 0

    def _disk_merge_due_locked(self) -> bool:
        """Under lock: a disk cascade is runnable — the trigger crossed and
        no cascade is already in flight (at most one at a time keeps the
        run-age bookkeeping trivial and bounds disk-write fan-out)."""
        return self._disk_claim is None and \
            len(self._disk_runs) >= self.merge_factor

    def _merge_loop(self) -> None:
        if self._pipeline is not None:
            return self._merge_loop_async()
        while True:
            with self.lock:
                while not self._closed and self._poisoned is None and \
                        not self._mem_merge_due() and \
                        len(self._disk_runs) < self.merge_factor:
                    # every trigger crossing notifies (commit threshold,
                    # disk-run registration, stall, close): the wait is a
                    # backstop, not the wake mechanism
                    self.lock.wait(2.0)
                if self._closed or self._poisoned is not None:
                    return
                work = None
                if self._mem_merge_due():
                    # CLAIM the batches: they leave _mem (so a concurrent
                    # slot reset can't silently mutate the working set) but
                    # stay accounted in _mem_bytes until the write lands
                    work = ("mem", list(self._mem))
                    self._merging = list(self._mem)
                    self._mem = []
                elif len(self._disk_runs) >= self.merge_factor:
                    work = ("disk", self._disk_runs[:self.merge_factor])
            try:
                if work[0] == "mem":
                    self._do_mem_to_disk(work[1])
                else:
                    self._do_disk_to_disk(work[1])
            except BaseException as e:  # noqa: BLE001 — surface to callers
                with self.lock:
                    self._error = e
                    self.lock.notify_all()
                return

    def _merge_loop_async(self) -> None:
        """Async flavor: CLAIM work under the lock, hand it to the merge
        pipeline, immediately look for more.  Completion accounting happens
        in _pipe_complete (seq order), so disk-run age order is identical
        to the synchronous merger's."""
        while True:
            with self.lock:
                while not self._closed and self._poisoned is None and \
                        not self._mem_merge_due() and \
                        not self._disk_merge_due_locked():
                    self.lock.wait(2.0)
                if self._closed or self._poisoned is not None:
                    return
                if self._mem_merge_due():
                    items = list(self._mem)
                    self._merging = self._merging + items
                    self._mem = []
                    work = ("mem", items)
                elif self._disk_merge_due_locked():
                    paths = self._disk_runs[:self.merge_factor]
                    self._disk_runs = self._disk_runs[self.merge_factor:]
                    self._disk_claim = list(paths)
                    work = ("disk", paths)
                else:
                    continue        # woken with nothing runnable
                seq = self._pipe_seq
                self._pipe_seq += 1
            try:
                self._pipeline.submit(seq, work)
            except BaseException as e:  # noqa: BLE001 — surface to callers
                with self.lock:
                    self._error = e
                    self.lock.notify_all()
                return

    # -------------------------------------------------- merge pipeline lane
    def _merge_mem_items(self, items: List[Tuple[int, int, KVBatch]],
                         engine: Optional[str] = None) -> Run:
        """One mem->disk merge body (slot-major, then arrival — the order
        every path in this file merges by).  engine overrides for the
        containment plane's host failover / on-device OOM retry."""
        items = sorted(items)
        runs = [_as_run(b) for _, _, b in items if b.num_records > 0]
        return merge_sorted_runs(runs, 1, self.key_width,
                                 engine=self.engine if engine is None
                                 else engine,
                                 device_min_records=self.device_min_records,
                                 merge_factor=self.merge_factor,
                                 key_normalizer=self.key_normalizer) \
            if runs else _as_run(KVBatch.empty())

    def _pipe_dispatch(self, payload):
        """Pipeline dispatch stage (staging thread): the device/host merge
        itself.  The chaos seams (device.dispatch.{oom,hang}) and the
        dispatch watchdog wrap this call exactly as they wrap sorts."""
        kind, raw = payload
        if kind == "mem":
            return (kind, raw, self._merge_mem_items(raw))
        return (kind, raw, self._stream_merge_to_disk(raw))

    def _pipe_readback(self, inflight, ids):
        """Pipeline readback stage (worker thread): persist a mem merge as
        a chunked run.  This is the stage that overlaps the NEXT merge's
        dispatch — disk write k runs concurrently with device merge k+1."""
        kind, raw, result = inflight
        if kind == "mem":
            return (kind, raw, self._write_chunked([result]))
        return (kind, raw, result)      # disk cascades write while merging

    def _pipe_failover(self, ids, payloads):
        """Containment: re-run a claimed merge on the HOST engine from the
        raw payload (committed batches / input run paths) and persist it —
        the merge twin of DeviceSorter._async_failover."""
        kind, raw = payloads[0]
        if kind == "mem":
            merged = self._merge_mem_items(raw, engine="host")
            return (kind, raw, self._write_chunked([merged]))
        return (kind, raw, self._stream_merge_to_disk(raw, engine="host"))

    def _pipe_oom_retry(self, ids, payloads):
        """OOM ladder: halve the run set, merge each half on device, then
        merge the two results — halves are contiguous prefixes of the
        slot-major order, so the composed merge is bit-identical (run-age
        tie order preserved).  Raises to decline below 2 live runs (the
        ladder then falls through to host failover)."""
        kind, raw = payloads[0]
        if kind != "mem":
            raise MemoryError("disk cascade OOM: no device span to split")
        items = sorted(raw)
        live = [t for t in items if t[2].num_records > 0]
        if len(live) < 2:
            raise MemoryError("merge OOM split floor reached")
        mid = len(live) // 2
        halves = [self._merge_mem_items(part, engine="device")
                  for part in (live[:mid], live[mid:])]
        merged = merge_sorted_runs(halves, 1, self.key_width,
                                   engine="device",
                                   device_min_records=self.device_min_records,
                                   merge_factor=self.merge_factor,
                                   key_normalizer=self.key_normalizer)
        return (kind, raw, self._write_chunked([merged]))

    def _pipe_complete(self, ids, result) -> None:
        """Pipeline completion hook: stash by submission seq and fold every
        consecutive finished merge into the manager state (out-of-order
        readbacks never reorder the disk-run age list)."""
        with self.lock:
            for sid in ids:             # merge groups are single-span
                self._pending_out[sid] = result
            while self._next_out in self._pending_out:
                kind, raw, path = self._pending_out.pop(self._next_out)
                self._next_out += 1
                if kind == "mem":
                    self._fold_mem_locked(raw, path)
                else:
                    self._fold_disk_locked(raw, path)
            self.lock.notify_all()

    def _fold_mem_locked(self, items, path: str) -> None:
        claimed = {q for _, q, _ in items}
        self._merging = [t for t in self._merging if t[1] not in claimed]
        if self._poisoned is not None:
            # a claimed slot reset mid-merge: the written file contains
            # stale data — discard it; the consumer attempt re-runs
            try:
                os.remove(path)
            except OSError:
                pass
            return
        self._disk_slots.update(s for s, _, _ in items)
        self._mem_bytes -= sum(b.nbytes for _, _, b in items)
        self._disk_runs.append(path)
        self._mem_to_disk += 1
        self.counters.increment(TaskCounter.NUM_MEM_TO_DISK_MERGES)

    def _fold_disk_locked(self, paths: List[str], out: str) -> None:
        self._disk_claim = None
        if self._poisoned is not None:
            for p in list(paths) + [out]:
                try:
                    os.remove(p)
                except OSError:
                    pass
            return
        # the claimed paths were the OLDEST runs (list prefix): the result
        # re-enters at the front, preserving age order exactly like the
        # synchronous index-based replace
        self._disk_runs.insert(0, out)
        self._disk_to_disk += 1
        for p in paths:
            try:
                os.remove(p)
            except OSError:
                pass
        self.counters.increment(TaskCounter.NUM_DISK_TO_DISK_MERGES)

    def _do_mem_to_disk(self, items: List[Tuple[int, int, KVBatch]]) -> None:
        merged = self._merge_mem_items(items)
        path = self._write_chunked([merged])
        freed = sum(b.nbytes for _, _, b in items)
        with self.lock:
            self._merging = []
            if self._poisoned is not None:
                # a claimed slot reset mid-merge: the written file contains
                # stale data — discard it; the consumer attempt re-runs
                try:
                    os.remove(path)
                except OSError:
                    pass
                self.lock.notify_all()
                return
            self._disk_slots.update(s for s, _, _ in items)
            self._mem_bytes -= freed
            self._disk_runs.append(path)
            self._mem_to_disk += 1
            self.lock.notify_all()
        self.counters.increment(TaskCounter.NUM_MEM_TO_DISK_MERGES)

    def _do_disk_to_disk(self, paths: List[str]) -> None:
        out = self._stream_merge_to_disk(paths)
        with self.lock:
            # replace the merged inputs with the result, keeping age order
            i = self._disk_runs.index(paths[0])
            self._disk_runs = [p for p in self._disk_runs if p not in paths]
            self._disk_runs.insert(i, out)
            self._disk_to_disk += 1
            self.lock.notify_all()
        for p in paths:
            try:
                os.remove(p)
            except OSError:
                pass
        self.counters.increment(TaskCounter.NUM_DISK_TO_DISK_MERGES)

    # ------------------------------------------------------------ disk I/O
    def _write_chunked(self, runs: Sequence[Run]) -> str:
        path = os.path.join(self.spill_dir,
                            f"mmerge_{uuid.uuid4().hex}.crun")
        w = ChunkedRunWriter(path, codec=self.codec,
                             block_records=self.block_records)
        for r in runs:
            w.append(r.batch)
        w.close()
        self.counters.increment(TaskCounter.ADDITIONAL_SPILLS_BYTES_WRITTEN,
                                w.bytes_written)
        return path

    def _block_iter(self, source) -> Iterator[KVBatch]:
        """Sorted KVBatch blocks from a chunked run path, a disk-direct
        file source, or an in-RAM batch; resident memory is one block at a
        time for the disk shapes."""
        if isinstance(source, str):
            return iter_chunked_run(source)
        if isinstance(source, _FileSource):
            from tez_tpu.ops.runformat import FileRun
            return FileRun(source.path).iter_partition_blocks(
                source.partition)
        return iter([source])

    def _merged_block_iter(self, sources: Sequence,
                           engine: Optional[str] = None) -> Iterator[KVBatch]:
        """Blockwise vectorized k-way merge over paths/batches (age order =
        source order, so equal keys keep the reference MergeQueue's
        arrival-order semantics)."""
        return iter_merged_blocks(
            [self._block_iter(s) for s in sources], self.key_width,
            engine=self.engine if engine is None else engine,
            key_normalizer=self.key_normalizer,
            merge_factor=self.merge_factor,
            device_min_records=self.device_min_records)

    def _stream_merge_to_disk(self, paths: List[str],
                              engine: Optional[str] = None) -> str:
        out_path = os.path.join(self.spill_dir,
                                f"mmerge_{uuid.uuid4().hex}.crun")
        w = ChunkedRunWriter(out_path, codec=self.codec,
                             block_records=self.block_records)
        for block in self._merged_block_iter(paths, engine=engine):
            w.append(block)
        w.close()
        self.counters.increment(TaskCounter.ADDITIONAL_SPILLS_BYTES_WRITTEN,
                                w.bytes_written)
        return out_path

    # ------------------------------------------------------------- finish
    def finish(self) -> "MergedResult":
        """Join the merger; decide in-RAM vs streaming final merge."""
        with self.lock:
            self._closed = True
            self.lock.notify_all()
        if self._merger is not None:
            self._merger.join(timeout=300)
        if self._pipeline is not None:
            # in the async plane the background merges were mostly staged
            # (or finished) while fetches were still landing: drain is
            # usually a no-op wait on the tail merge, not a serial replay
            try:
                self._pipeline.drain()
            except BaseException as e:  # noqa: BLE001 — containment floor
                with self.lock:
                    if self._error is None:
                        self._error = e
                    self.lock.notify_all()
        with self.lock:
            self._raise_if_broken()
            mem = sorted(self._mem)
            disk = list(self._disk_runs)
            # no byte-size filter: empty PARTITIONS never commit (gated by
            # the producer's row-count flags), and a committed source whose
            # records are all zero-length pairs still carries rows
            file_entries = sorted(self._file_sources)
        files = [fs for _, _, fs in file_entries]
        file_bytes = sum(fs.nbytes for fs in files)
        if files and self.budget > 0 and not disk and \
                file_bytes + self._mem_bytes <= \
                self.budget * self.merge_threshold:
            # small disk-direct inputs: cheaper to materialize and take the
            # in-RAM merged-batch path than to stream; slot-major order is
            # preserved by merging them into the mem list under their real
            # (slot, seq) keys
            from tez_tpu.ops.runformat import FileRun
            for s, q, fs in file_entries:
                batch = FileRun(fs.path).partition(fs.partition)
                if batch.num_records > 0:
                    mem.append((s, q, batch))
            mem.sort(key=lambda t: t[:2])
            files = []
        if not disk and not files:
            runs = [_as_run(b) for _, _, b in mem if b.num_records > 0]
            if not runs:
                return MergedResult(batch=KVBatch.empty())
            merged = runs[0] if len(runs) == 1 else merge_sorted_runs(
                runs, 1, self.key_width, counters=self.counters,
                engine=self.engine, merge_factor=self.merge_factor,
                device_min_records=self.device_min_records,
                key_normalizer=self.key_normalizer)
            return MergedResult(batch=merged.batch)
        # leftover memory becomes one more (bounded) sorted segment
        mem_runs = [_as_run(b) for _, _, b in mem if b.num_records > 0]
        mem_seg: Optional[KVBatch] = None
        if mem_runs:
            mem_seg = merge_sorted_runs(
                mem_runs, 1, self.key_width, counters=self.counters,
                engine=self.engine, merge_factor=self.merge_factor,
                device_min_records=self.device_min_records,
                key_normalizer=self.key_normalizer).batch
        return MergedResult(stream=_StreamPlan(self, disk + files, mem_seg))

    def pipeline_events(self) -> List[Tuple[Any, str, str, float]]:
        """Instrumentation events of the async merge lane (instrument=True):
        feed to ops.async_stage.overlap_pairs for the overlap witness."""
        return [] if self._pipeline is None else list(self._pipeline.events)

    def cleanup(self) -> None:
        with self.lock:
            self._closed = True
            self.lock.notify_all()
            paths = list(self._disk_runs)
            self._disk_runs = []
        for p in paths:
            try:
                os.remove(p)
            except OSError:
                pass


class _StreamPlan:
    """Re-iterable streaming merge over disk runs + the leftover mem segment
    (disk blocks re-read on every iteration; memory stays bounded)."""

    def __init__(self, mm: ShuffleMergeManager, disk: List[str],
                 mem_seg: Optional[KVBatch]):
        self.mm = mm
        self.disk = disk
        self.mem_seg = mem_seg

    def _sources(self) -> List[Any]:
        sources: List[Any] = list(self.disk)
        if self.mem_seg is not None:
            sources.append(self.mem_seg)
        return sources

    def iter_batches(self) -> Iterator[KVBatch]:
        """Globally-sorted merged blocks (the vectorized consumer path)."""
        return self.mm._merged_block_iter(self._sources())

    def iter_records(self) -> Iterator[Tuple[bytes, bytes, bytes]]:
        """Per-record view for generic consumers, built on the blockwise
        merge (one normalization pass per block, not per comparison)."""
        norm = self.mm.key_normalizer
        for batch in self.iter_batches():
            if norm is not None:
                nb, no = normalize_batch_keys(batch, norm)
                for i in range(batch.num_records):
                    yield (nb[no[i]:no[i + 1]].tobytes(), batch.key(i),
                           batch.value(i))
            else:
                for i in range(batch.num_records):
                    k = batch.key(i)
                    yield (k, k, batch.value(i))


class MergedResult:
    """Either a fully-merged in-RAM batch or a streaming merge plan."""

    def __init__(self, batch: Optional[KVBatch] = None,
                 stream: Optional[_StreamPlan] = None):
        self.batch = batch
        self.stream = stream

    @property
    def is_streaming(self) -> bool:
        return self.stream is not None
