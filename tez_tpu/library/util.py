"""Shared helpers for the stock IO library."""
from __future__ import annotations

from typing import Any, Dict


def conf_get(context: Any, key: str, default: Any) -> Any:
    """Resolve a runtime config key: IO payload overrides task conf
    (the 'runtime config travels inside the edge payload' rule)."""
    payload = context.user_payload.load()
    conf: Dict[str, Any] = dict(context.conf)
    if isinstance(payload, dict):
        conf.update(payload)
    return conf.get(key, default)
