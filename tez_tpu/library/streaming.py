"""Stock streaming-window processors (docs/streaming.md).

The window source/sink pair for micro-batch DAG templates run by
``tez_tpu.am.streaming.StreamDriver``.  The driver stamps the window
coordinate into each window-DAG's ``dag_conf``; these processors read it
back through ``context.conf`` (TaskSpec carries the merged vertex conf)
and ``context.window_id``:

- :class:`StreamWindowSourceProcessor` reads the window's sealed spool
  (``tez.runtime.stream.input``), striping records across source tasks by
  record index — deterministic, so a window-exact replay re-emits byte-
  identical partitions.
- :class:`StreamWindowSinkProcessor` groups its input and writes window-
  tagged HIDDEN tmp part files (``.w<N>.part<i>.tmp``) into
  ``tez.runtime.stream.output-dir``; the driver's exactly-once commit
  bracket renames them to their final ``w<N>.part<i>`` names.  A task
  never publishes final names itself — that is what makes a killed
  mid-window attempt harmless (its tmp is overwritten by the replay) and
  the commit idempotent.
"""
from __future__ import annotations

from typing import Dict

from tez_tpu.api.runtime import LogicalInput, LogicalOutput
from tez_tpu.library.processors import SimpleProcessor


class StreamWindowSourceProcessor(SimpleProcessor):
    """Emits one window's spool records into the shuffle edge.

    Spool records are JSON values; dicts shaped ``{"k": str, "v": num}``
    become (key, value) pairs, anything else (punctuation markers) is
    skipped.  Task ``i`` of ``p`` emits the records whose index satisfies
    ``idx % p == i``."""

    def run(self, inputs: Dict[str, LogicalInput],
            outputs: Dict[str, LogicalOutput]) -> None:
        from tez_tpu.am.streaming import read_spool
        conf = self.context.conf
        path = str(conf.get("tez.runtime.stream.input", "") or "")
        if not path:
            raise RuntimeError(
                "StreamWindowSourceProcessor needs tez.runtime.stream.input "
                "(set by the StreamDriver when it clones the window plan)")
        records = read_spool(path)
        parallelism = max(1, self.context.vertex_parallelism)
        writers = [out.get_writer() for out in outputs.values()]
        for idx, rec in enumerate(records):
            if idx % parallelism != self.context.task_index:
                continue
            if not isinstance(rec, dict) or "k" not in rec:
                continue            # punctuation / control record
            for writer in writers:
                writer.write(str(rec["k"]).encode(), rec.get("v", 1))


class StreamWindowSinkProcessor(SimpleProcessor):
    """Groups the window's shuffled input and writes sorted ``key total``
    lines to a hidden window-tagged tmp part file.  Output is a pure
    function of the window's spool, so committed windows are bit-exact
    across replays regardless of fetch interleaving or attempt kills."""

    def run(self, inputs: Dict[str, LogicalInput],
            outputs: Dict[str, LogicalOutput]) -> None:
        import os
        conf = self.context.conf
        out_dir = str(conf.get("tez.runtime.stream.output-dir", "") or "")
        w = self.context.window_id
        if not out_dir or w <= 0:
            raise RuntimeError(
                "StreamWindowSinkProcessor needs tez.runtime.stream."
                "output-dir and a window id (driver-stamped dag_conf)")
        totals: Dict[bytes, int] = {}
        for inp in inputs.values():
            for k, vs in inp.get_reader():
                totals[k] = totals.get(k, 0) + sum(vs)
        lines = [f"{k.decode()} {v}" for k, v in sorted(totals.items())]
        os.makedirs(out_dir, exist_ok=True)
        tmp = os.path.join(
            out_dir, f".w{w:06d}.part{self.context.task_index}.tmp")
        with open(tmp, "w") as fh:
            fh.write("\n".join(lines) + "\n")
            fh.flush()
            os.fsync(fh.fileno())
