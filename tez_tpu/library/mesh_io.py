"""Mesh-backed ordered scatter-gather IO: the ICI data plane as a DAG edge.

Reference parity: these classes stand in the exact seam of
OrderedPartitionedKVOutput / OrderedGroupedKVInput (tez-runtime-library
library/{output,input}/), but the edge's data movement is the SPMD
all-to-all exchange (parallel/exchange.py via parallel/coordinator.py)
instead of per-task spill files + N^2 fetches: the producer-side sort, the
shuffle transport, and the consumer-side merge are ONE jitted program over
the device mesh.  Event flow is unchanged — producers still emit
DataMovementEvents through the AM (so vertex managers, recovery and
counters all see a normal SCATTER_GATHER edge); the payload's
`host="(mesh)"` marks that the bytes move through the exchange, not the
shuffle servers.

Contract: key/value slot widths auto-widen to the data, up to
tez.runtime.tpu.mesh.max.key.bytes (256) / .max.value.bytes (1024) — the
configured widths are slot-size hints (loud MeshCapacityError beyond the
caps: per-row HBM slots make one huge record tax every row — such records
belong on the host shuffle edge).  Consumer parallelism MAY exceed the
device count: the exchange routes over the largest device count dividing
the consumer count and splits each device's key-sorted output into its
consumer partitions on host.
"""
from __future__ import annotations

import logging
from typing import Any, List, Optional, Sequence

from tez_tpu.api.events import (CompositeDataMovementEvent,
                                CompositeRoutedDataMovementEvent,
                                DataMovementEvent, InputFailedEvent,
                                ShufflePayload, TezAPIEvent,
                                VertexManagerEvent)
from tez_tpu.api.runtime import (KeyValuesWriter, LogicalInput, LogicalOutput,
                                 Writer)
from tez_tpu.common.counters import TaskCounter
from tez_tpu.library.inputs import GroupedKVReader
from tez_tpu.library.util import conf_get as _conf_get
from tez_tpu.ops.runformat import KVBatch
from tez_tpu.ops.serde import get_serde

log = logging.getLogger(__name__)

MESH_HOST = "(mesh)"


def _edge_id(dag_id: Any, src_vertex: str, dst_vertex: str) -> str:
    return f"{dag_id}/{src_vertex}->{dst_vertex}"


class MeshOrderedPartitionedKVOutput(LogicalOutput):
    """Producer half of a mesh SCATTER_GATHER edge: collects this task's
    records and registers them with the exchange coordinator; the last
    producer to close triggers the SPMD exchange (the gang barrier)."""

    def initialize(self) -> List[TezAPIEvent]:
        ctx = self.context
        self.key_serde = get_serde(_conf_get(ctx, "tez.runtime.key.class",
                                             "bytes"))
        self.val_serde = get_serde(_conf_get(ctx, "tez.runtime.value.class",
                                             "bytes"))
        self.key_width = int(_conf_get(ctx, "tez.runtime.tpu.key.width.bytes",
                                       16))
        self.value_width = int(_conf_get(
            ctx, "tez.runtime.tpu.mesh.value.width.bytes", 16))
        self.max_key_bytes = int(_conf_get(
            ctx, "tez.runtime.tpu.mesh.max.key.bytes", 256))
        self.max_value_bytes = int(_conf_get(
            ctx, "tez.runtime.tpu.mesh.max.value.bytes", 1024))
        self.max_rows_per_round = int(_conf_get(
            ctx, "tez.runtime.tpu.mesh.max-rows-per-round", 0))
        self.exchange_engine = str(_conf_get(
            ctx, "tez.runtime.mesh.exchange.engine", "auto"))
        self.exchange_coded = str(_conf_get(
            ctx, "tez.runtime.mesh.exchange.coded", "off"))
        self.exchange_split_after = int(_conf_get(
            ctx, "tez.runtime.mesh.exchange.split.after", 2))
        if _conf_get(ctx, "tez.runtime.key.comparator.class", ""):
            raise ValueError(
                "mesh edges sort by raw key bytes on device; custom "
                "comparators need the host shuffle edge "
                "(OrderedPartitionedKVEdgeConfig)")
        self._pairs: List = []
        self._batches: List[KVBatch] = []
        ctx.request_initial_memory(0, None,
                                   component_type="PARTITIONED_SORTED_OUTPUT")
        return []

    def get_writer(self) -> Writer:
        output = self

        class _W(KeyValuesWriter):
            supports_batch = True   # no custom-partitioner mode on mesh edges

            def write(self, key, value) -> None:
                k = output.key_serde.to_bytes(key)
                v = output.val_serde.to_bytes(value)
                output._pairs.append((k, v))
                output.context.counters.increment(TaskCounter.OUTPUT_RECORDS)
                output.context.counters.increment(
                    TaskCounter.OUTPUT_BYTES, len(k) + len(v))
                if (len(output._pairs) & 0x3FFF) == 0:
                    output.context.notify_progress()

            def write_batch(self, batch: KVBatch) -> None:
                """Batch-first path: pre-serialized records."""
                output._batches.append(batch)
                output.context.counters.increment(
                    TaskCounter.OUTPUT_RECORDS, batch.num_records)
                output.context.counters.increment(
                    TaskCounter.OUTPUT_BYTES, batch.nbytes)
                output.context.notify_progress()

        return _W()

    def handle_events(self, events: Sequence[TezAPIEvent]) -> None:
        pass

    def close(self) -> List[TezAPIEvent]:
        from tez_tpu.parallel.coordinator import mesh_coordinator
        ctx = self.context
        parts = list(self._batches)
        if self._pairs:
            parts.append(KVBatch.from_pairs(self._pairs))
        batch = KVBatch.concat(parts) if parts else KVBatch.empty()
        self._pairs = []
        self._batches = []
        edge = _edge_id(ctx.task_attempt_id.dag_id, ctx.vertex_name,
                        ctx.destination_vertex_name)
        mesh_coordinator().register_producer(
            edge, ctx.task_index,
            num_producers=ctx.vertex_parallelism,
            num_consumers=self.num_physical_outputs,
            batch=batch, key_width=self.key_width,
            value_width=self.value_width,
            max_rows_per_round=self.max_rows_per_round,
            max_key_bytes=self.max_key_bytes,
            max_value_bytes=self.max_value_bytes,
            engine=self.exchange_engine,
            coded=self.exchange_coded,
            split_after=self.exchange_split_after,
            counters=ctx.counters)
        ctx.counters.increment(TaskCounter.SHUFFLE_BYTES, batch.nbytes)
        payload = ShufflePayload(host=MESH_HOST, port=0,
                                 path_component=edge, last_event=True)
        return [
            CompositeDataMovementEvent(0, self.num_physical_outputs, payload),
            VertexManagerEvent(
                target_vertex_name=ctx.destination_vertex_name,
                user_payload={"output_size": batch.nbytes,
                              "partition_sizes": None}),
        ]


class MeshOrderedGroupedKVInput(LogicalInput):
    """Consumer half: waits for every producer's mesh DME, then reads its
    worker's sorted partition straight off the exchange (already merged —
    there is no consumer-side fetch or merge phase at all)."""

    def initialize(self) -> List[TezAPIEvent]:
        ctx = self.context
        self.key_serde = get_serde(_conf_get(ctx, "tez.runtime.key.class",
                                             "bytes"))
        self.val_serde = get_serde(_conf_get(ctx, "tez.runtime.value.class",
                                             "bytes"))
        import threading
        self._lock = threading.Condition()
        self._complete = set()
        self._failed: Optional[str] = None
        self._batch: Optional[KVBatch] = None
        self._reading = False
        self._group_starts = None
        # straggler defense on the gang barrier (0 = wait forever)
        self._deadline = float(_conf_get(
            ctx, "tez.runtime.tpu.mesh.exchange.deadline.secs", 0.0)) or None
        ctx.request_initial_memory(0, None,
                                   component_type="SORTED_MERGED_INPUT")
        return []

    def handle_events(self, events: Sequence[TezAPIEvent]) -> None:
        with self._lock:
            for ev in events:
                if isinstance(ev, (CompositeRoutedDataMovementEvent,
                                   DataMovementEvent)):
                    slot = ev.target_index_start if isinstance(
                        ev, CompositeRoutedDataMovementEvent) \
                        else ev.target_index
                    payload = ev.user_payload
                    assert isinstance(payload, ShufflePayload), payload
                    if payload.host != MESH_HOST:
                        self._failed = (
                            f"mesh input received non-mesh payload from "
                            f"slot {slot} (host {payload.host!r}): the "
                            f"edge's output class must be the mesh output")
                    self._complete.add(slot)
                elif isinstance(ev, InputFailedEvent):
                    if self._batch is not None or self._reading:
                        # this attempt already materialized the (now stale)
                        # merged result: fail loudly; the retry waits for
                        # the coordinator's re-exchange below
                        self._failed = (f"producer slot {ev.target_index} "
                                        f"re-ran after this attempt read "
                                        f"the mesh exchange")
                    else:
                        # producer re-running before we read anything: the
                        # coordinator invalidates and re-runs the exchange
                        # when the replacement span registers — just wait
                        # for the fresh DME
                        self._complete.discard(ev.target_index)
                else:
                    log.warning("MeshOrderedGroupedKVInput: unexpected "
                                "event %r", ev)
            self._lock.notify_all()

    def _wait_complete(self) -> Optional[float]:
        """Returns the REMAINING deadline budget (None = unbounded) so the
        coordinator barrier wait consumes the same window, not a fresh
        one — the configured deadline bounds the whole stall."""
        import time
        deadline = None if self._deadline is None \
            else time.monotonic() + self._deadline
        with self._lock:
            while len(self._complete) < self.num_physical_inputs:
                if self._failed:
                    raise RuntimeError(self._failed)
                if deadline is not None and time.monotonic() > deadline:
                    missing = sorted(set(range(self.num_physical_inputs)) -
                                     self._complete)
                    raise TimeoutError(
                        f"mesh edge into {self.context.vertex_name}: "
                        f"{len(self._complete)}/{self.num_physical_inputs} "
                        f"producers completed within "
                        f"{self._deadline:.0f}s; missing producer task "
                        f"indices {missing[:16]}"
                        f"{'...' if len(missing) > 16 else ''}")
                self._lock.wait(0.2)
                self.context.notify_progress()
            if self._failed:
                raise RuntimeError(self._failed)
            # atomically with the final completeness check: any producer
            # InputFailedEvent from here on marks this attempt failed —
            # no window where a failure lands between this check and the
            # batch read/assignment in get_reader
            self._reading = True
        if deadline is None:
            return None
        return max(0.5, deadline - time.monotonic())

    def get_reader(self) -> GroupedKVReader:
        with self._lock:
            if self._failed:
                raise RuntimeError(self._failed)
        if self._batch is None:
            import time
            ctx = self.context
            t0 = time.time()
            remaining = self._wait_complete()
            from tez_tpu.parallel.coordinator import mesh_coordinator
            edge = _edge_id(ctx.task_attempt_id.dag_id,
                            ctx.source_vertex_name, ctx.vertex_name)
            batch = mesh_coordinator().wait_consumer(
                edge, ctx.task_index,
                num_producers=self.num_physical_inputs,
                num_consumers=ctx.vertex_parallelism,
                timeout=remaining,
                progress=ctx.notify_progress)
            with self._lock:
                if self._failed:
                    raise RuntimeError(self._failed)
                self._batch = batch
            ctx.counters.find_counter(TaskCounter.SHUFFLE_PHASE_TIME)\
                .increment(int((time.time() - t0) * 1000))
            ctx.counters.increment(TaskCounter.REDUCE_INPUT_RECORDS,
                                   self._batch.num_records)
            ctx.counters.increment(TaskCounter.NUM_SHUFFLED_INPUTS,
                                   self.num_physical_inputs)
        if self._group_starts is None:
            self._group_starts = GroupedKVReader._compute_groups(self._batch)
        return GroupedKVReader(self._batch, self.key_serde, self.val_serde,
                               self.context, group_starts=self._group_starts)

    def close(self) -> List[TezAPIEvent]:
        self._batch = None
        self._group_starts = None
        with self._lock:
            if self._failed:
                # the attempt consumed a generation that a producer re-ran
                # out from under it: it must not complete successfully —
                # the retry reads the re-exchanged data
                raise RuntimeError(self._failed)
        return []
