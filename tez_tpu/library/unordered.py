"""Unordered data path: hash-partitioned (no sort) outputs and streaming
inputs.

Reference parity: tez-runtime-library UnorderedPartitionedKVOutput +
UnorderedPartitionedKVWriter.java:93 (per-partition chained buffers from a
shared pool, background spill, final merge or per-spill events, skip-buffer
direct-write for 1 partition), UnorderedKVOutput (broadcast writer),
ShuffleManager.java:108 + UnorderedKVReader (streaming consumption as
fetches complete, no merge).

TPU shape: records batch into spans; a span is partitioned with the device
hash kernel and grouped with a single-key partition sort pass (one u32 sort
— no key ordering), yielding the same Run container the shuffle service
serves.
"""
from __future__ import annotations

import logging
import os
import threading
from typing import Any, Dict, Iterator, List, Optional, Sequence, Tuple

import numpy as np

from tez_tpu.api.events import (CompositeDataMovementEvent,
                                CompositeRoutedDataMovementEvent,
                                DataMovementEvent, InputFailedEvent,
                                ShufflePayload, TezAPIEvent,
                                VertexManagerEvent, pack_empty_partitions)
from tez_tpu.api.runtime import (KeyValueReader, KeyValuesWriter,
                                 LogicalInput, LogicalOutput, Reader, Writer)
from tez_tpu.common.counters import TaskCounter
from tez_tpu.library.inputs import ShuffleFetchTable, _conf_get
from tez_tpu.library.outputs import output_path_component
from tez_tpu.ops import device
from tez_tpu.ops.keycodec import pad_to_matrix
from tez_tpu.ops.runformat import KVBatch, Run
from tez_tpu.ops.serde import get_serde
from tez_tpu.ops.sorter import SpanBuffer
from tez_tpu.shuffle.service import local_shuffle_service

log = logging.getLogger(__name__)


class UnorderedPartitionedWriter:
    """Hash-partition spans on device; no key sort."""

    def __init__(self, num_partitions: int, span_budget_bytes: int,
                 counters: Any, single_partition_skip_buffer: bool = True,
                 use_pallas_hash: bool = False):
        self.num_partitions = num_partitions
        self.span_budget = span_budget_bytes
        self.counters = counters
        self.use_pallas_hash = use_pallas_hash
        self._span = SpanBuffer()
        self._runs: List[Run] = []
        self.num_spills = 0
        self.on_spill = None   # pipelined / no-final-merge mode
        # resolved once: find_counter locks the registry per call
        self._out_records_ctr = self.counters.find_counter(
            TaskCounter.OUTPUT_RECORDS)

    def write(self, key: bytes, value: bytes) -> None:
        self._span.add(key, value)
        self._out_records_ctr.increment()
        if self._span.nbytes >= self.span_budget:
            self._partition_span()

    def _partition_span(self) -> None:
        if self._span.num_records == 0:
            return
        batch = self._span.to_batch()
        self._span = SpanBuffer()
        run = self.partition_batch(batch)
        if self.on_spill is not None:
            self.on_spill(run, self.num_spills)
        else:
            self._runs.append(run)
            self.counters.increment(TaskCounter.SPILLED_RECORDS,
                                    batch.num_records)
        self.num_spills += 1

    def partition_batch(self, batch: KVBatch) -> Run:
        if self.num_partitions == 1:
            # skip-buffer direct path (reference :direct-write mode)
            return Run(batch, np.array([0, batch.num_records], dtype=np.int64))
        klens = batch.key_offsets[1:] - batch.key_offsets[:-1]
        wmax = int(klens.max(initial=1))
        hash_w = 1 << max(2, (wmax - 1).bit_length())
        mat, lengths = pad_to_matrix(batch.key_bytes, batch.key_offsets,
                                     hash_w)
        partitions = device.hash_partition(mat, lengths, self.num_partitions,
                                           use_pallas=self.use_pallas_hash)
        # single stable pass groups rows by partition, preserving arrival
        # order within each partition
        sorted_parts, perm = device.sort_run(
            partitions, np.zeros((len(partitions), 0), dtype=np.uint32),
            np.zeros(len(partitions), dtype=np.int64))
        return Run.from_sorted_batch(batch.take(perm), sorted_parts,
                                     self.num_partitions)

    def flush(self) -> Optional[Run]:
        if self.on_spill is not None:
            self._partition_span()
            return None
        self._partition_span()
        if not self._runs:
            return Run(KVBatch.empty(),
                       np.zeros(self.num_partitions + 1, dtype=np.int64))
        if len(self._runs) == 1:
            return self._runs[0]
        # final "merge": per-partition concatenation (no ordering contract)
        parts: List[KVBatch] = []
        counts = np.zeros(self.num_partitions, dtype=np.int64)
        for p in range(self.num_partitions):
            for r in self._runs:
                pb = r.partition(p)
                if pb.num_records:
                    parts.append(pb)
                    counts[p] += pb.num_records
        batch = KVBatch.concat(parts) if parts else KVBatch.empty()
        row_index = np.zeros(self.num_partitions + 1, dtype=np.int64)
        np.cumsum(counts, out=row_index[1:])
        return Run(batch, row_index)


class _UnorderedWriterFacade(KeyValuesWriter):
    def __init__(self, writer: UnorderedPartitionedWriter, key_serde, val_serde,
                 context: Any):
        self.writer = writer
        self.key_serde = key_serde
        self.val_serde = val_serde
        self.context = context
        self._n = 0
        self._out_bytes_ctr = context.counters.find_counter(
            TaskCounter.OUTPUT_BYTES)

    def write(self, key: Any, value: Any) -> None:
        k = self.key_serde.to_bytes(key)
        v = self.val_serde.to_bytes(value)
        self.writer.write(k, v)
        self._out_bytes_ctr.increment(len(k) + len(v))
        self._n += 1
        if (self._n & 0x3FFF) == 0:
            self.context.notify_progress()


class UnorderedPartitionedKVOutput(LogicalOutput):
    """Hash-partitioned, unsorted output."""

    def initialize(self) -> List[TezAPIEvent]:
        ctx = self.context
        buffer_mb = int(_conf_get(
            ctx, "tez.runtime.unordered.output.buffer.size-mb", 100))
        self.key_serde = get_serde(_conf_get(ctx, "tez.runtime.key.class",
                                             "bytes"))
        self.val_serde = get_serde(_conf_get(ctx, "tez.runtime.value.class",
                                             "bytes"))
        self._final_merge = bool(_conf_get(
            ctx, "tez.runtime.enable.final-merge.in.output", True))
        self.writer_impl = UnorderedPartitionedWriter(
            self.num_physical_outputs, buffer_mb << 20, ctx.counters,
            use_pallas_hash=bool(_conf_get(
                ctx, "tez.runtime.tpu.pallas.hash", False)))
        ctx.request_initial_memory(buffer_mb << 20, None,
                           component_type="PARTITIONED_UNSORTED_OUTPUT")
        self.service = local_shuffle_service()
        self.host = ctx.get_service_provider_metadata("shuffle") or \
            {"host": "local", "port": 0}
        self._spills_sent = 0
        if not self._final_merge:
            self.writer_impl.on_spill = self._ship_spill
        return []

    def get_writer(self) -> Writer:
        return _UnorderedWriterFacade(self.writer_impl, self.key_serde,
                                      self.val_serde, self.context)

    def handle_events(self, events: Sequence[TezAPIEvent]) -> None:
        pass

    def _payload(self, run: Run, spill_id: int, last: bool) -> ShufflePayload:
        return ShufflePayload(
            host=self.host["host"], port=self.host["port"],
            path_component=output_path_component(self.context),
            empty_partitions=pack_empty_partitions(
                run.empty_partition_flags()),
            spill_id=spill_id, last_event=last)

    def _ship_spill(self, run: Run, spill_id: int) -> None:
        self.service.register(output_path_component(self.context), spill_id,
                              run)
        self.context.send_events([
            CompositeDataMovementEvent(0, run.num_partitions,
                                       self._payload(run, spill_id, False))])
        self._spills_sent += 1

    def close(self) -> List[TezAPIEvent]:
        run = self.writer_impl.flush()
        path = output_path_component(self.context)
        if run is None:   # per-spill mode: send final marker
            empty = Run(KVBatch.empty(),
                        np.zeros(self.num_physical_outputs + 1,
                                 dtype=np.int64))
            self.service.register(path, self._spills_sent, empty)
            return [CompositeDataMovementEvent(
                0, self.num_physical_outputs,
                self._payload(empty, self._spills_sent, True))]
        self.service.register(path, -1, run)
        from tez_tpu.common import config as C
        from tez_tpu.library.util import conf_get as _conf_get
        vm_payload = {"output_size": run.nbytes}
        if _conf_get(self.context, C.REPORT_PARTITION_STATS.name,
                     C.REPORT_PARTITION_STATS.default):
            vm_payload["partition_sizes"] = [
                run.partition_nbytes(p) for p in range(run.num_partitions)]
        return [
            CompositeDataMovementEvent(
                0, run.num_partitions,
                ShufflePayload(host=self.host["host"], port=self.host["port"],
                               path_component=path,
                               empty_partitions=pack_empty_partitions(
                                   run.empty_partition_flags()),
                               spill_id=-1, last_event=True)),
            VertexManagerEvent(
                target_vertex_name=self.context.destination_vertex_name,
                user_payload=vm_payload),
        ]


class UnorderedKVOutput(UnorderedPartitionedKVOutput):
    """Single-partition / broadcast writer (reference: UnorderedKVOutput
    wrapping the partitioned writer with 1 partition)."""


class StreamingKVReader(KeyValueReader):
    """Yields records as fetches complete — no global wait (reference:
    UnorderedKVReader streaming from the completedInputs queue)."""

    def __init__(self, table: ShuffleFetchTable, key_serde, val_serde,
                 context: Any):
        self.table = table
        self.key_serde = key_serde
        self.val_serde = val_serde
        self.context = context

    def __iter__(self) -> Iterator[Tuple[Any, Any]]:
        import time
        consumed_slots: set = set()
        consumed_batches: Dict[int, int] = {}
        n = 0
        while True:
            with self.table.lock:
                ready: List[Tuple[int, KVBatch]] = []
                done = self.table.completed >= self.table.num_slots
                for si, s in enumerate(self.table.slots):
                    start = consumed_batches.get(si, 0)
                    for b in s.batches[start:]:
                        ready.append((si, b))
                    consumed_batches[si] = len(s.batches)
            for si, batch in ready:
                for k, v in batch.iter_pairs():
                    yield (self.key_serde.from_bytes(k),
                           self.val_serde.from_bytes(v))
                    n += 1
            if done and not ready:
                break
            if not ready:
                time.sleep(0.02)
                self.context.notify_progress()
        self.context.counters.increment(TaskCounter.INPUT_RECORDS_PROCESSED, n)


class UnorderedKVInput(LogicalInput):
    """Streaming unordered input (ShuffleManager consumer side)."""

    def initialize(self) -> List[TezAPIEvent]:
        ctx = self.context
        self.key_serde = get_serde(_conf_get(ctx, "tez.runtime.key.class",
                                             "bytes"))
        self.val_serde = get_serde(_conf_get(ctx, "tez.runtime.value.class",
                                             "bytes"))
        self.table = ShuffleFetchTable(ctx, self.num_physical_inputs,
                                       my_partition=ctx.task_index)
        ctx.request_initial_memory(0, None)
        return []

    def handle_events(self, events: Sequence[TezAPIEvent]) -> None:
        for ev in events:
            if isinstance(ev, CompositeRoutedDataMovementEvent):
                payload = ev.user_payload
                for i in range(ev.count):
                    # expansion advances BOTH indices (reference:
                    # CompositeRoutedDataMovementEvent.expand)
                    self.table.on_payload(ev.target_index_start + i,
                                          ev.source_index + i, payload,
                                          version=ev.version)
            elif isinstance(ev, DataMovementEvent):
                self.table.on_payload(ev.target_index, ev.source_index,
                                      ev.user_payload, version=ev.version)
            elif isinstance(ev, InputFailedEvent):
                self.table.on_input_failed(ev.target_index, ev.version)

    def get_reader(self) -> Reader:
        return StreamingKVReader(self.table, self.key_serde, self.val_serde,
                                 self.context)

    def close(self) -> List[TezAPIEvent]:
        self.table.shutdown()
        return []
