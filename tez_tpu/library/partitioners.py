"""Partitioner SPI + stock partitioners.

Reference parity: tez-runtime-library/.../library/partitioner/
{HashPartitioner,RoundRobinPartitioner}.java.  Device-side batch
partitioning for the TPU data plane lives in tez_tpu.ops.partition; these
host-side partitioners remain for scalar/record-at-a-time paths and parity.
"""
from __future__ import annotations

from typing import Any


def _stable_hash(key: Any) -> int:
    """Deterministic across processes (Python's hash() is salted)."""
    if isinstance(key, (bytes, bytearray)):
        data = bytes(key)
    elif isinstance(key, str):
        data = key.encode()
    elif isinstance(key, int):
        data = key.to_bytes(8, "little", signed=True)
    else:
        data = repr(key).encode()
    # FNV-1a 32-bit — matches ops/partition.py device kernel
    h = 2166136261
    for b in data:
        h = ((h ^ b) * 16777619) & 0xFFFFFFFF
    return h


class Partitioner:
    def get_partition(self, key: Any, value: Any, num_partitions: int) -> int:
        raise NotImplementedError


class HashPartitioner(Partitioner):
    def get_partition(self, key: Any, value: Any, num_partitions: int) -> int:
        return _stable_hash(key) % num_partitions


class RoundRobinPartitioner(Partitioner):
    def __init__(self) -> None:
        self._next = 0

    def get_partition(self, key: Any, value: Any, num_partitions: int) -> int:
        p = self._next % num_partitions
        self._next += 1
        return p
