"""Fluent edge configuration builders.

Reference parity: tez-runtime-library/.../library/conf/
{OrderedPartitionedKVEdgeConfig,UnorderedKVEdgeConfig,
UnorderedPartitionedKVEdgeConfig}.java — build EdgeProperty instances with
the runtime config serialized into the IO payloads (the "runtime config
travels inside the edge payload" rule, SURVEY.md §5.6).
"""
from __future__ import annotations

from typing import Any, Dict, Optional

from tez_tpu.common.payload import InputDescriptor, OutputDescriptor
from tez_tpu.dag.edge_property import (DataMovementType, DataSourceType,
                                       EdgeProperty, SchedulingType)


class _BaseEdgeConfigBuilder:
    _output_class: str = ""
    _input_class: str = ""
    _movement: DataMovementType = DataMovementType.SCATTER_GATHER

    def __init__(self, key_serde: str = "bytes", value_serde: str = "bytes"):
        self.conf: Dict[str, Any] = {
            "tez.runtime.key.class": key_serde,
            "tez.runtime.value.class": value_serde,
        }

    def set_conf(self, key: str, value: Any) -> "_BaseEdgeConfigBuilder":
        self.conf[key] = value
        return self

    def set_from_configuration(self, conf: Dict[str, Any]
                               ) -> "_BaseEdgeConfigBuilder":
        for k, v in conf.items():
            if k.startswith("tez.runtime."):
                self.conf[k] = v
        return self

    def build(self) -> "_EdgeConfig":
        return _EdgeConfig(self._output_class, self._input_class,
                           self._movement, dict(self.conf))


class _EdgeConfig:
    def __init__(self, output_class: str, input_class: str,
                 movement: DataMovementType, conf: Dict[str, Any]):
        self.output_class = output_class
        self.input_class = input_class
        self.movement = movement
        self.conf = conf

    def _descriptors(self) -> tuple:
        return (OutputDescriptor.create(self.output_class, payload=self.conf),
                InputDescriptor.create(self.input_class, payload=self.conf))

    def create_default_edge_property(self) -> EdgeProperty:
        out, inp = self._descriptors()
        return EdgeProperty.create(self.movement, DataSourceType.PERSISTED,
                                   SchedulingType.SEQUENTIAL, out, inp)

    def create_default_broadcast_edge_property(self) -> EdgeProperty:
        out, inp = self._descriptors()
        return EdgeProperty.create(DataMovementType.BROADCAST,
                                   DataSourceType.PERSISTED,
                                   SchedulingType.SEQUENTIAL, out, inp)

    def create_default_one_to_one_edge_property(self) -> EdgeProperty:
        out, inp = self._descriptors()
        return EdgeProperty.create(DataMovementType.ONE_TO_ONE,
                                   DataSourceType.PERSISTED,
                                   SchedulingType.SEQUENTIAL, out, inp)

    def create_default_custom_edge_property(self, edge_manager) -> EdgeProperty:
        out, inp = self._descriptors()
        return EdgeProperty.create_custom(edge_manager,
                                          DataSourceType.PERSISTED, out, inp)


class OrderedPartitionedKVEdgeConfig(_BaseEdgeConfigBuilder):
    """Sorted scatter-gather edge (DeviceSorter -> grouped merge input)."""
    _output_class = "tez_tpu.library.outputs:OrderedPartitionedKVOutput"
    _input_class = "tez_tpu.library.inputs:OrderedGroupedKVInput"
    _movement = DataMovementType.SCATTER_GATHER

    @staticmethod
    def new_builder(key_serde: str = "bytes", value_serde: str = "bytes"
                    ) -> "OrderedPartitionedKVEdgeConfig":
        return OrderedPartitionedKVEdgeConfig(key_serde, value_serde)

    def set_combiner(self, combiner: str) -> "OrderedPartitionedKVEdgeConfig":
        self.conf["tez.runtime.combiner.class"] = combiner
        return self

    def set_key_width(self, width: int) -> "OrderedPartitionedKVEdgeConfig":
        self.conf["tez.runtime.tpu.key.width.bytes"] = width
        return self

    def set_pipelined(self, enabled: bool = True
                      ) -> "OrderedPartitionedKVEdgeConfig":
        self.conf["tez.runtime.pipelined-shuffle.enabled"] = enabled
        return self

    def set_sort_mb(self, mb: int) -> "OrderedPartitionedKVEdgeConfig":
        self.conf["tez.runtime.io.sort.mb"] = mb
        return self


class UnorderedKVEdgeConfig(_BaseEdgeConfigBuilder):
    """Unsorted single-partition edge (broadcast / pass-through)."""
    _output_class = "tez_tpu.library.unordered:UnorderedKVOutput"
    _input_class = "tez_tpu.library.unordered:UnorderedKVInput"
    _movement = DataMovementType.BROADCAST

    @staticmethod
    def new_builder(key_serde: str = "bytes", value_serde: str = "bytes"
                    ) -> "UnorderedKVEdgeConfig":
        return UnorderedKVEdgeConfig(key_serde, value_serde)


class UnorderedPartitionedKVEdgeConfig(_BaseEdgeConfigBuilder):
    """Hash-partitioned unsorted scatter-gather edge."""
    _output_class = "tez_tpu.library.unordered:UnorderedPartitionedKVOutput"
    _input_class = "tez_tpu.library.unordered:UnorderedKVInput"
    _movement = DataMovementType.SCATTER_GATHER

    @staticmethod
    def new_builder(key_serde: str = "bytes", value_serde: str = "bytes"
                    ) -> "UnorderedPartitionedKVEdgeConfig":
        return UnorderedPartitionedKVEdgeConfig(key_serde, value_serde)

    def set_buffer_mb(self, mb: int) -> "UnorderedPartitionedKVEdgeConfig":
        self.conf["tez.runtime.unordered.output.buffer.size-mb"] = mb
        return self


class MeshOrderedPartitionedKVEdgeConfig(_BaseEdgeConfigBuilder):
    """Sorted scatter-gather edge over the ICI mesh exchange: producer sort,
    all-to-all transport and consumer merge are ONE SPMD program
    (library/mesh_io.py; reference roles: PipelinedSorter + ShuffleHandler +
    Fetcher + MergeManager).  Consumer parallelism must not exceed the mesh
    device count; keys/values are bounded by the configured widths."""
    _output_class = "tez_tpu.library.mesh_io:MeshOrderedPartitionedKVOutput"
    _input_class = "tez_tpu.library.mesh_io:MeshOrderedGroupedKVInput"
    _movement = DataMovementType.SCATTER_GATHER

    @staticmethod
    def new_builder(key_serde: str = "bytes", value_serde: str = "bytes"
                    ) -> "MeshOrderedPartitionedKVEdgeConfig":
        return MeshOrderedPartitionedKVEdgeConfig(key_serde, value_serde)

    def set_key_width(self, width: int
                      ) -> "MeshOrderedPartitionedKVEdgeConfig":
        self.conf["tez.runtime.tpu.key.width.bytes"] = width
        return self

    def set_value_width(self, width: int
                        ) -> "MeshOrderedPartitionedKVEdgeConfig":
        self.conf["tez.runtime.tpu.mesh.value.width.bytes"] = width
        return self
