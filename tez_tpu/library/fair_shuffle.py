"""Fair shuffle: skew-splitting vertex + edge managers.

Reference parity: tez-runtime-library FairShuffleVertexManager.java (637 LoC)
+ FairShuffleEdgeManager + FairShufflePayloads.proto — oversized partitions
are SPLIT across several consumer tasks by source-task range, small
partitions are merged; each destination task covers
(partition, [src_lo, src_hi)).

This is the "one logical shuffle bigger than one task" machinery
(SURVEY.md §5.7) — the context-parallel splitting analog.
"""
from __future__ import annotations

import logging
import math
from typing import Any, Dict, List, Optional, Sequence, Tuple

from tez_tpu.api.edge_manager import (CompositeEventRouteMetadata,
                                      EdgeManagerPluginOnDemand,
                                      EventRouteMetadata)
from tez_tpu.api.events import VertexManagerEvent
from tez_tpu.common.payload import EdgeManagerPluginDescriptor
from tez_tpu.dag.edge_property import DataMovementType, EdgeProperty
from tez_tpu.library.vertex_managers import ShuffleVertexManager

log = logging.getLogger(__name__)

#: mapping entry: (partition, src_lo, src_hi)
DestMapping = Tuple[int, int, int]


class FairShuffleEdgeManager(EdgeManagerPluginOnDemand):
    """Routes (partition, source-range) slices to destination tasks.
    Payload: {"mappings": [(partition, src_lo, src_hi), ...]} indexed by
    destination task."""

    def initialize(self) -> None:
        payload = self.context.user_payload.load() or {}
        self.mappings: List[DestMapping] = [tuple(m) for m in
                                            payload["mappings"]]
        self.num_source_partitions = payload["num_source_partitions"]

    def _mapping(self, dest_task: int) -> DestMapping:
        return self.mappings[dest_task]

    def get_num_destination_task_physical_inputs(self, dest_task: int) -> int:
        _, lo, hi = self._mapping(dest_task)
        return hi - lo

    def get_num_source_task_physical_outputs(self, src_task: int) -> int:
        return self.num_source_partitions

    def get_num_destination_consumer_tasks(self, src_task: int) -> int:
        return sum(1 for (_, lo, hi) in self.mappings if lo <= src_task < hi)

    def route_data_movement_event_to_destination(
            self, src_task: int, src_output_index: int, dest_task: int
    ) -> Optional[EventRouteMetadata]:
        part, lo, hi = self._mapping(dest_task)
        if src_output_index != part or not (lo <= src_task < hi):
            return None
        return EventRouteMetadata(1, (src_task - lo,), (src_output_index,))

    def route_composite_data_movement_event_to_destination(
            self, src_task: int, dest_task: int
    ) -> Optional[CompositeEventRouteMetadata]:
        part, lo, hi = self._mapping(dest_task)
        if not (lo <= src_task < hi):
            return None
        return CompositeEventRouteMetadata(1, src_task - lo, part)

    def route_input_source_task_failed_event_to_destination(
            self, src_task: int, dest_task: int) -> Optional[EventRouteMetadata]:
        part, lo, hi = self._mapping(dest_task)
        if not (lo <= src_task < hi):
            return None
        return EventRouteMetadata(1, (src_task - lo,))

    def route_input_error_event_to_source(self, dest_task: int,
                                          dest_failed_input_index: int) -> int:
        _, lo, _ = self._mapping(dest_task)
        return lo + dest_failed_input_index


def compute_fair_mappings(partition_totals: Sequence[int], num_sources: int,
                          desired_task_input_size: int,
                          max_tasks: int) -> List[DestMapping]:
    """Split oversized partitions by source range, keep small ones whole
    (reference: FairShuffleVertexManager routing computation).  When a task
    cap is set, the per-task size target is grown until the slice count
    fits — slices are COARSENED, never dropped (every (partition, source)
    pair must keep exactly one destination)."""
    size = max(1, desired_task_input_size)
    while True:
        mappings: List[DestMapping] = []
        for p, total in enumerate(partition_totals):
            pieces = max(1, int(math.ceil(total / size)))
            pieces = min(pieces, num_sources)  # can't split finer than sources
            if pieces == 1:
                mappings.append((p, 0, num_sources))
                continue
            base = num_sources // pieces
            extra = num_sources % pieces
            lo = 0
            for i in range(pieces):
                hi = lo + base + (1 if i < extra else 0)
                mappings.append((p, lo, hi))
                lo = hi
        if max_tasks <= 0 or len(mappings) <= max_tasks or \
                len(mappings) <= len(partition_totals):
            return mappings
        size *= 2
        log.info("fair shuffle: %d slices over cap %d, growing target to %d",
                 len(mappings), max_tasks, size)


class FairShuffleVertexManager(ShuffleVertexManager):
    """ShuffleVertexManager whose parallelism decision both merges small
    partitions AND splits skewed ones by source range."""

    def initialize(self) -> None:
        super().initialize()
        payload = self.context.user_payload.load() or {}
        if not isinstance(payload, dict):
            payload = {}
        self.max_task_parallelism = payload.get("max_task_parallelism", 0)
        # the whole point of this manager is runtime routing — it always
        # decides parallelism itself, auto_parallel flag or not
        self._parallelism_determined = False
        # keyed by (producer vertex, task): dedupes pipelined spills and
        # speculative duplicate attempts (last vector wins)
        self._partition_stats: Dict[Tuple[str, int], Sequence[int]] = {}

    def _sg_source_names(self) -> List[str]:
        return [name for name, p in
                self.context.get_input_vertex_edge_properties().items()
                if p.data_movement_type is DataMovementType.SCATTER_GATHER]

    def on_vertex_manager_event_received(self, event: VertexManagerEvent) -> None:
        payload = event.user_payload
        att = event.producer_attempt
        if isinstance(payload, dict) and "partition_sizes" in payload and \
                att is not None:
            vec = payload["partition_sizes"]
            declared = self.context.get_vertex_num_tasks(
                self.context.vertex_name)
            # only scatter-gather stats match the partition space; e.g. a
            # broadcast side-input reports a 1-element vector — ignore it
            if len(vec) == declared:
                key = (str(getattr(att, "vertex_id", att)),
                       att.task_id.id if hasattr(att, "task_id") else 0)
                self._partition_stats[key] = vec
        super().on_vertex_manager_event_received(event)

    def _try_determine_parallelism(self) -> bool:
        if self._parallelism_determined:
            return True
        sg_sources = self._sg_source_names()
        if len(sg_sources) != 1:
            # source-range splitting needs ONE scatter-gather source; with
            # several, ranges are ambiguous per edge — fall back to plain
            # shuffle behavior (round-1 limitation; reference supports
            # per-edge range payloads)
            if len(sg_sources) > 1:
                log.warning("%s: fair shuffle with %d SG sources -> "
                            "no splitting", self.context.vertex_name,
                            len(sg_sources))
            self._parallelism_determined = True
            return True
        num_sources = self.context.get_vertex_num_tasks(sg_sources[0])
        if num_sources <= 0:
            return False
        fraction = len(self._completed_sources) / num_sources
        if not self._partition_stats:
            if fraction >= 1.0:
                self._parallelism_determined = True
                return True
            return False
        if fraction < self.min_fraction:
            return False
        # project observed per-partition sizes to the full source count
        observed = len(self._partition_stats)
        vectors = list(self._partition_stats.values())
        num_partitions = len(vectors[0])
        totals = [0] * num_partitions
        for vec in vectors:
            for p, sz in enumerate(vec):
                totals[p] += sz
        scale = num_sources / observed
        totals = [int(t * scale) for t in totals]

        mappings = compute_fair_mappings(
            totals, num_sources, self.desired_task_input_size,
            self.max_task_parallelism)
        current = self.context.get_vertex_num_tasks(self.context.vertex_name)
        if mappings and len(mappings) != current:
            prop = self.context.get_input_vertex_edge_properties()[
                sg_sources[0]]
            desc = EdgeManagerPluginDescriptor.create(
                "tez_tpu.library.fair_shuffle:FairShuffleEdgeManager",
                payload={"mappings": mappings,
                         "num_source_partitions": current})
            new_props = {sg_sources[0]: EdgeProperty.create_custom(
                desc, prop.data_source_type, prop.edge_source,
                prop.edge_destination, prop.scheduling_type)}
            log.info("%s: fair shuffle %d partitions -> %d slices",
                     self.context.vertex_name, num_partitions, len(mappings))
            self.context.reconfigure_vertex(len(mappings),
                                            source_edge_properties=new_props)
            self.context.done_reconfiguring_vertex()
        self._parallelism_determined = True
        return True
