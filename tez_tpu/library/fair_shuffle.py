"""Fair shuffle: skew-splitting vertex + edge managers.

Reference parity: tez-runtime-library FairShuffleVertexManager.java (637 LoC)
+ FairShuffleEdgeManager + FairShufflePayloads.proto — oversized partitions
are SPLIT across several consumer tasks by source-task range, small
partitions are merged; each destination task covers
(partition, [src_lo, src_hi)).

This is the "one logical shuffle bigger than one task" machinery
(SURVEY.md §5.7) — the context-parallel splitting analog.
"""
from __future__ import annotations

import logging
import math
from typing import Any, Dict, List, Optional, Sequence, Tuple

from tez_tpu.api.edge_manager import (CompositeEventRouteMetadata,
                                      EdgeManagerPluginOnDemand,
                                      EventRouteMetadata)
from tez_tpu.api.events import VertexManagerEvent
from tez_tpu.common.payload import EdgeManagerPluginDescriptor
from tez_tpu.dag.edge_property import DataMovementType, EdgeProperty
from tez_tpu.library.vertex_managers import ShuffleVertexManager

log = logging.getLogger(__name__)

#: mapping entry: (partition, src_lo, src_hi)
DestMapping = Tuple[int, int, int]


class FairShuffleEdgeManager(EdgeManagerPluginOnDemand):
    """Routes (partition, source-range) slices to destination tasks.
    Payload: {"mappings": [(partition, src_lo, src_hi), ...]} indexed by
    destination task."""

    def initialize(self) -> None:
        payload = self.context.user_payload.load() or {}
        self.mappings: List[DestMapping] = [tuple(m) for m in
                                            payload["mappings"]]
        self.num_source_partitions = payload["num_source_partitions"]

    def _mapping(self, dest_task: int) -> DestMapping:
        return self.mappings[dest_task]

    def get_num_destination_task_physical_inputs(self, dest_task: int) -> int:
        _, lo, hi = self._mapping(dest_task)
        return hi - lo

    def get_num_source_task_physical_outputs(self, src_task: int) -> int:
        return self.num_source_partitions

    def get_num_destination_consumer_tasks(self, src_task: int) -> int:
        return sum(1 for (_, lo, hi) in self.mappings if lo <= src_task < hi)

    def route_data_movement_event_to_destination(
            self, src_task: int, src_output_index: int, dest_task: int
    ) -> Optional[EventRouteMetadata]:
        part, lo, hi = self._mapping(dest_task)
        if src_output_index != part or not (lo <= src_task < hi):
            return None
        return EventRouteMetadata(1, (src_task - lo,), (src_output_index,))

    def route_composite_data_movement_event_to_destination(
            self, src_task: int, dest_task: int
    ) -> Optional[CompositeEventRouteMetadata]:
        part, lo, hi = self._mapping(dest_task)
        if not (lo <= src_task < hi):
            return None
        return CompositeEventRouteMetadata(1, src_task - lo, part)

    def route_input_source_task_failed_event_to_destination(
            self, src_task: int, dest_task: int) -> Optional[EventRouteMetadata]:
        part, lo, hi = self._mapping(dest_task)
        if not (lo <= src_task < hi):
            return None
        return EventRouteMetadata(1, (src_task - lo,))

    def route_input_error_event_to_source(self, dest_task: int,
                                          dest_failed_input_index: int) -> int:
        _, lo, _ = self._mapping(dest_task)
        return lo + dest_failed_input_index


#: abstract slice: (partition, piece index, total pieces of that partition)
FairSlice = Tuple[int, int, int]


def compute_fair_slices(partition_totals: Sequence[float],
                        desired_task_input_size: int, max_tasks: int,
                        max_split: int) -> List[FairSlice]:
    """Split oversized partitions into abstract pieces, keep small ones whole
    (reference: FairShuffleVertexManager routing computation).  Pieces are
    edge-independent — each edge later maps piece i/n onto its OWN source
    range, which is how several scatter-gather sources share one slicing.
    When a task cap is set, the per-task size target is grown until the
    slice count fits — slices are COARSENED, never dropped (every
    (partition, source) pair must keep exactly one destination)."""
    size = max(1, desired_task_input_size)
    while True:
        slices: List[FairSlice] = []
        for p, total in enumerate(partition_totals):
            pieces = max(1, int(math.ceil(total / size)))
            pieces = min(pieces, max(1, max_split))
            for i in range(pieces):
                slices.append((p, i, pieces))
        if max_tasks <= 0 or len(slices) <= max_tasks or \
                len(slices) <= len(partition_totals):
            return slices
        size *= 2
        log.info("fair shuffle: %d slices over cap %d, growing target to %d",
                 len(slices), max_tasks, size)


def source_range(piece: int, pieces: int, num_sources: int) -> Tuple[int, int]:
    """Piece i of n over this edge's source tasks.  Ranges tile
    [0, num_sources) exactly; an edge with fewer sources than pieces yields
    empty ranges for some pieces (that slice just reads nothing here)."""
    return (piece * num_sources // pieces,
            (piece + 1) * num_sources // pieces)


def compute_fair_mappings(partition_totals: Sequence[int], num_sources: int,
                          desired_task_input_size: int,
                          max_tasks: int) -> List[DestMapping]:
    """Single-edge convenience: slices resolved to concrete source ranges."""
    slices = compute_fair_slices(partition_totals, desired_task_input_size,
                                 max_tasks, num_sources)
    return [(p, *source_range(i, pieces, num_sources))
            for p, i, pieces in slices]


class FairShuffleVertexManager(ShuffleVertexManager):
    """ShuffleVertexManager whose parallelism decision both merges small
    partitions AND splits skewed ones by source range."""

    def initialize(self) -> None:
        super().initialize()
        payload = self.context.user_payload.load() or {}
        if not isinstance(payload, dict):
            payload = {}
        self.max_task_parallelism = payload.get("max_task_parallelism", 0)
        # the whole point of this manager is runtime routing — it always
        # decides parallelism itself, auto_parallel flag or not
        self._parallelism_determined = False
        # keyed by (producer vertex, task): dedupes pipelined spills and
        # speculative duplicate attempts (last vector wins)
        self._partition_stats: Dict[Tuple[str, int], Sequence[int]] = {}

    def _sg_source_names(self) -> List[str]:
        return [name for name, p in
                self.context.get_input_vertex_edge_properties().items()
                if p.data_movement_type is DataMovementType.SCATTER_GATHER]

    def on_vertex_manager_event_received(self, event: VertexManagerEvent) -> None:
        payload = event.user_payload
        att = event.producer_attempt
        if isinstance(payload, dict) and "partition_sizes" in payload and \
                att is not None:
            vec = payload["partition_sizes"]
            declared = self.context.get_vertex_num_tasks(
                self.context.vertex_name)
            # only scatter-gather stats match the partition space; e.g. a
            # broadcast side-input reports a 1-element vector — ignore it
            if len(vec) == declared:
                vname = event.producer_vertex_name or \
                    str(getattr(att, "vertex_id", att))
                key = (vname,
                       att.task_id.id if hasattr(att, "task_id") else 0)
                self._partition_stats[key] = vec
        super().on_vertex_manager_event_received(event)

    def _try_determine_parallelism(self) -> bool:
        if self._parallelism_determined:
            return True
        if self.context.vertex_reconfiguration_restored():
            # recovery already re-applied the journaled slicing; a fresh
            # decision could slice differently and orphan restored tasks
            self._parallelism_determined = True
            return True
        sg_sources = self._sg_source_names()
        if not sg_sources:
            self._parallelism_determined = True
            return True
        num_by_src = {s: self.context.get_vertex_num_tasks(s)
                      for s in sg_sources}
        if any(n <= 0 for n in num_by_src.values()):
            return False
        total_sources = sum(num_by_src.values())
        fraction = self._completed_fraction(sg_sources, total_sources)
        if not self._partition_stats:
            if fraction >= 1.0:
                self._parallelism_determined = True
                return True
            return False
        if fraction < self.min_fraction:
            return False
        # Project observed per-partition sizes to full scale, per source
        # vertex where attributable (stats missing a vertex name fall back to
        # a global projection).  An SG source with NO reports yet still
        # contributes: it's projected at the observed per-task average —
        # counting it as zero would hide its skew permanently.
        grouped: Dict[str, List[Sequence[int]]] = {}
        for (vname, _task), vec in self._partition_stats.items():
            grouped.setdefault(vname, []).append(vec)
        num_partitions = len(next(iter(self._partition_stats.values())))
        totals = [0.0] * num_partitions
        reported = len(self._partition_stats)
        avg = [0.0] * num_partitions
        for vec in self._partition_stats.values():
            for p, sz in enumerate(vec):
                avg[p] += sz / reported
        if all(v in num_by_src for v in grouped):
            for vname, vecs in grouped.items():
                scale = num_by_src[vname] / len(vecs)
                for vec in vecs:
                    for p, sz in enumerate(vec):
                        totals[p] += sz * scale
            for vname, n in num_by_src.items():
                if vname not in grouped:
                    for p in range(num_partitions):
                        totals[p] += avg[p] * n
        else:
            for p in range(num_partitions):
                totals[p] = avg[p] * total_sources

        # One edge-independent slicing; each edge resolves pieces onto its
        # own source range (reference: per-edge FairShufflePayloads ranges).
        slices = compute_fair_slices(
            totals, self.desired_task_input_size, self.max_task_parallelism,
            max_split=max(num_by_src.values()))
        current = self.context.get_vertex_num_tasks(self.context.vertex_name)
        if slices and len(slices) != current:
            props = self.context.get_input_vertex_edge_properties()
            new_props = {}
            for name in sg_sources:
                prop = props[name]
                mappings = [(p, *source_range(i, pieces, num_by_src[name]))
                            for p, i, pieces in slices]
                desc = EdgeManagerPluginDescriptor.create(
                    "tez_tpu.library.fair_shuffle:FairShuffleEdgeManager",
                    payload={"mappings": mappings,
                             "num_source_partitions": current})
                new_props[name] = EdgeProperty.create_custom(
                    desc, prop.data_source_type, prop.edge_source,
                    prop.edge_destination, prop.scheduling_type)
            log.info("%s: fair shuffle %d partitions -> %d slices over %d "
                     "source edges", self.context.vertex_name, num_partitions,
                     len(slices), len(sg_sources))
            self.context.reconfigure_vertex(len(slices),
                                            source_edge_properties=new_props)
            self.context.done_reconfiguring_vertex()
        self._parallelism_determined = True
        return True
