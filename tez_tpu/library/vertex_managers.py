"""Stock vertex managers.

Reference parity:
- ImmediateStartVertexManager (tez-runtime-library/.../vertexmanager/)
- RootInputVertexManager (tez-dag/.../dag/impl/RootInputVertexManager.java:
  slow-start for root-input vertices; simplified to immediate here)
- InputReadyVertexManager (ONE_TO_ONE/location affinity scheduling)
- ShuffleVertexManager{Base} (slow-start by source completion fraction +
  auto-parallelism, ShuffleVertexManagerBase.java:271,320; auto-parallelism
  shrink in tez_tpu.library.shuffle_vm_payloads)
"""
from __future__ import annotations

import logging
import math
from typing import Any, Dict, List, Optional, Sequence, Set

from tez_tpu.api.events import VertexManagerEvent
from tez_tpu.api.vertex_manager import (ScheduleTaskRequest,
                                        TaskAttemptIdentifier,
                                        VertexManagerPlugin,
                                        VertexManagerPluginContext,
                                        VertexStateUpdate)
from tez_tpu.dag.edge_property import DataMovementType

log = logging.getLogger(__name__)


class ImmediateStartVertexManager(VertexManagerPlugin):
    """Schedule every task as soon as the vertex starts."""

    def initialize(self) -> None:
        pass

    def on_vertex_started(self, completions: Sequence[TaskAttemptIdentifier]) -> None:
        n = self.context.get_vertex_num_tasks(self.context.vertex_name)
        self.context.schedule_tasks(
            [ScheduleTaskRequest(i) for i in range(n)])

    def on_source_task_completed(self, attempt: TaskAttemptIdentifier) -> None:
        pass

    def on_vertex_manager_event_received(self, event: VertexManagerEvent) -> None:
        pass

    def on_root_vertex_initialized(self, input_name: str, descriptor: Any,
                                   events: List[Any]) -> None:
        pass


class RootInputVertexManager(ImmediateStartVertexManager):
    """Vertices fed by root inputs; parallelism is already settled by the
    initializer when the vertex starts, so scheduling is immediate.  The
    reference adds optional slow-start against *source vertices* which root
    vertices don't have."""


class InputReadyVertexManager(VertexManagerPlugin):
    """Schedule task i when every ONE_TO_ONE source task i has completed and
    broadcast sources are fully done (reference: InputReadyVertexManager —
    also provides affinity; locality is a no-op in-process)."""

    def initialize(self) -> None:
        self._scheduled: Set[int] = set()
        self._one_to_one_done: Dict[str, Set[int]] = {}
        self._broadcast_done: Dict[str, Set[int]] = {}
        self._started = False

    def _edge_sources(self) -> Dict[str, Any]:
        return self.context.get_input_vertex_edge_properties()

    def on_vertex_started(self, completions: Sequence[TaskAttemptIdentifier]) -> None:
        self._started = True
        for c in completions:
            self._record(c)
        self._maybe_schedule()

    def on_source_task_completed(self, attempt: TaskAttemptIdentifier) -> None:
        self._record(attempt)
        if self._started:
            self._maybe_schedule()

    def _record(self, attempt: TaskAttemptIdentifier) -> None:
        props = self._edge_sources()
        prop = props.get(attempt.vertex_name)
        if prop is None:
            return
        if prop.data_movement_type is DataMovementType.ONE_TO_ONE:
            self._one_to_one_done.setdefault(
                attempt.vertex_name, set()).add(attempt.task_index)
        else:
            self._broadcast_done.setdefault(
                attempt.vertex_name, set()).add(attempt.task_index)

    def _maybe_schedule(self) -> None:
        props = self._edge_sources()
        num = self.context.get_vertex_num_tasks(self.context.vertex_name)
        def _source_done(name: str) -> bool:
            target = self.context.get_vertex_num_tasks(name)
            # parallelism undetermined (-1) means the source hasn't even
            # initialized — definitely not ready
            return target >= 0 and \
                len(self._broadcast_done.get(name, ())) >= target

        bcast_ready = all(
            _source_done(name) for name, p in props.items()
            if p.data_movement_type is not DataMovementType.ONE_TO_ONE)
        if not bcast_ready:
            return
        o2o = [name for name, p in props.items()
               if p.data_movement_type is DataMovementType.ONE_TO_ONE]
        ready = []
        for i in range(num):
            if i in self._scheduled:
                continue
            if all(i in self._one_to_one_done.get(name, ()) for name in o2o):
                ready.append(i)
        if ready:
            self._scheduled.update(ready)
            self.context.schedule_tasks(
                [ScheduleTaskRequest(i) for i in ready])

    def on_vertex_manager_event_received(self, event: VertexManagerEvent) -> None:
        pass

    def on_root_vertex_initialized(self, input_name: str, descriptor: Any,
                                   events: List[Any]) -> None:
        pass


class ShuffleVertexManager(VertexManagerPlugin):
    """Slow-start + (phase 5) auto-parallelism for scatter-gather consumers.

    Reference: ShuffleVertexManagerBase.java:271 — tasks are released as the
    fraction of completed source tasks crosses [min, max]; between the two
    fractions tasks are released proportionally.  Config via payload dict:
    {"min_fraction": 0.25, "max_fraction": 0.75, "auto_parallel": False,
     "desired_task_input_size": 100MiB, "min_task_parallelism": 1}.
    """

    DEFAULT_MIN_FRACTION = 0.25
    DEFAULT_MAX_FRACTION = 0.75

    @classmethod
    def create_descriptor(cls, conf: Any = None, **overrides: Any):
        """Config-builder parity (ShuffleVertexManager.createConfigBuilder):
        translate the registered tez.shuffle-vertex-manager.* conf keys into
        the plugin payload; keyword overrides win."""
        from tez_tpu.common import config as C
        from tez_tpu.common.payload import VertexManagerPluginDescriptor
        conf = conf or {}
        # single source of defaults: the registered ConfKeys
        payload = {
            "min_fraction": conf.get(C.SHUFFLE_VM_MIN_SRC_FRACTION.name,
                                     C.SHUFFLE_VM_MIN_SRC_FRACTION.default),
            "max_fraction": conf.get(C.SHUFFLE_VM_MAX_SRC_FRACTION.name,
                                     C.SHUFFLE_VM_MAX_SRC_FRACTION.default),
            "auto_parallel": conf.get(C.SHUFFLE_VM_AUTO_PARALLEL.name,
                                      C.SHUFFLE_VM_AUTO_PARALLEL.default),
            "desired_task_input_size": conf.get(
                C.SHUFFLE_VM_DESIRED_TASK_INPUT_SIZE.name,
                C.SHUFFLE_VM_DESIRED_TASK_INPUT_SIZE.default),
            "min_task_parallelism": conf.get(
                C.SHUFFLE_VM_MIN_TASK_PARALLELISM.name,
                C.SHUFFLE_VM_MIN_TASK_PARALLELISM.default),
        }
        payload.update(overrides)
        return VertexManagerPluginDescriptor.create(
            f"{cls.__module__}:{cls.__name__}", payload=payload)

    def initialize(self) -> None:
        payload = self.context.user_payload.load() or {}
        if not isinstance(payload, dict):
            payload = {}
        self.min_fraction = payload.get("min_fraction", self.DEFAULT_MIN_FRACTION)
        self.max_fraction = payload.get("max_fraction", self.DEFAULT_MAX_FRACTION)
        # push-based shuffle ingest mode: with eager push enabled, the
        # consumer should sit INGESTING pushed spills while the map wave
        # runs — release every task once the start fraction of sources has
        # finished (min==max makes _maybe_schedule release all) instead of
        # riding the slow-start ramp.  An explicit payload fraction wins:
        # someone who configured slow-start asked for it.  getattr: custom
        # duck-typed contexts written before get_vertex_conf existed keep
        # working (they simply never see ingest mode).
        conf = getattr(self.context, "get_vertex_conf", dict)() or {}
        push_on = conf.get("tez.runtime.shuffle.push.enabled", False) \
            if hasattr(conf, "get") else False
        if isinstance(push_on, str):
            push_on = push_on.lower() in ("1", "true", "yes")
        if push_on and "min_fraction" not in payload and \
                "max_fraction" not in payload:
            start = float(conf.get(
                "tez.runtime.shuffle.push.start-fraction", 0.05) or 0.05)
            self.min_fraction = self.max_fraction = start
        self.auto_parallel = payload.get("auto_parallel", False)
        self.desired_task_input_size = payload.get(
            "desired_task_input_size", 100 * 1024 * 1024)
        self.min_task_parallelism = payload.get("min_task_parallelism", 1)
        self._started = False
        self._scheduled: Set[int] = set()
        self._completed_sources: Set[tuple] = set()
        self._pending_completions: List[TaskAttemptIdentifier] = []
        self._output_stats: Dict[tuple, int] = {}   # (vertex, task) -> bytes
        self._parallelism_determined = not self.auto_parallel
        # A source whose parallelism is still unresolved (num_tasks == -1,
        # e.g. an InputInitializer racing this vertex's init) must hold
        # scheduling: releasing consumers against an unconfigured source
        # snapshots physical_input_count=-1 into their specs and they
        # complete empty.  Register for CONFIGURED so scheduling re-fires
        # the moment the source resolves (reference:
        # ShuffleVertexManagerBase registers for every source vertex's
        # state updates and counts numSourceTasksConfigured).  getattr:
        # duck-typed test contexts without the registry keep working.
        reg = getattr(self.context, "register_for_vertex_state_updates", None)
        if reg is not None:
            for name in self._shuffle_source_names():
                reg(name, ["CONFIGURED"])

    # -- source bookkeeping --------------------------------------------------
    def _shuffle_source_names(self) -> List[str]:
        return [name for name, prop in
                self.context.get_input_vertex_edge_properties().items()
                if prop.data_movement_type in (DataMovementType.SCATTER_GATHER,
                                               DataMovementType.CUSTOM)]

    def _total_source_tasks(self) -> int:
        """Total shuffle-source tasks, or -1 while ANY source is still
        unconfigured.  -1 must not be clamped into the sum: 'unknown' and
        'empty' are different answers — an unknown total makes the
        completed fraction meaningless (0/0 reads as 1.0 and releases the
        whole consumer against a source that hasn't resolved yet)."""
        total = 0
        for name in self._shuffle_source_names():
            n = self.context.get_vertex_num_tasks(name)
            if n < 0:
                return -1
            total += n
        return total

    def _completed_fraction(self, source_names: Sequence[str],
                            total_sources: int) -> float:
        """Completions from the given sources only — a BROADCAST side-input
        finishing first must not inflate the shuffle completion fraction
        (reference: ShuffleVertexManagerBase tracks per-srcVertex stats)."""
        names = set(source_names)
        done = sum(1 for (vname, _t) in self._completed_sources
                   if vname in names)
        return done / total_sources if total_sources else 1.0

    def on_vertex_started(self, completions: Sequence[TaskAttemptIdentifier]) -> None:
        self._started = True
        for c in completions:
            self._completed_sources.add((c.vertex_name, c.task_index))
        self._maybe_schedule()

    def on_source_task_completed(self, attempt: TaskAttemptIdentifier) -> None:
        self._completed_sources.add((attempt.vertex_name, attempt.task_index))
        if self._started:
            self._maybe_schedule()

    def on_vertex_manager_event_received(self, event: VertexManagerEvent) -> None:
        """Collect per-task output sizes (feeds auto-parallelism; reference:
        ShuffleVertexManagerBase.onVertexManagerEventReceived + compute of
        expected total output)."""
        payload = event.user_payload
        if isinstance(payload, dict) and "output_size" in payload and \
                event.producer_attempt is not None:
            att = event.producer_attempt
            vname = event.producer_vertex_name or \
                (str(att.vertex_id) if hasattr(att, "vertex_id") else str(att))
            key = (vname, att.task_id.id) \
                if hasattr(att, "task_id") else (vname, 0)
            self._output_stats[key] = payload["output_size"]
        if self._started:
            self._maybe_schedule()

    def _shuffle_output_stats(self) -> Dict[tuple, int]:
        """Stats from shuffle (SG/CUSTOM) sources only — a BROADCAST
        side-input's tiny output reports must not drag the per-task average
        down and over-shrink the consumer.  Falls back to all stats only
        when NO key is attributable to any input edge (older event path
        keyed by vertex id); 'only broadcast reported' yields {} so the
        no-shrink finalization path runs instead."""
        shuffle_names = set(self._shuffle_source_names())
        all_edges = set(self.context.get_input_vertex_edge_properties())
        attributable = any(k[0] in all_edges for k in self._output_stats)
        if not attributable:
            return dict(self._output_stats)
        return {k: v for k, v in self._output_stats.items()
                if k[0] in shuffle_names}

    def on_root_vertex_initialized(self, input_name: str, descriptor: Any,
                                   events: List[Any]) -> None:
        pass

    def on_vertex_state_updated(self, update) -> None:
        """A source vertex just resolved its parallelism (CONFIGURED):
        anything held back by the unconfigured-source gate can go now."""
        if update.state == "CONFIGURED" and self._started:
            self._maybe_schedule()

    # -- auto-parallelism (reference: ShuffleVertexManagerBase.computeRouting
    # :444 — shrink to ceil(totalSize/desiredTaskInputDataSize) and swap the
    # edge managers for range routing) ---------------------------------------
    def _try_determine_parallelism(self) -> bool:
        if self._parallelism_determined:
            return True
        if self.context.vertex_reconfiguration_restored():
            # a recovering AM already re-applied the journaled decision;
            # re-deciding here could shrink differently and orphan the
            # restored tasks (reference: recovered VertexConfigurationDone)
            self._parallelism_determined = True
            return True
        total_sources = self._total_source_tasks()
        if total_sources < 0:
            return False        # a source is unconfigured — wait for it
        if total_sources == 0:
            self._parallelism_determined = True
            return True
        fraction = self._completed_fraction(self._shuffle_source_names(),
                                            total_sources)
        stats = self._shuffle_output_stats()
        if not stats:
            if fraction >= 1.0:
                # every source finished without reporting stats (e.g. all
                # outputs empty): finalize with no shrink rather than
                # deadlocking the consumer (reference finalizes parallelism
                # unconditionally once sources complete)
                self._parallelism_determined = True
                return True
            return False
        if fraction < self.min_fraction:
            return False
        expected_total = (sum(stats.values()) / len(stats)) * total_sources
        current = self.context.get_vertex_num_tasks(self.context.vertex_name)
        desired = int(math.ceil(expected_total /
                                max(1, self.desired_task_input_size)))
        desired = max(self.min_task_parallelism, min(desired, current))
        if desired < current:
            from tez_tpu.common.payload import EdgeManagerPluginDescriptor
            from tez_tpu.dag.edge_property import (DataMovementType,
                                                   EdgeProperty)
            base_range = int(math.ceil(current / desired))
            # recompute so no trailing task gets an empty partition range
            # (e.g. 10 partitions, desired 6 -> base 2 -> 5 real tasks)
            desired = int(math.ceil(current / base_range))
            new_props = {}
            for name, prop in \
                    self.context.get_input_vertex_edge_properties().items():
                if prop.data_movement_type is not \
                        DataMovementType.SCATTER_GATHER:
                    continue
                desc = EdgeManagerPluginDescriptor.create(
                    "tez_tpu.library.range_edge_manager:"
                    "RangeScatterGatherEdgeManager",
                    payload={"num_source_partitions": current,
                             "base_range": base_range})
                new_props[name] = EdgeProperty.create_custom(
                    desc, prop.data_source_type, prop.edge_source,
                    prop.edge_destination, prop.scheduling_type)
            log.info("%s: auto-parallelism %d -> %d (expected %.0f bytes)",
                     self.context.vertex_name, current, desired,
                     expected_total)
            self.context.reconfigure_vertex(desired,
                                            source_edge_properties=new_props)
            self.context.done_reconfiguring_vertex()
        self._parallelism_determined = True
        return True

    # -- scheduling ----------------------------------------------------------
    def _maybe_schedule(self) -> None:
        if not self._try_determine_parallelism():
            return
        total_sources = self._total_source_tasks()
        if total_sources < 0:
            return              # a source is unconfigured — wait for it
        num_tasks = self.context.get_vertex_num_tasks(self.context.vertex_name)
        if num_tasks <= 0:
            return
        fraction = self._completed_fraction(self._shuffle_source_names(),
                                            total_sources)
        if fraction < self.min_fraction:
            return
        if self.max_fraction <= self.min_fraction:
            release = num_tasks
        elif fraction >= self.max_fraction:
            release = num_tasks
        else:
            release = int(math.ceil(
                num_tasks * (fraction - self.min_fraction)
                / (self.max_fraction - self.min_fraction)))
        ready = [i for i in range(min(release, num_tasks))
                 if i not in self._scheduled]
        if ready:
            self._scheduled.update(ready)
            self.context.schedule_tasks(
                [ScheduleTaskRequest(i) for i in ready])


class VertexManagerWithConcurrentInput(ImmediateStartVertexManager):
    """Gang-schedules all tasks at vertex start: CONCURRENT edges mean the
    consumer runs alongside its producers rather than after them
    (reference: VertexManagerWithConcurrentInput.java, 248 LoC — the
    concurrent-edge trigger variants collapse to start-time scheduling in
    the runner-pool model)."""
