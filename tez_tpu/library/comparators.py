"""Custom key ordering: comparator-as-normalizer.

Reference parity: tez-runtime-library pluggable raw comparators
(`tez.runtime.key.comparator.class`, common/comparator/ incl. ProxyComparator)
— on TPU an arbitrary compare(a, b) callback cannot vectorize, so the SPI is
the *normalized-key* form the reference's own sorters use internally: a
comparator maps each key to bytes whose natural byte order IS the desired
order (ties broken shorter-first).  Keys with equal normalized forms fall
into one group at the consumer (comparator-equality grouping, like a
case-insensitive RawComparator).

The hash partitioner keeps using the ORIGINAL key bytes — comparators change
order, not placement (reference semantics; override the partitioner too if
comparator-equal keys must colocate).
"""
from __future__ import annotations

from typing import Any, Callable, Optional

_REVERSE_TABLE = bytes(255 - i for i in range(256))


class KeyComparator:
    """SPI: define sort order by normalization (raw-comparator analog)."""

    def normalize(self, key: bytes) -> bytes:
        raise NotImplementedError


class CaseInsensitiveKeyComparator(KeyComparator):
    """ASCII case-insensitive ordering; 'Foo' and 'foo' form one group."""

    def normalize(self, key: bytes) -> bytes:
        return key.lower()


class ReverseByteKeyComparator(KeyComparator):
    """Descending byte order (complemented bytes); among keys where one is a
    prefix of the other the shorter still sorts first."""

    def normalize(self, key: bytes) -> bytes:
        return key.translate(_REVERSE_TABLE)


def load_comparator(ctx_or_get: Any) -> Optional[Callable[[bytes], bytes]]:
    """Resolve tez.runtime.key.comparator.class into a normalize callable
    (None when unset — the zero-cost default path)."""
    from tez_tpu.library.inputs import _conf_get
    name = _conf_get(ctx_or_get, "tez.runtime.key.comparator.class", "")
    if not name:
        return None
    from tez_tpu.common.payload import resolve_class
    return resolve_class(name)().normalize
