"""Edge manager routing a RANGE of source partitions to each consumer task.

Reference parity: the ShuffleVertexManager auto-parallelism edge
reconfiguration (ShuffleEdgeManagerConfigPayloadProto, ShufflePayloads.proto:60
+ ScatterGatherEdgeManager's custom-routing counterpart): after the consumer
vertex shrinks from P tasks to n, new task i reads source partitions
[i*base, min((i+1)*base, P)).

Payload: {"num_source_partitions": P, "base_range": ceil(P/n)}.
"""
from __future__ import annotations

from typing import Optional

from tez_tpu.api.edge_manager import (CompositeEventRouteMetadata,
                                      EdgeManagerPluginOnDemand,
                                      EventRouteMetadata)


class RangeScatterGatherEdgeManager(EdgeManagerPluginOnDemand):
    def initialize(self) -> None:
        payload = self.context.user_payload.load() or {}
        self.num_source_partitions = payload["num_source_partitions"]
        self.base_range = payload["base_range"]

    def _range(self, dest_task: int) -> tuple:
        lo = dest_task * self.base_range
        hi = min(lo + self.base_range, self.num_source_partitions)
        return lo, hi

    def get_num_destination_task_physical_inputs(self, dest_task: int) -> int:
        lo, hi = self._range(dest_task)
        return self.context.source_vertex_num_tasks * (hi - lo)

    def get_num_source_task_physical_outputs(self, src_task: int) -> int:
        return self.num_source_partitions

    def get_num_destination_consumer_tasks(self, src_task: int) -> int:
        return self.context.destination_vertex_num_tasks

    def route_data_movement_event_to_destination(
            self, src_task: int, src_output_index: int, dest_task: int
    ) -> Optional[EventRouteMetadata]:
        lo, hi = self._range(dest_task)
        if not (lo <= src_output_index < hi):
            return None
        # slot layout: src-major, partition-minor
        slot = src_task * (hi - lo) + (src_output_index - lo)
        return EventRouteMetadata(1, (slot,), (src_output_index,))

    def route_composite_data_movement_event_to_destination(
            self, src_task: int, dest_task: int
    ) -> Optional[CompositeEventRouteMetadata]:
        lo, hi = self._range(dest_task)
        if hi <= lo:
            return None
        return CompositeEventRouteMetadata(
            count=hi - lo, target=src_task * (hi - lo), source=lo)

    def route_input_source_task_failed_event_to_destination(
            self, src_task: int, dest_task: int) -> Optional[EventRouteMetadata]:
        lo, hi = self._range(dest_task)
        if hi <= lo:
            return None
        slots = tuple(src_task * (hi - lo) + i for i in range(hi - lo))
        return EventRouteMetadata(len(slots), slots)

    def route_input_error_event_to_source(self, dest_task: int,
                                          dest_failed_input_index: int) -> int:
        lo, hi = self._range(dest_task)
        width = max(1, hi - lo)
        return dest_failed_input_index // width
