"""Stock inputs: the sorted-shuffle consumer side.

Reference parity: tez-runtime-library/.../library/input/
OrderedGroupedKVInput.java:101 (owns Shuffle orchestrator, blocking
waitForInput, KeyValuesReader grouping via ValuesIterator) with the
Shuffle/ShuffleScheduler/MergeManager trio collapsed into a fetch table +
device merge: fetches are local buffer handoffs (or DCN fetches later), the
final merge is the device k-way merge kernel.
"""
from __future__ import annotations

import logging
import threading
from typing import Any, Dict, Iterator, List, Optional, Sequence, Tuple

import numpy as np

from tez_tpu.api.events import (CompositeRoutedDataMovementEvent,
                                DataMovementEvent, InputFailedEvent,
                                InputReadErrorEvent, ShufflePayload,
                                TezAPIEvent)
from tez_tpu.api.runtime import (KeyValueReader, KeyValuesReader,
                                 LogicalInput, MergedLogicalInput, Reader)
from tez_tpu.common.counters import TaskCounter
from tez_tpu.ops.runformat import KVBatch, Run
from tez_tpu.ops.serde import Serde, get_serde
from tez_tpu.ops.sorter import merge_sorted_runs
from tez_tpu.shuffle.service import (ShuffleDataNotFound,
                                     local_shuffle_service)

log = logging.getLogger(__name__)


from tez_tpu.library.util import conf_get as _conf_get  # noqa: E402


class _SlotState:
    """Fetch bookkeeping for one physical input (one source task)."""
    __slots__ = ("batches", "spills_seen", "complete", "version")

    def __init__(self) -> None:
        self.batches: List[KVBatch] = []
        self.spills_seen: set = set()
        self.complete = False
        self.version = -1


class ShuffleFetchTable:
    """Tracks per-source fetch state; thread-safe (events arrive on the
    heartbeat thread, the reader blocks on the processor thread).

    This is the ShuffleScheduler+MergeManager seam: local fetches are
    immediate; a DCN fetcher would enqueue here instead."""

    def __init__(self, context: Any, num_slots: int, my_partition: int):
        self.context = context
        self.num_slots = num_slots
        self.my_partition = my_partition
        self.slots = [_SlotState() for _ in range(num_slots)]
        self.completed = 0
        self.lock = threading.Condition()
        self.service = local_shuffle_service()
        self.failed = False
        self.diagnostics = ""
        meta = context.get_service_provider_metadata("shuffle") or {}
        self.local_host = meta.get("host", "local")
        self.local_port = meta.get("port", 0)
        self._secret = meta.get("secret")

    def _fetch(self, payload: ShufflePayload, partition: int) -> KVBatch:
        """Local short-circuit or DCN socket fetch (Fetcher.java:288 local
        short-circuit vs HTTP fetch split)."""
        if payload.port == 0 or (payload.host, payload.port) == \
                (self.local_host, self.local_port):
            batch = self.service.fetch_partition(
                payload.path_component, payload.spill_id, partition)
            self.context.counters.increment(
                TaskCounter.LOCAL_SHUFFLED_INPUTS)
            return batch
        from tez_tpu.shuffle.server import ShuffleFetcher
        if self._secret is None:
            # config gap on THIS consumer, not producer data loss: must not
            # masquerade as a local fetch failure (which force-reruns the
            # healthy producer)
            raise PermissionError(
                f"no shuffle secret for remote fetch from "
                f"{payload.host}:{payload.port}")
        fetcher = ShuffleFetcher(self._secret)
        batch = fetcher.fetch(payload.host, payload.port,
                              payload.path_component, payload.spill_id,
                              partition)[0]
        self.context.counters.increment(TaskCounter.SHUFFLE_BYTES_DISK_DIRECT,
                                        batch.nbytes)
        return batch

    def on_payload(self, slot: int, partition: int, payload: ShufflePayload,
                   version: int = 0) -> None:
        with self.lock:
            s = self.slots[slot]
            if s.complete or \
                    (payload.spill_id >= 0 and payload.spill_id in s.spills_seen):
                return  # duplicate delivery (e.g. after slot reset race)
            s.version = version
            stamp = s   # identity captured: if on_input_failed resets the
            # slot while the (un-locked) fetch below runs, this stale
            # producer version's batch must not land in the fresh slot
        try:
            if payload.is_empty(partition):
                batch = None
            else:
                batch = self._fetch(payload, partition)
                self.context.counters.increment(
                    TaskCounter.SHUFFLE_BYTES, batch.nbytes)
                self.context.counters.increment(
                    TaskCounter.SHUFFLE_BYTES_TO_MEM, batch.nbytes)
                self.context.counters.increment(TaskCounter.NUM_SHUFFLED_INPUTS)
        except (ShuffleDataNotFound, ConnectionError, PermissionError) as e:
            log.warning("fetch failed for slot %d: %s", slot, e)
            self.context.send_events([InputReadErrorEvent(
                diagnostics=str(e), index=slot, version=version,
                is_local_fetch=isinstance(e, ShuffleDataNotFound))])
            self.context.counters.increment(
                TaskCounter.NUM_FAILED_SHUFFLE_INPUTS)
            return
        with self.lock:
            s = self.slots[slot]
            if s is not stamp or s.version != version:
                return   # slot was reset mid-fetch: drop the stale batch
            if batch is not None:
                s.batches.append(batch)
            if payload.spill_id >= 0:
                s.spills_seen.add(payload.spill_id)
            if payload.last_event:
                if not s.complete:
                    s.complete = True
                    self.completed += 1
            self.lock.notify_all()

    def on_input_failed(self, slot: int, version: int) -> None:
        """Producer re-running: discard and re-wait (reference:
        InputFailedEvent handling in shuffle event handlers)."""
        with self.lock:
            s = self.slots[slot]
            if s.complete:
                self.completed -= 1
            self.slots[slot] = _SlotState()
            self.lock.notify_all()

    def wait_all(self, timeout: Optional[float] = None) -> List[KVBatch]:
        import time
        deadline = None if timeout is None else time.time() + timeout
        with self.lock:
            while True:
                if self.failed:
                    raise RuntimeError(f"shuffle failed: {self.diagnostics}")
                if self.completed >= self.num_slots:
                    out: List[KVBatch] = []
                    for s in self.slots:
                        out.extend(s.batches)
                    return out
                if deadline is not None and time.time() > deadline:
                    raise TimeoutError(
                        f"shuffle incomplete: {self.completed}/{self.num_slots}")
                self.lock.wait(0.2)
                # raises TaskKilledError if the AM killed this attempt (or
                # the heartbeat died) — never block forever
                self.context.notify_progress()


class OrderedGroupedKVInput(LogicalInput):
    """Sorted, grouped input (reduce side)."""

    def initialize(self) -> List[TezAPIEvent]:
        ctx = self.context
        self.key_serde = get_serde(_conf_get(ctx, "tez.runtime.key.class",
                                             "bytes"))
        self.val_serde = get_serde(_conf_get(ctx, "tez.runtime.value.class",
                                             "bytes"))
        self.key_width = int(_conf_get(ctx, "tez.runtime.tpu.key.width.bytes",
                                       16))
        self.table = ShuffleFetchTable(ctx, self.num_physical_inputs,
                                       my_partition=ctx.task_index)
        ctx.request_initial_memory(0, None,
                           component_type="SORTED_MERGED_INPUT")
        self._merged: Optional[KVBatch] = None
        from tez_tpu.library.comparators import load_comparator
        self._key_normalizer = load_comparator(ctx)   # resolved ONCE
        self._group_starts = None                     # cached across readers
        return []

    def handle_events(self, events: Sequence[TezAPIEvent]) -> None:
        for ev in events:
            if isinstance(ev, CompositeRoutedDataMovementEvent):
                # source_index = my partition, target_index_start = slot
                payload = ev.user_payload
                assert isinstance(payload, ShufflePayload), payload
                for i in range(ev.count):
                    # expansion advances BOTH indices (reference:
                    # CompositeRoutedDataMovementEvent.expand)
                    self.table.on_payload(ev.target_index_start + i,
                                          ev.source_index + i, payload,
                                          version=ev.version)
            elif isinstance(ev, DataMovementEvent):
                payload = ev.user_payload
                assert isinstance(payload, ShufflePayload), payload
                self.table.on_payload(ev.target_index, ev.source_index,
                                      payload, version=ev.version)
            elif isinstance(ev, InputFailedEvent):
                self.table.on_input_failed(ev.target_index, ev.version)
            else:
                log.warning("OrderedGroupedKVInput: unexpected event %r", ev)

    def _wait_and_merge(self) -> KVBatch:
        if self._merged is None:
            import time
            t0 = time.time()
            batches = self.table.wait_all()
            self.context.counters.find_counter(TaskCounter.SHUFFLE_PHASE_TIME)\
                .increment(int((time.time() - t0) * 1000))
            t1 = time.time()
            runs = [Run(b, np.array([0, b.num_records], dtype=np.int64))
                    for b in batches if b.num_records > 0]
            if runs:
                engine = _conf_get(self.context, "tez.runtime.sorter.class",
                                   "device")
                factor = int(_conf_get(self.context,
                                       "tez.runtime.io.sort.factor", 64))
                merged = merge_sorted_runs(runs, 1, self.key_width,
                                           counters=self.context.counters,
                                           engine=engine,
                                           merge_factor=factor,
                                           key_normalizer=self._key_normalizer)
                self._merged = merged.batch
            else:
                self._merged = KVBatch.empty()
            self.context.counters.find_counter(TaskCounter.MERGE_PHASE_TIME)\
                .increment(int((time.time() - t1) * 1000))
            self.context.counters.increment(
                TaskCounter.REDUCE_INPUT_RECORDS, self._merged.num_records)
        return self._merged

    def get_reader(self) -> "GroupedKVReader":
        batch = self._wait_and_merge()
        if self._group_starts is None:
            # one normalization pass for group detection, cached so repeat
            # readers are free (the merge normalized pre-sort; deriving its
            # arrays post-refinement isn't worth the plumbing)
            self._group_starts = GroupedKVReader._compute_groups(
                batch, self._key_normalizer)
        return GroupedKVReader(batch, self.key_serde,
                               self.val_serde, self.context,
                               group_starts=self._group_starts)

    def close(self) -> List[TezAPIEvent]:
        self._merged = None
        self._group_starts = None
        return []


class GroupedKVReader(KeyValuesReader):
    """Groups adjacent equal keys (ValuesIterator analog, vectorized group
    boundary detection)."""

    def __init__(self, batch: KVBatch, key_serde: Serde, val_serde: Serde,
                 context: Any, key_normalizer: Any = None,
                 group_starts: Any = None):
        self.batch = batch
        self.key_serde = key_serde
        self.val_serde = val_serde
        self.context = context
        self._group_starts = group_starts if group_starts is not None \
            else self._compute_groups(batch, key_normalizer)

    @staticmethod
    def _compute_groups(batch: KVBatch, key_normalizer: Any = None
                        ) -> np.ndarray:
        n = batch.num_records
        if n == 0:
            return np.zeros(0, dtype=np.int64)
        if key_normalizer is not None:
            # comparator-equality grouping (e.g. case-insensitive): adjacent
            # keys with equal NORMALIZED forms form one group — materialize
            # the normalized keys once, then the same vectorized path
            from tez_tpu.ops.sorter import normalize_batch_keys
            kb, ko = normalize_batch_keys(batch, key_normalizer)
        else:
            kb, ko = batch.key_bytes, batch.key_offsets
        lengths = ko[1:] - ko[:-1]
        same = np.zeros(n, dtype=bool)
        cand = np.flatnonzero(lengths[1:] == lengths[:-1])
        for i in cand:
            same[i + 1] = kb[ko[i]:ko[i + 1]].tobytes() == \
                kb[ko[i + 1]:ko[i + 2]].tobytes()
        return np.flatnonzero(~same).astype(np.int64)

    def __iter__(self) -> Iterator[Tuple[Any, Iterator[Any]]]:
        n = self.batch.num_records
        bounds = np.append(self._group_starts, n)
        groups = 0
        for s, e in zip(bounds[:-1], bounds[1:]):
            key = self.key_serde.from_bytes(self.batch.key(int(s)))
            values = (self.val_serde.from_bytes(self.batch.value(i))
                      for i in range(int(s), int(e)))
            groups += 1
            if (groups & 0x3FF) == 0:
                self.context.notify_progress()
            yield key, values
        self.context.counters.increment(TaskCounter.REDUCE_INPUT_GROUPS,
                                        groups)


class UnorderedKVReaderAdapter(KeyValueReader):
    """Flat (key, value) iteration over a batch (used by unordered inputs and
    tests)."""

    def __init__(self, batch: KVBatch, key_serde: Serde, val_serde: Serde):
        self.batch = batch
        self.key_serde = key_serde
        self.val_serde = val_serde

    def __iter__(self):
        for k, v in self.batch.iter_pairs():
            yield self.key_serde.from_bytes(k), self.val_serde.from_bytes(v)


class ConcatenatedMergedKVInput(MergedLogicalInput):
    """Merged view over a vertex group's constituent inputs: concatenates
    their readers (reference: tez-runtime-library
    ConcatenatedMergedKeyValueInput / the MergedLogicalInput family)."""

    def get_reader(self) -> Reader:
        merged_self = self

        class _Concat(KeyValueReader):
            def __iter__(self):
                for inp in merged_self.inputs:
                    reader = inp.get_reader()
                    # grouped readers yield (k, values); flat ones (k, v)
                    if isinstance(reader, KeyValuesReader):
                        for k, vs in reader:
                            for v in vs:
                                yield k, v
                    else:
                        for k, v in reader:
                            yield k, v

        return _Concat()
