"""Stock inputs: the sorted-shuffle consumer side.

Reference parity: tez-runtime-library/.../library/input/
OrderedGroupedKVInput.java:101 (owns Shuffle orchestrator, blocking
waitForInput, KeyValuesReader grouping via ValuesIterator) with the
Shuffle/ShuffleScheduler/MergeManager trio collapsed into a fetch table +
device merge: fetches are local buffer handoffs (or DCN fetches later), the
final merge is the device k-way merge kernel.
"""
from __future__ import annotations

import logging
import os
import threading
from typing import Any, Dict, Iterator, List, Optional, Sequence, Tuple

import numpy as np

from tez_tpu.api.events import (CompositeRoutedDataMovementEvent,
                                DataMovementEvent, InputFailedEvent,
                                InputReadErrorEvent, ShufflePayload,
                                TezAPIEvent)
from tez_tpu.api.runtime import (KeyValueReader, KeyValuesReader,
                                 LogicalInput, MergedLogicalInput, Reader)
from tez_tpu.common import faults, metrics, tracing
from tez_tpu.common.counters import TaskCounter
from tez_tpu.ops.runformat import KVBatch, adjacent_equal_rows
from tez_tpu.ops.serde import Serde, get_serde
from tez_tpu.shuffle.service import (ShuffleDataNotFound,
                                     local_shuffle_service)

log = logging.getLogger(__name__)


from tez_tpu.library.util import conf_get as _conf_get  # noqa: E402


def _is_checksum_error(e: Exception) -> bool:
    """Run.from_bytes signals payload damage as IOError('checksum mismatch
    in ...' / 'bad run magic in ...') — the only OSErrors that mean the
    bytes (not the transport) are bad."""
    return isinstance(e, IOError) and \
        ("checksum mismatch" in str(e) or "bad run magic" in str(e))


class _SlotState:
    """Fetch bookkeeping for one physical input (one source task)."""
    __slots__ = ("batches", "spills_seen", "complete", "version")

    def __init__(self) -> None:
        self.batches: List[KVBatch] = []
        self.spills_seen: set = set()
        self.complete = False
        self.version = -1


class ShuffleFetchTable:
    """Tracks per-source fetch state; thread-safe (events arrive on the
    heartbeat thread, the reader blocks on the processor thread).

    This is the ShuffleScheduler+MergeManager seam: local fetches are
    immediate; a DCN fetcher would enqueue here instead.  With a merge
    manager attached, fetched batches go through bounded-memory admission
    (MergeManager.reserve semantics) instead of accumulating per-slot."""

    def __init__(self, context: Any, num_slots: int, my_partition: int,
                 merge_manager: Optional[Any] = None):
        if num_slots < 0:
            # a negative slot count means this task's spec was built while
            # its source vertex parallelism was still unresolved; wait_all's
            # `completed >= num_slots` would be instantly true and the task
            # would SUCCEED empty — silent data loss.  Fail loudly instead:
            # the AM retries the attempt against a configured source.
            raise ValueError(
                f"shuffle input for partition {my_partition} constructed "
                f"with unresolved physical input count {num_slots}; source "
                f"vertex parallelism was not configured when this task was "
                f"scheduled")
        self.context = context
        self.num_slots = num_slots
        self.my_partition = my_partition
        self.slots = [_SlotState() for _ in range(num_slots)]
        self.completed = 0
        self.lock = threading.Condition()
        self.service = local_shuffle_service()
        self.failed = False
        self.diagnostics = ""
        self.merge_manager = merge_manager
        meta = context.get_service_provider_metadata("shuffle") or {}
        self.local_host = meta.get("host", "local")
        self.local_port = meta.get("port", 0)
        self._secret = meta.get("secret")
        self._scheduler = None   # created on first remote payload
        self._closing = False
        # counters document a single-writer rule; fetch-pool deliveries come
        # from many threads, so the table serializes ITS counter writes
        self._deliver_lock = threading.Lock()
        # Fetch deliveries arrive on heartbeat/fetcher threads where no span
        # is active, so the task's trace context is captured HERE (the table
        # is built on the processor thread inside the attempt span) and every
        # fetch span parents under it explicitly.
        self._trace = tracing.current_context()

    def _is_local(self, payload: ShufflePayload) -> bool:
        return payload.port == 0 or (payload.host, payload.port) == \
            (self.local_host, self.local_port)

    def _scheduler_for_remote(self):
        """DCN fetches go through the bounded fetcher pool with per-host
        queues, coalescing, penalty box and speculative refetch
        (ShuffleScheduler.java:91,179,295 analog; see shuffle/scheduler.py).
        Created lazily: purely-local shuffles never pay for the threads."""
        if self._scheduler is None:
            from tez_tpu.common.payload import resolve_class
            from tez_tpu.shuffle.scheduler import (FetchScheduler,
                                                   TcpFetchSession)
            from tez_tpu.common import config as C
            ctx = self.context

            def _k(key):   # one source of truth: the registered ConfKey
                return _conf_get(ctx, key.name, key.default)

            session_cls = _k(C.SHUFFLE_FETCHER_CLASS)
            if session_cls:
                factory = resolve_class(session_cls)
            else:
                from tez_tpu.common.tls import (client_context,
                                                resolve_conf)
                ssl_ctx = client_context(resolve_conf(
                    lambda k: _conf_get(ctx, k, None)))
                conn_to = float(_k(C.SHUFFLE_CONNECT_TIMEOUT_MS)) / 1e3
                read_to = float(_k(C.SHUFFLE_READ_TIMEOUT_MS)) / 1e3
                factory = lambda h, p: TcpFetchSession(  # noqa: E731
                    self._secret, h, p, connect_timeout=conn_to,
                    ssl_context=ssl_ctx, read_timeout=read_to,
                    epoch=getattr(ctx, "am_epoch", 0),
                    app_id=getattr(ctx, "app_id", ""))
            self._scheduler = FetchScheduler(
                deliver=self._remote_done,
                session_factory=factory,
                num_fetchers=int(_k(C.SHUFFLE_PARALLEL_COPIES)),
                max_per_fetch=int(_k(C.SHUFFLE_FETCH_MAX_TASK_OUTPUT_AT_ONCE)),
                penalty_base=float(_k(C.SHUFFLE_HOST_PENALTY_BASE_MS)) / 1e3,
                penalty_cap=float(_k(C.SHUFFLE_HOST_PENALTY_CAP_MS)) / 1e3,
                max_attempts=int(_k(C.SHUFFLE_FETCH_ATTEMPTS)),
                stall_timeout=float(
                    _k(C.SHUFFLE_SPECULATIVE_FETCH_WAIT_MS)) / 1e3,
                session_ttl=float(
                    _k(C.SHUFFLE_FETCH_SESSION_TTL_MS)) / 1e3,
                local_probe=self._store_probe
                if self.service.buffer_store() is not None else None)
        return self._scheduler

    def _store_probe(self, path: str, spill: int,
                     partition: int) -> Optional[KVBatch]:
        """Buffer-store short-circuit for the remote pool: a fetch whose
        data this process already holds (store-registered or lineage-
        republished) is served zero-copy instead of over TCP."""
        try:
            batch = self.service.fetch_partition(
                path, spill, partition, counters=self.context.counters,
                app_id=getattr(self.context, "app_id", ""),
                window_id=getattr(self.context, "window_id", 0),
                stream=getattr(self.context, "stream", ""))
        except ShuffleDataNotFound:
            return None
        with self._deliver_lock:
            self.context.counters.find_counter(
                "ShuffleStore", "store.short_circuit").increment(1)
        return batch

    def shutdown(self) -> None:
        self._closing = True
        if self._scheduler is not None:
            self._scheduler.stop()

    def _fetch_local(self, payload: ShufflePayload,
                     partition: int) -> KVBatch:
        """Same-host short-circuit (Fetcher.java:288 local-disk fetch)."""
        import time as _time
        t0 = _time.perf_counter()
        with tracing.span("shuffle.fetch", cat="shuffle",
                          parent=self._trace, mode="local",
                          src=payload.path_component,
                          spill=payload.spill_id, partition=partition):
            faults.fire("shuffle.fetch.read", detail=payload.path_component)
            batch = self.service.fetch_partition(
                payload.path_component, payload.spill_id, partition,
                app_id=getattr(self.context, "app_id", ""),
                window_id=getattr(self.context, "window_id", 0),
                stream=getattr(self.context, "stream", ""))
        metrics.observe("shuffle.fetch.rtt",
                        (_time.perf_counter() - t0) * 1000.0,
                        counters=self.context.counters)
        self.context.counters.increment(TaskCounter.LOCAL_SHUFFLED_INPUTS)
        return batch

    def _fetch_error(self, slot: int, version: int, e: Exception) -> None:
        log.warning("fetch failed for slot %d: %s", slot, e)
        tracing.event("shuffle.fetch.retry_requested", parent=self._trace,
                      slot=slot, version=version,
                      error=f"{type(e).__name__}: {e}")
        from tez_tpu.common import config as C
        if _conf_get(self.context, C.SHUFFLE_NOTIFY_READERROR.name,
                     C.SHUFFLE_NOTIFY_READERROR.default):
            self.context.send_events([InputReadErrorEvent(
                diagnostics=str(e), index=slot, version=version,
                is_local_fetch=isinstance(e, ShuffleDataNotFound))])
        else:
            # producer-blame suppressed (reference knob: fetch faults are
            # presumed environmental) — the CONSUMER attempt must then
            # fail locally; dropping the error would strand wait_all
            # forever with heartbeats still flowing
            with self.lock:
                self.failed = True
                self.diagnostics = (f"fetch failed for slot {slot} and "
                                    f"notify.readerror is off: {e}")
                self.lock.notify_all()
        with self._deliver_lock:
            self.context.counters.increment(
                TaskCounter.NUM_FAILED_SHUFFLE_INPUTS)

    def _remote_done(self, req, batch, error) -> None:
        """Fetch-pool delivery: runs on a fetcher thread."""
        if self._closing:
            return   # input closed mid-fetch: nothing to deliver into
        slot, partition, payload, version, stamp, generation = req.cookie
        if error is not None:
            self._fetch_error(slot, version, error)
            return
        with self._deliver_lock:
            if getattr(req, "rtt_ms", 0.0) > 0.0:
                metrics.observe("shuffle.fetch.rtt", req.rtt_ms,
                                counters=self.context.counters)
            self.context.counters.increment(TaskCounter.SHUFFLE_BYTES,
                                            batch.nbytes)
            self.context.counters.increment(
                TaskCounter.SHUFFLE_BYTES_DISK_DIRECT, batch.nbytes)
            if self.merge_manager is None:
                self.context.counters.increment(
                    TaskCounter.SHUFFLE_BYTES_TO_MEM, batch.nbytes)
            self.context.counters.increment(TaskCounter.NUM_SHUFFLED_INPUTS)
        self._commit_fetch(slot, payload, version, stamp, generation, batch)

    def on_payload(self, slot: int, partition: int, payload: ShufflePayload,
                   version: int = 0) -> None:
        mm = self.merge_manager
        with self.lock:
            s = self.slots[slot]
            if s.complete or \
                    (payload.spill_id >= 0 and payload.spill_id in s.spills_seen):
                return  # duplicate delivery (e.g. after slot reset race)
            s.version = version
            stamp = s   # identity captured: if on_input_failed resets the
            # slot while the (un-locked) fetch below runs, this stale
            # producer version's batch must not land in the fresh slot
        generation = mm.slot_generation(slot) if mm is not None else 0
        if not payload.is_empty(partition) and not self._is_local(payload):
            if self._secret is None:
                # config gap on THIS consumer, not producer data loss: must
                # not masquerade as a local fetch failure (which force-reruns
                # the healthy producer)
                self._fetch_error(slot, version, PermissionError(
                    f"no shuffle secret for remote fetch from "
                    f"{payload.host}:{payload.port}"))
                return
            from tez_tpu.shuffle.scheduler import FetchRequest
            self._scheduler_for_remote().enqueue(FetchRequest(
                payload.host, payload.port, payload.path_component,
                payload.spill_id, partition,
                cookie=(slot, partition, payload, version, stamp,
                        generation),
                trace=self._trace))
            return
        try:
            if payload.is_empty(partition):
                batch = None
            else:
                if mm is not None:
                    # disk-direct short-circuit: a disk-backed producer run
                    # on this host merges straight off its partition-
                    # indexed file — no materialization, no re-spill
                    # (reference: LocalDiskFetchedInput / the
                    # SHUFFLE_BYTES_DISK_DIRECT path)
                    src = self.service.local_file_source(
                        payload.path_component, payload.spill_id, partition)
                    if src is not None:
                        path, nbytes = src
                        if mm.commit_local_file(slot, path, partition,
                                                nbytes, generation):
                            with self._deliver_lock:
                                ctr = self.context.counters
                                ctr.increment(TaskCounter.SHUFFLE_BYTES,
                                              nbytes)
                                ctr.increment(
                                    TaskCounter.SHUFFLE_BYTES_DISK_DIRECT,
                                    nbytes)
                                ctr.increment(
                                    TaskCounter.LOCAL_SHUFFLED_INPUTS)
                                ctr.increment(
                                    TaskCounter.NUM_SHUFFLED_INPUTS)
                            self._commit_fetch(slot, payload, version,
                                               stamp, generation, None)
                        return
                batch = self._fetch_local(payload, partition)
                with self._deliver_lock:
                    self.context.counters.increment(
                        TaskCounter.SHUFFLE_BYTES, batch.nbytes)
                    if mm is None:
                        # with a merge manager the TO_MEM/TO_DISK split is
                        # its admission decision, counted there exactly once
                        self.context.counters.increment(
                            TaskCounter.SHUFFLE_BYTES_TO_MEM, batch.nbytes)
                    self.context.counters.increment(
                        TaskCounter.NUM_SHUFFLED_INPUTS)
        except (ShuffleDataNotFound, OSError, PermissionError) as e:
            # OSError covers both connection faults and the checksum IOError
            # from a corrupted payload — either way the producer output is
            # unusable from here and must be re-fetched or re-produced
            if _is_checksum_error(e):
                # quarantine: drop every registered spill of this producer
                # output so the re-fetch after producer re-run can't serve
                # the damaged copy again (reference: fetch failure discards
                # the MapOutput before reporting)
                self.service.unregister_prefix(payload.path_component)
                log.warning("quarantined corrupt shuffle output %s",
                            payload.path_component)
            self._fetch_error(slot, version, e)
            return
        self._commit_fetch(slot, payload, version, stamp, generation, batch)

    def _commit_fetch(self, slot: int, payload: ShufflePayload, version: int,
                      stamp: "_SlotState", generation: int,
                      batch: Optional[KVBatch]) -> None:
        mm = self.merge_manager
        if mm is not None and batch is not None:
            # bounded-memory admission; may stall while the background
            # merger frees memory (MergeManager.reserve():404 semantics).
            # The captured generation makes a stale commit (slot reset
            # mid-fetch) a silent no-op inside the manager — it can never
            # displace the new attempt's data.
            try:
                if not mm.commit(slot, batch, generation):
                    return
            except RuntimeError as e:
                with self.lock:
                    self.failed = True
                    self.diagnostics = str(e)
                    self.lock.notify_all()
                return
        with self.lock:
            s = self.slots[slot]
            if s is not stamp or s.version != version:
                return   # slot was reset mid-fetch: drop the stale delivery
            if mm is None and batch is not None:
                s.batches.append(batch)
            if payload.spill_id >= 0:
                s.spills_seen.add(payload.spill_id)
            if payload.last_event:
                if not s.complete:
                    s.complete = True
                    self.completed += 1
            self.lock.notify_all()

    def on_input_failed(self, slot: int, version: int) -> None:
        """Producer re-running: discard and re-wait (reference:
        InputFailedEvent handling in shuffle event handlers)."""
        with self.lock:
            s = self.slots[slot]
            if s.complete:
                self.completed -= 1
            self.slots[slot] = _SlotState()
            self.lock.notify_all()
        if self.merge_manager is not None:
            self.merge_manager.on_slot_reset(slot)

    def wait_all(self, timeout: Optional[float] = None) -> List[KVBatch]:
        import time
        deadline = None if timeout is None else time.time() + timeout
        with self.lock:
            while True:
                if self.failed:
                    raise RuntimeError(f"shuffle failed: {self.diagnostics}")
                if self.completed >= self.num_slots:
                    out: List[KVBatch] = []
                    for s in self.slots:
                        out.extend(s.batches)
                    return out
                if deadline is not None and time.time() > deadline:
                    raise TimeoutError(
                        f"shuffle incomplete: {self.completed}/{self.num_slots}")
                self.lock.wait(0.2)
                # raises TaskKilledError if the AM killed this attempt (or
                # the heartbeat died) — never block forever
                self.context.notify_progress()


class OrderedGroupedKVInput(LogicalInput):
    """Sorted, grouped input (reduce side)."""

    def initialize(self) -> List[TezAPIEvent]:
        ctx = self.context
        self.key_serde = get_serde(_conf_get(ctx, "tez.runtime.key.class",
                                             "bytes"))
        self.val_serde = get_serde(_conf_get(ctx, "tez.runtime.value.class",
                                             "bytes"))
        self.key_width = int(_conf_get(ctx, "tez.runtime.tpu.key.width.bytes",
                                       16))
        self._merged: Optional[KVBatch] = None
        self._stream_plan = None
        from tez_tpu.library.comparators import load_comparator
        self._key_normalizer = load_comparator(ctx)   # resolved ONCE
        self._group_starts = None                     # cached across readers

        # Bounded-memory merge (MergeManager.java:83 analog).  The budget
        # comes from an explicit key, or else from the MemoryDistributor
        # grant for buffer.percent x io.sort.mb (reference: shuffle buffer
        # = fetch.buffer.percent of task memory).  0 explicit + 0 grant =
        # unbounded accumulation (grant callback delivers before start()).
        budget_mb = int(_conf_get(ctx, "tez.runtime.shuffle.merge.budget.mb",
                                  0))
        sort_mb = int(_conf_get(ctx, "tez.runtime.io.sort.mb", 256))
        frac = float(_conf_get(ctx,
                               "tez.runtime.shuffle.fetch.buffer.percent",
                               0.9))
        spill_dir = _conf_get(ctx, "tez.runtime.tpu.host.spill.dir", "") or \
            os.path.join(ctx.work_dirs[0], "spill")
        codec = None
        if _conf_get(ctx, "tez.runtime.compress", False):
            codec = _conf_get(ctx, "tez.runtime.compress.codec", "zlib")
        engine = _conf_get(ctx, "tez.runtime.sorter.class", "auto")
        factor = int(_conf_get(ctx, "tez.runtime.io.sort.factor", 64))
        # reduce-side merge plane knobs: engine / min-records default to the
        # sort plane's routing so a plain deployment tunes ONE engine choice
        merge_engine = _conf_get(ctx, "tez.runtime.merge.engine", "") or \
            engine
        merge_min = int(_conf_get(
            ctx, "tez.runtime.merge.engine.min-records", 0)) or \
            int(_conf_get(
                ctx, "tez.runtime.tpu.device.sort.min.records", 1 << 16))

        # push-based shuffle: eager merge overlaps the map wave — the
        # background merger starts once the eager fraction of the budget
        # is committed instead of waiting for admission pressure
        push_on = bool(_conf_get(
            ctx, "tez.runtime.shuffle.push.enabled", False))
        self._push_enabled = push_on
        eager = float(_conf_get(
            ctx, "tez.runtime.shuffle.push.eager-merge-threshold",
            0.5)) if push_on else 0.0
        self._mm_budget = budget_mb << 20
        self._mm_kwargs = dict(
            key_width=self.key_width, engine=merge_engine,
            merge_factor=factor,
            device_min_records=merge_min,
            eager_threshold=eager,
            merge_threshold=float(_conf_get(
                ctx, "tez.runtime.shuffle.merge.percent", 0.9)),
            max_single_fraction=float(_conf_get(
                ctx, "tez.runtime.shuffle.memory.limit.percent", 0.25)),
            key_normalizer=self._key_normalizer, codec=codec,
            async_depth=int(_conf_get(
                ctx, "tez.runtime.merge.async.depth", 2)))
        self._spill_dir = spill_dir

        from tez_tpu.api.runtime import MemoryUpdateCallback

        class _Granted(MemoryUpdateCallback):
            def memory_assigned(cb_self, assigned_size: int) -> None:
                if self._mm_budget <= 0:
                    self._mm_budget = int(assigned_size)

        ctx.request_initial_memory(int(frac * (sort_mb << 20)), _Granted(),
                           component_type="SORTED_MERGED_INPUT")
        self.merge_manager = None     # created in start(): grant lands first
        self._push_listener = None    # registered in start() when push on
        self.table = ShuffleFetchTable(ctx, self.num_physical_inputs,
                                       my_partition=ctx.task_index)
        return []

    def start(self) -> None:
        """Memory grants are delivered between initialize() and start():
        build the merge manager with the final budget and attach it before
        any event can deliver a fetch (events replay after start)."""
        from tez_tpu.library.merge_manager import ShuffleMergeManager
        self.merge_manager = ShuffleMergeManager(
            self.context.counters, self._mm_budget, self._spill_dir,
            **self._mm_kwargs)
        self.table.merge_manager = self.merge_manager
        if self._push_enabled:
            # merge-wake seam: a pushed arrival pokes the merger so the
            # async merge lane re-evaluates eager-merge eligibility the
            # moment bytes land, not a poll period later
            from tez_tpu.shuffle.service import local_shuffle_service
            mm = self.merge_manager

            def _push_wake(_path: str, _spill: int, _mm=mm) -> None:
                with _mm.lock:
                    _mm.lock.notify_all()

            self._push_listener = _push_wake
            local_shuffle_service().add_push_listener(_push_wake)

    def handle_events(self, events: Sequence[TezAPIEvent]) -> None:
        for ev in events:
            if isinstance(ev, CompositeRoutedDataMovementEvent):
                # source_index = my partition, target_index_start = slot
                payload = ev.user_payload
                assert isinstance(payload, ShufflePayload), payload
                for i in range(ev.count):
                    # expansion advances BOTH indices (reference:
                    # CompositeRoutedDataMovementEvent.expand)
                    self.table.on_payload(ev.target_index_start + i,
                                          ev.source_index + i, payload,
                                          version=ev.version)
            elif isinstance(ev, DataMovementEvent):
                payload = ev.user_payload
                assert isinstance(payload, ShufflePayload), payload
                self.table.on_payload(ev.target_index, ev.source_index,
                                      payload, version=ev.version)
            elif isinstance(ev, InputFailedEvent):
                self.table.on_input_failed(ev.target_index, ev.version)
            else:
                log.warning("OrderedGroupedKVInput: unexpected event %r", ev)

    def _wait_and_merge(self) -> None:
        if self._merged is None and self._stream_plan is None:
            import time
            t0 = time.time()
            with tracing.span("shuffle.wait", cat="shuffle"):
                self.table.wait_all()
            self.context.counters.find_counter(TaskCounter.SHUFFLE_PHASE_TIME)\
                .increment(int((time.time() - t0) * 1000))
            t1 = time.time()
            with tracing.span("shuffle.merge", cat="shuffle"):
                result = self.merge_manager.finish()
            metrics.observe("shuffle.merge",
                            (time.time() - t1) * 1000.0,
                            counters=self.context.counters)
            if result.is_streaming:
                # partition exceeds the memory budget: records stream from
                # chunked disk runs with bounded resident memory
                self._stream_plan = result.stream
            else:
                self._merged = result.batch
                self.context.counters.increment(
                    TaskCounter.REDUCE_INPUT_RECORDS,
                    self._merged.num_records)
            self.context.counters.find_counter(TaskCounter.MERGE_PHASE_TIME)\
                .increment(int((time.time() - t1) * 1000))

    def get_reader(self):
        self._wait_and_merge()
        if self._stream_plan is not None:
            return StreamingGroupedKVReader(self._stream_plan, self.key_serde,
                                            self.val_serde, self.context,
                                            key_normalizer=self._key_normalizer)
        batch = self._merged
        if self._group_starts is None:
            # one normalization pass for group detection, cached so repeat
            # readers are free (the merge normalized pre-sort; deriving its
            # arrays post-refinement isn't worth the plumbing)
            self._group_starts = GroupedKVReader._compute_groups(
                batch, self._key_normalizer)
        return GroupedKVReader(batch, self.key_serde,
                               self.val_serde, self.context,
                               group_starts=self._group_starts)

    def close(self) -> List[TezAPIEvent]:
        self._merged = None
        self._group_starts = None
        self._stream_plan = None
        if self._push_listener is not None:
            from tez_tpu.shuffle.service import local_shuffle_service
            local_shuffle_service().remove_push_listener(self._push_listener)
            self._push_listener = None
        self.table.shutdown()
        if self.merge_manager is not None:
            self.merge_manager.cleanup()
        return []


class GroupedKVReader(KeyValuesReader):
    """Groups adjacent equal keys (ValuesIterator analog, vectorized group
    boundary detection)."""

    def __init__(self, batch: KVBatch, key_serde: Serde, val_serde: Serde,
                 context: Any, key_normalizer: Any = None,
                 group_starts: Any = None):
        self.batch = batch
        self.key_serde = key_serde
        self.val_serde = val_serde
        self.context = context
        self._group_starts = group_starts if group_starts is not None \
            else self._compute_groups(batch, key_normalizer)

    @staticmethod
    def _compute_groups(batch: KVBatch, key_normalizer: Any = None
                        ) -> np.ndarray:
        n = batch.num_records
        if n == 0:
            return np.zeros(0, dtype=np.int64)
        if key_normalizer is not None:
            # comparator-equality grouping (e.g. case-insensitive): adjacent
            # keys with equal NORMALIZED forms form one group — materialize
            # the normalized keys once, then the same vectorized path
            from tez_tpu.ops.sorter import normalize_batch_keys
            kb, ko = normalize_batch_keys(batch, key_normalizer)
        else:
            kb, ko = batch.key_bytes, batch.key_offsets
        lengths = ko[1:] - ko[:-1]
        same = np.zeros(n, dtype=bool)
        cand = np.flatnonzero(lengths[1:] == lengths[:-1])
        same[cand + 1] = adjacent_equal_rows(kb, ko, cand)
        return np.flatnonzero(~same).astype(np.int64)

    def __iter__(self) -> Iterator[Tuple[Any, Iterator[Any]]]:
        n = self.batch.num_records
        bounds = np.append(self._group_starts, n)
        groups = 0
        for s, e in zip(bounds[:-1], bounds[1:]):
            key = self.key_serde.from_bytes(self.batch.key(int(s)))
            values = (self.val_serde.from_bytes(self.batch.value(i))
                      for i in range(int(s), int(e)))
            groups += 1
            if (groups & 0x3FF) == 0:
                self.context.notify_progress()
            yield key, values
        self.context.counters.increment(TaskCounter.REDUCE_INPUT_GROUPS,
                                        groups)

    def grouped_batch(self) -> Tuple[KVBatch, np.ndarray]:
        """Vectorized view for batch-first consumers: the merged sorted
        KVBatch plus group-start row indices (one per distinct key).  A
        zero-Python-per-record alternative to __iter__.  Increments
        REDUCE_INPUT_GROUPS exactly as a full iteration would
        (REDUCE_INPUT_RECORDS is recorded once at merge time by the input,
        not here); callers that inspect the batch and then fall back to
        __iter__ should use `peek_batch()` instead."""
        self.context.counters.increment(TaskCounter.REDUCE_INPUT_GROUPS,
                                        len(self._group_starts))
        return self.batch, self._group_starts

    def grouped_blocks(self) -> Iterator[Tuple[KVBatch, np.ndarray]]:
        """Block-stream view: yields (sorted KVBatch, group_starts) with
        every group complete within its block.  For the in-RAM reader that
        is a single block; the streaming reader yields many — consumers
        written against this API handle both without branching."""
        yield self.grouped_batch()

    def peek_batch(self) -> KVBatch:
        """The merged batch WITHOUT counter effects — for consumers probing
        whether the vectorized path applies (e.g. uniform value widths)
        before committing to grouped_batch() or __iter__."""
        return self.batch


class StreamingGroupedKVReader(KeyValuesReader):
    """Grouped reader over a streaming merge plan (bounded memory): sorted
    blocks arrive from the vectorized disk-run block merge; adjacent equal
    SORT keys (normalized form when a comparator is configured) form one
    group.  Re-iterable — each iteration re-reads the chunked disk runs."""

    def __init__(self, plan: Any, key_serde: Serde, val_serde: Serde,
                 context: Any, key_normalizer: Any = None):
        self.plan = plan
        self.key_serde = key_serde
        self.val_serde = val_serde
        self.context = context
        self.key_normalizer = key_normalizer

    def grouped_blocks(self) -> Iterator[Tuple[KVBatch, np.ndarray]]:
        """Yields (sorted KVBatch, group_starts) with every group COMPLETE
        within its block: each merged block's trailing group is carried into
        the next block, so batch-first consumers need no cross-block
        bookkeeping.  Each incoming block is group-scanned ONCE; a group
        spanning m blocks accumulates as a piece list (one concat when it
        closes), so total work stays linear in records.  Resident memory is
        one merged block plus the open group (a single key's records — the
        pathological one-giant-key case degrades to holding that key's
        group, which any grouped consumer must materialize anyway).  Counts
        REDUCE_INPUT_GROUPS and — unlike grouped_batch(), whose records
        were counted at merge time — REDUCE_INPUT_RECORDS, since the
        streaming merge never materializes a counted whole."""
        counters = self.context.counters
        norm = self.key_normalizer
        carry: List[KVBatch] = []     # pieces of the one open group
        carry_key: Optional[bytes] = None   # its SORT key (normalized form)

        def sort_key(batch: KVBatch, i: int) -> bytes:
            k = batch.key(i)
            return norm(k) if norm is not None else k

        def close_carry() -> Tuple[KVBatch, np.ndarray]:
            out = carry[0] if len(carry) == 1 else KVBatch.concat(carry)
            return out, np.zeros(1, dtype=np.int64)

        groups = 0
        records = 0
        for block in self.plan.iter_batches():
            n = block.num_records
            if n == 0:
                continue
            starts = GroupedKVReader._compute_groups(block, norm)
            if carry_key is not None and sort_key(block, 0) == carry_key:
                if len(starts) <= 1:
                    carry.append(block)   # whole block continues the group
                    continue
                cut = int(starts[1])      # the open group closes here
                carry.append(block.slice_rows(0, cut))
                groups += 1
                records += sum(p.num_records for p in carry)
                yield close_carry()
                carry = []
                block = block.slice_rows(cut, n)
                n -= cut
                starts = (starts[1:] - cut).astype(np.int64)
            elif carry:
                groups += 1
                records += sum(p.num_records for p in carry)
                yield close_carry()
                carry = []
            # hold the trailing (possibly open) group; emit the rest
            last = int(starts[-1])
            carry = [block.slice_rows(last, n)]
            carry_key = sort_key(block, last)
            if last > 0:
                groups += len(starts) - 1
                records += last
                self.context.notify_progress()
                yield block.slice_rows(0, last), starts[:-1]
        if carry and sum(p.num_records for p in carry) > 0:
            groups += 1
            records += sum(p.num_records for p in carry)
            yield close_carry()
        counters.increment(TaskCounter.REDUCE_INPUT_GROUPS, groups)
        counters.increment(TaskCounter.REDUCE_INPUT_RECORDS, records)

    def __iter__(self) -> Iterator[Tuple[Any, Iterator[Any]]]:
        for batch, starts in self.grouped_blocks():
            bounds = np.append(starts, batch.num_records)
            for s, e in zip(bounds[:-1], bounds[1:]):
                key = self.key_serde.from_bytes(batch.key(int(s)))
                values = (self.val_serde.from_bytes(batch.value(i))
                          for i in range(int(s), int(e)))
                yield key, values


class UnorderedKVReaderAdapter(KeyValueReader):
    """Flat (key, value) iteration over a batch (used by unordered inputs and
    tests)."""

    def __init__(self, batch: KVBatch, key_serde: Serde, val_serde: Serde):
        self.batch = batch
        self.key_serde = key_serde
        self.val_serde = val_serde

    def __iter__(self):
        for k, v in self.batch.iter_pairs():
            yield self.key_serde.from_bytes(k), self.val_serde.from_bytes(v)


class ConcatenatedMergedKVInput(MergedLogicalInput):
    """Merged view over a vertex group's constituent inputs: concatenates
    their readers (reference: tez-runtime-library
    ConcatenatedMergedKeyValueInput / the MergedLogicalInput family)."""

    def get_reader(self) -> Reader:
        merged_self = self

        class _Concat(KeyValueReader):
            def __iter__(self):
                for inp in merged_self.inputs:
                    reader = inp.get_reader()
                    # grouped readers yield (k, values); flat ones (k, v)
                    if isinstance(reader, KeyValuesReader):
                        for k, vs in reader:
                            for v in vs:
                                yield k, v
                    else:
                        for k, v in reader:
                            yield k, v

        return _Concat()
