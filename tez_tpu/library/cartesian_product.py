"""Cartesian-product edges: first-class CUSTOM edge implementation.

Reference parity: tez-runtime-library/.../cartesianproduct/ (13 files:
CartesianProductVertexManager.java:62, CartesianProductEdgeManager,
CartesianProductCombination) — the fair/unpartitioned variant: the consumer
runs one task per combination of source tasks; each source edge routes
source task s to every combination whose coordinate at that source is s.

Config payload (both manager and edge managers):
  {"sources": ["A", "B", ...]}           # order defines the mixed radix
  edge manager additionally gets {"position": k, "num_tasks": [nA, nB, ..]}
"""
from __future__ import annotations

import logging
import math
from typing import Any, List, Optional, Sequence

from tez_tpu.api.edge_manager import (CompositeEventRouteMetadata,
                                      EdgeManagerPluginOnDemand,
                                      EventRouteMetadata)
from tez_tpu.api.events import VertexManagerEvent
from tez_tpu.api.vertex_manager import (ScheduleTaskRequest,
                                        TaskAttemptIdentifier,
                                        VertexManagerPlugin)
from tez_tpu.common.payload import EdgeManagerPluginDescriptor
from tez_tpu.dag.edge_property import DataMovementType, EdgeProperty

log = logging.getLogger(__name__)


class CartesianProductCombination:
    """Mixed-radix combination math (reference:
    CartesianProductCombination.java)."""

    def __init__(self, num_tasks: Sequence[int]):
        self.num_tasks = list(num_tasks)
        self.strides = [1] * len(num_tasks)
        for i in range(len(num_tasks) - 2, -1, -1):
            self.strides[i] = self.strides[i + 1] * num_tasks[i + 1]

    @property
    def total(self) -> int:
        return self.strides[0] * self.num_tasks[0] if self.num_tasks else 0

    def coordinate(self, dest_task: int, position: int) -> int:
        return (dest_task // self.strides[position]) % self.num_tasks[position]

    def dests_for(self, position: int, src_task: int) -> List[int]:
        return [d for d in range(self.total)
                if self.coordinate(d, position) == src_task]


class CartesianProductEdgeManager(EdgeManagerPluginOnDemand):
    def initialize(self) -> None:
        # The DAG-construction payload may be empty: the vertex manager
        # swaps in a fully-configured manager before any routing happens.
        payload = self.context.user_payload.load() or {}
        self.position = payload.get("position", 0)
        self.combo = CartesianProductCombination(payload.get("num_tasks", [1]))

    def get_num_destination_task_physical_inputs(self, dest_task: int) -> int:
        return 1

    def get_num_source_task_physical_outputs(self, src_task: int) -> int:
        return 1

    def get_num_destination_consumer_tasks(self, src_task: int) -> int:
        return self.combo.total // max(1, self.combo.num_tasks[self.position])

    def route_data_movement_event_to_destination(
            self, src_task: int, src_output_index: int, dest_task: int
    ) -> Optional[EventRouteMetadata]:
        if self.combo.coordinate(dest_task, self.position) != src_task:
            return None
        return EventRouteMetadata(1, (0,), (src_output_index,))

    def route_composite_data_movement_event_to_destination(
            self, src_task: int, dest_task: int
    ) -> Optional[CompositeEventRouteMetadata]:
        if self.combo.coordinate(dest_task, self.position) != src_task:
            return None
        return CompositeEventRouteMetadata(1, 0, 0)

    def route_input_source_task_failed_event_to_destination(
            self, src_task: int, dest_task: int) -> Optional[EventRouteMetadata]:
        if self.combo.coordinate(dest_task, self.position) != src_task:
            return None
        return EventRouteMetadata(1, (0,))

    def route_input_error_event_to_source(self, dest_task: int,
                                          dest_failed_input_index: int) -> int:
        return self.combo.coordinate(dest_task, self.position)


class CartesianProductVertexManager(VertexManagerPlugin):
    """Sets consumer parallelism = product of source task counts, rewires
    each CUSTOM in-edge with a positioned edge manager, and schedules once
    every source has started producing (all-at-once; the reference adds
    slow-start by completed-combination fraction)."""

    def initialize(self) -> None:
        payload = self.context.user_payload.load() or {}
        self.sources: List[str] = payload["sources"]
        self._configured = False
        self._scheduled = False
        self._started = False
        self._completed_srcs: set = set()

    def _try_configure(self) -> None:
        if self._configured:
            return
        counts = [self.context.get_vertex_num_tasks(s) for s in self.sources]
        if any(c < 0 for c in counts):
            return
        total = math.prod(counts)
        props = self.context.get_input_vertex_edge_properties()
        new_props = {}
        for pos, src in enumerate(self.sources):
            prop = props[src]
            desc = EdgeManagerPluginDescriptor.create(
                "tez_tpu.library.cartesian_product:"
                "CartesianProductEdgeManager",
                payload={"position": pos, "num_tasks": counts})
            new_props[src] = EdgeProperty.create_custom(
                desc, prop.data_source_type, prop.edge_source,
                prop.edge_destination, prop.scheduling_type)
        self.context.reconfigure_vertex(total,
                                        source_edge_properties=new_props)
        self.context.done_reconfiguring_vertex()
        self._configured = True
        log.info("cartesian product: %s -> %d combinations",
                 dict(zip(self.sources, counts)), total)

    def on_vertex_started(self, completions) -> None:
        self._started = True
        self._try_configure()
        for c in completions:
            self._completed_srcs.add((c.vertex_name, c.task_index))
        self._maybe_schedule()

    def on_source_task_completed(self, attempt: TaskAttemptIdentifier) -> None:
        self._try_configure()
        self._completed_srcs.add((attempt.vertex_name, attempt.task_index))
        self._maybe_schedule()

    def _maybe_schedule(self) -> None:
        if self._scheduled or not self._configured or not self._started:
            return
        # schedule everything once at least one task per source completed
        # (outputs are EPHEMERAL-safe only when sources persist; stock
        # usage pairs this with PERSISTED unordered outputs)
        have = {v for v, _ in self._completed_srcs}
        if not all(s in have for s in self.sources):
            return
        n = self.context.get_vertex_num_tasks(self.context.vertex_name)
        self._scheduled = True
        self.context.schedule_tasks([ScheduleTaskRequest(i)
                                     for i in range(n)])

    def on_vertex_manager_event_received(self, event: VertexManagerEvent) -> None:
        pass

    def on_root_vertex_initialized(self, input_name: str, descriptor: Any,
                                   events: List[Any]) -> None:
        pass
