"""Socket transport for the task umbilical: AM server + runner-side client.

Reference parity: the TezTaskUmbilicalProtocol RPC boundary
(tez-runtime-internals common/TezTaskUmbilicalProtocol.java:42 served by
tez-dag's TaskCommunicator RPC server) — the control-plane seam between the
orchestrator process and out-of-process runners (TezChild JVMs there, runner
processes here; a multi-host deployment points runners at the AM host over
DCN).

Wire format: a RAW challenge-response handshake (no deserialization of
untrusted bytes before authentication) — the server sends a random 16-byte
nonce first and the client replies the 32-byte HMAC(secret, purpose||nonce),
so an observed handshake cannot be replayed (reference: the umbilical's
SASL/DIGEST job-token challenge-response auth) — then length-prefixed
pickled (method, args) requests / (ok, payload) responses.  Pickle is
acceptable on the post-handshake channel because both ends are the
framework's own trusted processes inside one job holding the job token (the
reference's Writable RPC makes the same assumption); unauthenticated peers
never reach the unpickler.
"""
from __future__ import annotations

import logging
import pickle
import socket
import socketserver
import struct
import threading
from typing import Any, Optional, Tuple

from tez_tpu.common import faults
from tez_tpu.common.security import JobTokenSecretManager

log = logging.getLogger(__name__)

_METHODS = frozenset({"get_task", "heartbeat", "can_commit", "task_done",
                      "task_failed", "task_killed", "should_die"})


def _send_msg(wfile: Any, obj: Any) -> None:
    blob = pickle.dumps(obj, protocol=4)
    wfile.write(struct.pack("<I", len(blob)) + blob)
    wfile.flush()


def _recv_msg(rfile: Any) -> Any:
    raw = rfile.read(4)
    if len(raw) < 4:
        raise ConnectionError("umbilical closed")
    (n,) = struct.unpack("<I", raw)
    blob = rfile.read(n)
    if len(blob) < n:
        raise ConnectionError("umbilical truncated")
    return pickle.loads(blob)


def authenticate_stream(rfile, wfile, secrets: JobTokenSecretManager,
                        purpose: bytes) -> bool:
    """Server side of the challenge-response handshake: send a fresh 16-byte
    nonce, read EXACTLY 32 bytes (the HMAC of purpose||nonce), compare,
    reply b"OK"/b"NO".  The nonce makes an observed handshake worthless to a
    replaying peer; nothing is unpickled before this succeeds."""
    import os as _os
    nonce = _os.urandom(16)
    try:
        wfile.write(nonce)
        wfile.flush()
    except OSError:
        return False
    sig = rfile.read(32)
    if len(sig) != 32 or not secrets.verify_hash(sig, purpose + nonce):
        try:
            wfile.write(b"NO")
            wfile.flush()
        except OSError:
            pass
        return False
    wfile.write(b"OK")
    wfile.flush()
    return True


def client_handshake(rfile, wfile, secrets: JobTokenSecretManager,
                     purpose: bytes) -> None:
    nonce = rfile.read(16)
    if len(nonce) != 16:
        raise ConnectionError("handshake: server closed before challenge")
    wfile.write(secrets.compute_hash(purpose + nonce))
    wfile.flush()
    reply = rfile.read(2)
    if reply != b"OK":
        raise PermissionError(f"handshake rejected ({reply!r})")


class _Handler(socketserver.StreamRequestHandler):
    def handle(self) -> None:
        server = self.server
        comm = server.task_comm          # type: ignore[attr-defined]
        secrets = server.secrets         # type: ignore[attr-defined]
        try:
            if not authenticate_stream(self.rfile, self.wfile, secrets,
                                       b"umbilical-hello"):
                return
            while True:
                method, args, kwargs = _recv_msg(self.rfile)
                if method not in _METHODS:
                    _send_msg(self.wfile, (False, f"no method {method}"))
                    continue
                try:
                    # inside the try: an injected fault ships to the runner
                    # as a failed RPC (what a dying AM thread looks like)
                    faults.fire("am.umbilical", detail=method)
                    result = getattr(comm, method)(*args, **kwargs)
                    _send_msg(self.wfile, (True, result))
                except BaseException as e:  # noqa: BLE001 — ship to runner
                    try:
                        _send_msg(self.wfile, (False, e))
                    except (pickle.PicklingError, TypeError, AttributeError):
                        # unpicklable exception: ship a repr instead of
                        # killing the connection
                        _send_msg(self.wfile, (False, RuntimeError(repr(e))))
        except (ConnectionError, EOFError, pickle.UnpicklingError):
            return


class UmbilicalServer:
    """Serves the AM's TaskCommunicatorManager to remote runners."""

    def __init__(self, task_comm: Any, secrets: JobTokenSecretManager,
                 host: str = "127.0.0.1", port: int = 0,
                 ssl_context=None):
        # host "0.0.0.0" for multi-host deployments
        # (conf: tez.am.umbilical.bind-host)
        from tez_tpu.common.tls import wrap_server_class
        server_cls = wrap_server_class(socketserver.ThreadingTCPServer,
                                       ssl_context)
        self._tcp = server_cls((host, port), _Handler)
        self._tcp.daemon_threads = True
        self._tcp.task_comm = task_comm     # type: ignore[attr-defined]
        self._tcp.secrets = secrets         # type: ignore[attr-defined]
        self._thread = threading.Thread(target=self._tcp.serve_forever,
                                        daemon=True, name="umbilical-server")

    @property
    def port(self) -> int:
        return self._tcp.server_address[1]

    def start(self) -> "UmbilicalServer":
        self._thread.start()
        return self

    def stop(self) -> None:
        self._tcp.shutdown()
        self._tcp.server_close()


class FramedClient:
    """Shared wire-protocol client: raw handshake + locked request/reply
    framing.  One connection; requests serialized by a lock."""

    _purpose = b"override-me"

    def __init__(self, host: str, port: int,
                 secrets: JobTokenSecretManager, timeout: float = 60.0,
                 ssl_context=None):
        self._sock = socket.create_connection((host, port), timeout=timeout)
        if ssl_context is None:
            # runners launched before any conf arrives read TEZ_TPU_SSL_*
            # from their launch env (common/tls.py export_env)
            from tez_tpu.common.tls import client_context
            ssl_context = client_context(None)
        if ssl_context is not None:
            self._sock = ssl_context.wrap_socket(self._sock)
        self._rfile = self._sock.makefile("rb")
        self._wfile = self._sock.makefile("wb")
        self._lock = threading.Lock()
        client_handshake(self._rfile, self._wfile, secrets, self._purpose)

    def _call(self, method: str, *args: Any, **kwargs: Any) -> Any:
        with self._lock:
            _send_msg(self._wfile, (method, args, kwargs))
            ok, payload = _recv_msg(self._rfile)
        if not ok:
            if isinstance(payload, BaseException):
                raise payload
            raise RuntimeError(str(payload))
        return payload

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass


class RemoteUmbilical(FramedClient):
    """Runner-side client with the TaskCommunicatorManager surface that
    TaskRunner expects (mirroring the reference's single umbilical RPC proxy
    per TezChild)."""

    _purpose = b"umbilical-hello"

    def get_task(self, container_id: Any, timeout: float = 1.0,
                 node_id: str = "") -> Any:
        return self._call("get_task", container_id, timeout, node_id)

    def heartbeat(self, request: Any) -> Any:
        return self._call("heartbeat", request)

    def can_commit(self, attempt_id: Any, epoch: int = 0,
                   window_id: int = 0, stream: str = "") -> bool:
        return self._call("can_commit", attempt_id, epoch=epoch,
                          window_id=window_id, stream=stream)

    def task_done(self, attempt_id: Any, events: Any, counters: Any,
                  epoch: int = 0, window_id: int = 0,
                  stream: str = "") -> None:
        self._call("task_done", attempt_id, events, counters, epoch=epoch,
                   window_id=window_id, stream=stream)

    def task_failed(self, attempt_id: Any, diagnostics: str,
                    fatal: bool = False, counters: Any = None) -> None:
        self._call("task_failed", attempt_id, diagnostics, fatal=fatal,
                   counters=counters)

    def task_killed(self, attempt_id: Any, diagnostics: str) -> None:
        self._call("task_killed", attempt_id, diagnostics)
