"""Socket transport for the task umbilical: AM server + runner-side client.

Reference parity: the TezTaskUmbilicalProtocol RPC boundary
(tez-runtime-internals common/TezTaskUmbilicalProtocol.java:42 served by
tez-dag's TaskCommunicator RPC server) — the control-plane seam between the
orchestrator process and out-of-process runners (TezChild JVMs there, runner
processes here; a multi-host deployment points runners at the AM host over
DCN).

Wire format: job-token handshake, then length-prefixed pickled
(method, args) requests / (ok, payload) responses.  Pickle is acceptable on
this channel because both ends are the framework's own trusted processes
inside one job (the reference's Writable RPC makes the same assumption);
the handshake rejects foreign connections.
"""
from __future__ import annotations

import logging
import pickle
import socket
import socketserver
import struct
import threading
from typing import Any, Optional, Tuple

from tez_tpu.common.security import JobTokenSecretManager

log = logging.getLogger(__name__)

_METHODS = frozenset({"get_task", "heartbeat", "can_commit", "task_done",
                      "task_failed", "task_killed", "should_die"})


def _send_msg(wfile: Any, obj: Any) -> None:
    blob = pickle.dumps(obj, protocol=4)
    wfile.write(struct.pack("<I", len(blob)) + blob)
    wfile.flush()


def _recv_msg(rfile: Any) -> Any:
    raw = rfile.read(4)
    if len(raw) < 4:
        raise ConnectionError("umbilical closed")
    (n,) = struct.unpack("<I", raw)
    blob = rfile.read(n)
    if len(blob) < n:
        raise ConnectionError("umbilical truncated")
    return pickle.loads(blob)


class _Handler(socketserver.StreamRequestHandler):
    def handle(self) -> None:
        server = self.server
        comm = server.task_comm          # type: ignore[attr-defined]
        secrets = server.secrets         # type: ignore[attr-defined]
        try:
            hello = _recv_msg(self.rfile)
            if not (isinstance(hello, dict) and
                    secrets.verify_hash(hello.get("sig", b""),
                                        b"umbilical-hello")):
                _send_msg(self.wfile, (False, "auth failed"))
                return
            _send_msg(self.wfile, (True, "ok"))
            while True:
                method, args, kwargs = _recv_msg(self.rfile)
                if method not in _METHODS:
                    _send_msg(self.wfile, (False, f"no method {method}"))
                    continue
                try:
                    result = getattr(comm, method)(*args, **kwargs)
                    _send_msg(self.wfile, (True, result))
                except BaseException as e:  # noqa: BLE001 — ship to runner
                    try:
                        _send_msg(self.wfile, (False, e))
                    except (pickle.PicklingError, TypeError, AttributeError):
                        # unpicklable exception: ship a repr instead of
                        # killing the connection
                        _send_msg(self.wfile, (False, RuntimeError(repr(e))))
        except (ConnectionError, EOFError, pickle.UnpicklingError):
            return


class UmbilicalServer:
    """Serves the AM's TaskCommunicatorManager to remote runners."""

    def __init__(self, task_comm: Any, secrets: JobTokenSecretManager,
                 host: str = "127.0.0.1", port: int = 0):
        # host "0.0.0.0" for multi-host deployments
        # (conf: tez.am.umbilical.bind-host)
        self._tcp = socketserver.ThreadingTCPServer((host, port), _Handler)
        self._tcp.daemon_threads = True
        self._tcp.task_comm = task_comm     # type: ignore[attr-defined]
        self._tcp.secrets = secrets         # type: ignore[attr-defined]
        self._thread = threading.Thread(target=self._tcp.serve_forever,
                                        daemon=True, name="umbilical-server")

    @property
    def port(self) -> int:
        return self._tcp.server_address[1]

    def start(self) -> "UmbilicalServer":
        self._thread.start()
        return self

    def stop(self) -> None:
        self._tcp.shutdown()
        self._tcp.server_close()


class RemoteUmbilical:
    """Runner-side client with the TaskCommunicatorManager surface that
    TaskRunner expects.  One connection, requests serialized by a lock
    (the runner's main + heartbeat threads share it, mirroring the
    reference's single umbilical RPC proxy per TezChild)."""

    def __init__(self, host: str, port: int,
                 secrets: JobTokenSecretManager, timeout: float = 60.0):
        self._sock = socket.create_connection((host, port), timeout=timeout)
        self._rfile = self._sock.makefile("rb")
        self._wfile = self._sock.makefile("wb")
        self._lock = threading.Lock()
        _send_msg(self._wfile,
                  {"sig": secrets.compute_hash(b"umbilical-hello")})
        ok, msg = _recv_msg(self._rfile)
        if not ok:
            raise PermissionError(f"umbilical handshake failed: {msg}")

    def _call(self, method: str, *args: Any, **kwargs: Any) -> Any:
        with self._lock:
            _send_msg(self._wfile, (method, args, kwargs))
            ok, payload = _recv_msg(self._rfile)
        if not ok:
            if isinstance(payload, BaseException):
                raise payload
            raise RuntimeError(str(payload))
        return payload

    def get_task(self, container_id: Any, timeout: float = 1.0) -> Any:
        return self._call("get_task", container_id, timeout)

    def heartbeat(self, request: Any) -> Any:
        return self._call("heartbeat", request)

    def can_commit(self, attempt_id: Any) -> bool:
        return self._call("can_commit", attempt_id)

    def task_done(self, attempt_id: Any, events: Any, counters: Any) -> None:
        self._call("task_done", attempt_id, events, counters)

    def task_failed(self, attempt_id: Any, diagnostics: str,
                    fatal: bool = False, counters: Any = None) -> None:
        self._call("task_failed", attempt_id, diagnostics, fatal=fatal,
                   counters=counters)

    def task_killed(self, attempt_id: Any, diagnostics: str) -> None:
        self._call("task_killed", attempt_id, diagnostics)

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass
