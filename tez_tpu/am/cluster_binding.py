"""External cluster-manager binding: the AM ACQUIRES executor pods.

Reference parity: tez-dag YarnTaskSchedulerService.java:87 (AMRMClient
allocate loop: request containers against backlog, hold while useful,
release when idle) and TezContainerLauncherImpl.java:81 (NMClient launching
the container on its node).  There is no YARN here by design; the cluster
manager is abstracted as a *pod driver* — the same seam a GKE/Kubernetes
deployment binds to.  Each pod is one runner on one (logical) host: it
gets a stable node id (the unit of failure accumulation/blacklisting),
hosts its own shuffle server, and dials back into the AM's umbilical over
TCP exactly like a hand-started multi-host worker (docs/multihost.md).

Drivers:
- ProcessPodDriver — pods are local runner processes, one per simulated
  host.  This is the process-per-"host" harness with the REAL plugin seam
  (the MiniCluster analog): everything above the driver (scaling, reaping,
  node ids, umbilical, TCP shuffle) is identical in production.
- KubernetesPodDriver — builds pod specs and drives the Kubernetes API;
  gated loudly on the `kubernetes` client being importable (not baked into
  this image).
"""
from __future__ import annotations

import itertools
import logging
import os
import subprocess
import sys
import threading
from typing import Any, Dict, Optional, Tuple

from tez_tpu.am.history import HistoryEvent, HistoryEventType

log = logging.getLogger(__name__)


class PodDriver:
    """Cluster-manager seam (the AMRMClient/NMClient analog)."""

    def launch(self, pod_name: str, node_id: str, env: Dict[str, str],
               am_host: str, am_port: int, idle_timeout: float) -> Any:
        """Start one runner pod; returns an opaque handle."""
        raise NotImplementedError

    def poll(self, handle: Any) -> Optional[int]:
        """None while running, else an exit status."""
        raise NotImplementedError

    def stop(self, handle: Any) -> None:
        raise NotImplementedError


class ProcessPodDriver(PodDriver):
    """Runner processes as pods, each with its own stable node id."""

    def launch(self, pod_name: str, node_id: str, env: Dict[str, str],
               am_host: str, am_port: int, idle_timeout: float) -> Any:
        penv = dict(os.environ)
        for k, v in env.items():
            if v == "":          # same contract as tez.am.runner.env in
                penv.pop(k, None)   # subprocess mode: empty value = unset
            else:
                penv[k] = v
        repo_root = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        existing = penv.get("PYTHONPATH", "")
        penv["PYTHONPATH"] = repo_root + (
            os.pathsep + existing if existing else "")
        return subprocess.Popen(
            [sys.executable, "-m", "tez_tpu.runtime.remote_runner",
             "--am-host", am_host, "--am-port", str(am_port),
             "--node-id", node_id,
             "--container-id", pod_name,
             "--idle-timeout", str(idle_timeout)],
            env=penv)

    def poll(self, handle: Any) -> Optional[int]:
        return handle.poll()

    def stop(self, handle: Any) -> None:
        handle.terminate()
        try:
            handle.wait(timeout=10)
        except Exception:  # noqa: BLE001
            handle.kill()


class KubernetesPodDriver(PodDriver):
    """Kubernetes binding: one runner pod per TPU host.

    Requires the `kubernetes` Python client and in-cluster (or kubeconfig)
    credentials; both are absent from this image, so construction fails
    loudly rather than pretending to schedule."""

    def __init__(self, namespace: str = "default",
                 image: str = "tez-tpu-runner:latest",
                 pod_template: Optional[Dict[str, Any]] = None,
                 core_api: Optional[Any] = None):
        """`core_api` injects a CoreV1Api-shaped client (tests drive a fake
        API server through the full manifest/launch/poll/stop protocol);
        None = the real kubernetes client + in-cluster/kubeconfig creds."""
        if core_api is None:
            try:
                import kubernetes  # noqa: F401
            except ImportError:
                raise RuntimeError(
                    "KubernetesPodDriver needs the `kubernetes` client, "
                    "which is not installed in this environment; use the "
                    "ProcessPodDriver (process-per-host) or install the "
                    "client in your deployment image") from None
            from kubernetes import client, config
            try:
                config.load_incluster_config()
            except Exception:  # noqa: BLE001
                config.load_kube_config()
            core_api = client.CoreV1Api()
        self._core = core_api
        self.namespace = namespace
        self.image = image
        self.pod_template = pod_template or {}
        self._poll_faults: Dict[Any, int] = {}

    def _pod_manifest(self, pod_name: str, node_id: str,
                      env: Dict[str, str], am_host: str, am_port: int,
                      idle_timeout: float) -> Dict[str, Any]:
        manifest = {
            "apiVersion": "v1", "kind": "Pod",
            "metadata": {"name": pod_name,
                         "labels": {"app": "tez-tpu-runner"}},
            "spec": {
                "restartPolicy": "Never",
                "containers": [{
                    "name": "runner",
                    "image": self.image,
                    "command": ["python", "-m",
                                "tez_tpu.runtime.remote_runner",
                                "--am-host", am_host,
                                "--am-port", str(am_port),
                                "--node-id", node_id,
                                "--container-id", pod_name,
                                "--advertise-host",
                                "$(POD_IP)",
                                "--idle-timeout", str(idle_timeout)],
                    "env": [{"name": k, "value": str(v)}
                            for k, v in env.items() if v != ""] +
                           [{"name": "POD_IP", "valueFrom": {"fieldRef": {
                               "fieldPath": "status.podIP"}}}],
                }],
            },
        }
        # deployment-supplied template wins on conflicts (resources,
        # nodeSelector for TPU node pools, tolerations, ...)
        spec = manifest["spec"]
        for k, v in self.pod_template.items():
            spec[k] = v
        return manifest

    def launch(self, pod_name: str, node_id: str, env: Dict[str, str],
               am_host: str, am_port: int, idle_timeout: float) -> Any:
        self._core.create_namespaced_pod(
            self.namespace,
            self._pod_manifest(pod_name, node_id, env, am_host, am_port,
                               idle_timeout))
        return pod_name

    #: consecutive poll failures per pod before the fault stops being
    #: treated as transient and surfaces (dead creds / broken API server
    #: must not leave a phantom fleet "running" forever)
    POLL_FAULT_LIMIT = 20

    def poll(self, handle: Any) -> Optional[int]:
        try:
            pod = self._core.read_namespaced_pod(handle, self.namespace)
        except Exception as e:  # noqa: BLE001 — ApiException-shaped (any
            # client impl raises its own type; the contract is `.status`)
            if getattr(e, "status", None) == 404:
                return 1   # deleted/evicted outside the pool: reap it
            faults = self._poll_faults.get(handle, 0) + 1
            self._poll_faults[handle] = faults
            if faults >= self.POLL_FAULT_LIMIT:
                raise RuntimeError(
                    f"pod {handle}: {faults} consecutive poll failures "
                    f"(last: {e}); the Kubernetes API is not answering — "
                    f"check credentials/connectivity") from e
            log.warning("pod %s poll failed (%s); keeping it (%d/%d)",
                        handle, e, faults, self.POLL_FAULT_LIMIT)
            return None    # transient API fault must not kill the fleet
        self._poll_faults.pop(handle, None)
        phase = pod.status.phase
        if phase in ("Succeeded", "Failed"):
            return 0 if phase == "Succeeded" else 1
        return None

    def stop(self, handle: Any) -> None:
        try:
            self._core.delete_namespaced_pod(handle, self.namespace)
        except Exception:  # noqa: BLE001
            pass


class PodPoolRunnerPool:
    """RunnerPool-shaped facade over a PodDriver: grows the pod fleet
    against scheduler backlog, reaps exited pods (the scheduler's next
    ensure_runners respawns them while work remains), stops the fleet at
    shutdown.  Pod index -> stable node id, so a respawned pod on the same
    slot keeps accumulating node failures (blacklisting semantics)."""

    def __init__(self, ctx: Any, max_pods: int, driver: PodDriver,
                 idle_timeout: float = 5.0):
        self.ctx = ctx
        self.max_pods = max_pods
        self.driver = driver
        self.idle_timeout = idle_timeout
        self._pods: Dict[int, Tuple[Any, str]] = {}   # slot -> (handle, name)
        self._launched = itertools.count()
        self._lock = threading.Lock()
        self._stopped = False

    def _env(self) -> Dict[str, str]:
        env = {}
        for k, v in (self.ctx.conf.get("tez.am.runner.env") or {}).items():
            env[k] = str(v)
        env["TEZ_TPU_JOB_TOKEN"] = self.ctx.secrets.secret.hex()
        from tez_tpu.common.tls import export_env
        env.update(export_env(self.ctx.conf))
        return env

    def ensure_runners(self, backlog: int) -> None:
        with self._lock:
            if self._stopped:
                return
            self._reap()
            want = min(self.max_pods, len(self._pods) + max(0, backlog))
            free_slots = (s for s in itertools.count()
                          if s not in self._pods)
            while len(self._pods) < want:
                slot = next(free_slots)
                n = next(self._launched)
                node_id = f"pod-{slot}"
                name = f"tez-pod-{self.ctx.app_id}-{slot}-{n}".lower()\
                    .replace("_", "-")
                handle = self.driver.launch(
                    name, node_id, self._env(),
                    am_host=self.ctx.conf.get(
                        "tez.am.pod-pool.advertise-host", "127.0.0.1"),
                    am_port=self.ctx.umbilical_server.port,
                    idle_timeout=self.idle_timeout)
                self._pods[slot] = (handle, name)
                self.ctx.history(HistoryEvent(
                    HistoryEventType.CONTAINER_LAUNCHED,
                    container_id=name,
                    data={"node_id": node_id, "driver":
                          type(self.driver).__name__}))

    def _reap(self) -> None:
        for slot, (handle, name) in list(self._pods.items()):
            status = self.driver.poll(handle)
            if status is not None:
                del self._pods[slot]
                self.ctx.history(HistoryEvent(
                    HistoryEventType.CONTAINER_STOPPED,
                    container_id=name, data={"returncode": status}))

    def live_count(self) -> int:
        with self._lock:
            self._reap()
            return len(self._pods)

    def shutdown(self, wait: bool = True) -> None:
        with self._lock:
            self._stopped = True
            pods = list(self._pods.values())
            self._pods.clear()
        for handle, _name in pods:
            self.driver.stop(handle)


def create_pod_pool(ctx: Any, num_slots: int) -> PodPoolRunnerPool:
    """Build the pool from conf: tez.am.pod-pool.driver.class (registry
    shorthand 'process'/'kubernetes' or a module:Class path)."""
    name = ctx.conf.get("tez.am.pod-pool.driver.class") or "process"
    if name == "process":
        driver: PodDriver = ProcessPodDriver()
    elif name == "kubernetes":
        driver = KubernetesPodDriver(
            namespace=ctx.conf.get("tez.am.pod-pool.k8s.namespace")
            or "default",
            image=ctx.conf.get("tez.am.pod-pool.k8s.image")
            or "tez-tpu-runner:latest",
            pod_template=ctx.conf.get("tez.am.pod-pool.k8s.pod-template"))
    else:
        from tez_tpu.common.payload import resolve_class
        driver = resolve_class(name)()
    max_pods = int(ctx.conf.get("tez.am.pod-pool.max-pods") or num_slots)
    return PodPoolRunnerPool(ctx, max_pods, driver)
