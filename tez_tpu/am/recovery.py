"""Event-sourced recovery journal.

Reference parity: tez-dag/.../history/recovery/RecoveryService.java:53 (per-DAG
journal + synchronously-flushed summary events) and app/RecoveryParser.java:89
(replay: completed work short-circuited, in-flight work re-run, commit-in-
flight without completion => DAG failed).

Journal layout: <staging>/<app_id>/recovery/<attempt>/journal.jsonl.
"""
from __future__ import annotations

import dataclasses
import json
import logging
import os
import threading
import time
import zlib
from typing import Any, Dict, List, Optional, Set

from tez_tpu.am.history import HistoryEvent, HistoryEventType
from tez_tpu.common import clock, config as C
from tez_tpu.common import faults
from tez_tpu.dag.plan import DAGPlan

log = logging.getLogger(__name__)

#: Commit-ledger records: the write-ahead log of the DAG commit protocol.
#: STARTED is fsync'd BEFORE any committer mutates the filesystem;
#: FINISHED/ABORTED are fsync'd before the DAG reaches its terminal record.
COMMIT_LEDGER_TYPES = frozenset({
    HistoryEventType.DAG_COMMIT_STARTED,
    HistoryEventType.DAG_COMMIT_FINISHED,
    HistoryEventType.DAG_COMMIT_ABORTED,
    # streaming window ledger (am/streaming.py): the per-window analog of
    # the DAG commit WAL — STARTED fsync'd before the committer touches
    # the output dir, FINISHED/ABORTED before the stream advances, so the
    # commit.ledger.fsync fault point crashes streams mid-commit exactly
    # like batch DAGs
    HistoryEventType.WINDOW_COMMIT_STARTED,
    HistoryEventType.WINDOW_COMMIT_FINISHED,
    HistoryEventType.WINDOW_COMMIT_ABORTED,
})


class JournalLineError(ValueError):
    """A journal line failed CRC validation or JSON/event decoding."""


def encode_journal_line(event: HistoryEvent) -> str:
    """`crc32-hex SP json` — the CRC covers the JSON bytes so a torn or
    bit-flipped record is detected instead of silently replayed."""
    payload = event.to_json()
    crc = zlib.crc32(payload.encode("utf-8")) & 0xFFFFFFFF
    return "%08x %s" % (crc, payload)


def decode_journal_line(line: str) -> HistoryEvent:
    """Inverse of encode_journal_line.  Legacy raw-JSON lines (pre-CRC
    journals) decode too.  Raises JournalLineError on corruption."""
    if line.startswith("{"):
        try:
            return HistoryEvent.from_json(line)
        except Exception as e:  # noqa: BLE001
            raise JournalLineError(f"bad legacy record: {e}") from e
    if len(line) < 10 or line[8] != " ":
        raise JournalLineError("malformed record framing")
    try:
        want = int(line[:8], 16)
    except ValueError as e:
        raise JournalLineError("malformed CRC prefix") from e
    payload = line[9:]
    got = zlib.crc32(payload.encode("utf-8")) & 0xFFFFFFFF
    if got != want:
        raise JournalLineError(
            f"CRC mismatch (recorded {want:08x}, computed {got:08x})")
    try:
        return HistoryEvent.from_json(payload)
    except Exception as e:  # noqa: BLE001
        raise JournalLineError(f"bad event JSON: {e}") from e


class RecoveryService:
    """Append-only journal of history events (summary events fsync'd)."""

    def __init__(self, ctx: Any, attempt: int = 1):
        self.ctx = ctx
        self.attempt = attempt
        staging = ctx.conf.get(C.STAGING_DIR)
        self.dir = os.path.join(staging, ctx.app_id, "recovery", str(attempt))
        self._fh = None
        #: non-summary events flush at most this often WHILE EVENTS FLOW
        #: (the check runs on each handle(); a quiet journal flushes on the
        #: next event or stop()).  Summary events always fsync immediately.
        #: (reference: tez.dag.recovery.flush.interval.secs,
        #: RecoveryService.java maxUnflushedEvents/flushInterval)
        self.flush_interval = float(
            ctx.conf.get(C.DAG_RECOVERY_FLUSH_INTERVAL_SECS) or 0)
        self._last_flush = 0.0
        # appenders are no longer single-threaded: the dispatcher,
        # admission threads AND every resident stream driver journal
        # through here — unserialized writes interleave records mid-line
        self._write_lock = threading.Lock()

    def start(self) -> None:
        os.makedirs(self.dir, exist_ok=True)
        self._fh = open(os.path.join(self.dir, "journal.jsonl"), "a")
        self._last_flush = clock.wall_s()

    def handle(self, event: HistoryEvent) -> None:
        if self._fh is None:
            return
        faults.fire("am.recovery.append", detail=event.event_type.name)
        if event.is_summary and event.event_type in COMMIT_LEDGER_TYPES:
            # fail mode here IS the mid-commit AM crash: the ledger
            # record may or may not have reached disk, and recovery
            # must cope with either
            faults.fire("commit.ledger.fsync",
                        detail=event.event_type.name)
        fd = None
        with self._write_lock:
            if self._fh is None:  # lost a race with stop()
                return
            self._fh.write(encode_journal_line(event) + "\n")
            if event.is_summary:
                # flush under the lock (it drains the shared buffer in
                # write order); the fsync happens OUTSIDE it so bulk task
                # events never queue behind a disk barrier — the kernel
                # syncs everything flushed so far, which includes ours
                self._fh.flush()
                fd = self._fh.fileno()
                self._last_flush = clock.wall_s()
            elif self.flush_interval > 0:
                now = clock.wall_s()
                if now - self._last_flush >= self.flush_interval:
                    self._fh.flush()
                    self._last_flush = now
        if fd is not None:
            faults.fire("am.recovery.fsync", detail=event.event_type.name)
            from tez_tpu.common import metrics, tracing
            t0 = time.perf_counter()
            try:
                os.fsync(fd)
            except OSError:      # raced stop(); the flush already landed
                pass
            fsync_ms = (time.perf_counter() - t0) * 1000.0
            metrics.observe("commit.ledger.fsync", fsync_ms)
            if event.event_type in COMMIT_LEDGER_TYPES:
                tracing.event("commit.ledger.fsync",
                              record=event.event_type.name,
                              dag_id=event.dag_id, ms=round(fsync_ms, 3))

    def stop(self) -> None:
        with self._write_lock:
            if self._fh is not None:
                self._fh.flush()
                self._fh.close()
                self._fh = None


@dataclasses.dataclass
class DAGRecoveryData:
    """What the parser reconstructs for the new AM attempt."""
    dag_id: str
    plan: Optional[DAGPlan]
    dag_state: Optional[str]                  # terminal state if finished
    commit_in_flight: bool
    completed_vertices: Dict[str, Dict[str, Any]]   # vertex name -> finish data
    succeeded_tasks: Set[str]                 # task id strings
    events: List[HistoryEvent]
    # Commit-ledger state at crash time: None (commit never started),
    # "STARTED" (in the mutation window — roll forward or abort per
    # tez.am.commit.recovery.policy), "FINISHED" (committers completed; only
    # the terminal DAG record was lost — roll forward to SUCCEEDED),
    # "ABORTED" (partial commit rolled back — DAG is FAILED).
    commit_state: Optional[str] = None
    # task id string -> {"attempt": attempt id str, "generated_events": wire,
    # "counters": dict} — only for tasks whose final state was SUCCEEDED and
    # whose successful attempt journaled its generated events.
    task_data: Dict[str, Dict[str, Any]] = dataclasses.field(default_factory=dict)
    # vertex name -> num_tasks at crash time (last INITIALIZED/CONFIGURE_DONE);
    # a vertex is only short-circuitable when its new parallelism matches.
    vertex_num_tasks: Dict[str, int] = dataclasses.field(default_factory=dict)
    # vertex names whose per-vertex commit finished before the crash
    # (VERTEX_COMMIT_STARTED followed by that vertex's VERTEX_FINISHED) —
    # recovery must not commit them a second time.
    committed_vertices: Set[str] = dataclasses.field(default_factory=set)
    # vertex name -> journaled reconfiguration ({"parallelism": n, "edges":
    # {src: {"class_name", "payload"}}}) from the last VERTEX_CONFIGURE_DONE
    # that carried one; the recovering AM re-applies it so the vertex's
    # completed tasks stay restorable (RecoveryParser.java:658 semantics).
    vertex_reconfig: Dict[str, Dict[str, Any]] = dataclasses.field(
        default_factory=dict)


# ---------------------------------------------------------------------------
# Wire (de)serialization of task-generated events for the journal.
# Reference: TaskAttemptFinishedEvent carries taGeneratedEvents so a new AM
# attempt can re-route completed producers' DataMovementEvents without
# re-running the tasks (RecoveryParser.parseRecoveryData:658).
# ---------------------------------------------------------------------------

def _payload_to_wire(p: Any) -> Any:
    from tez_tpu.api.events import ShufflePayload
    if p is None:
        return None
    if isinstance(p, ShufflePayload):
        d = dataclasses.asdict(p)
        ep = d.get("empty_partitions")
        d["empty_partitions"] = ep.hex() if ep is not None else None
        return {"__kind__": "shuffle", **d}
    if isinstance(p, (bytes, bytearray)):
        return {"__kind__": "bytes", "hex": bytes(p).hex()}
    if isinstance(p, (dict, list, str, int, float, bool)):
        return {"__kind__": "json", "value": p}
    import pickle
    return {"__kind__": "pickle", "hex": pickle.dumps(p).hex()}


def _payload_from_wire(w: Any, allow_pickle: bool = False) -> Any:
    from tez_tpu.api.events import ShufflePayload
    if w is None:
        return None
    kind = w.get("__kind__")
    if kind == "shuffle":
        d = {k: v for k, v in w.items() if k != "__kind__"}
        ep = d.get("empty_partitions")
        d["empty_partitions"] = bytes.fromhex(ep) if ep else None
        return ShufflePayload(**d)
    if kind == "bytes":
        return bytes.fromhex(w["hex"])
    if kind == "json":
        return w["value"]
    if not allow_pickle:
        # the journal lives in the shared staging dir: unpickling it hands
        # code execution to anyone with write access there.  The framework's
        # own events all use the typed kinds above; arbitrary payloads only
        # replay under the explicit tez.dag.recovery.trusted-staging opt-in.
        raise UntrustedJournalPayload(
            f"journal payload kind {kind!r} requires pickle; set "
            f"tez.dag.recovery.trusted-staging=true to replay it")
    import pickle
    return pickle.loads(bytes.fromhex(w["hex"]))


def event_to_wire(ev: Any) -> Dict[str, Any]:
    from tez_tpu.api.events import (CompositeDataMovementEvent,
                                    DataMovementEvent)
    if isinstance(ev, DataMovementEvent):
        return {"t": "DME", "source_index": ev.source_index,
                "version": ev.version,
                "payload": _payload_to_wire(ev.user_payload)}
    if isinstance(ev, CompositeDataMovementEvent):
        return {"t": "CDME", "source_index_start": ev.source_index_start,
                "count": ev.count, "version": ev.version,
                "payload": _payload_to_wire(ev.user_payload)}
    import pickle
    return {"t": "pickle", "hex": pickle.dumps(ev).hex()}


class UntrustedJournalPayload(RuntimeError):
    """A journaled event/payload needs pickle to decode but the staging dir
    is not trusted (tez.dag.recovery.trusted-staging unset)."""


def event_from_wire(w: Dict[str, Any], allow_pickle: bool = False) -> Any:
    from tez_tpu.api.events import (CompositeDataMovementEvent,
                                    DataMovementEvent)
    t = w["t"]
    if t == "DME":
        return DataMovementEvent(
            source_index=w["source_index"],
            user_payload=_payload_from_wire(w["payload"], allow_pickle),
            version=w["version"])
    if t == "CDME":
        return CompositeDataMovementEvent(
            source_index_start=w["source_index_start"], count=w["count"],
            user_payload=_payload_from_wire(w["payload"], allow_pickle),
            version=w["version"])
    if not allow_pickle:
        raise UntrustedJournalPayload(
            f"journal event kind {t!r} requires pickle; set "
            f"tez.dag.recovery.trusted-staging=true to replay it")
    import pickle
    return pickle.loads(bytes.fromhex(w["hex"]))


class RecoveryParser:
    """Reference: RecoveryParser.parseRecoveryData:658."""

    def __init__(self, staging_dir: str, app_id: str):
        self.base = os.path.join(staging_dir, app_id, "recovery")

    def journal_files(self) -> List[str]:
        if not os.path.isdir(self.base):
            return []
        out = []
        for attempt in sorted(os.listdir(self.base), key=lambda s: int(s)):
            p = os.path.join(self.base, attempt, "journal.jsonl")
            if os.path.exists(p):
                out.append(p)
        return out

    def read_events(self) -> List[HistoryEvent]:
        """Decode every journal record across all attempts.  A corrupt LAST
        line of the LAST journal is a torn tail write (the AM died mid-
        append) — tolerated quietly; corruption anywhere else means the
        journal was damaged at rest and is logged loudly."""
        events: List[HistoryEvent] = []
        files = self.journal_files()
        for fi, path in enumerate(files):
            # lenient decode: a crash can tear the tail mid-byte, and the
            # CRC frame (not the codec) is what rejects mangled records
            with open(path, errors="replace") as fh:
                lines = [ln.strip() for ln in fh]
            while lines and not lines[-1]:
                lines.pop()
            for li, line in enumerate(lines):
                if not line:
                    continue
                try:
                    events.append(decode_journal_line(line))
                except JournalLineError as e:
                    if fi == len(files) - 1 and li == len(lines) - 1:
                        log.info("tolerating torn trailing journal record "
                                 "in %s: %s", path, e)
                    else:
                        log.warning("skipping corrupt journal record "
                                    "(%s line %d): %s", path, li + 1, e)
        return events

    def parse(self) -> Optional[DAGRecoveryData]:
        """Returns recovery data for the last in-progress DAG, or None when
        there is nothing to recover (no DAG, or last DAG finished)."""
        all_dags = self.parse_all()
        return all_dags[-1] if all_dags else None

    def parse_all(self) -> List[DAGRecoveryData]:
        """Recovery data for EVERY submitted DAG, in submit order.  The
        resident session AM recovers all of them: finished DAGs roll
        forward to their terminal record, in-flight ones are resubmitted
        (docs/recovery.md)."""
        events = self.read_events()
        submitted: List[str] = []               # dag ids in submit order
        plans: Dict[str, Optional[DAGPlan]] = {}
        for ev in events:
            if ev.event_type is HistoryEventType.DAG_SUBMITTED:
                if ev.dag_id not in plans:
                    submitted.append(ev.dag_id)
                raw = ev.data.get("plan")
                plans[ev.dag_id] = \
                    DAGPlan.deserialize(bytes.fromhex(raw)) if raw else None
        return [self._parse_dag(dag_id, plans[dag_id], events)
                for dag_id in submitted]

    def queued_submissions(self) -> List[Dict[str, Any]]:
        """Unresolved admission-queue records, in arrival order.

        A ``DAG_QUEUED`` (or a successor attempt's
        ``DAG_REQUEUED_ON_RECOVERY``) record is resolved by a later
        ``DAG_SUBMITTED`` stamped with its ``sub_id`` — the promotion.  A
        record with no promotion is a submission the dead AM accepted but
        never started (parked in the queue OR popped by the consumer and
        lost mid-promote — the ``am.queue.delay`` window); the successor
        incarnation must replay it.  Each entry:
        ``{"sub_id", "dag_name", "tenant", "plan" (hex str or None),
        "decode_error" (str or "")}``.
        """
        events = self.read_events()
        queued: Dict[str, Dict[str, Any]] = {}
        order: List[str] = []
        for ev in events:
            t = ev.event_type
            if t in (HistoryEventType.DAG_QUEUED,
                     HistoryEventType.DAG_REQUEUED_ON_RECOVERY):
                sub_id = ev.dag_id
                if sub_id not in queued:
                    order.append(sub_id)
                # latest record wins: a requeue carries the plan again, so
                # a second crash replays from it
                queued[sub_id] = {
                    "sub_id": sub_id,
                    "dag_name": ev.data.get("dag_name", ""),
                    "tenant": ev.data.get("tenant", ""),
                    "plan": ev.data.get("plan"),
                    "decode_error": "",
                }
            elif t is HistoryEventType.DAG_SUBMITTED:
                sub_id = ev.data.get("sub_id")
                if sub_id:
                    queued.pop(sub_id, None)
        out = []
        for sub_id in order:
            rec = queued.get(sub_id)
            if rec is None:
                continue
            raw = rec["plan"]
            if raw:
                try:
                    DAGPlan.deserialize(bytes.fromhex(raw))
                except Exception as e:  # noqa: BLE001 — flagged, not fatal
                    rec["decode_error"] = repr(e)
            else:
                rec["decode_error"] = "queued record carries no plan"
            out.append(rec)
        return out

    def stream_records(self) -> Dict[str, Dict[str, Any]]:
        """Streaming recovery state, per stream id (docs/streaming.md).

        Replays the window-commit ledger: a window with a
        ``WINDOW_COMMIT_FINISHED`` record is sealed forever (a successor
        AM serves it from disk, never re-runs it); the first window
        after ``last_committed`` is where the resumed stream picks up.
        Each value::

            {"spec": <STREAM_OPENED data>, "retired": bool,
             "committed": set[int], "aborted": set[int],
             "open_started": set[int],   # STARTED with no FINISHED/ABORTED
             "last_committed": int}
        """
        out: Dict[str, Dict[str, Any]] = {}

        def rec(stream: str) -> Dict[str, Any]:
            if stream not in out:
                out[stream] = {"spec": None, "retired": False,
                               "committed": set(), "aborted": set(),
                               "open_started": set(), "last_committed": 0}
            return out[stream]

        for ev in self.read_events():
            t = ev.event_type
            if t is HistoryEventType.STREAM_OPENED:
                r = rec(ev.data.get("stream", ""))
                r["spec"] = dict(ev.data)
            elif t is HistoryEventType.STREAM_RETIRED:
                rec(ev.data.get("stream", ""))["retired"] = True
            elif t in (HistoryEventType.WINDOW_COMMIT_STARTED,
                       HistoryEventType.WINDOW_COMMIT_FINISHED,
                       HistoryEventType.WINDOW_COMMIT_ABORTED):
                r = rec(ev.data.get("stream", ""))
                w = int(ev.data.get("window_id", 0))
                if t is HistoryEventType.WINDOW_COMMIT_STARTED:
                    r["open_started"].add(w)
                elif t is HistoryEventType.WINDOW_COMMIT_FINISHED:
                    r["open_started"].discard(w)
                    r["committed"].add(w)
                    r["last_committed"] = max(r["last_committed"], w)
                else:
                    r["open_started"].discard(w)
                    r["aborted"].add(w)
        return out

    def _parse_dag(self, dag_id: str, plan: Optional[DAGPlan],
                   events: List[HistoryEvent]) -> DAGRecoveryData:
        dag_events = [e for e in events if e.dag_id == dag_id]
        dag_state = None
        commit_state: Optional[str] = None
        # per-vertex commits are in flight only until that vertex's
        # VERTEX_FINISHED lands — a long-finished vertex commit must not
        # poison recovery of a DAG that crashed hours later
        pending_vertex_commits: Set[str] = set()
        pending_group_commits: Set[str] = set()
        committed_vertices: Set[str] = set()
        vertex_reconfig: Dict[str, Dict[str, Any]] = {}
        completed_vertices: Dict[str, Dict[str, Any]] = {}
        attempt_records: Dict[str, Dict[str, Any]] = {}  # attempt id -> data
        task_last: Dict[str, Dict[str, Any]] = {}        # task id -> last finish
        vertex_num_tasks: Dict[str, int] = {}
        for ev in dag_events:
            t = ev.event_type
            if t is HistoryEventType.DAG_FINISHED:
                dag_state = ev.data.get("state")
            elif t is HistoryEventType.DAG_COMMIT_STARTED:
                commit_state = "STARTED"
            elif t is HistoryEventType.DAG_COMMIT_FINISHED:
                commit_state = "FINISHED"
            elif t is HistoryEventType.DAG_COMMIT_ABORTED:
                commit_state = "ABORTED"
            elif t is HistoryEventType.VERTEX_COMMIT_STARTED:
                pending_vertex_commits.add(ev.vertex_id)
            elif t is HistoryEventType.VERTEX_GROUP_COMMIT_STARTED:
                pending_group_commits.add(ev.data.get("group", ""))
            elif t is HistoryEventType.VERTEX_GROUP_COMMIT_FINISHED:
                pending_group_commits.discard(ev.data.get("group", ""))
            elif t is HistoryEventType.VERTEX_FINISHED:
                if ev.vertex_id in pending_vertex_commits and \
                        ev.data.get("state") == "SUCCEEDED":
                    committed_vertices.add(ev.data.get("vertex_name"))
                pending_vertex_commits.discard(ev.vertex_id)
                if ev.data.get("state") == "SUCCEEDED":
                    completed_vertices[ev.data.get("vertex_name")] = ev.data
            elif t in (HistoryEventType.VERTEX_INITIALIZED,
                       HistoryEventType.VERTEX_CONFIGURE_DONE):
                name = ev.data.get("vertex_name")
                n = ev.data.get("num_tasks")
                if name is not None and n is not None:
                    vertex_num_tasks[name] = n
                if name is not None and ev.data.get("reconfig") is not None:
                    vertex_reconfig[name] = ev.data["reconfig"]
            elif t is HistoryEventType.TASK_ATTEMPT_FINISHED and \
                    ev.data.get("state") == "SUCCEEDED":
                attempt_records[ev.attempt_id] = ev.data
            elif t is HistoryEventType.TASK_FINISHED:
                task_last[ev.task_id] = ev.data    # last record wins: a task
                # re-run after output loss journals a second TASK_FINISHED
        succeeded_tasks: Set[str] = set()
        task_data: Dict[str, Dict[str, Any]] = {}
        for tid, td in task_last.items():
            if td.get("state") != "SUCCEEDED":
                continue
            succeeded_tasks.add(tid)
            att_id = td.get("successful_attempt")
            att = attempt_records.get(att_id) if att_id else None
            if att is None:
                continue
            task_data[tid] = {
                "attempt": att_id,
                "generated_events": att.get("generated_events", []),
                "counters": att.get("counters", {}),
            }
        return DAGRecoveryData(
            dag_id=dag_id, plan=plan, dag_state=dag_state,
            commit_in_flight=(commit_state == "STARTED"
                              or bool(pending_vertex_commits)
                              or bool(pending_group_commits))
            and dag_state is None,
            commit_state=commit_state if dag_state is None else None,
            completed_vertices=completed_vertices,
            succeeded_tasks=succeeded_tasks, events=dag_events,
            task_data=task_data, vertex_num_tasks=vertex_num_tasks,
            committed_vertices=committed_vertices,
            vertex_reconfig=vertex_reconfig)
