"""Event-sourced recovery journal.

Reference parity: tez-dag/.../history/recovery/RecoveryService.java:53 (per-DAG
journal + synchronously-flushed summary events) and app/RecoveryParser.java:89
(replay: completed work short-circuited, in-flight work re-run, commit-in-
flight without completion => DAG failed).

Journal layout: <staging>/<app_id>/recovery/<attempt>/journal.jsonl.
"""
from __future__ import annotations

import dataclasses
import json
import logging
import os
from typing import Any, Dict, List, Optional, Set

from tez_tpu.am.history import HistoryEvent, HistoryEventType
from tez_tpu.common import config as C
from tez_tpu.dag.plan import DAGPlan

log = logging.getLogger(__name__)


class RecoveryService:
    """Append-only journal of history events (summary events fsync'd)."""

    def __init__(self, ctx: Any, attempt: int = 1):
        self.ctx = ctx
        self.attempt = attempt
        staging = ctx.conf.get(C.STAGING_DIR)
        self.dir = os.path.join(staging, ctx.app_id, "recovery", str(attempt))
        self._fh = None

    def start(self) -> None:
        os.makedirs(self.dir, exist_ok=True)
        self._fh = open(os.path.join(self.dir, "journal.jsonl"), "a")

    def handle(self, event: HistoryEvent) -> None:
        if self._fh is None:
            return
        self._fh.write(event.to_json() + "\n")
        if event.is_summary:
            self._fh.flush()
            os.fsync(self._fh.fileno())

    def stop(self) -> None:
        if self._fh is not None:
            self._fh.flush()
            self._fh.close()
            self._fh = None


@dataclasses.dataclass
class DAGRecoveryData:
    """What the parser reconstructs for the new AM attempt."""
    dag_id: str
    plan: Optional[DAGPlan]
    dag_state: Optional[str]                  # terminal state if finished
    commit_in_flight: bool
    completed_vertices: Dict[str, Dict[str, Any]]   # vertex name -> finish data
    succeeded_tasks: Set[str]                 # task id strings
    events: List[HistoryEvent]


class RecoveryParser:
    """Reference: RecoveryParser.parseRecoveryData:658."""

    def __init__(self, staging_dir: str, app_id: str):
        self.base = os.path.join(staging_dir, app_id, "recovery")

    def journal_files(self) -> List[str]:
        if not os.path.isdir(self.base):
            return []
        out = []
        for attempt in sorted(os.listdir(self.base), key=lambda s: int(s)):
            p = os.path.join(self.base, attempt, "journal.jsonl")
            if os.path.exists(p):
                out.append(p)
        return out

    def parse(self) -> Optional[DAGRecoveryData]:
        """Returns recovery data for the last in-progress DAG, or None when
        there is nothing to recover (no DAG, or last DAG finished)."""
        events: List[HistoryEvent] = []
        for path in self.journal_files():
            with open(path) as fh:
                for line in fh:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        events.append(HistoryEvent.from_json(line))
                    except Exception:  # noqa: BLE001 — torn tail write
                        log.warning("skipping corrupt journal line")
        if not events:
            return None
        # find last submitted DAG
        last_dag_id: Optional[str] = None
        plan: Optional[DAGPlan] = None
        for ev in events:
            if ev.event_type is HistoryEventType.DAG_SUBMITTED:
                last_dag_id = ev.dag_id
                raw = ev.data.get("plan")
                plan = DAGPlan.deserialize(bytes.fromhex(raw)) if raw else None
        if last_dag_id is None:
            return None
        dag_events = [e for e in events if e.dag_id == last_dag_id]
        dag_state = None
        commit_started = False
        completed_vertices: Dict[str, Dict[str, Any]] = {}
        succeeded_tasks: Set[str] = set()
        for ev in dag_events:
            t = ev.event_type
            if t is HistoryEventType.DAG_FINISHED:
                dag_state = ev.data.get("state")
            elif t in (HistoryEventType.DAG_COMMIT_STARTED,
                       HistoryEventType.VERTEX_COMMIT_STARTED,
                       HistoryEventType.VERTEX_GROUP_COMMIT_STARTED):
                commit_started = True
            elif t is HistoryEventType.VERTEX_FINISHED and \
                    ev.data.get("state") == "SUCCEEDED":
                completed_vertices[ev.data.get("vertex_name")] = ev.data
            elif t is HistoryEventType.TASK_FINISHED and \
                    ev.data.get("state") == "SUCCEEDED":
                succeeded_tasks.add(ev.task_id)
        return DAGRecoveryData(
            dag_id=last_dag_id, plan=plan, dag_state=dag_state,
            commit_in_flight=commit_started and dag_state is None,
            completed_vertices=completed_vertices,
            succeeded_tasks=succeeded_tasks, events=dag_events)
