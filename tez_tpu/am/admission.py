"""Admission control for the resident multi-tenant session AM.

The reference session AM runs one DAG at a time and *rejects* a second
submit outright (DAGAppMaster.submitDAGToAppMaster).  A resident service
needs a real overload policy instead: every ``submit_dag`` gets one of
three verdicts —

ACCEPT
    capacity is available now; the DAG starts immediately.
QUEUE
    the AM is at ``tez.am.session.max-concurrent-dags``; the submission
    parks in a bounded FIFO and the submitter blocks until the queue
    consumer promotes it.  The plan is journaled (``DAG_QUEUED``, a
    summary event) *before* the submitter unblocks, so a crashed
    consumer can never silently lose an accepted submission — the
    lossless-admission contract.
SHED
    the queue is full, the tenant is over its in-flight cap, or the
    buffer store's host tier is beyond the admission watermark even
    after a relief attempt.  The verdict travels to the client as a
    typed :class:`~tez_tpu.client.errors.DAGRejectedError` carrying a
    RETRY-AFTER hint — clients resubmit with full-jitter backoff
    (TezClient.submit_dag_with_retry); nothing server-side remembers
    the submission.

Fairness (deficit round-robin over task-scheduler slots) and store byte
quotas live elsewhere (task_scheduler.py, store/buffer_store.py); this
module only decides *whether and when* a DAG may occupy the AM.  See
docs/multitenancy.md for the full model.
"""
from __future__ import annotations

import collections
import dataclasses
import itertools
import logging
import threading
from typing import Any, Dict, List, Optional

from tez_tpu.client.errors import DAGRejectedError
from tez_tpu.common import clock, config as C
from tez_tpu.common import faults, metrics
from tez_tpu.obs import flight as _flight

log = logging.getLogger(__name__)


@dataclasses.dataclass
class _QueuedSubmission:
    """One parked submit: the submitter blocks on ``done`` until the
    consumer resolves it with either a dag_id or an error."""
    sub_id: str
    plan: Any
    tenant: str
    recovery_data: Any
    enqueued_at: float
    done: threading.Event = dataclasses.field(default_factory=threading.Event)
    dag_id: Any = None
    error: Optional[BaseException] = None


class _TenantStats:
    __slots__ = ("running", "queued", "accepted", "shed", "completed",
                 "failed")

    def __init__(self) -> None:
        self.running = 0
        self.queued = 0
        self.accepted = 0
        self.shed = 0
        self.completed = 0
        self.failed = 0

    def inflight(self) -> int:
        return self.running + self.queued

    def to_dict(self) -> Dict[str, int]:
        return {"running": self.running, "queued": self.queued,
                "accepted": self.accepted, "shed": self.shed,
                "completed": self.completed, "failed": self.failed}


class AdmissionController:
    """Accept/queue/shed verdicts + the FIFO queue consumer thread."""

    def __init__(self, am: Any) -> None:
        self._am = am
        conf = am.conf
        self.max_concurrent = max(
            1, int(conf.get(C.AM_SESSION_MAX_CONCURRENT_DAGS) or 1))
        self.queue_size = max(0, int(conf.get(C.AM_SESSION_QUEUE_SIZE) or 0))
        self.tenant_max_inflight = int(
            conf.get(C.AM_SESSION_TENANT_MAX_INFLIGHT) or 0)
        self.retry_after_ms = float(
            conf.get(C.AM_SESSION_SHED_RETRY_AFTER_MS) or 500.0)
        self.admit_watermark = float(
            conf.get(C.AM_SESSION_ADMIT_STORE_WATERMARK) or 0.95)
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._queue: "collections.deque[_QueuedSubmission]" = \
            collections.deque()
        self._running = 0
        self._tenants: Dict[str, _TenantStats] = {}
        self._sub_seq = itertools.count(1)
        self._draining: Optional[_QueuedSubmission] = None
        self._stopped = False
        self._consumer = threading.Thread(
            target=self._consume, name=f"am-admit-{am.app_id}", daemon=True)
        self._consumer.start()

    # -- submit path (client-server / local-client threads) ------------------
    def submit(self, plan: Any, recovery_data: Any = None) -> Any:
        tenant = str(getattr(plan, "tenant", "") or "")
        with self._lock:
            ts = self._tenants.setdefault(tenant, _TenantStats())
            shed_reason = self._shed_reason_locked(tenant, ts, plan.name)
            if shed_reason is not None:
                ts.shed += 1
                depth, inflight = len(self._queue), ts.inflight()
                _flight.record(_flight.ADMIT, "shed", tenant,
                               a=depth, b=inflight)
            elif self._running < self.max_concurrent and not self._queue \
                    and self._draining is None:
                ts.accepted += 1
                ts.running += 1
                self._running += 1
                sub = None
                _flight.record(_flight.ADMIT, "accept", tenant,
                               a=0, b=self._running)
            else:
                sub = _QueuedSubmission(
                    sub_id=f"{self._am.app_id}-sub{next(self._sub_seq)}",
                    plan=plan, tenant=tenant, recovery_data=recovery_data,
                    enqueued_at=clock.mono_s())
                ts.accepted += 1
                ts.queued += 1
                self._queue.append(sub)
                self._cond.notify_all()
                _flight.record(_flight.ADMIT, "queue", tenant,
                               a=len(self._queue), b=self._running)
            self._publish_gauges_locked()
        if shed_reason is not None:
            self._journal_shed(plan, tenant, shed_reason, depth, inflight)
            self._slo_tick()
            # snapshot AFTER the slo tick so a shed-forced breach lands in
            # the dump the acceptance assertions read
            _flight.auto_dump("am.admit.shed", scope=tenant)
            raise DAGRejectedError(
                shed_reason, retry_after_s=self.retry_after_ms / 1000.0,
                tenant=tenant, queue_depth=depth, tenant_inflight=inflight)
        if sub is None:                       # ACCEPT: start inline
            try:
                return self._am._start_dag(plan, recovery_data, tenant)
            except BaseException:
                self._rollback_start(tenant)
                raise
        # QUEUE: journal the parked plan FIRST (summary event, fsync'd) so
        # a consumer crash cannot silently drop it, then block.
        from tez_tpu.am.history import HistoryEvent, HistoryEventType
        try:
            self._am.history(HistoryEvent(
                HistoryEventType.DAG_QUEUED, dag_id=sub.sub_id,
                data={"dag_name": plan.name, "tenant": tenant,
                      "plan": plan.serialize().hex()}))
        except BaseException as e:
            # un-journaled = not accepted: the lossless ledger only covers
            # records that landed, so pull the park back out and surface a
            # typed verdict — AMCrashedError when the AM died under the
            # append (its journal fd closes mid-write), else the original
            with self._lock:
                try:
                    self._queue.remove(sub)
                    ts.queued -= 1
                    ts.accepted -= 1
                except ValueError:
                    pass     # consumer already popped it; it may promote
            if self._stopped:
                from tez_tpu.client.errors import AMCrashedError
                raise AMCrashedError(sub.sub_id,
                                     dag_name=plan.name) from e
            raise
        log.info("dag %s (tenant=%s): QUEUED as %s behind %d running",
                 plan.name, tenant or "<anon>", sub.sub_id, self._running)
        sub.done.wait()
        if sub.error is not None:
            raise sub.error
        return sub.dag_id

    def _shed_reason_locked(self, tenant: str, ts: _TenantStats,
                            dag_name: str) -> Optional[str]:
        try:
            faults.fire("am.admit.shed", f"{tenant or '<anon>'}/{dag_name}")
        except Exception as e:  # noqa: BLE001 — fault-forced verdict
            return f"fault-injected shed: {e!r}"
        if self.tenant_max_inflight > 0 and \
                ts.inflight() >= self.tenant_max_inflight:
            return (f"tenant over tez.am.session.tenant.max-inflight="
                    f"{self.tenant_max_inflight}")
        if self._store_pressure():
            return ("store host tier beyond "
                    "tez.am.session.admit.store-watermark")
        if self._running >= self.max_concurrent and \
                len(self._queue) >= self.queue_size:
            return (f"admission queue full "
                    f"(tez.am.session.queue-size={self.queue_size})")
        return None

    def _store_pressure(self) -> bool:
        """Host-tier occupancy gate, after one relief attempt (the PR-7
        relieve_* signals double as the admission pressure valve)."""
        from tez_tpu.shuffle.service import local_shuffle_service
        store = local_shuffle_service().buffer_store()
        if store is None:
            return False
        from tez_tpu.store.buffer_store import HOST
        cap = store.capacity(HOST)
        if cap <= 0:
            return False
        used = store.tier_bytes(HOST)
        if used <= cap * self.admit_watermark:
            return False
        store.relieve_host_pressure(int(used - cap * self.admit_watermark))
        return store.tier_bytes(HOST) > cap * self.admit_watermark

    def _rollback_start(self, tenant: str) -> None:
        with self._lock:
            self._running -= 1
            ts = self._tenants.get(tenant)
            if ts is not None:
                ts.running -= 1
                ts.failed += 1
            self._cond.notify_all()
            self._publish_gauges_locked()

    def _journal_shed(self, plan: Any, tenant: str, reason: str,
                      depth: int, inflight: int) -> None:
        from tez_tpu.am.history import HistoryEvent, HistoryEventType
        self._am.history(HistoryEvent(
            HistoryEventType.DAG_ADMISSION_SHED,
            data={"dag_name": plan.name, "tenant": tenant, "reason": reason,
                  "queue_depth": depth, "tenant_inflight": inflight,
                  "retry_after_ms": self.retry_after_ms}))
        log.warning("dag %s (tenant=%s): SHED (%s)", plan.name,
                    tenant or "<anon>", reason)

    # -- queue consumer -------------------------------------------------------
    def _consume(self) -> None:
        while True:
            with self._lock:
                self._cond.wait_for(
                    lambda: self._stopped or (
                        self._queue and self._running < self.max_concurrent))
                if self._stopped:
                    return
                sub = self._queue.popleft()
                ts = self._tenants.setdefault(sub.tenant, _TenantStats())
                ts.queued -= 1
                ts.running += 1
                self._running += 1
                self._draining = sub
                self._publish_gauges_locked()
            # fired OUTSIDE the lock and OUTSIDE any try: fail mode kills
            # this thread mid-drain with `sub` popped but not yet started —
            # the lossless-admission regression lever (the DAG_QUEUED ledger
            # record is the submission's only surviving trace, and
            # unresolved() still reports it)
            faults.fire("am.queue.delay", sub.sub_id)
            try:
                sub.dag_id = self._am._start_dag(
                    sub.plan, sub.recovery_data, sub.tenant,
                    sub_id=sub.sub_id)
            except BaseException as e:  # noqa: BLE001 — fail loudly, not drop
                log.exception("queued dag %s failed to start", sub.sub_id)
                sub.error = e
                self._rollback_start(sub.tenant)
            with self._lock:
                self._draining = None
                self._publish_gauges_locked()
            metrics.observe("am.admit.queue_wait",
                            (clock.mono_s() - sub.enqueued_at) * 1000.0)
            self._slo_tick()
            sub.done.set()

    # -- crash recovery -------------------------------------------------------
    def requeue(self, plan: Any, tenant: str, sub_id: str) -> None:
        """Re-park a journaled-but-unpromoted submission from a dead AM
        incarnation (docs/recovery.md).  Non-blocking — the original
        submitter is gone; it re-attaches and waits by dag name.  Keeps
        the ORIGINAL sub_id so the journal's queued/promoted pairing spans
        incarnations, and journals a ``DAG_REQUEUED_ON_RECOVERY`` record
        (plan included) so a second crash replays from THIS record."""
        tenant = str(tenant or "")
        sub = _QueuedSubmission(
            sub_id=sub_id, plan=plan, tenant=tenant, recovery_data=None,
            enqueued_at=clock.mono_s())
        with self._lock:
            # future fresh submissions must never collide with a replayed
            # sub_id: advance the sequence past the replayed number
            tail = sub_id.rsplit("-sub", 1)
            if len(tail) == 2 and tail[1].isdigit():
                nxt = next(self._sub_seq)
                if nxt <= int(tail[1]):
                    self._sub_seq = itertools.count(int(tail[1]) + 1)
                else:
                    self._sub_seq = itertools.count(nxt)
            ts = self._tenants.setdefault(tenant, _TenantStats())
            ts.accepted += 1
            ts.queued += 1
            self._queue.append(sub)
            self._cond.notify_all()
            _flight.record(_flight.ADMIT, "requeue", tenant,
                           a=len(self._queue), b=self._running)
            self._publish_gauges_locked()
        from tez_tpu.am.history import HistoryEvent, HistoryEventType
        self._am.history(HistoryEvent(
            HistoryEventType.DAG_REQUEUED_ON_RECOVERY, dag_id=sub_id,
            data={"dag_name": plan.name, "tenant": tenant,
                  "plan": plan.serialize().hex(),
                  "attempt": getattr(self._am, "attempt", 0)}))
        log.info("dag %s (tenant=%s): REQUEUED on recovery as %s",
                 plan.name, tenant or "<anon>", sub_id)

    def crash(self) -> None:
        """SIGKILL analog for tests/chaos: abandon the queue WITHOUT the
        graceful resolution ``stop()`` performs.  Parked submitters get a
        typed :class:`~tez_tpu.client.errors.AMCrashedError` (their
        ``DAG_QUEUED`` records stay unresolved in the journal — the
        successor incarnation replays them); nothing terminal is
        journaled."""
        from tez_tpu.client.errors import AMCrashedError
        with self._lock:
            self._stopped = True
            parked = list(self._queue)
            if self._draining is not None and \
                    not self._draining.done.is_set():
                parked.append(self._draining)
            self._queue.clear()
            self._cond.notify_all()
        for sub in parked:
            sub.error = AMCrashedError(
                sub.sub_id, dag_name=getattr(sub.plan, "name", ""))
            sub.done.set()

    # -- AM lifecycle hooks ---------------------------------------------------
    def on_dag_finished(self, tenant: str, final_name: str,
                        latency_ms: float) -> None:
        tenant = tenant or ""
        with self._lock:
            self._running -= 1
            ts = self._tenants.setdefault(tenant, _TenantStats())
            ts.running -= 1
            if final_name == "SUCCEEDED":
                ts.completed += 1
            else:
                ts.failed += 1
            self._cond.notify_all()
            self._publish_gauges_locked()
        # dynamic per-tenant name: decoded by chaos --tenant-storm and the
        # counter_diff tenant section straight from the registry
        metrics.observe(f"tenant.{tenant or 'default'}.dag.latency",
                        latency_ms)
        self._slo_tick()

    def _slo_tick(self) -> None:
        """Run one SLO watchdog sweep off the AM's own completion/shed
        ticks (pull-based: no timer thread, no new lock ordering)."""
        wd = getattr(self._am, "slo_watchdog", None)
        if wd is None:
            return
        with self._lock:
            stats = {t: ts.to_dict() for t, ts in self._tenants.items()}
        try:
            wd.evaluate(stats)
        except Exception:  # noqa: BLE001 — diagnostics never fail admission
            log.exception("SLO watchdog sweep failed")

    def stop(self) -> None:
        with self._lock:
            self._stopped = True
            parked = list(self._queue)
            self._queue.clear()
            self._cond.notify_all()
        for sub in parked:
            sub.error = RuntimeError(
                f"AM stopping; queued submission {sub.sub_id} not started")
            sub.done.set()
        self._consumer.join(timeout=5.0)

    def consumer_alive(self) -> bool:
        return self._consumer.is_alive()

    # -- observability --------------------------------------------------------
    def unresolved(self) -> List[str]:
        """Queued-or-draining submission ids not yet resolved to a dag_id
        or an error — what a crashed consumer leaves behind."""
        with self._lock:
            out = [s.sub_id for s in self._queue]
            if self._draining is not None and \
                    not self._draining.done.is_set():
                out.append(self._draining.sub_id)
            return out

    def queued_names(self) -> List[str]:
        """DAG names parked in the queue, arrival order (client re-attach
        probes these before declaring a DAG lost)."""
        with self._lock:
            out = [getattr(s.plan, "name", "") for s in self._queue]
            if self._draining is not None and \
                    not self._draining.done.is_set():
                out.append(getattr(self._draining.plan, "name", ""))
            return out

    def status(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "max_concurrent_dags": self.max_concurrent,
                "queue_size": self.queue_size,
                "queue_depth": len(self._queue),
                "running": self._running,
                "consumer_alive": self._consumer.is_alive(),
                "tenants": {t or "<anon>": ts.to_dict()
                            for t, ts in sorted(self._tenants.items())},
            }

    def _publish_gauges_locked(self) -> None:
        metrics.set_gauge("am.session.queue.depth", float(len(self._queue)))
        metrics.set_gauge("am.session.running_dags", float(self._running))
