"""Task-runtime estimators behind the speculation SPI.

Reference parity: tez-dag/.../dag/speculation/legacy/TaskRuntimeEstimator.java:56
(estimator contract), LegacyTaskRuntimeEstimator.java (progress-rate estimate
over start/end statistics), SimpleExponentialTaskRuntimeEstimator.java +
forecast/SimpleExponentialSmoothing.java (exponentially-smoothed progress
rate with stagnation detection), DataStatistics.java (mean/std/outlier).

The estimator is selected per vertex via ``tez.am.legacy.speculative.
estimator.class`` — a registry shorthand ("legacy", "simple_exponential")
or a fully-qualified class path.
"""
from __future__ import annotations

import math
import time
from typing import Dict, Optional

from tez_tpu.common import config as C


class DataStatistics:
    """Streaming mean/variance of completed-attempt durations
    (reference: DataStatistics.java)."""

    def __init__(self) -> None:
        self.count = 0
        self._sum = 0.0
        self._sumsq = 0.0

    def add(self, x: float) -> None:
        self.count += 1
        self._sum += x
        self._sumsq += x * x

    def mean(self) -> float:
        return self._sum / self.count if self.count else 0.0

    def var(self) -> float:
        if self.count <= 1:
            return 0.0
        m = self.mean()
        return max(0.0, self._sumsq / self.count - m * m)

    def std(self) -> float:
        return math.sqrt(self.var())

    def outlier(self, sigma: float) -> float:
        """Runtime above which an attempt counts as an outlier."""
        return self.mean() + self.std() * sigma


class TaskRuntimeEstimator:
    """SPI: per-vertex estimator the speculator consults
    (reference: TaskRuntimeEstimator.java:56)."""

    def contextualize(self, conf, vertex_name: str) -> None:
        self.conf = conf
        self.vertex_name = vertex_name

    def enroll(self, attempt_id: str, launch_time: float) -> None:
        """A new attempt started running."""
        raise NotImplementedError

    def update_attempt(self, attempt_id: str, progress: float,
                       timestamp: float) -> None:
        """A progress report arrived for a running attempt."""
        raise NotImplementedError

    def attempt_succeeded(self, duration: float) -> None:
        """A sibling attempt in this vertex completed in ``duration``s."""
        raise NotImplementedError

    def estimated_runtime(self, attempt_id: str,
                          now: float) -> Optional[float]:
        """Estimated TOTAL runtime (seconds since launch) of the attempt, or
        None when no estimate is possible yet."""
        raise NotImplementedError

    def estimated_new_attempt_runtime(self) -> Optional[float]:
        """Expected runtime of a freshly launched replacement attempt."""
        raise NotImplementedError

    def threshold_runtime(self, sigma: float) -> Optional[float]:
        """Runtime beyond which an attempt is a statistical straggler."""
        raise NotImplementedError

    def has_stagnated(self, attempt_id: str, now: float) -> bool:
        """True when the attempt stopped making progress entirely."""
        return False


class StartEndTimesBase(TaskRuntimeEstimator):
    """Shared bookkeeping: launch times + completed-duration statistics
    (reference: StartEndTimesBase.java)."""

    def __init__(self) -> None:
        self.launch_times: Dict[str, float] = {}
        self.stats = DataStatistics()

    def enroll(self, attempt_id: str, launch_time: float) -> None:
        self.launch_times.setdefault(attempt_id, launch_time)

    def attempt_succeeded(self, duration: float) -> None:
        self.stats.add(duration)

    def estimated_new_attempt_runtime(self) -> Optional[float]:
        if self.stats.count == 0:
            return None
        return self.stats.mean()

    def threshold_runtime(self, sigma: float) -> Optional[float]:
        if self.stats.count == 0:
            return None
        return self.stats.outlier(sigma)

    def forget(self, attempt_id: str) -> None:
        self.launch_times.pop(attempt_id, None)


class LegacyRuntimeEstimator(StartEndTimesBase):
    """Whole-lifetime progress rate: estimate = elapsed / progress
    (reference: LegacyTaskRuntimeEstimator.java)."""

    def __init__(self) -> None:
        super().__init__()
        self._progress: Dict[str, float] = {}

    def update_attempt(self, attempt_id: str, progress: float,
                       timestamp: float) -> None:
        self._progress[attempt_id] = progress

    def forget(self, attempt_id: str) -> None:
        super().forget(attempt_id)
        self._progress.pop(attempt_id, None)

    def estimated_runtime(self, attempt_id: str,
                          now: float) -> Optional[float]:
        launch = self.launch_times.get(attempt_id)
        if launch is None:
            return None
        elapsed = now - launch
        progress = max(self._progress.get(attempt_id, 0.0), 1e-6)
        return elapsed / progress


class _Smoothing:
    """Exponentially-smoothed progress RATE for one attempt (reference:
    forecast/SimpleExponentialSmoothing.java — alpha = 1 - exp(-dt/lambda),
    so old samples decay with a fixed time constant regardless of report
    cadence)."""

    def __init__(self, time_constant: float):
        self.time_constant = max(time_constant, 1e-3)
        self.samples = 0
        self.rate: Optional[float] = None
        self.last_progress = 0.0
        self.last_time: Optional[float] = None
        self.last_change_time: Optional[float] = None

    def update(self, progress: float, timestamp: float) -> None:
        if self.last_time is None:
            self.last_time = timestamp
            self.last_progress = progress
            self.last_change_time = timestamp
            return
        dt = timestamp - self.last_time
        if dt <= 0:
            return
        if progress > self.last_progress:
            self.last_change_time = timestamp
        raw = max(progress - self.last_progress, 0.0) / dt
        alpha = 1.0 - math.exp(-dt / self.time_constant)
        self.rate = raw if self.rate is None else \
            alpha * raw + (1.0 - alpha) * self.rate
        self.samples += 1
        self.last_time = timestamp
        self.last_progress = progress


class SimpleExponentialRuntimeEstimator(StartEndTimesBase):
    """Forecasts remaining time from the smoothed RECENT progress rate, so a
    task that started slowly but is now moving is not condemned by its
    lifetime average — and a stagnated task is, even if its average looks
    healthy (reference: SimpleExponentialTaskRuntimeEstimator.java:113-134)."""

    def __init__(self) -> None:
        super().__init__()
        self._smooth: Dict[str, _Smoothing] = {}

    def contextualize(self, conf, vertex_name: str) -> None:
        super().contextualize(conf, vertex_name)
        self.time_constant = conf.get(C.SPECULATION_SMOOTH_LAMBDA_MS) / 1e3
        self.stagnated_window = conf.get(C.SPECULATION_STAGNATED_MS) / 1e3
        self.skip_initials = conf.get(C.SPECULATION_SKIP_INITIALS)

    def update_attempt(self, attempt_id: str, progress: float,
                       timestamp: float) -> None:
        sm = self._smooth.get(attempt_id)
        if sm is None:
            sm = self._smooth[attempt_id] = _Smoothing(self.time_constant)
        sm.update(progress, timestamp)

    def forget(self, attempt_id: str) -> None:
        super().forget(attempt_id)
        self._smooth.pop(attempt_id, None)

    def has_stagnated(self, attempt_id: str, now: float) -> bool:
        sm = self._smooth.get(attempt_id)
        if sm is None or sm.last_change_time is None:
            return False
        return (now - sm.last_change_time) > self.stagnated_window

    def estimated_runtime(self, attempt_id: str,
                          now: float) -> Optional[float]:
        launch = self.launch_times.get(attempt_id)
        sm = self._smooth.get(attempt_id)
        if launch is None or sm is None:
            return None
        if self.has_stagnated(attempt_id, now):
            return math.inf
        if sm.rate is None or sm.samples < self.skip_initials:
            return None   # not enough signal yet — don't condemn early
        elapsed = now - launch
        remaining = max(1.0 - sm.last_progress, 0.0)
        rate = max(sm.rate, 1e-10)
        return elapsed + remaining / rate


_REGISTRY = {
    "legacy": LegacyRuntimeEstimator,
    "simple_exponential": SimpleExponentialRuntimeEstimator,
}


def create_estimator(conf, vertex_name: str) -> TaskRuntimeEstimator:
    name = conf.get(C.SPECULATION_ESTIMATOR)
    cls = _REGISTRY.get(name)
    if cls is None:
        from tez_tpu.common.payload import resolve_class
        cls = resolve_class(name)
    est = cls()
    est.contextualize(conf, vertex_name)
    return est
