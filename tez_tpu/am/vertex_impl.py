"""Vertex state machine: task creation, root-input init, vertex-manager
hosting, edge wiring, event routing, completion bookkeeping.

Reference parity: tez-dag/.../dag/impl/VertexImpl.java:218 (the reference's
single biggest class) — here split between this file and
vertex_manager_host.py.  Collapsed states: the reference's
NEW/INITIALIZING/INITED/RUNNING/COMMITTING/TERMINATING/... map onto
NEW -> INITIALIZING -> INITED -> RUNNING -> terminal, with commit handled at
the DAG level (default commit-on-DAG-success mode).
"""
from __future__ import annotations

import enum
import logging
from typing import Any, Dict, List, Optional, Sequence, Set, TYPE_CHECKING

from tez_tpu.api.events import (CustomProcessorEvent,
                                CompositeDataMovementEvent, DataMovementEvent,
                                InputDataInformationEvent, InputFailedEvent,
                                InputInitializerEvent, InputReadErrorEvent,
                                TezAPIEvent, TezEvent, VertexManagerEvent)
from tez_tpu.am.edge import EdgeImpl
from tez_tpu.am.events import (TaskAttemptEvent, TaskAttemptEventType,
                               TaskEvent, TaskEventType, VertexEvent,
                               VertexEventType, DAGEvent, DAGEventType)
from tez_tpu.am.history import HistoryEvent, HistoryEventType
from tez_tpu.am.task_impl import (TaskAttemptState, TaskImpl, TaskState,
                                  TERMINAL_TASK_STATES)
from tez_tpu.common import clock, config as C
from tez_tpu.common.counters import TezCounters
from tez_tpu.common.ids import TaskAttemptId, VertexId
from tez_tpu.common.statemachine import StateMachineFactory
from tez_tpu.dag.edge_property import DataMovementType, SchedulingType
from tez_tpu.dag.plan import VertexPlan
from tez_tpu.runtime.task_spec import (GroupInputSpec, InputSpec, OutputSpec,
                                       TaskSpec)

if TYPE_CHECKING:
    from tez_tpu.am.dag_impl import DAGImpl

log = logging.getLogger(__name__)


class VertexState(enum.Enum):
    NEW = enum.auto()
    INITIALIZING = enum.auto()
    INITED = enum.auto()
    RUNNING = enum.auto()
    SUCCEEDED = enum.auto()
    FAILED = enum.auto()
    KILLED = enum.auto()
    ERROR = enum.auto()


TERMINAL_VERTEX_STATES = frozenset(
    {VertexState.SUCCEEDED, VertexState.FAILED, VertexState.KILLED,
     VertexState.ERROR})


class VertexImpl:
    _factory: StateMachineFactory = None

    def __init__(self, vertex_id: VertexId, plan: VertexPlan, dag: "DAGImpl"):
        self.vertex_id = vertex_id
        self.plan = plan
        self.name = plan.name
        self.dag = dag
        self.ctx = dag.ctx
        self.conf = dag.conf.merged(plan.conf)
        self.num_tasks = plan.parallelism
        self.tasks: Dict[int, TaskImpl] = {}
        self.in_edges: Dict[str, EdgeImpl] = {}    # keyed by source vertex name
        self.out_edges: Dict[str, EdgeImpl] = {}   # keyed by dest vertex name
        self.group_input_specs: List[GroupInputSpec] = []
        self.priority = 0                          # set by DAG scheduler
        self.distance_from_root = 0
        self.counters = TezCounters()
        self.diagnostics: List[str] = []
        self.vertex_manager: Any = None            # VertexManagerHost
        self.completed_tasks = 0
        self.succeeded_tasks = 0
        self.failed_tasks = 0
        self.killed_tasks = 0
        self.scheduled_task_indices: Set[int] = set()
        self.init_time = 0.0
        self.start_time = 0.0
        self.finish_time = 0.0
        # root input machinery
        self.root_input_events: Dict[str, List[InputDataInformationEvent]] = {}
        self.pending_initializers: Set[str] = set()
        self.initializers: Dict[str, Any] = {}
        self.vm_tasks_scheduled = False
        self.start_requested = False
        self._recovered_tasks: Dict[int, Any] = {}  # task index -> journal data
        self._deferred_schedule: List[int] = []   # controlled-mode holdback
        import threading
        self._commit_lock = threading.Lock()  # commit vs abort serialization
        self.started_sources: Set[str] = set()
        self.completed_source_attempts: Set[TaskAttemptId] = set()
        self.sm = self._factory.make(self)

    # ------------------------------------------------------------------ util
    @property
    def state(self) -> VertexState:
        return self.sm.state

    def handle(self, event: VertexEvent) -> None:
        if self.state in TERMINAL_VERTEX_STATES:
            # A SUCCEEDED vertex still routes late events and can be pulled
            # back to RUNNING by output loss (reference: VertexImpl handles
            # V_TASK_RESCHEDULED from SUCCEEDED via VertexRerun).
            if self.state is VertexState.SUCCEEDED:
                if event.event_type is VertexEventType.V_ROUTE_EVENT:
                    self._on_route_event(event)
                elif event.event_type is VertexEventType.V_TASK_RESCHEDULED:
                    self.sm.force_state(self._on_task_rescheduled(event))
                elif event.event_type is VertexEventType.V_TASK_COMPLETED:
                    self.sm.force_state(self._on_task_completed(event))
            return
        if not self.sm.can_handle(event.event_type):
            log.debug("vertex %s: ignoring %s in %s", self.name,
                      event.event_type, self.state)
            return
        self.sm.handle(event)

    def task(self, index: int) -> TaskImpl:
        return self.tasks[index]

    def attempt(self, attempt_id: TaskAttemptId) -> Any:
        t = self.tasks.get(attempt_id.task_id.id)
        return t.attempt(attempt_id) if t else None

    def downstream_consumer_count(self, src_task: int) -> int:
        return sum(e.edge_manager.get_num_destination_consumer_tasks(src_task)
                   for e in self.out_edges.values())

    def progress(self) -> float:
        if not self.tasks:
            return 1.0 if self.state is VertexState.SUCCEEDED else 0.0
        return self.succeeded_tasks / len(self.tasks)

    # ------------------------------------------------------- initialization
    def _on_init(self, event: VertexEvent) -> VertexState:
        self.init_time = clock.wall_s()
        for spec in self.plan.root_inputs:
            if spec.initializer_descriptor is not None:
                self.pending_initializers.add(spec.name)
            elif spec.events:
                self.root_input_events[spec.name] = list(spec.events)
                if spec.parallelism >= 0 and self.num_tasks < 0:
                    self.num_tasks = spec.parallelism
        if self.pending_initializers:
            self._run_initializers()
            return VertexState.INITIALIZING
        return self._try_finish_init()

    def _run_initializers(self) -> None:
        """Reference: RootInputInitializerManager.java:82 — run initializers
        on an executor, feed events back through the dispatcher."""
        from tez_tpu.am.initializer_host import run_initializer
        for spec in self.plan.root_inputs:
            if spec.initializer_descriptor is None:
                continue
            run_initializer(self, spec)

    def _on_root_input_initialized(self, event: VertexEvent) -> VertexState:
        name = event.input_name
        events: List[Any] = event.events or []
        data_events: List[InputDataInformationEvent] = []
        for ev in events:
            from tez_tpu.api.initializer import InputConfigureVertexTasksEvent
            if isinstance(ev, InputConfigureVertexTasksEvent):
                if self.num_tasks < 0:
                    self.num_tasks = ev.num_tasks
            else:
                data_events.append(ev)
        # assign target indices round-robin by source index (reference:
        # RootInputVertexManager assigns event i -> task i)
        for i, ev in enumerate(data_events):
            if ev.target_index < 0:
                ev.target_index = i % max(1, self.num_tasks if self.num_tasks > 0
                                          else len(data_events))
        self.root_input_events[name] = data_events
        if self.num_tasks < 0 and len(data_events) > 0:
            self.num_tasks = len(data_events)
        self.pending_initializers.discard(name)
        if self.vertex_manager is not None:
            self.vertex_manager.on_root_vertex_initialized(
                name, self.plan.root_inputs, data_events)
        if self.pending_initializers:
            return VertexState.INITIALIZING
        return self._try_finish_init()

    def _on_root_input_failed(self, event: VertexEvent) -> VertexState:
        self.diagnostics.append(
            f"root input {getattr(event, 'input_name', '?')} failed: "
            f"{getattr(event, 'diagnostics', '')}")
        self._abort("FAILED")
        return VertexState.FAILED

    def _try_finish_init(self) -> VertexState:
        # ONE_TO_ONE edges inherit source parallelism when unset.
        if self.num_tasks < 0:
            for e in self.in_edges.values():
                if (e.edge_property.data_movement_type is DataMovementType.ONE_TO_ONE
                        and e.source_vertex.num_tasks >= 0):
                    self.num_tasks = e.source_vertex.num_tasks
                    break
        if self.num_tasks < 0:
            self.diagnostics.append("parallelism never determined")
            self._abort("FAILED")
            return VertexState.FAILED
        self._create_tasks()
        self._maybe_restore_reconfiguration()
        self._load_recovered_tasks()
        self._create_committers()
        self._create_vertex_manager()
        self.ctx.history(HistoryEvent(
            HistoryEventType.VERTEX_INITIALIZED,
            dag_id=str(self.vertex_id.dag_id), vertex_id=str(self.vertex_id),
            data={"vertex_name": self.name, "num_tasks": self.num_tasks}))
        self.dag.on_vertex_inited(self)
        # tell downstream vertices our parallelism is now real: anything
        # their schedule_tasks gate held back on this source can release
        for e in self.out_edges.values():
            self.ctx.dispatch(VertexEvent(
                VertexEventType.V_SOURCE_CONFIGURED,
                e.destination_vertex.vertex_id,
                source_vertex_name=self.name))
        if self.start_requested:
            return self._do_start()
        return VertexState.INITED

    def _create_tasks(self) -> None:
        for i in range(self.num_tasks):
            tid = self.vertex_id.task(i)
            self.tasks[i] = TaskImpl(tid, self)

    def _create_committers(self) -> None:
        """Instantiate + setup leaf-output committers in the AM (reference:
        VertexImpl OutputCommitter handling; commit itself runs at DAG
        success in the default commit mode)."""
        self.committers: Dict[str, Any] = {}
        from tez_tpu.api.initializer import SimpleCommitterContext

        for sink in self.plan.leaf_outputs:
            if sink.committer_descriptor is None:
                continue
            ctx = SimpleCommitterContext(
                sink.name, self.name, sink.committer_descriptor.payload,
                app_id=getattr(self.dag.ctx, "app_id", ""),
                am_epoch=getattr(self.dag.ctx, "attempt", 0))
            committer = sink.committer_descriptor.instantiate(ctx)
            committer.initialize()
            committer.setup_output()
            self.committers[sink.name] = committer

    def _maybe_restore_reconfiguration(self) -> None:
        """Re-apply a journaled auto-parallelism reconfiguration BEFORE task
        recovery, so the vertex's completed tasks remain restorable and the
        vertex manager does not re-decide (reference: recovered
        VertexConfigurationDoneEvent, RecoveryParser.java:658).  Any decode
        failure degrades to re-running the vertex from scratch."""
        rec = getattr(self.dag, "recovery_data", None)
        if rec is None:
            return
        rc = getattr(rec, "vertex_reconfig", {}).get(self.name)
        if rc is None:
            return
        from tez_tpu.am.recovery import (UntrustedJournalPayload,
                                          _payload_from_wire)
        from tez_tpu.common.payload import EdgeManagerPluginDescriptor
        from tez_tpu.dag.edge_property import EdgeProperty
        allow_pickle = bool(self.conf.get(C.RECOVERY_TRUSTED_STAGING))
        try:
            decoded = {}
            for src_name, ed in (rc.get("edges") or {}).items():
                decoded[src_name] = EdgeManagerPluginDescriptor.create(
                    ed["class_name"],
                    payload=_payload_from_wire(ed["payload"],
                                               allow_pickle=allow_pickle))
        except UntrustedJournalPayload as e:
            log.warning("vertex %s: journaled reconfiguration not restored "
                        "(%s); vertex re-runs and re-decides", self.name, e)
            return
        except Exception as e:  # noqa: BLE001 — corrupt journal entry must
            # degrade to a clean re-run, never fail the recovery
            log.warning("vertex %s: reconfiguration journal undecodable "
                        "(%s: %s); vertex re-runs", self.name,
                        type(e).__name__, e)
            return
        parallelism = rc.get("parallelism")
        if parallelism is not None and parallelism != self.num_tasks:
            self._recreate_tasks(parallelism)
        for src_name, desc in decoded.items():
            edge = self.in_edges.get(src_name)
            if edge is None:
                continue
            prop = edge.edge_property
            edge.edge_property = EdgeProperty.create_custom(
                desc, prop.data_source_type, prop.edge_source,
                prop.edge_destination, prop.scheduling_type)
            edge.set_edge_manager(desc)
        self._reconfig_restored = True
        self._reconfig_journal = rc   # re-journal on this attempt's
        # CONFIGURE_DONE so a THIRD AM attempt can restore it again
        log.info("vertex %s: restored journaled reconfiguration "
                 "(parallelism=%s, %d edges)", self.name, parallelism,
                 len(decoded))
        self.ctx.history_vertex_configured(self)

    def _load_recovered_tasks(self) -> None:
        """AM recovery: map journaled SUCCEEDED tasks onto this vertex's task
        indices.  Only valid when the vertex's parallelism matches what the
        journal recorded — a vertex whose auto-parallelism decision could
        differ this run re-executes from scratch (safe default)."""
        rec = getattr(self.dag, "recovery_data", None)
        if rec is None:
            return
        if self.name in getattr(rec, "committed_vertices", ()):
            # this vertex's per-vertex commit landed before the crash —
            # never run commit_output() a second time
            self._committed = True
        if not rec.task_data:
            return
        if rec.vertex_num_tasks.get(self.name) != self.num_tasks:
            return
        from tez_tpu.am.recovery import (UntrustedJournalPayload,
                                          event_from_wire)
        allow_pickle = bool(self.conf.get(C.RECOVERY_TRUSTED_STAGING))
        for i in range(self.num_tasks):
            td = rec.task_data.get(str(self.vertex_id.task(i)))
            if td is None:
                continue
            # Decode the journaled output events NOW: a task whose events
            # cannot be replayed (pickle-encoded journal without the
            # trusted-staging opt-in) is not restorable — short-circuiting
            # it while dropping events would leave consumers waiting on a
            # DME that never comes, so it re-runs normally instead.
            try:
                td = dict(td)
                td["decoded_events"] = [
                    (edge_name, event_from_wire(w, allow_pickle=allow_pickle))
                    for edge_name, w in td.get("generated_events", [])]
            except UntrustedJournalPayload as e:
                log.warning("vertex %s task %d: not restoring from journal "
                            "(%s); task will re-run", self.name, i, e)
                continue
            except Exception as e:  # noqa: BLE001 — a journal entry that
                # fails to decode for ANY reason (stale pickled class from an
                # older build, truncated/corrupt wire fields) must degrade to
                # re-running that task, never fail the whole DAG's recovery
                log.warning("vertex %s task %d: journal entry undecodable "
                            "(%s: %s); task will re-run", self.name, i,
                            type(e).__name__, e)
                continue
            self._recovered_tasks[i] = td
        if self._recovered_tasks:
            log.info("vertex %s: %d/%d tasks restorable from recovery journal",
                     self.name, len(self._recovered_tasks), self.num_tasks)

    def _recreate_tasks(self, new_parallelism: int) -> None:
        """Auto-parallelism reconfiguration before any task scheduled."""
        assert not self.scheduled_task_indices and \
            not self._deferred_schedule, \
            "cannot reconfigure after tasks scheduled (incl. held back)"
        self.num_tasks = new_parallelism
        self.tasks.clear()
        self._recovered_tasks.clear()   # indices no longer meaningful
        self._create_tasks()

    def _create_vertex_manager(self) -> None:
        from tez_tpu.am.vertex_manager_host import (VertexManagerHost,
                                                    pick_default_manager)
        desc = self.plan.vertex_manager
        if desc is None:
            desc = pick_default_manager(self)
        self.vertex_manager = VertexManagerHost(self, desc)
        self.vertex_manager.initialize()

    # ------------------------------------------------------------- start
    def _on_start(self, event: VertexEvent) -> VertexState:
        if self.state is VertexState.NEW or self.pending_initializers:
            self.start_requested = True
            return self.state
        return self._do_start()

    def _do_start(self) -> VertexState:
        self.start_time = clock.wall_s()
        self.ctx.history(HistoryEvent(
            HistoryEventType.VERTEX_STARTED,
            dag_id=str(self.vertex_id.dag_id), vertex_id=str(self.vertex_id),
            data={"vertex_name": self.name}))
        # tell downstream vertices their source started (slow-start triggers)
        for e in self.out_edges.values():
            self.ctx.dispatch(VertexEvent(
                VertexEventType.V_SOURCE_VERTEX_STARTED,
                e.destination_vertex.vertex_id, source_vertex_name=self.name))
        self.vertex_manager.on_vertex_started(
            sorted(self.completed_source_attempts))
        if self.num_tasks == 0:
            return self._check_complete() or VertexState.RUNNING
        return VertexState.RUNNING

    def _on_source_vertex_started(self, event: VertexEvent) -> None:
        self.started_sources.add(event.source_vertex_name)

    def _on_source_scheduled(self, event: VertexEvent) -> None:
        self._drain_deferred_schedule()

    def _on_source_configured(self, event: VertexEvent) -> None:
        self._drain_deferred_schedule()

    # ---------------------------------------------------------- scheduling
    def _sources_configured(self) -> bool:
        """Every source vertex has resolved parallelism.  Scheduling a task
        before this snapshots physical_input_count=-1 into its spec
        (build_task_spec reads num_dest_physical_inputs off the live source
        count) and the task completes empty — so schedule_tasks holds ALL
        requests, whatever manager issued them, until sources configure."""
        return all(e.source_vertex.num_tasks >= 0
                   for e in self.in_edges.values())

    def _sources_fully_scheduled(self) -> bool:
        """Controlled-scheduling gate (DAGSchedulerNaturalOrderControlled):
        every SEQUENTIAL source vertex must have scheduled ALL its tasks."""
        for e in self.in_edges.values():
            if e.edge_property.scheduling_type is not SchedulingType.SEQUENTIAL:
                continue
            src = e.source_vertex
            if src.num_tasks == 0:
                continue   # an empty source is trivially fully scheduled
            if src.num_tasks < 0 or \
                    len(src.scheduled_task_indices) < src.num_tasks:
                return False
        return True

    def _schedule_gate_open(self) -> bool:
        if self.in_edges and not self._sources_configured():
            return False
        if getattr(self, "controlled_scheduling", False) and \
                self.in_edges and not self._sources_fully_scheduled():
            return False
        return True

    def _drain_deferred_schedule(self) -> None:
        if self._deferred_schedule and self._schedule_gate_open():
            pending, self._deferred_schedule = self._deferred_schedule, []
            log.info("vertex %s: sources ready, releasing %d held tasks",
                     self.name, len(pending))
            self.schedule_tasks(pending)

    def schedule_tasks(self, task_indices: Sequence[int]) -> None:
        """Called by the vertex manager host (reference:
        VertexImpl.scheduleTasks:1775)."""
        self.vm_tasks_scheduled = True
        if not self._schedule_gate_open():
            seen = set(self._deferred_schedule)
            self._deferred_schedule.extend(
                i for i in task_indices
                if i not in self.scheduled_task_indices and i not in seen)
            return
        newly_scheduled = False
        for i in task_indices:
            if i in self.scheduled_task_indices:
                continue
            self.scheduled_task_indices.add(i)
            newly_scheduled = True
            recovered = self._recovered_tasks.get(i)
            if recovered is not None:
                self.ctx.dispatch(TaskEvent(TaskEventType.T_RECOVER,
                                            self.vertex_id.task(i),
                                            recovered=recovered))
            else:
                self.ctx.dispatch(TaskEvent(TaskEventType.T_SCHEDULE,
                                            self.vertex_id.task(i)))
        if newly_scheduled and self.num_tasks > 0 and \
                len(self.scheduled_task_indices) >= self.num_tasks:
            # we just became FULLY scheduled: release controlled downstream
            # holdbacks (one signal, not one per schedule_tasks call)
            for e in self.out_edges.values():
                dst = e.destination_vertex
                if getattr(dst, "controlled_scheduling", False):
                    self.ctx.dispatch(VertexEvent(
                        VertexEventType.V_SOURCE_SCHEDULED,
                        dst.vertex_id, source_vertex_name=self.name))

    # ------------------------------------------------- completion tracking
    def _on_task_completed(self, event: VertexEvent) -> VertexState:
        final_state: TaskState = event.final_state
        self.completed_tasks += 1
        if final_state is TaskState.SUCCEEDED:
            self.succeeded_tasks += 1
            task = self.tasks[event.task_id.id]
            att = task.successful_attempt_impl()
            if att is not None:
                self._notify_source_completion(att.attempt_id)
        elif final_state is TaskState.FAILED:
            self.failed_tasks += 1
            self.diagnostics.append(
                f"task {event.task_id} failed: {getattr(event, 'diagnostics', '')}")
            self._abort("FAILED", terminate_tasks=True)
            return VertexState.FAILED
        else:
            self.killed_tasks += 1
        res = self._check_complete()
        return res or VertexState.RUNNING

    def _notify_source_completion(self, attempt_id: TaskAttemptId) -> None:
        """Tell downstream vertex managers a source task finished."""
        for e in self.out_edges.values():
            self.ctx.dispatch(VertexEvent(
                VertexEventType.V_SOURCE_TASK_ATTEMPT_COMPLETED,
                e.destination_vertex.vertex_id, attempt_id=attempt_id,
                source_vertex_name=self.name))

    def _on_source_task_attempt_completed(self, event: VertexEvent) -> None:
        if event.attempt_id in self.completed_source_attempts:
            return
        self.completed_source_attempts.add(event.attempt_id)
        if self.vertex_manager is not None:
            self.vertex_manager.on_source_task_completed(event.attempt_id)

    def _on_task_rescheduled(self, event: VertexEvent) -> VertexState:
        """A SUCCEEDED task is re-running (output loss): tell consumers to
        discard the dead attempt's outputs (reference: InputFailedEvent
        routing on source-attempt output failure)."""
        self.completed_tasks -= 1
        self.succeeded_tasks -= 1
        failed_version = getattr(event, "failed_version", 0)
        for edge in self.out_edges.values():
            edge.add_source_event(event.task_id.id, failed_version,
                                  InputFailedEvent(target_index=-1,
                                                   version=failed_version))
        if self.state is VertexState.SUCCEEDED:
            self.dag.on_vertex_rerunning(self)
        return VertexState.RUNNING

    def _check_complete(self) -> Optional[VertexState]:
        if self.completed_tasks >= len(self.tasks) and \
                self.succeeded_tasks == len(self.tasks):
            # per-vertex commit mode (reference: VertexImpl commit when
            # tez.am.commit-all-outputs-on-dag-success is false): commit this
            # vertex's outputs NOW, off the dispatcher; completion arrives
            # back as V_COMMIT_COMPLETED
            if self._committing:
                return None     # commit already in flight: its completion
                # event decides the outcome; a re-entrant completion (e.g.
                # after an output-loss rerun) must not bypass it
            if not self.conf.get("tez.am.commit-all-outputs-on-dag-success",
                                 True) and getattr(self, "committers", None) \
                    and not self._committed:
                self._committing = True
                self.ctx.history(HistoryEvent(
                    HistoryEventType.VERTEX_COMMIT_STARTED,
                    dag_id=str(self.vertex_id.dag_id),
                    vertex_id=str(self.vertex_id),
                    data={"vertex_name": self.name}))

                def _commit() -> None:
                    try:
                        with self._commit_lock:   # serialize vs abort
                            if self._aborted:
                                # the vertex was killed/failed first and its
                                # outputs aborted — committing now would
                                # publish a dead vertex's output
                                ok, diag = False, "vertex aborted before " \
                                    "commit ran"
                            else:
                                for committer in self.committers.values():
                                    committer.commit_output()
                                # set INSIDE the lock: a racing abort must
                                # see the commit landed and leave it alone
                                self._committed = True
                                ok, diag = True, ""
                    except BaseException as e:  # noqa: BLE001
                        log.exception("vertex %s: commit failed", self.name)
                        ok, diag = False, repr(e)
                    self.ctx.dispatch(VertexEvent(
                        VertexEventType.V_COMMIT_COMPLETED, self.vertex_id,
                        succeeded=ok, diagnostics=diag))

                self.ctx.submit_to_executor(_commit)
                return None     # stay RUNNING until the commit lands
            return self._finish_succeeded()
        if self.completed_tasks >= len(self.tasks) and self.killed_tasks > 0:
            self._abort("KILLED")
            return VertexState.KILLED
        return None

    _committing = False
    _committed = False
    _aborted = False

    def _finish_succeeded(self) -> VertexState:
        self.finish_time = clock.wall_s()
        self.counters = TezCounters()  # fresh roll-up (vertex may rerun)
        for t in self.tasks.values():
            att = t.successful_attempt_impl()
            if att is not None:
                self.counters.aggregate(att.counters)
        self.ctx.history(HistoryEvent(
            HistoryEventType.VERTEX_FINISHED,
            dag_id=str(self.vertex_id.dag_id),
            vertex_id=str(self.vertex_id),
            data={"vertex_name": self.name, "state": "SUCCEEDED",
                  "num_tasks": self.num_tasks,
                  "time_taken": self.finish_time - (self.start_time or
                                                    self.finish_time),
                  "counters": self.counters.to_dict()}))
        # a finished source is definitionally fully scheduled: release any
        # controlled downstream holdback (covers 0-task sources, which never
        # emit the schedule-time signal)
        for e in self.out_edges.values():
            dst = e.destination_vertex
            if getattr(dst, "controlled_scheduling", False):
                self.ctx.dispatch(VertexEvent(
                    VertexEventType.V_SOURCE_SCHEDULED, dst.vertex_id,
                    source_vertex_name=self.name))
        self.dag.on_vertex_completed(self, VertexState.SUCCEEDED)
        return VertexState.SUCCEEDED

    def _on_commit_completed(self, event: VertexEvent) -> VertexState:
        """Per-vertex commit finished (reference: commit failure fails the
        vertex, not just the DAG)."""
        self._committing = False
        if getattr(event, "succeeded", False):
            self._committed = True
            # an output-loss reschedule may have landed while the commit was
            # in flight: only finish if every task is still complete (the
            # rerun's completion re-enters _check_complete, which sees
            # _committed and finishes without re-committing)
            if self.completed_tasks >= len(self.tasks) and \
                    self.succeeded_tasks == len(self.tasks):
                return self._finish_succeeded()
            return VertexState.RUNNING
        self.diagnostics.append(
            f"output commit failed: {getattr(event, 'diagnostics', '')}")
        self._abort("FAILED")
        return VertexState.FAILED

    def _abort(self, final: str, terminate_tasks: bool = False) -> None:
        self.finish_time = clock.wall_s()
        # per-vertex commit mode: this vertex's outputs never committed —
        # abort them (committed vertices stay committed; reference does not
        # roll back per-vertex commits on later DAG failure).  The commit
        # lock serializes against an in-flight commit_output on the executor.
        if not self.conf.get("tez.am.commit-all-outputs-on-dag-success",
                             True) and not self._committed:
            with self._commit_lock:
                self._aborted = True   # a queued commit must not run later
                if not self._committed:
                    for name, committer in getattr(self, "committers",
                                                   {}).items():
                        try:
                            committer.abort_output(final)
                        except BaseException:  # noqa: BLE001
                            log.exception("abort of %s:%s failed",
                                          self.name, name)
        if terminate_tasks:
            for t in self.tasks.values():
                if t.state not in TERMINAL_TASK_STATES:
                    self.ctx.dispatch(TaskEvent(TaskEventType.T_TERMINATE,
                                                t.task_id))
        self.ctx.history(HistoryEvent(
            HistoryEventType.VERTEX_FINISHED,
            dag_id=str(self.vertex_id.dag_id), vertex_id=str(self.vertex_id),
            data={"vertex_name": self.name, "state": final,
                  "diagnostics": "; ".join(self.diagnostics)}))
        self.dag.on_vertex_completed(
            self, VertexState[final] if final in VertexState.__members__
            else VertexState.FAILED)

    def _on_terminate(self, event: VertexEvent) -> VertexState:
        diag = getattr(event, "diagnostics", "vertex terminated")
        self.diagnostics.append(diag)
        live = [t for t in self.tasks.values()
                if t.state not in TERMINAL_TASK_STATES]
        if not live:
            self._abort("KILLED")
            return VertexState.KILLED
        for t in live:
            self.ctx.dispatch(TaskEvent(TaskEventType.T_TERMINATE, t.task_id,
                                        diagnostics=diag))
        return VertexState.RUNNING if self.state is VertexState.RUNNING \
            else VertexState.KILLED

    def _on_manager_error(self, event: VertexEvent) -> VertexState:
        self.diagnostics.append(
            f"vertex manager error: {getattr(event, 'diagnostics', '')}")
        self._abort("FAILED", terminate_tasks=True)
        return VertexState.FAILED

    # ------------------------------------------------------- event routing
    def _on_route_event(self, event: VertexEvent) -> None:
        """Route one task-generated TezEvent (reference: VertexImpl event
        routing + Edge.sendTezEventToDestinationTasks)."""
        tez_event: TezEvent = event.tez_event
        ev = tez_event.event
        src = tez_event.source_info
        attempt_id: Optional[TaskAttemptId] = src.task_attempt_id if src else None
        src_task = attempt_id.task_id.id if attempt_id else -1
        version = attempt_id.id if attempt_id else 0

        if isinstance(ev, (DataMovementEvent, CompositeDataMovementEvent)):
            edge = self.out_edges.get(src.edge_vertex_name) if src else None
            if edge is None:
                log.warning("vertex %s: DME for unknown edge %s", self.name,
                            src.edge_vertex_name if src else None)
                return
            edge.add_source_event(src_task, version, ev)
            # Remember what this attempt generated: journaled on success so AM
            # recovery can re-route without re-running (taGeneratedEvents).
            task = self.tasks.get(src_task)
            att = task.attempts.get(version) if task is not None else None
            if att is not None:
                att.generated_events.append((src.edge_vertex_name, ev))
            self.dag.notify_new_edge_events(edge)
        elif isinstance(ev, InputFailedEvent):
            edge = self.out_edges.get(src.edge_vertex_name) if src else None
            if edge is not None:
                edge.add_source_event(src_task, version, ev)
        elif isinstance(ev, VertexManagerEvent):
            target = self.dag.vertex_by_name(ev.target_vertex_name)
            if target is not None and target.vertex_manager is not None:
                ev.producer_attempt = attempt_id
                ev.producer_vertex_name = src.task_vertex_name if src else ""
                target.vertex_manager.on_vertex_manager_event(ev)
        elif isinstance(ev, InputReadErrorEvent):
            self._handle_input_read_error(ev, src, src_task)
        elif isinstance(ev, InputInitializerEvent):
            target = self.dag.vertex_by_name(ev.target_vertex_name)
            if target is not None:
                from tez_tpu.am.initializer_host import deliver_initializer_event
                deliver_initializer_event(target, ev)
        elif isinstance(ev, CustomProcessorEvent):
            pass  # delivered directly to processors via task pull
        else:
            log.warning("vertex %s: unroutable event %r", self.name, ev)

    def _handle_input_read_error(self, ev: InputReadErrorEvent,
                                 src: Any, consumer_task: int) -> None:
        """Fetch failure: blame the producer attempt (§3.5)."""
        edge = self.in_edges.get(src.edge_vertex_name) if src else None
        if edge is None:
            return
        src_task_idx = edge.route_input_error_to_source(consumer_task, ev.index)
        producer_vertex: VertexImpl = edge.source_vertex
        task = producer_vertex.tasks.get(src_task_idx)
        if task is None:
            return
        target_attempt = task.task_id.attempt(ev.version)
        self.ctx.dispatch(TaskAttemptEvent(
            TaskAttemptEventType.TA_OUTPUT_FAILED, target_attempt,
            consumer_task_index=consumer_task,
            is_local_fetch=ev.is_local_fetch,
            is_disk_error_at_source=ev.is_disk_error_at_source,
            diagnostics=ev.diagnostics))

    # ------------------------------------------------ consumer event pull
    def get_task_events(self, task_index: int,
                        seqs: Dict[str, int],
                        max_events: int = 0) -> List[tuple]:
        """Pull routed events for one of this vertex's tasks as
        (input_name, event) pairs.  ``seqs`` maps in-edge id -> consumed
        high-water mark, updated in place.  ``max_events`` > 0 bounds one
        pull (tez.task.max-event-backlog): the high-water marks only
        advance past what was returned, so the remainder arrives on later
        heartbeats instead of one giant response."""
        out: List[tuple] = []
        for edge in self.in_edges.values():
            if max_events and len(out) >= max_events:
                return out
            seq = seqs.get(edge.id, 0)
            limit = max_events - len(out) if max_events else 0
            events, new_seq = edge.get_events_for_task(task_index, seq,
                                                       max_events=limit)
            seqs[edge.id] = new_seq
            out.extend((edge.source_vertex.name, e) for e in events)
        # root input events, delivered once
        key = "__root__"
        if not seqs.get(key):
            for name, events in self.root_input_events.items():
                for ev in events:
                    if ev.target_index == task_index:
                        out.append((name, ev))
            seqs[key] = 1
        return out

    # ---------------------------------------------------------- task specs
    def build_task_spec(self, attempt_id: TaskAttemptId) -> TaskSpec:
        task_idx = attempt_id.task_id.id
        inputs: List[InputSpec] = []
        for e in self.in_edges.values():
            inputs.append(InputSpec(
                source_vertex_name=e.source_vertex.name,
                input_descriptor=e.edge_property.edge_destination,
                physical_input_count=e.num_dest_physical_inputs(task_idx),
                is_root_input=False))
        for spec in self.plan.root_inputs:
            inputs.append(InputSpec(
                source_vertex_name=spec.name,
                input_descriptor=spec.input_descriptor,
                physical_input_count=1, is_root_input=True))
        outputs: List[OutputSpec] = []
        for e in self.out_edges.values():
            outputs.append(OutputSpec(
                destination_vertex_name=e.destination_vertex.name,
                output_descriptor=e.edge_property.edge_source,
                physical_output_count=e.num_source_physical_outputs(task_idx),
                is_leaf_output=False))
        for sink in self.plan.leaf_outputs:
            outputs.append(OutputSpec(
                destination_vertex_name=sink.name,
                output_descriptor=sink.output_descriptor,
                physical_output_count=1, is_leaf_output=True))
        return TaskSpec(
            attempt_id=attempt_id,
            dag_name=self.dag.name,
            vertex_name=self.name,
            vertex_parallelism=self.num_tasks,
            processor_descriptor=self.plan.processor,
            inputs=tuple(inputs),
            outputs=tuple(outputs),
            group_inputs=tuple(self.group_input_specs),
            conf=dict(self.conf),
            am_epoch=getattr(self.dag.ctx, "attempt", 0),
            trace_context=getattr(self.dag, "trace_carrier", ""),
            lineage=getattr(self.dag, "lineage_hashes", {}).get(self.name,
                                                                ""),
            tenant=getattr(self.dag, "tenant", ""),
            window_id=int(self.conf.get(C.STREAM_WINDOW_ID) or 0),
            stream=str(self.conf.get(C.STREAM_ID) or ""),
        )

    def status_dict(self) -> Dict[str, Any]:
        running = sum(1 for t in self.tasks.values()
                      if t.state is TaskState.RUNNING)
        return {
            "name": self.name, "state": self.state.name,
            "total_tasks": len(self.tasks), "succeeded": self.succeeded_tasks,
            "running": running, "failed": self.failed_tasks,
            "killed": self.killed_tasks,
            "progress": self.progress(),
            "diagnostics": list(self.diagnostics),
        }


def _build_vertex_factory() -> StateMachineFactory:
    S, E = VertexState, VertexEventType
    f = StateMachineFactory(S.NEW)
    # SUCCEEDED is reachable directly when a (possibly initializer-provided)
    # 0-task vertex starts: _do_start -> _check_complete finishes it
    f.add_multi(S.NEW, (S.INITIALIZING, S.INITED, S.FAILED, S.RUNNING,
                        S.SUCCEEDED),
                E.V_INIT, VertexImpl._on_init)
    f.add_multi(S.NEW, (S.NEW,), E.V_START, VertexImpl._on_start)
    f.add(S.NEW, S.NEW, E.V_SOURCE_VERTEX_STARTED,
          VertexImpl._on_source_vertex_started)
    f.add(S.NEW, S.KILLED, E.V_TERMINATE, VertexImpl._on_terminate)

    f.add_multi(S.INITIALIZING, (S.INITIALIZING, S.INITED, S.FAILED,
                                 S.RUNNING, S.SUCCEEDED),
                E.V_ROOT_INPUT_INITIALIZED, VertexImpl._on_root_input_initialized)
    f.add_multi(S.INITIALIZING, (S.FAILED,), E.V_ROOT_INPUT_FAILED,
                VertexImpl._on_root_input_failed)
    f.add_multi(S.INITIALIZING, (S.INITIALIZING,), E.V_START,
                VertexImpl._on_start)
    f.add(S.INITIALIZING, S.INITIALIZING, E.V_SOURCE_VERTEX_STARTED,
          VertexImpl._on_source_vertex_started)
    f.add(S.INITIALIZING, S.KILLED, E.V_TERMINATE, VertexImpl._on_terminate)

    f.add_multi(S.INITED, (S.RUNNING, S.SUCCEEDED), E.V_START,
                VertexImpl._on_start)
    f.add(S.INITED, S.INITED, E.V_SOURCE_VERTEX_STARTED,
          VertexImpl._on_source_vertex_started)
    f.add(S.INITED, S.INITED, E.V_SOURCE_TASK_ATTEMPT_COMPLETED,
          VertexImpl._on_source_task_attempt_completed)
    f.add(S.INITED, S.INITED, E.V_ROUTE_EVENT, VertexImpl._on_route_event)
    f.add(S.INITED, S.KILLED, E.V_TERMINATE, VertexImpl._on_terminate)
    f.add_multi(S.INITED, (S.FAILED,), E.V_MANAGER_USER_CODE_ERROR,
                VertexImpl._on_manager_error)

    f.add_multi(S.RUNNING, (S.RUNNING, S.SUCCEEDED, S.FAILED, S.KILLED),
                E.V_TASK_COMPLETED, VertexImpl._on_task_completed)
    f.add_multi(S.RUNNING, (S.RUNNING,), E.V_TASK_RESCHEDULED,
                VertexImpl._on_task_rescheduled)
    f.add(S.RUNNING, S.RUNNING, E.V_ROUTE_EVENT, VertexImpl._on_route_event)
    f.add(S.RUNNING, S.RUNNING, E.V_SOURCE_TASK_ATTEMPT_COMPLETED,
          VertexImpl._on_source_task_attempt_completed)
    f.add(S.RUNNING, S.RUNNING, E.V_SOURCE_VERTEX_STARTED,
          VertexImpl._on_source_vertex_started)
    f.add(S.RUNNING, S.RUNNING, E.V_SOURCE_SCHEDULED,
          VertexImpl._on_source_scheduled)
    f.add(S.INITED, S.INITED, E.V_SOURCE_SCHEDULED,
          VertexImpl._on_source_scheduled)
    # a source vertex resolved its parallelism: release held schedules.
    # Registered across pre-terminal states — the signal can land while
    # the destination is still initializing (no-op then; the gate re-checks
    # live source counts whenever schedule_tasks runs).
    f.add(S.NEW, S.NEW, E.V_SOURCE_CONFIGURED,
          VertexImpl._on_source_configured)
    f.add(S.INITIALIZING, S.INITIALIZING, E.V_SOURCE_CONFIGURED,
          VertexImpl._on_source_configured)
    f.add(S.INITED, S.INITED, E.V_SOURCE_CONFIGURED,
          VertexImpl._on_source_configured)
    f.add(S.RUNNING, S.RUNNING, E.V_SOURCE_CONFIGURED,
          VertexImpl._on_source_configured)
    f.add_multi(S.RUNNING, (S.RUNNING, S.KILLED), E.V_TERMINATE,
                VertexImpl._on_terminate)
    f.add_multi(S.RUNNING, (S.FAILED,), E.V_MANAGER_USER_CODE_ERROR,
                VertexImpl._on_manager_error)
    f.add_multi(S.RUNNING, (S.SUCCEEDED, S.FAILED), E.V_COMMIT_COMPLETED,
                VertexImpl._on_commit_completed)
    # SUCCEEDED vertices can still route events (late consumers) and see
    # task reschedules — handled via handle() terminal-state guard override:
    return f


VertexImpl._factory = _build_vertex_factory()
