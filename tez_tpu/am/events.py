"""Orchestrator-internal events flowing through the central dispatcher.

Reference parity: tez-dag/.../dag/event/ (DAGEvent*, VertexEvent*, TaskEvent*,
TaskAttemptEvent*, AMSchedulerEvent*...).  One enum class per state-machine
family — the dispatcher routes on the enum class (AsyncDispatcher model).
"""
from __future__ import annotations

import enum
from typing import Any, List, Optional, Sequence

from tez_tpu.common.dispatcher import Event
from tez_tpu.common.ids import ContainerId, DAGId, TaskAttemptId, TaskId, VertexId


# -- DAG ---------------------------------------------------------------------
class DAGEventType(enum.Enum):
    DAG_INIT = enum.auto()
    DAG_START = enum.auto()
    DAG_VERTEX_COMPLETED = enum.auto()
    DAG_VERTEX_RERUNNING = enum.auto()
    DAG_COMPLETED = enum.auto()          # internal: all vertices done
    DAG_KILL = enum.auto()
    DAG_COMMIT_COMPLETED = enum.auto()
    INTERNAL_ERROR = enum.auto()


class DAGEvent(Event):
    def __init__(self, event_type: DAGEventType, dag_id: DAGId, **kw: Any):
        super().__init__(event_type)
        self.dag_id = dag_id
        self.__dict__.update(kw)


# -- Vertex ------------------------------------------------------------------
class VertexEventType(enum.Enum):
    V_INIT = enum.auto()
    V_START = enum.auto()
    V_SOURCE_TASK_ATTEMPT_COMPLETED = enum.auto()
    V_SOURCE_VERTEX_STARTED = enum.auto()
    V_ROOT_INPUT_INITIALIZED = enum.auto()
    V_ROOT_INPUT_FAILED = enum.auto()
    V_TASK_COMPLETED = enum.auto()
    V_TASK_RESCHEDULED = enum.auto()
    V_TASK_ATTEMPT_COMPLETED = enum.auto()
    V_ROUTE_EVENT = enum.auto()
    V_MANAGER_USER_CODE_ERROR = enum.auto()
    V_TERMINATE = enum.auto()
    V_COMPLETED = enum.auto()            # internal bookkeeping check
    V_COMMIT_COMPLETED = enum.auto()     # per-vertex commit mode result
    V_SOURCE_SCHEDULED = enum.auto()     # controlled-mode holdback release
    V_SOURCE_CONFIGURED = enum.auto()    # source parallelism resolved
    V_RECONFIGURE_DONE = enum.auto()


class VertexEvent(Event):
    def __init__(self, event_type: VertexEventType, vertex_id: VertexId, **kw: Any):
        super().__init__(event_type)
        self.vertex_id = vertex_id
        self.__dict__.update(kw)


# -- Task --------------------------------------------------------------------
class TaskEventType(enum.Enum):
    T_SCHEDULE = enum.auto()
    T_RECOVER = enum.auto()              # AM recovery: restore SUCCEEDED task
    T_ATTEMPT_LAUNCHED = enum.auto()
    T_ATTEMPT_SUCCEEDED = enum.auto()
    T_ATTEMPT_FAILED = enum.auto()
    T_ATTEMPT_KILLED = enum.auto()
    T_ADD_SPEC_ATTEMPT = enum.auto()     # speculation: launch extra attempt
    T_TERMINATE = enum.auto()


class TaskEvent(Event):
    def __init__(self, event_type: TaskEventType, task_id: TaskId, **kw: Any):
        super().__init__(event_type)
        self.task_id = task_id
        self.__dict__.update(kw)


# -- TaskAttempt -------------------------------------------------------------
class TaskAttemptEventType(enum.Enum):
    TA_SCHEDULE = enum.auto()
    TA_SUBMITTED = enum.auto()           # container assigned, launch requested
    TA_STARTED_REMOTELY = enum.auto()    # runner picked the task up
    TA_STATUS_UPDATE = enum.auto()
    TA_DONE = enum.auto()
    TA_FAILED = enum.auto()
    TA_TIMED_OUT = enum.auto()
    TA_KILL_REQUEST = enum.auto()
    TA_CONTAINER_TERMINATED = enum.auto()
    TA_OUTPUT_FAILED = enum.auto()       # consumer reported fetch failure
    TA_TEZ_EVENT_UPDATE = enum.auto()


class TaskAttemptEvent(Event):
    def __init__(self, event_type: TaskAttemptEventType,
                 attempt_id: TaskAttemptId, **kw: Any):
        super().__init__(event_type)
        self.attempt_id = attempt_id
        self.__dict__.update(kw)


# -- Scheduler / launcher ----------------------------------------------------
class SchedulerEventType(enum.Enum):
    S_TA_LAUNCH_REQUEST = enum.auto()
    S_TA_ENDED = enum.auto()
    S_CONTAINER_ALLOCATED = enum.auto()
    S_CONTAINER_COMPLETED = enum.auto()
    S_NODE_BLACKLISTED = enum.auto()


class SchedulerEvent(Event):
    def __init__(self, event_type: SchedulerEventType, **kw: Any):
        super().__init__(event_type)
        self.__dict__.update(kw)


class LauncherEventType(enum.Enum):
    LAUNCH_REQUEST = enum.auto()
    STOP_REQUEST = enum.auto()


class LauncherEvent(Event):
    def __init__(self, event_type: LauncherEventType,
                 container_id: ContainerId, **kw: Any):
        super().__init__(event_type)
        self.container_id = container_id
        self.__dict__.update(kw)


# -- Speculator --------------------------------------------------------------
class SpeculatorEventType(enum.Enum):
    S_ATTEMPT_STATUS_UPDATE = enum.auto()
    S_TASK_SUCCEEDED = enum.auto()


class SpeculatorEvent(Event):
    def __init__(self, event_type: SpeculatorEventType, **kw: Any):
        super().__init__(event_type)
        self.__dict__.update(kw)
