"""AM-side edge: routes producer events to consumer tasks on demand.

Reference parity: tez-dag/.../dag/impl/Edge.java:72 with on-demand (pull)
routing (:151) as the only mode — SURVEY.md §7's event-storm lesson — plus
the stock edge managers ScatterGatherEdgeManager, BroadcastEdgeManager,
OneToOneEdgeManagerOnDemand (tez-dag/.../dag/impl/).

Producers append events (in completion order); each consumer task pulls the
suffix it hasn't seen, and routing metadata is computed per (src,dst) pair at
pull time — O(pulled events), never O(src*dst) materialized up front.
"""
from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional, Sequence, Tuple

from tez_tpu.api.edge_manager import (CompositeEventRouteMetadata,
                                      EdgeManagerPluginContext,
                                      EdgeManagerPluginOnDemand,
                                      EventRouteMetadata)
from tez_tpu.api.events import (CompositeDataMovementEvent,
                                CompositeRoutedDataMovementEvent,
                                DataMovementEvent, InputFailedEvent,
                                TezAPIEvent)
from tez_tpu.common.payload import UserPayload
from tez_tpu.dag.edge_property import DataMovementType, EdgeProperty


class ScatterGatherEdgeManager(EdgeManagerPluginOnDemand):
    """Source task produces one partition per destination task; destination d
    reads partition d of every source task (reference:
    ScatterGatherEdgeManager.java)."""

    def initialize(self) -> None:
        pass

    def get_num_destination_task_physical_inputs(self, dest_task: int) -> int:
        return self.context.source_vertex_num_tasks

    def get_num_source_task_physical_outputs(self, src_task: int) -> int:
        return self.context.destination_vertex_num_tasks

    def get_num_destination_consumer_tasks(self, src_task: int) -> int:
        return self.context.destination_vertex_num_tasks

    def route_data_movement_event_to_destination(
            self, src_task: int, src_output_index: int, dest_task: int
    ) -> Optional[EventRouteMetadata]:
        if src_output_index != dest_task:
            return None
        return EventRouteMetadata(1, (src_task,), (src_output_index,))

    def route_composite_data_movement_event_to_destination(
            self, src_task: int, dest_task: int
    ) -> Optional[CompositeEventRouteMetadata]:
        # Producer emitted partitions [0, P); partition dest_task lands at
        # input index src_task of the destination.
        return CompositeEventRouteMetadata(1, src_task, dest_task)

    def route_input_source_task_failed_event_to_destination(
            self, src_task: int, dest_task: int) -> Optional[EventRouteMetadata]:
        return EventRouteMetadata(1, (src_task,))

    def route_input_error_event_to_source(self, dest_task: int,
                                          dest_failed_input_index: int) -> int:
        return dest_failed_input_index


class BroadcastEdgeManager(EdgeManagerPluginOnDemand):
    """Every source output goes to all destination tasks (reference:
    BroadcastEdgeManager.java)."""

    def initialize(self) -> None:
        pass

    def get_num_destination_task_physical_inputs(self, dest_task: int) -> int:
        return self.context.source_vertex_num_tasks

    def get_num_source_task_physical_outputs(self, src_task: int) -> int:
        return 1

    def get_num_destination_consumer_tasks(self, src_task: int) -> int:
        return self.context.destination_vertex_num_tasks

    def route_data_movement_event_to_destination(
            self, src_task: int, src_output_index: int, dest_task: int
    ) -> Optional[EventRouteMetadata]:
        return EventRouteMetadata(1, (src_task,), (src_output_index,))

    def route_composite_data_movement_event_to_destination(
            self, src_task: int, dest_task: int
    ) -> Optional[CompositeEventRouteMetadata]:
        return CompositeEventRouteMetadata(1, src_task, 0)

    def route_input_source_task_failed_event_to_destination(
            self, src_task: int, dest_task: int) -> Optional[EventRouteMetadata]:
        return EventRouteMetadata(1, (src_task,))

    def route_input_error_event_to_source(self, dest_task: int,
                                          dest_failed_input_index: int) -> int:
        return dest_failed_input_index


class OneToOneEdgeManager(EdgeManagerPluginOnDemand):
    """Pointwise: src i -> dst i (reference: OneToOneEdgeManagerOnDemand)."""

    def initialize(self) -> None:
        pass

    def get_num_destination_task_physical_inputs(self, dest_task: int) -> int:
        return 1

    def get_num_source_task_physical_outputs(self, src_task: int) -> int:
        return 1

    def get_num_destination_consumer_tasks(self, src_task: int) -> int:
        return 1

    def route_data_movement_event_to_destination(
            self, src_task: int, src_output_index: int, dest_task: int
    ) -> Optional[EventRouteMetadata]:
        if src_task != dest_task:
            return None
        return EventRouteMetadata(1, (0,), (src_output_index,))

    def route_composite_data_movement_event_to_destination(
            self, src_task: int, dest_task: int
    ) -> Optional[CompositeEventRouteMetadata]:
        if src_task != dest_task:
            return None
        return CompositeEventRouteMetadata(1, 0, 0)

    def route_input_source_task_failed_event_to_destination(
            self, src_task: int, dest_task: int) -> Optional[EventRouteMetadata]:
        if src_task != dest_task:
            return None
        return EventRouteMetadata(1, (0,))

    def route_input_error_event_to_source(self, dest_task: int,
                                          dest_failed_input_index: int) -> int:
        return dest_task


class _EdgeManagerContext(EdgeManagerPluginContext):
    def __init__(self, edge: "EdgeImpl", payload: UserPayload):
        self._edge = edge
        self._payload = payload

    @property
    def source_vertex_name(self) -> str:
        return self._edge.source_vertex.name

    @property
    def destination_vertex_name(self) -> str:
        return self._edge.destination_vertex.name

    @property
    def source_vertex_num_tasks(self) -> int:
        return self._edge.source_vertex.num_tasks

    @property
    def destination_vertex_num_tasks(self) -> int:
        return self._edge.destination_vertex.num_tasks

    @property
    def user_payload(self) -> UserPayload:
        return self._payload


class EdgeImpl:
    """One DAG edge at runtime: owns the edge manager and the on-demand event
    log (reference: dag/impl/Edge.java)."""

    def __init__(self, edge_id: str, edge_property: EdgeProperty,
                 source_vertex: Any, destination_vertex: Any):
        self.id = edge_id
        self.edge_property = edge_property
        self.source_vertex = source_vertex
        self.destination_vertex = destination_vertex
        self._lock = threading.Lock()
        # Ordered producer event log: (src_task, attempt_number, event)
        self._events: List[Tuple[int, int, TezAPIEvent]] = []
        self.edge_manager: EdgeManagerPluginOnDemand = None  # type: ignore

    def initialize(self) -> None:
        prop = self.edge_property
        ctx_payload = UserPayload()
        if prop.data_movement_type is DataMovementType.CUSTOM:
            desc = prop.edge_manager_descriptor
            assert desc is not None, f"CUSTOM edge {self.id} without manager"
            ctx_payload = desc.payload
            ctx = _EdgeManagerContext(self, ctx_payload)
            self.edge_manager = desc.instantiate(ctx)
        else:
            cls = {
                DataMovementType.SCATTER_GATHER: ScatterGatherEdgeManager,
                DataMovementType.BROADCAST: BroadcastEdgeManager,
                DataMovementType.ONE_TO_ONE: OneToOneEdgeManager,
            }[prop.data_movement_type]
            self.edge_manager = cls(_EdgeManagerContext(self, ctx_payload))
        self.edge_manager.initialize()

    def set_edge_manager(self, descriptor: Any) -> None:
        """Runtime edge reconfiguration (reference: Edge.setCustomEdgeManager
        used by ShuffleVertexManager auto-parallelism)."""
        ctx = _EdgeManagerContext(self, descriptor.payload)
        self.edge_manager = descriptor.instantiate(ctx)
        self.edge_manager.initialize()

    # -- producer side -------------------------------------------------------
    def add_source_event(self, src_task: int, attempt_number: int,
                         event: TezAPIEvent) -> None:
        with self._lock:
            self._events.append((src_task, attempt_number, event))

    def source_event_count(self) -> int:
        with self._lock:
            return len(self._events)

    # -- consumer side (on-demand pull) --------------------------------------
    def get_events_for_task(self, dest_task: int, from_seq: int,
                            max_events: int = 0
                            ) -> Tuple[List[TezAPIEvent], int]:
        """Route events [from_seq:] for one destination task.  Returns the
        routed events and the new high-water mark.  ``max_events`` > 0
        stops consuming log entries once that many routed events are out
        (tez.task.max-event-backlog); the high-water mark then points at
        the first unconsumed entry so the rest arrive on later pulls."""
        with self._lock:
            snapshot = self._events[from_seq:]
        consumed = 0
        out: List[TezAPIEvent] = []
        em = self.edge_manager
        for src_task, version, ev in snapshot:
            routed: List[TezAPIEvent] = []
            if isinstance(ev, CompositeDataMovementEvent):
                meta = em.route_composite_data_movement_event_to_destination(
                    src_task, dest_task)
                if meta is not None:
                    routed.append(CompositeRoutedDataMovementEvent(
                        source_index=meta.source, target_index_start=meta.target,
                        count=meta.count, user_payload=ev.user_payload,
                        version=version))
            elif isinstance(ev, DataMovementEvent):
                meta = em.route_data_movement_event_to_destination(
                    src_task, ev.source_index, dest_task)
                if meta is not None:
                    for t in meta.target_indices:
                        routed.append(DataMovementEvent(
                            source_index=ev.source_index,
                            user_payload=ev.user_payload,
                            target_index=t, version=version))
            elif isinstance(ev, InputFailedEvent):
                meta = em.route_input_source_task_failed_event_to_destination(
                    src_task, dest_task)
                if meta is not None:
                    for t in meta.target_indices:
                        routed.append(InputFailedEvent(target_index=t,
                                                       version=version))
            else:
                routed.append(ev)
            # strict cap: an entry whose expansion would overshoot is NOT
            # consumed (unless nothing is out yet — progress guarantee for
            # a single entry that expands past the whole cap)
            if max_events and out and len(out) + len(routed) > max_events:
                break
            consumed += 1
            out.extend(routed)
            if max_events and len(out) >= max_events:
                break
        return out, from_seq + consumed

    def route_input_error_to_source(self, dest_task: int,
                                    failed_input_index: int) -> int:
        return self.edge_manager.route_input_error_event_to_source(
            dest_task, failed_input_index)

    def num_dest_physical_inputs(self, dest_task: int) -> int:
        return self.edge_manager.get_num_destination_task_physical_inputs(dest_task)

    def num_source_physical_outputs(self, src_task: int) -> int:
        return self.edge_manager.get_num_source_task_physical_outputs(src_task)
