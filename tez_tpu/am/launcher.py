"""Runner pool: the container launcher + TezChild loop, in-process.

Reference parity: tez-dag ContainerLauncherManager.java:62 +
LocalContainerLauncher.java:87 (tasks as threads, uber-style) + the TezChild
run loop (tez-runtime-internals TezChild.java:214).  A "container" here is a
worker thread with an object registry (so kernel caches survive across tasks
— the TPU analog of JVM container reuse); on a real pod each would be a
runner process on a TPU host.
"""
from __future__ import annotations

import itertools
import logging
import threading
from typing import Any, Dict, Optional

from tez_tpu.am.history import HistoryEvent, HistoryEventType
from tez_tpu.api.runtime import ObjectRegistry
from tez_tpu.common import clock, faults
from tez_tpu.common.counters import DAGCounter
from tez_tpu.common.ids import ContainerId

log = logging.getLogger(__name__)


class RunnerPool:
    def __init__(self, ctx: Any, max_runners: int,
                 idle_timeout: Optional[float] = None):
        self.ctx = ctx
        self.max_runners = max_runners
        conf = getattr(ctx, "conf", None)
        if idle_timeout is None:
            ms = conf.get("tez.am.container.idle.release-timeout-min.millis") \
                if conf is not None else None
            idle_timeout = (5000 if ms is None else ms) / 1000.0
        self.idle_timeout = idle_timeout
        #: session mode holds this many runners even when idle (reference:
        #: tez.am.session.min.held-containers)
        self.min_held = int(conf.get("tez.am.session.min.held-containers")
                            or 0) if conf is not None else 0
        #: reuse off = one task per container, fresh ObjectRegistry/caches
        #: every time (reference: tez.am.container.reuse.enabled)
        from tez_tpu.common import config as C
        reuse = conf.get(C.AM_CONTAINER_REUSE_ENABLED) \
            if conf is not None else None
        self.reuse_enabled = True if reuse is None else bool(reuse)
        self._runners: Dict[ContainerId, threading.Thread] = {}
        self._seq = itertools.count()
        self._lock = threading.Lock()
        self._stopped = False

    def ensure_runners(self, backlog: int) -> None:
        """Spin up runner threads while there is queued work and capacity."""
        with self._lock:
            if self._stopped:
                return
            want = min(self.max_runners, len(self._runners) + max(0, backlog))
            while len(self._runners) < want:
                cid = ContainerId(self.ctx.app_id, next(self._seq))
                t = threading.Thread(target=self._runner_loop, args=(cid,),
                                     name=str(cid), daemon=True)
                self._runners[cid] = t
                t.start()

    def _runner_loop(self, container_id: ContainerId) -> None:
        """The TezChild loop: pull task, run, repeat until idle."""
        from tez_tpu.runtime.task_runner import TaskRunner
        self.ctx.history(HistoryEvent(
            HistoryEventType.CONTAINER_LAUNCHED,
            container_id=str(container_id)))
        registry = ObjectRegistry()
        tasks_run = 0
        try:
            try:
                faults.fire("am.container.launch", detail=str(container_id))
            except Exception as e:  # noqa: BLE001 — injected launch failure
                # dies like a container that crashed at startup: the finally
                # emits CONTAINER_STOPPED and the watchdog respawns while
                # backlog remains
                log.warning("container %s launch failed: %s", container_id, e)
                return
            while not self._stopped:
                spec = self.ctx.task_comm.get_task(container_id,
                                                   timeout=self.idle_timeout)
                if spec is None:
                    tracker = getattr(self.ctx, "node_tracker", None)
                    if tracker is not None and \
                            not tracker.is_usable(self.ctx.node_id):
                        break   # blacklisted node must not hold-and-spin
                    # idle release — but session mode keeps min.held runners
                    # warm (container reuse across DAGs; kernel caches
                    # live).  Decision and table removal are ATOMIC so
                    # several simultaneously-idle runners can't all leave.
                    with self._lock:
                        if len(self._runners) > self.min_held:
                            self._runners.pop(container_id, None)
                            break
                    continue
                if tasks_run > 0:
                    self.ctx.dag_counters.increment(
                        DAGCounter.TOTAL_CONTAINER_REUSE_COUNT)
                tasks_run += 1
                runner = TaskRunner(spec, self.ctx.task_comm, registry,
                                    work_dir=self.ctx.work_dir,
                                    node_id=self.ctx.node_id)
                runner.run()
                registry.clear_scope(ObjectRegistry.VERTEX)
                if not self.reuse_enabled:
                    # one task per container: exit; ensure_runners spawns a
                    # fresh one (fresh registry) while backlog remains
                    with self._lock:
                        self._runners.pop(container_id, None)
                    break
        finally:
            with self._lock:
                self._runners.pop(container_id, None)
            self.ctx.history(HistoryEvent(
                HistoryEventType.CONTAINER_STOPPED,
                container_id=str(container_id),
                data={"tasks_run": tasks_run}))

    def live_count(self) -> int:
        with self._lock:
            return len(self._runners)

    def shutdown(self, wait: bool = True) -> None:
        self._stopped = True
        if wait:
            deadline = clock.wall_s() + 10
            for t in list(self._runners.values()):
                t.join(timeout=max(0.1, deadline - clock.wall_s()))


class SubprocessRunnerPool:
    """Launches runner PROCESSES (the TezChild-as-JVM analog) instead of
    threads.  Reference: ContainerLauncherManager + TezContainerLauncherImpl
    launching containers on NodeManagers; here runners are subprocesses of
    the AM host (a multi-host deployment execs the same module on each
    worker pointed at the AM's umbilical address)."""

    def __init__(self, ctx: Any, max_runners: int,
                 idle_timeout: float = 5.0):
        self.ctx = ctx
        self.max_runners = max_runners
        self.idle_timeout = idle_timeout
        self._procs: Dict[int, Any] = {}
        self._seq = itertools.count()
        self._lock = threading.Lock()
        self._stopped = False

    def ensure_runners(self, backlog: int) -> None:
        import os
        import socket
        import subprocess
        import sys
        # node id = HOST, not process: failure accounting must accumulate
        # across respawns on the same machine (a multi-host deployment
        # passes each host's own stable --node-id)
        node = f"{socket.gethostname()}-{self.ctx.app_id}"
        with self._lock:
            if self._stopped:
                return
            self._reap()
            want = min(self.max_runners, len(self._procs) + max(0, backlog))
            while len(self._procs) < want:
                n = next(self._seq)
                env = dict(os.environ)
                # conf-supplied runner environment (reference: container
                # launch context env); empty value = unset the variable
                for k, v in (self.ctx.conf.get("tez.am.runner.env")
                             or {}).items():
                    if v == "":
                        env.pop(k, None)
                    else:
                        env[k] = str(v)
                env["TEZ_TPU_JOB_TOKEN"] = self.ctx.secrets.secret.hex()
                from tez_tpu.common.tls import export_env
                env.update(export_env(self.ctx.conf))
                repo_root = os.path.dirname(os.path.dirname(os.path.dirname(
                    os.path.abspath(__file__))))
                existing = env.get("PYTHONPATH", "")
                env["PYTHONPATH"] = repo_root + (
                    os.pathsep + existing if existing else "")
                cid = f"container_proc_{self.ctx.app_id}_{n:06d}"
                try:
                    faults.fire("am.container.launch", detail=cid)
                except Exception as e:  # noqa: BLE001 — injected failure
                    log.warning("container %s launch failed: %s", cid, e)
                    break   # retried on the watchdog's next ensure_runners
                from tez_tpu.common import config as C
                reuse = self.ctx.conf.get(C.AM_CONTAINER_REUSE_ENABLED)
                cmd = [sys.executable, "-m",
                       "tez_tpu.runtime.remote_runner",
                       "--am-port", str(self.ctx.umbilical_server.port),
                       "--node-id", node,
                       "--container-id", cid,
                       "--idle-timeout", str(self.idle_timeout)]
                if reuse is not None and not reuse:
                    cmd += ["--max-tasks", "1"]
                proc = subprocess.Popen(cmd, env=env)
                self._procs[n] = (proc, cid)
                self.ctx.history(HistoryEvent(
                    HistoryEventType.CONTAINER_LAUNCHED,
                    container_id=cid, data={"pid": proc.pid}))

    def _reap(self) -> None:
        for n, (proc, cid) in list(self._procs.items()):
            if proc.poll() is not None:
                del self._procs[n]
                self.ctx.history(HistoryEvent(
                    HistoryEventType.CONTAINER_STOPPED,
                    container_id=cid,
                    data={"returncode": proc.returncode}))

    def live_count(self) -> int:
        with self._lock:
            self._reap()
            return len(self._procs)

    def shutdown(self, wait: bool = True) -> None:
        with self._lock:
            self._stopped = True
            procs = [p for p, _ in self._procs.values()]
        for p in procs:
            p.terminate()
        if wait:
            for p in procs:
                try:
                    p.wait(timeout=10)
                except Exception:  # noqa: BLE001
                    p.kill()
        with self._lock:
            self._reap()
