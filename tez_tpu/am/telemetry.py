"""The AM's live telemetry sampler + the continuous-doctor surface.

One daemon thread per AM (``tez.am.metrics.sample-period-ms``, 0 = off):
every tick it sweeps the metrics registry and every registered collector
into the bounded rings of :mod:`tez_tpu.obs.timeseries`, then runs the
SLO watchdog's burn-rate evaluation against the fresh windows — so
burn-alert latency is bounded by the sampler period, not by DAG
completions.  The tick is the ONLY hot-path cost of the live plane: a
dict snapshot plus ring appends, off every data-plane lock, which is how
the always-on plane stays inside the 3% armed-overhead gate.

:meth:`TelemetrySampler.live_status` is the continuous doctor: the
post-hoc blame sweep of ``tools/doctor.py`` re-runs *incrementally* over
the live windows (per-plane instrumented-busy deltas via the shared
PREFIX_PLANE mapping), next to tenants, streams, queue depth and lane
occupancy — served at ``GET /doctor/live`` and rendered in place by
``graft top`` (tools/top.py).

On a graceful stop the sampler journals one ``TELEMETRY_SNAPSHOT``
summary event carrying the plane's overflow accounting (ring evictions,
collector failures, scrape errors), which is how counter_diff's
telemetry section sees ring health without scraping a live AM.  A crash
journals nothing — the accounting dies with the incarnation, exactly
like the flight ring.
"""
from __future__ import annotations

import logging
import threading
from typing import Any, Dict, List, Optional

from tez_tpu.obs import timeseries

log = logging.getLogger(__name__)

#: collector hooks registered on start: (name, "module:function") —
#: resolved lazily so an AM without a store/mesh imports nothing extra
_COLLECTOR_HOOKS = (
    ("store", "tez_tpu.store.buffer_store:telemetry_collector"),
    ("shuffle", "tez_tpu.shuffle.service:telemetry_collector"),
    ("mesh", "tez_tpu.parallel.coordinator:telemetry_collector"),
)


class TelemetrySampler:
    """Periodic sampler thread + live status aggregation for one AM."""

    def __init__(self, am: Any) -> None:
        from tez_tpu.common import config as C
        self.am = am
        conf = am.conf
        self.period_s = max(
            0.0,
            float(conf.get(C.AM_METRICS_SAMPLE_PERIOD_MS) or 0.0) / 1000.0)
        self.window_s = float(conf.get(C.AM_METRICS_WINDOW_S) or 10.0)
        self.metrics_enabled = bool(conf.get(C.METRICS_ENABLED))
        reg = timeseries.registry()
        reg.capacity = max(2, int(conf.get(C.AM_METRICS_RING_SAMPLES)
                                  or timeseries.DEFAULT_CAPACITY))
        self._stop_event = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.ticks = 0

    def enabled(self) -> bool:
        return self.period_s > 0 and self.metrics_enabled

    # -- lifecycle ----------------------------------------------------------
    def start(self) -> None:
        if not self.enabled() or self._thread is not None:
            return
        reg = timeseries.registry()
        for name, spec in _COLLECTOR_HOOKS:
            mod_name, _, fn_name = spec.partition(":")
            import importlib
            try:
                fn = getattr(importlib.import_module(mod_name), fn_name)
            except Exception:  # noqa: BLE001 — a gated plane just skips
                continue
            reg.register_collector(name, fn)
        self._stop_event.clear()
        self._thread = threading.Thread(
            target=self._run, daemon=True,
            name=f"am-telemetry-{self.am.app_id}")
        self._thread.start()

    def _run(self) -> None:
        while not self._stop_event.wait(self.period_s):
            try:
                self.tick()
            except Exception:  # noqa: BLE001 — telemetry must not die
                log.exception("telemetry tick failed")

    def tick(self, now_ns: Optional[int] = None) -> None:
        """One sweep: sample the rings, then burn-evaluate.  Public so
        chaos/tests drive the plane deterministically without a thread."""
        timeseries.registry().sample(now_ns)
        self.ticks += 1
        wd = getattr(self.am, "slo_watchdog", None)
        if wd is not None:
            wd.evaluate_burn(now_ns)

    def stop(self) -> None:
        """Graceful stop: halt the thread, then journal the plane's
        overflow accounting as a TELEMETRY_SNAPSHOT summary event."""
        self._halt()
        if not self.enabled():
            return
        from tez_tpu.am.history import HistoryEvent, HistoryEventType
        acct = timeseries.registry().accounting()
        acct["ticks"] = self.ticks
        try:
            self.am.history(HistoryEvent(
                HistoryEventType.TELEMETRY_SNAPSHOT, data=acct))
        except Exception:  # noqa: BLE001 — diagnostics never fail a stop
            log.exception("TELEMETRY_SNAPSHOT journal write failed")

    def crash(self) -> None:
        """SIGKILL analog: no journal, just thread hygiene."""
        self._halt()

    def _halt(self) -> None:
        self._stop_event.set()
        t = self._thread
        if t is not None:
            t.join(timeout=5.0)
            self._thread = None

    # -- the continuous doctor (GET /doctor/live, graft top) ----------------
    def live_status(self, window_s: Optional[float] = None
                    ) -> Dict[str, Any]:
        """The live triage payload: over the last ``window_s`` seconds,
        per-plane instrumented-busy blame (the post-hoc sweep's
        incremental form), plus tenants, streams, queue depth, lane
        occupancy, and active SLO breach/burn state."""
        win = float(window_s or self.window_s)
        reg = timeseries.registry()
        busy = reg.plane_busy_ms(win)
        blamed = {p: ms for p, ms in busy.items() if ms > 0}
        dominant = max(blamed, key=lambda p: blamed[p]) if blamed else None
        out: Dict[str, Any] = {
            "window_s": win,
            "sampler": {"enabled": self.enabled(),
                        "period_s": self.period_s, "ticks": self.ticks},
            "planes": {"busy_ms": busy, "dominant": dominant},
            "accounting": reg.accounting(),
        }
        admission = getattr(self.am, "admission", None)
        if admission is not None:
            st = admission.status()
            out["queue_depth"] = st.get("queue_depth", 0)
            out["running_dags"] = st.get("running", 0)
            out["tenants"] = st.get("tenants", {})
        streams: Dict[str, Any] = {}
        for name, driver in list(getattr(self.am, "streams", {}).items()):
            try:
                status = driver.status()
            except Exception:  # noqa: BLE001 — a dying driver is skipped
                continue
            w = reg.window(f"stream.{name}.window.latency", win)
            if w is not None:
                status["window_latency"] = w
            streams[name] = status
        out["streams"] = streams
        from tez_tpu.common import metrics
        gauges = metrics.registry().gauges()
        out["lanes"] = {
            name.split(".")[2]: v for name, v in sorted(gauges.items())
            if name.startswith("mesh.lane.")
            and name.endswith(".occupancy")}
        wd = getattr(self.am, "slo_watchdog", None)
        if wd is not None:
            st = wd.status()
            out["slo"] = {"breaches": st["active"],
                          "burn": st["burn"]["active"]}
        return out


def window_rows(window_s: float, kind: Optional[str] = None
                ) -> List[Dict[str, Any]]:
    """Flat windowed-aggregate rows for every live series — the
    ``graft top`` series table (label-split like the exposition)."""
    from tez_tpu.obs import exposition
    reg = timeseries.registry()
    rows: List[Dict[str, Any]] = []
    for name, w in reg.windows(window_s, kind=kind).items():
        if w is None:
            continue
        base, labels = exposition.split_labels(name)
        rows.append(dict(w, name=base, labels=labels, series=name))
    return rows
