"""Speculative execution of straggler attempts.

Reference parity: tez-dag/.../dag/speculation/legacy/LegacySpeculator.java:63
with the SimpleExponentialTaskRuntimeEstimator idea collapsed to a
progress-rate estimator: per-vertex mean runtime of completed tasks; a
running attempt whose estimated completion (from its progress rate) exceeds
the mean by the slowtask threshold gets a speculative sibling, at most one
per task, and only while spare capacity exists.
"""
from __future__ import annotations

import logging
import threading
import time
from typing import Any, Dict

from tez_tpu.am.events import TaskEvent, TaskEventType
from tez_tpu.am.task_impl import TaskAttemptState, TaskState
from tez_tpu.common import config as C

log = logging.getLogger(__name__)

#: Attempts younger than this are never speculated (reference:
#: SOONEST_RETRY_AFTER_NO_SPECULATE spirit).
MIN_RUNTIME_BEFORE_SPECULATION = 0.5
SPECULATION_INTERVAL = 0.25


class Speculator:
    """Per-DAG straggler watcher; runs its own scan thread (the reference
    speculator is also timer-driven outside the dispatcher)."""

    def __init__(self, dag: Any):
        self.dag = dag
        self.ctx = dag.ctx
        self.threshold = dag.conf.get(C.SPECULATION_SLOWTASK_THRESHOLD)
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name=f"speculator-{dag.dag_id}")

    def start(self) -> None:
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()

    def _loop(self) -> None:
        while not self._stop.wait(SPECULATION_INTERVAL):
            try:
                self._scan()
            except BaseException:  # noqa: BLE001
                log.exception("speculator scan failed")

    def _scan(self) -> None:
        from tez_tpu.am.dag_impl import TERMINAL_DAG_STATES
        if self.dag.state in TERMINAL_DAG_STATES:
            self._stop.set()
            return
        now = time.time()
        for vertex in self.dag.vertices.values():
            completed: list = []
            for task in vertex.tasks.values():
                att = task.successful_attempt_impl()
                if att is not None and att.launch_time:
                    completed.append(att.finish_time - att.launch_time)
            if not completed:
                continue
            mean_runtime = sum(completed) / len(completed)
            for task in vertex.tasks.values():
                if task.state is not TaskState.RUNNING:
                    continue
                live = task.live_attempts()
                if len(live) != 1:
                    continue  # already speculating (or nothing to watch)
                att = live[0]
                if att.state is not TaskAttemptState.RUNNING or \
                        not att.launch_time:
                    continue
                runtime = now - att.launch_time
                if runtime < max(MIN_RUNTIME_BEFORE_SPECULATION,
                                 mean_runtime * (1 + self.threshold)):
                    continue
                # estimate completion from progress rate; no progress means
                # estimate = infinity
                progress = max(att.progress, 1e-6)
                estimated_total = runtime / progress
                if estimated_total <= mean_runtime * (1 + self.threshold):
                    continue
                log.info("speculating %s (runtime %.2fs, mean %.2fs, "
                         "progress %.2f)", att.attempt_id, runtime,
                         mean_runtime, att.progress)
                self.ctx.dispatch(TaskEvent(
                    TaskEventType.T_ADD_SPEC_ATTEMPT, task.task_id))
