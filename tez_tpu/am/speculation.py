"""Speculative execution of straggler attempts.

Reference parity: tez-dag/.../dag/speculation/legacy/LegacySpeculator.java:63.
The runtime estimate comes from a pluggable ``TaskRuntimeEstimator``
(``tez.am.legacy.speculative.estimator.class`` — see am/estimators.py):
"simple_exponential" (default, smoothed recent progress rate + stagnation
detection) or "legacy" (whole-lifetime progress rate).  A running attempt is
speculated when its estimated completion is later than the estimated
completion of a fresh replacement AND its estimated total runtime clears the
slowtask threshold over the vertex mean; at most one new speculation per
vertex per scan (the best candidate), one speculative sibling per task.
"""
from __future__ import annotations

import logging
import math
import threading
from typing import Any, Dict, Set

from tez_tpu.am.estimators import TaskRuntimeEstimator, create_estimator
from tez_tpu.am.events import TaskEvent, TaskEventType
from tez_tpu.am.task_impl import TaskAttemptState, TaskState
from tez_tpu.common import clock, config as C

log = logging.getLogger(__name__)

#: Attempts younger than this are never speculated.
MIN_RUNTIME_BEFORE_SPECULATION = 0.5


class Speculator:
    """Per-DAG straggler watcher; runs its own scan thread (the reference
    speculator is also timer-driven outside the dispatcher)."""

    def __init__(self, dag: Any):
        self.dag = dag
        self.ctx = dag.ctx
        self.threshold = dag.conf.get(C.SPECULATION_SLOWTASK_THRESHOLD)
        # concurrent-speculation budget + scan pacing (reference:
        # LegacySpeculator.maybeScheduleASpeculation / computeSpeculations)
        self.min_allowed = dag.conf.get(C.SPECULATION_MIN_ALLOWED_TASKS)
        self.prop_total = dag.conf.get(C.SPECULATION_PROPORTION_TOTAL)
        self.prop_running = dag.conf.get(C.SPECULATION_PROPORTION_RUNNING)
        self.retry_no_spec = max(
            dag.conf.get(C.SPECULATION_RETRY_AFTER_NO_SPECULATE_MS), 50) \
            / 1000.0
        self.retry_spec = max(
            dag.conf.get(C.SPECULATION_RETRY_AFTER_SPECULATE_MS), 50) \
            / 1000.0
        stvt = dag.conf.get(C.SPECULATION_SINGLE_TASK_VERTEX_TIMEOUT_MS)
        self.single_task_timeout = stvt / 1000.0 if stvt >= 0 else None
        # fail fast on a bad estimator class name — a typo must surface at
        # DAG submit, not as a logged exception every scan forever
        create_estimator(dag.conf, "<probe>")
        self.estimators: Dict[str, TaskRuntimeEstimator] = {}
        self._fed_durations: Set[str] = set()
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name=f"speculator-{dag.dag_id}")

    def start(self) -> None:
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()

    def _loop(self) -> None:
        # adaptive pacing: back off hard after launching a speculation (it
        # needs time to prove itself), rescan on the no-speculate cadence
        # otherwise (reference: SOONEST_RETRY_AFTER_SPECULATE /
        # _NO_SPECULATE — the operator's value is honored as-is)
        wait = self.retry_no_spec
        while not self._stop.wait(wait):
            speculated = 0
            try:
                speculated = self._scan()
            except BaseException:  # noqa: BLE001
                log.exception("speculator scan failed")
            wait = self.retry_spec if speculated else self.retry_no_spec

    def _estimator(self, vertex: Any) -> TaskRuntimeEstimator:
        est = self.estimators.get(vertex.name)
        if est is None:
            est = create_estimator(self.dag.conf, vertex.name)
            self.estimators[vertex.name] = est
        return est

    def _speculation_budget(self) -> int:
        """How many NEW speculative attempts may launch now: the cap is
        max(minimum floor, proportion of total tasks, proportion of running
        tasks) minus speculations already in flight (reference:
        LegacySpeculator.computeSpeculations)."""
        total = running = in_flight = 0
        for vertex in self.dag.vertices.values():
            for task in vertex.tasks.values():
                total += 1
                if task.state is TaskState.RUNNING:
                    running += 1
                    if len(task.live_attempts()) > 1:
                        in_flight += 1
        cap = max(self.min_allowed,
                  int(self.prop_total * total),
                  int(self.prop_running * running))
        return cap - in_flight

    def _scan(self) -> int:
        from tez_tpu.am.dag_impl import TERMINAL_DAG_STATES
        if self.dag.state in TERMINAL_DAG_STATES:
            self._stop.set()
            return 0
        now = clock.wall_s()
        budget = self._speculation_budget()
        speculated = 0
        for vertex in self.dag.vertices.values():
            est = self._estimator(vertex)
            # feed newly completed durations into the vertex statistics
            for task in vertex.tasks.values():
                att = task.successful_attempt_impl()
                if att is not None and att.launch_time and \
                        att.attempt_id not in self._fed_durations:
                    self._fed_durations.add(att.attempt_id)
                    est.attempt_succeeded(att.finish_time - att.launch_time)
                    est.forget(att.attempt_id)  # prune per-attempt state
            if budget - speculated <= 0:
                break      # concurrent-speculation budget exhausted
            new_runtime = est.estimated_new_attempt_runtime()
            if new_runtime is None:
                # a single-task vertex never produces a sibling-completion
                # estimate; the reference gates it on a wall-clock timeout
                # instead (single.task.vertex.timeout, -1 = never)
                if self.single_task_timeout is not None and \
                        len(vertex.tasks) == 1:
                    speculated += self._maybe_speculate_single_task(
                        vertex, now)
                continue   # nothing completed yet: no replacement estimate
            best_task = None
            best_value = 0.0
            for task in vertex.tasks.values():
                if task.state is not TaskState.RUNNING:
                    continue
                live = task.live_attempts()
                if len(live) != 1:
                    continue  # already speculating (or nothing to watch)
                att = live[0]
                if att.state is not TaskAttemptState.RUNNING or \
                        not att.launch_time:
                    continue
                est.enroll(att.attempt_id, att.launch_time)
                est.update_attempt(att.attempt_id, att.progress, now)
                runtime = now - att.launch_time
                if runtime < max(MIN_RUNTIME_BEFORE_SPECULATION,
                                 new_runtime * (1 + self.threshold)):
                    continue
                estimated_total = est.estimated_runtime(att.attempt_id, now)
                if estimated_total is None:
                    continue   # estimator not confident yet
                if estimated_total <= new_runtime * (1 + self.threshold):
                    continue
                # speculation value: how much earlier a replacement would end
                estimated_end = att.launch_time + estimated_total
                replacement_end = now + new_runtime
                value = estimated_end - replacement_end
                if value <= 0:
                    continue
                if best_task is None or value > best_value:
                    best_task, best_value = task, value
                    best_info = (att, runtime, estimated_total, new_runtime)
            if best_task is not None:
                att, runtime, estimated_total, new_runtime = best_info
                log.info("speculating %s (runtime %.2fs, estimate %s, "
                         "new-attempt %.2fs, progress %.2f)",
                         att.attempt_id, runtime,
                         "inf" if math.isinf(estimated_total)
                         else f"{estimated_total:.2f}s",
                         new_runtime, att.progress)
                self.ctx.dispatch(TaskEvent(
                    TaskEventType.T_ADD_SPEC_ATTEMPT, best_task.task_id))
                speculated += 1
        return speculated

    def _maybe_speculate_single_task(self, vertex: Any, now: float) -> int:
        """Wall-clock speculation for one-task vertices (reference:
        TEZ_AM_LEGACY_SPECULATIVE_SINGLE_TASK_VERTEX_TIMEOUT)."""
        task = next(iter(vertex.tasks.values()))
        if task.state is not TaskState.RUNNING:
            return 0
        live = task.live_attempts()
        if len(live) != 1:
            return 0
        att = live[0]
        if att.state is not TaskAttemptState.RUNNING or not att.launch_time:
            return 0
        if now - att.launch_time <= self.single_task_timeout:
            return 0
        log.info("speculating single-task vertex attempt %s "
                 "(runtime %.2fs > timeout %.2fs)", att.attempt_id,
                 now - att.launch_time, self.single_task_timeout)
        self.ctx.dispatch(TaskEvent(
            TaskEventType.T_ADD_SPEC_ATTEMPT, task.task_id))
        return 1
