"""Client <-> AM control transport: the DAGClientAMProtocol analog.

Reference parity: tez-dag DAGClientServer.java:48 + DAGClientHandler serving
DAGClientAMProtocol.proto:100-108 (submitDAG, getDAGStatus, tryKillDAG,
shutdownSession, getWebUIAddress) — here a token-authenticated socket server
in front of DAGAppMaster, so clients on other hosts can submit and monitor
DAGs (the reference's ZK-standalone mode with a well-known address instead
of a ZK registry).
"""
from __future__ import annotations

import logging
import socketserver
import threading
import time
from typing import Any, Optional

from tez_tpu.common import clock
from tez_tpu.am.umbilical_server import (_recv_msg, _send_msg,
                                          authenticate_stream)
from tez_tpu.common.security import JobTokenSecretManager

log = logging.getLogger(__name__)

_METHODS = frozenset({"submit_dag", "dag_status", "kill_dag", "wait_for_dag",
                      "web_ui_address", "shutdown_session", "prewarm",
                      "queue_status", "find_dag_id_by_name",
                      "queued_dag_names"})


class _Handler(socketserver.StreamRequestHandler):
    def handle(self) -> None:
        server = self.server
        am = server.am                   # type: ignore[attr-defined]
        secrets = server.secrets         # type: ignore[attr-defined]
        try:
            if not authenticate_stream(self.rfile, self.wfile, secrets,
                                       b"client-hello"):
                return
            while True:
                method, args, kwargs = _recv_msg(self.rfile)
                # any authenticated request is a client liveness signal
                # (reference: TezClient.sendAMHeartbeat / client keepalive)
                server.last_client_contact = clock.wall_s()
                if method not in _METHODS:
                    _send_msg(self.wfile, (False, f"no method {method}"))
                    continue
                try:
                    if method == "web_ui_address":
                        result = am.web_ui.url if am.web_ui else None
                    elif method == "shutdown_session":
                        result = None
                        def _shutdown():
                            am.stop()
                            ev = getattr(server, "shutdown_event", None)
                            if ev is not None:
                                ev.set()
                        threading.Thread(target=_shutdown,
                                         daemon=True).start()
                    else:
                        result = getattr(am, method)(*args, **kwargs)
                    _send_msg(self.wfile, (True, result))
                except BaseException as e:  # noqa: BLE001
                    try:
                        _send_msg(self.wfile, (False, e))
                    except Exception:  # noqa: BLE001 — unpicklable
                        _send_msg(self.wfile, (False, RuntimeError(repr(e))))
        except (ConnectionError, EOFError, Exception):  # noqa: BLE001 —
            # malformed input must never kill the server loop with a
            # traceback; the connection just closes
            return


class DAGClientServer:
    def __init__(self, am: Any, secrets: JobTokenSecretManager,
                 host: str = "127.0.0.1", port: int = 0,
                 ssl_context=None):
        from tez_tpu.common.tls import wrap_server_class
        server_cls = wrap_server_class(socketserver.ThreadingTCPServer,
                                       ssl_context)
        self._tcp = server_cls((host, port), _Handler)
        self._tcp.daemon_threads = True
        self._tcp.am = am                # type: ignore[attr-defined]
        self._tcp.secrets = secrets      # type: ignore[attr-defined]
        self._tcp.last_client_contact = clock.wall_s()  # type: ignore
        self.shutdown_event = threading.Event()
        self._tcp.shutdown_event = self.shutdown_event  # type: ignore
        self._thread = threading.Thread(target=self._tcp.serve_forever,
                                        daemon=True, name="dag-client-server")
        self._expiry_thread: Optional[threading.Thread] = None

    @property
    def port(self) -> int:
        return self._tcp.server_address[1]

    @property
    def last_client_contact(self) -> float:
        return self._tcp.last_client_contact  # type: ignore[attr-defined]

    def start(self) -> "DAGClientServer":
        self._thread.start()
        return self

    def start_session_expiry(self, timeout_secs: float) -> None:
        """Shut the session down when the client stops talking (reference:
        tez.am.client.heartbeat.timeout.secs — a session AM whose client
        died must not hold resources forever)."""

        def _watch() -> None:
            while not self.shutdown_event.wait(
                    min(5.0, max(0.2, timeout_secs / 3))):
                if clock.wall_s() - self.last_client_contact > timeout_secs:
                    log.warning("no client contact for %.0fs: shutting "
                                "session down", timeout_secs)
                    try:
                        self._tcp.am.stop()  # type: ignore[attr-defined]
                    finally:
                        self.shutdown_event.set()
                    return

        self._expiry_thread = threading.Thread(
            target=_watch, daemon=True, name="client-session-expiry")
        self._expiry_thread.start()

    def stop(self) -> None:
        self.shutdown_event.set()
        self._tcp.shutdown()
        self._tcp.server_close()


def main() -> int:
    """Standalone AM: python -m tez_tpu.am.client_server --port P
    [--umbilical-mode subprocess] with TEZ_TPU_JOB_TOKEN in the env.

    Prints 'READY <client-port>' once accepting submissions (reference:
    standalone AM registering its address for clients to find).
    """
    import argparse
    import os
    import sys
    from tez_tpu.am.app_master import DAGAppMaster
    from tez_tpu.common import config as C
    from tez_tpu.common.ids import new_app_id

    parser = argparse.ArgumentParser()
    parser.add_argument("--bind-host", default="127.0.0.1")
    parser.add_argument("--port", type=int, default=0)
    parser.add_argument("--runner-mode", default="threads")
    parser.add_argument("--num-containers", type=int, default=0)
    parser.add_argument("--staging-dir", default="/tmp/tez-tpu-staging")
    parser.add_argument("--client-heartbeat-timeout-secs", type=float,
                        default=-1, help="shut the session down after this "
                        "long without any client request (-1 = never)")
    args = parser.parse_args()
    token = os.environ.get("TEZ_TPU_JOB_TOKEN", "")
    if not token:
        print("TEZ_TPU_JOB_TOKEN env var required", file=sys.stderr)
        return 2
    logging.basicConfig(level=os.environ.get("TEZ_TPU_LOG", "INFO"))
    conf = C.TezConfiguration({
        "tez.staging-dir": args.staging_dir,
        "tez.runner.mode": args.runner_mode,
        "tez.am.local.num-containers": args.num_containers,
        "tez.am.umbilical.bind-host": args.bind_host,
        "tez.job.token": token,
        "tez.am.client.heartbeat.timeout.secs":
            args.client_heartbeat_timeout_secs,
    })
    am = DAGAppMaster(new_app_id(), conf)
    am.start()
    from tez_tpu.common.tls import server_context
    server = DAGClientServer(am, am.secrets, host=args.bind_host,
                             port=args.port,
                             ssl_context=server_context(conf)).start()
    hb_timeout = float(conf.get(C.AM_CLIENT_HEARTBEAT_TIMEOUT_SECS))
    if hb_timeout > 0:
        server.start_session_expiry(hb_timeout)
    print(f"READY {server.port}", flush=True)
    try:
        server.shutdown_event.wait()   # set by shutdown_session (or Ctrl-C)
        # linger before exit so a client that just triggered the shutdown
        # can still fetch final status/diagnostics (reference:
        # TEZ_AM_SLEEP_TIME_BEFORE_EXIT_MILLIS, DAGAppMaster sleep on exit)
        linger_ms = float(conf.get(C.AM_SLEEP_TIME_BEFORE_EXIT_MS))
        if linger_ms > 0:
            time.sleep(linger_ms / 1000.0)
    except KeyboardInterrupt:
        am.stop()
    server.stop()
    return 0


if __name__ == "__main__":
    import sys
    sys.exit(main())
