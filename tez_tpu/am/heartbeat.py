"""Liveness monitoring: stale task attempts are timed out.

Reference parity: tez-dag/.../app/TaskHeartbeatHandler.java +
ContainerHeartbeatHandler.java — attempts whose umbilical has gone silent
past the timeout are failed so the task retries elsewhere.
"""
from __future__ import annotations

import logging
import threading
from typing import Any

from tez_tpu.am.events import TaskAttemptEvent, TaskAttemptEventType
from tez_tpu.common import clock, config as C
from tez_tpu.common import faults

log = logging.getLogger(__name__)


class HeartbeatMonitor:
    def __init__(self, ctx: Any, check_interval: float = 1.0):
        self.ctx = ctx
        self.check_interval = check_interval
        self.timeout_ms = ctx.conf.get(C.TASK_HEARTBEAT_TIMEOUT_MS)
        #: -1 = off; >0 = kill attempts whose progress/events stall this
        #: long even though heartbeats keep arriving (hung-but-alive)
        self.stuck_ms = ctx.conf.get(C.TASK_PROGRESS_STUCK_INTERVAL_MS)
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="heartbeat-monitor")

    def start(self) -> None:
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()

    def _loop(self) -> None:
        while not self._stop.wait(self.check_interval):
            try:
                self._check()
            except BaseException:  # noqa: BLE001
                log.exception("heartbeat check failed")

    def _check(self) -> None:
        # delay mode stalls the liveness sweep itself (failure detection
        # latency under chaos); the loop's BaseException guard absorbs fail
        # mode into a logged, skipped tick
        faults.fire("am.heartbeat.monitor")
        # a superseded AM's monitor must not keep dispatching TA_TIMED_OUT
        # or respawning runners against the live incarnation's resources
        from tez_tpu.common import epoch as epoch_registry
        my_epoch = int(getattr(self.ctx, "attempt", 0) or 0)
        if my_epoch > 0 and epoch_registry.is_stale(
                getattr(self.ctx, "app_id", ""), my_epoch):
            faults.fire("fence.stale_epoch", detail="heartbeat.monitor")
            self._stop.set()
            return
        # Watchdog for the runner pool: a runner deciding to idle-exit still
        # counts as capacity at schedule time, so queued work could strand
        # with nothing re-triggering a spawn.  Re-examine the backlog every
        # tick (reference: the AM's scheduling heartbeat serves this role).
        backlog = self.ctx.task_scheduler.backlog()
        if backlog > 0:
            self.ctx.ensure_runners(backlog)
        now = clock.wall_s()
        if self.timeout_ms > 0:
            cutoff = self.timeout_ms / 1000.0
            for attempt_id, last in \
                    self.ctx.task_comm.sessions_snapshot().items():
                if now - last > cutoff:
                    log.warning("attempt %s heartbeat timed out (%.1fs)",
                                attempt_id, now - last)
                    self.ctx.dispatch(TaskAttemptEvent(
                        TaskAttemptEventType.TA_TIMED_OUT, attempt_id,
                        diagnostics=f"no heartbeat for {now - last:.1f}s"))
        if self.stuck_ms and self.stuck_ms > 0:
            # progress-stuck: heartbeats arrive but nothing moves
            # (TaskHeartbeatHandler progress check; reference:
            # tez.task.progress.stuck.interval-ms)
            cutoff = self.stuck_ms / 1000.0
            for attempt_id, last in \
                    self.ctx.task_comm.activity_snapshot().items():
                if now - last > cutoff:
                    log.warning("attempt %s made no progress for %.1fs; "
                                "killing for retry", attempt_id, now - last)
                    # the runner is ALIVE (it heartbeats): tell it to die
                    # so its container frees for the retry — TA_TIMED_OUT
                    # alone only updates AM state.  kill_attempt also
                    # drops the session from the snapshots, so this fires
                    # once per hang, not once per tick.
                    self.ctx.task_comm.kill_attempt(attempt_id)
                    self.ctx.dispatch(TaskAttemptEvent(
                        TaskAttemptEventType.TA_TIMED_OUT, attempt_id,
                        diagnostics=f"no progress for {now - last:.1f}s "
                                    f"(tez.task.progress.stuck.interval-ms="
                                    f"{self.stuck_ms})"))
