"""Hosts a user VertexManagerPlugin inside the AM with error containment.

Reference parity: tez-dag/.../dag/impl/VertexManager.java:93 (serialized
event queue + user-code error funnel) and VertexImpl's default-manager
selection: custom descriptor > ShuffleVertexManager for scatter-gather
inputs > InputReadyVertexManager for one-to-one > RootInputVertexManager for
root inputs > ImmediateStartVertexManager.
"""
from __future__ import annotations

import logging
from typing import Any, Dict, List, Optional, Sequence, TYPE_CHECKING

from tez_tpu.api.events import InputDataInformationEvent, VertexManagerEvent
from tez_tpu.api.vertex_manager import (ScheduleTaskRequest,
                                        TaskAttemptIdentifier,
                                        VertexManagerPluginContext,
                                        VertexLocationHint, VertexStateUpdate)
from tez_tpu.am.events import VertexEvent, VertexEventType
from tez_tpu.common.ids import TaskAttemptId
from tez_tpu.common.payload import (UserPayload,
                                    VertexManagerPluginDescriptor)
from tez_tpu.dag.edge_property import DataMovementType, EdgeProperty

if TYPE_CHECKING:
    from tez_tpu.am.vertex_impl import VertexImpl

log = logging.getLogger(__name__)


def pick_default_manager(vertex: "VertexImpl") -> VertexManagerPluginDescriptor:
    in_types = {e.edge_property.data_movement_type
                for e in vertex.in_edges.values()}
    if DataMovementType.SCATTER_GATHER in in_types or \
            DataMovementType.CUSTOM in in_types:
        return VertexManagerPluginDescriptor.create(
            "tez_tpu.library.vertex_managers:ShuffleVertexManager")
    if DataMovementType.ONE_TO_ONE in in_types:
        return VertexManagerPluginDescriptor.create(
            "tez_tpu.library.vertex_managers:InputReadyVertexManager")
    if vertex.plan.root_inputs:
        return VertexManagerPluginDescriptor.create(
            "tez_tpu.library.vertex_managers:RootInputVertexManager")
    return VertexManagerPluginDescriptor.create(
        "tez_tpu.library.vertex_managers:ImmediateStartVertexManager")


class _VMContext(VertexManagerPluginContext):
    def __init__(self, host: "VertexManagerHost"):
        self.host = host
        self.vertex = host.vertex
        self._reconfig_planned = False

    @property
    def vertex_name(self) -> str:
        return self.vertex.name

    @property
    def user_payload(self) -> UserPayload:
        return self.host.descriptor.payload

    def get_vertex_num_tasks(self, vertex_name: str) -> int:
        if vertex_name == self.vertex.name:
            return self.vertex.num_tasks
        v = self.vertex.dag.vertex_by_name(vertex_name)
        return v.num_tasks if v is not None else -1

    def get_vertex_conf(self) -> Any:
        """Effective vertex configuration (DAG conf merged with the plan
        conf) — payload-less default managers read runtime knobs here
        (e.g. push-shuffle ingest mode)."""
        return self.vertex.conf

    def get_input_vertex_edge_properties(self) -> Dict[str, EdgeProperty]:
        return {name: e.edge_property
                for name, e in self.vertex.in_edges.items()}

    def get_output_vertex_edge_properties(self) -> Dict[str, EdgeProperty]:
        return {name: e.edge_property
                for name, e in self.vertex.out_edges.items()}

    def get_input_vertex_groups(self) -> Dict[str, Sequence[str]]:
        return {g.group_name: g.group_vertices
                for g in self.vertex.group_input_specs}

    def schedule_tasks(self, requests: Sequence[ScheduleTaskRequest]) -> None:
        self.vertex.schedule_tasks([r.task_index for r in requests])

    def reconfigure_vertex(self, parallelism: int,
                           location_hint: Optional[VertexLocationHint] = None,
                           source_edge_properties: Optional[
                               Dict[str, EdgeProperty]] = None,
                           root_input_specs: Optional[Dict[str, Any]] = None
                           ) -> None:
        v = self.vertex
        if parallelism >= 0 and parallelism != v.num_tasks:
            v._recreate_tasks(parallelism)
        edge_journal = {}
        if source_edge_properties:
            from tez_tpu.am.recovery import _payload_to_wire
            for src_name, prop in source_edge_properties.items():
                edge = v.in_edges.get(src_name)
                if edge is None:
                    continue
                edge.edge_property = prop
                if prop.edge_manager_descriptor is not None:
                    edge.set_edge_manager(prop.edge_manager_descriptor)
                    desc = prop.edge_manager_descriptor
                    edge_journal[src_name] = {
                        "class_name": desc.class_name,
                        "payload": _payload_to_wire(desc.payload.load()),
                    }
        # journaled via VERTEX_CONFIGURE_DONE so a recovering AM can RESTORE
        # this decision instead of re-running the vertex from scratch
        # (reference: VertexConfigurationDoneEvent in RecoveryParser.java:658)
        v._reconfig_journal = {"parallelism": v.num_tasks,
                               "edges": edge_journal}

    def vertex_reconfiguration_planned(self) -> None:
        self._reconfig_planned = True

    def vertex_reconfiguration_restored(self) -> bool:
        return getattr(self.vertex, "_reconfig_restored", False)

    def done_reconfiguring_vertex(self) -> None:
        self._reconfig_planned = False
        self.vertex.ctx.history_vertex_configured(self.vertex)

    def send_event_to_processor(self, events: Sequence[Any],
                                task_indices: Sequence[int]) -> None:
        self.vertex.dag.send_custom_events_to_tasks(
            self.vertex, events, task_indices)

    def add_root_input_events(
            self, input_name: str,
            events: Sequence[InputDataInformationEvent]) -> None:
        self.vertex.root_input_events.setdefault(input_name, []).extend(events)

    def get_total_available_resource(self) -> int:
        return self.vertex.ctx.total_slots()

    def register_for_vertex_state_updates(self, vertex_name: str,
                                          states: Sequence[str]) -> None:
        self.vertex.dag.register_state_updates(
            vertex_name, self.host, states)


class VertexManagerHost:
    """Wraps the plugin; catches user-code errors into V_MANAGER_USER_CODE_ERROR."""

    def __init__(self, vertex: "VertexImpl",
                 descriptor: VertexManagerPluginDescriptor):
        self.vertex = vertex
        self.descriptor = descriptor
        self.context = _VMContext(self)
        self.plugin = descriptor.instantiate(self.context)

    def _guard(self, fn, *args: Any) -> None:
        try:
            fn(*args)
        except BaseException as e:  # noqa: BLE001 — user code containment
            log.exception("vertex manager error in %s", self.vertex.name)
            self.vertex.ctx.dispatch(VertexEvent(
                VertexEventType.V_MANAGER_USER_CODE_ERROR,
                self.vertex.vertex_id, diagnostics=repr(e)))

    def initialize(self) -> None:
        self._guard(self.plugin.initialize)

    def on_vertex_started(self, completions: Sequence[TaskAttemptId]) -> None:
        self._guard(self.plugin.on_vertex_started,
                    [self._ident(a) for a in completions])

    def on_source_task_completed(self, attempt_id: TaskAttemptId) -> None:
        self._guard(self.plugin.on_source_task_completed,
                    self._ident(attempt_id))

    def on_vertex_manager_event(self, event: VertexManagerEvent) -> None:
        self._guard(self.plugin.on_vertex_manager_event_received, event)

    def on_root_vertex_initialized(self, input_name: str, descriptor: Any,
                                   events: List[Any]) -> None:
        self._guard(self.plugin.on_root_vertex_initialized,
                    input_name, descriptor, events)

    def on_vertex_state_updated(self, update: VertexStateUpdate) -> None:
        self._guard(self.plugin.on_vertex_state_updated, update)

    def _ident(self, attempt_id: TaskAttemptId) -> TaskAttemptIdentifier:
        v = self.vertex.dag.vertex_by_id(attempt_id.vertex_id)
        return TaskAttemptIdentifier(
            vertex_name=v.name if v else "",
            task_index=attempt_id.task_id.id,
            attempt_number=attempt_id.id)
