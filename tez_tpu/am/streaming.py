"""Streaming execution mode: resident windowed DAGs with exactly-once
window commits (docs/streaming.md).

A stream is a DAG *template* that stays resident under the session AM and
processes unbounded input as numbered windows (micro-batches, Tez-style
"recurring DAG" pushed to its limit).  The client keeps one handle and
calls :meth:`StreamDriver.ingest`; the driver

1. spools records into CRC-framed files under
   ``<staging>/<app_id>/stream/<stream>/`` (one file per window; the same
   ``crc32-hex SP json`` framing as the recovery journal, so a torn tail
   left by a crash is detected, never replayed as data);
2. cuts window boundaries by record count
   (``tez.runtime.stream.window.count``) or punctuation record
   (``tez.runtime.stream.window.punctuation``) and atomically *seals* the
   spool (``wN.spool.open`` -> ``wN.spool`` rename);
3. runs each sealed window as a DAG named ``<stream>@w<N>`` cloned from
   the template with the window coordinate stamped into ``dag_conf``
   (``tez.runtime.stream.{id,window-id,input,output-dir}``) — windows run
   sequentially, admitted through the normal admission controller;
4. commits each window exactly once through a per-window commit ledger:
   ``WINDOW_COMMIT_STARTED`` -> atomic tmp->final renames in the output
   dir -> ``WINDOW_COMMIT_FINISHED`` (all fsync'd summary records,
   reusing the CRC journal).  The renames are idempotent, so a crash
   between STARTED and FINISHED rolls forward on replay without ever
   double-publishing a part file.

Correctness rails:

- **Window fence.**  Before window N's DAG is submitted the driver
  registers N in the epoch registry; because windows run sequentially,
  any straggler attempt still heartbeating/pushing for window N-1 when
  N is live carries a stale ``(attempt_epoch, window_id)`` stamp and is
  rejected at every seam a pre-crash zombie would be (common/epoch.py).
  Window N's zombie can therefore never contaminate window N+1.
- **Window-exact replay.**  Lineage hashes are salted with the window
  coordinate (store/lineage.py), so a task killed mid-window re-runs
  against window N's sealed spool and can reuse window N's own sealed
  store outputs — never a neighbouring window's.
- **Backpressure, never OOM.**  When ``cut - committed`` reaches
  ``tez.runtime.stream.max-lag`` the driver *blocks* ingest (source
  pacing), emits one typed ``WINDOW_LAGGING`` history event per lag
  episode, and feeds the ``stream.window.lag`` histogram.  Input is
  bounded by construction; nothing is silently dropped.
- **AM crash mid-stream.**  ``STREAM_OPENED`` journals a rebuildable
  spec (plan hex + knobs).  The successor incarnation resumes from the
  ledger (RecoveryParser.stream_records): ``WINDOW_COMMIT_FINISHED``
  windows are sealed forever and skipped, the first uncommitted sealed
  window re-runs from its surviving spool, and the ``.open`` spool —
  records that were ingested but not yet cut — becomes the open window
  again.  ``STREAM_RETIRED`` ends the stream's recovery obligation.
"""
from __future__ import annotations

import dataclasses
import glob
import json
import logging
import os
import queue
import threading
import zlib
from typing import Any, Dict, List, Optional, Set

from tez_tpu.am.dag_impl import DAGState
from tez_tpu.am.history import HistoryEvent, HistoryEventType
from tez_tpu.common import config as C
from tez_tpu.common import epoch as epoch_registry
from tez_tpu.common import clock, faults, metrics
from tez_tpu.dag.plan import DAGPlan

log = logging.getLogger(__name__)

#: spool filename width — windows sort lexically == numerically
_W = 6


class StreamError(RuntimeError):
    """Typed failure surface for stream operations."""


class StreamFailedError(StreamError):
    """The stream's window loop hit a non-recoverable failure; ingest and
    drain raise this instead of blocking forever."""


class StreamSpoolError(StreamError):
    """A spool record failed CRC validation or JSON decoding."""


# -- spool framing (shared with library/streaming.py readers) ---------------

def encode_spool_record(record: Any) -> str:
    """``crc32-hex SP json`` — identical framing to the recovery journal
    so a torn tail (crash mid-append) is detected, not replayed."""
    payload = json.dumps(record, sort_keys=True)
    crc = zlib.crc32(payload.encode("utf-8")) & 0xFFFFFFFF
    return "%08x %s" % (crc, payload)


def decode_spool_record(line: str) -> Any:
    if len(line) < 10 or line[8] != " ":
        raise StreamSpoolError("malformed spool framing")
    try:
        want = int(line[:8], 16)
    except ValueError as e:
        raise StreamSpoolError("malformed CRC prefix") from e
    payload = line[9:]
    got = zlib.crc32(payload.encode("utf-8")) & 0xFFFFFFFF
    if got != want:
        raise StreamSpoolError(
            f"spool CRC mismatch (recorded {want:08x}, computed {got:08x})")
    try:
        return json.loads(payload)
    except ValueError as e:
        raise StreamSpoolError(f"bad spool JSON: {e}") from e


def read_spool(path: str) -> List[Any]:
    """Decode a spool file; a torn FINAL line (crash mid-append) is
    dropped, corruption anywhere else raises."""
    records: List[Any] = []
    if not os.path.exists(path):
        return records
    with open(path, "r") as fh:
        lines = fh.read().splitlines()
    for i, line in enumerate(lines):
        if not line:
            continue
        try:
            records.append(decode_spool_record(line))
        except StreamSpoolError:
            if i == len(lines) - 1:
                log.warning("spool %s: dropping torn final record", path)
                break
            raise
    return records


def stream_dir(staging: str, app_id: str, stream: str) -> str:
    return os.path.join(staging, app_id, "stream", stream)


def spool_name(window_id: int, sealed: bool = True) -> str:
    base = f"w{window_id:0{_W}d}.spool"
    return base if sealed else base + ".open"


# -- the journalable stream spec --------------------------------------------

@dataclasses.dataclass
class StreamSpec:
    """Everything a successor AM needs to rebuild the driver.

    ``plan`` is the window *template*: a normal DAGPlan whose source
    vertex reads ``tez.runtime.stream.input`` and whose sink writes
    window-tagged tmp files into ``output_dir`` (library/streaming.py has
    the stock pair).  The driver clones it per window."""
    name: str
    plan: DAGPlan
    output_dir: str
    #: per-stream conf overrides layered over the AM conf (window count,
    #: punctuation, lag bound ... any tez.runtime.stream.* knob)
    conf: Dict[str, Any] = dataclasses.field(default_factory=dict)

    def journal_data(self) -> Dict[str, Any]:
        return {"stream": self.name,
                "plan": self.plan.serialize().hex(),
                "output_dir": self.output_dir,
                "conf": dict(self.conf)}

    @classmethod
    def from_journal(cls, data: Dict[str, Any]) -> "StreamSpec":
        return cls(name=str(data["stream"]),
                   plan=DAGPlan.deserialize(bytes.fromhex(data["plan"])),
                   output_dir=str(data.get("output_dir", "")),
                   conf=dict(data.get("conf") or {}))


class StreamDriver:
    """AM-side resident driver for ONE stream (built via
    DAGAppMaster.open_stream, or resumed by recovery)."""

    def __init__(self, am: Any, spec: StreamSpec,
                 resume: Optional[Dict[str, Any]] = None):
        # the stream name becomes a metric-name segment
        # (``stream.<name>.window.latency`` et al., split into a
        # stream= label at exposition) — so "window" is reserved for the
        # session-wide aggregate family, and a dot would make the label
        # split ambiguous
        if not spec.name or spec.name == "window" or "." in spec.name:
            raise StreamError(
                f"invalid stream name {spec.name!r}: must be non-empty, "
                f"not the reserved name 'window', and contain no '.'")
        self.am = am
        self.spec = spec
        conf = am.conf.merged(spec.conf)
        self.window_count = max(1, int(conf.get(C.STREAM_WINDOW_COUNT) or 1))
        self.punctuation = str(conf.get(C.STREAM_WINDOW_PUNCTUATION) or "")
        self.max_lag = max(1, int(conf.get(C.STREAM_MAX_LAG) or 1))
        self.poll_s = float(conf.get(C.STREAM_INGEST_POLL_MS) or 10.0) / 1e3
        self.window_timeout = float(
            conf.get(C.STREAM_WINDOW_TIMEOUT_SECS) or 120.0)
        self.dir = stream_dir(str(am.conf.get(C.STAGING_DIR)), am.app_id,
                              spec.name)
        os.makedirs(self.dir, exist_ok=True)
        os.makedirs(spec.output_dir, exist_ok=True)
        self._lock = threading.Condition()
        self._queue: "queue.Queue[Optional[int]]" = queue.Queue()
        self._committed: Set[int] = set()
        self._aborted: Set[int] = set()
        self._replayed: Set[int] = set()
        self._cut_monotonic: Dict[int, float] = {}
        self._cut = 0            # highest sealed window id
        self._open_id = 1        # window currently ingesting
        self._open_count = 0
        self._open_fh: Optional[Any] = None
        self._lag_episode = False
        self._lag_events = 0
        self._dead = False
        self._retired = False
        self._error: Optional[str] = None
        self._worker: Optional[threading.Thread] = None
        if resume is not None:
            self._resume_from(resume)

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> "StreamDriver":
        self._worker = threading.Thread(
            target=self._run, name=f"stream-{self.spec.name}", daemon=True)
        self._worker.start()
        return self

    def crash(self) -> None:
        """Non-graceful teardown (AM crash): stop the loop, journal
        nothing — the successor incarnation resumes from the ledger."""
        with self._lock:
            self._dead = True
            self._lock.notify_all()
        self._queue.put(None)

    # -- ingest (client-facing) ----------------------------------------------
    def ingest(self, records: List[Any]) -> int:
        """Append records to the open window, cutting boundaries as they
        cross.  BLOCKS when the stream is ``max_lag`` windows behind
        (bounded lag: source pacing instead of OOM/drop).  Returns the
        open window id after the append."""
        for record in records:
            self._check_alive()
            self._backpressure_wait()
            # pacing lever for chaos/tests: a delay rule here slows the
            # source exactly like a slow upstream would
            faults.fire("stream.ingest",
                        detail=f"{self.spec.name}@w{self._open_id}")
            self._append(record)
            if self._should_cut(record):
                self._cut_window()
        return self._open_id

    def punctuate(self) -> int:
        """Force-cut the open window (empty windows are skipped)."""
        self._check_alive()
        if self._open_count > 0:
            self._cut_window()
        return self._open_id

    def _append(self, record: Any) -> None:
        if self._open_fh is None:
            path = os.path.join(self.dir,
                                spool_name(self._open_id, sealed=False))
            self._open_fh = open(path, "a")
        self._open_fh.write(encode_spool_record(record) + "\n")
        self._open_fh.flush()
        self._open_count += 1

    def _should_cut(self, record: Any) -> bool:
        if self.punctuation and record == self.punctuation:
            return True
        return self._open_count >= self.window_count

    def _cut_window(self) -> None:
        w = self._open_id
        self._open_fh.flush()
        os.fsync(self._open_fh.fileno())
        self._open_fh.close()
        self._open_fh = None
        # atomic seal: the rename IS the cut record (no journal write —
        # a crash before it leaves the records in the open window, after
        # it leaves a sealed window the resume path re-runs)
        os.rename(os.path.join(self.dir, spool_name(w, sealed=False)),
                  os.path.join(self.dir, spool_name(w)))
        with self._lock:
            self._cut = w
            self._cut_monotonic[w] = clock.mono_s()
            self._open_id = w + 1
            self._open_count = 0
        self._queue.put(w)
        metrics.set_gauge(f"stream.{self.spec.name}.cut", float(w))

    def _lag(self) -> int:
        return self._cut - len(self._committed) - len(self._aborted)

    def _backpressure_wait(self) -> None:
        announce = None
        with self._lock:
            if self._lag() < self.max_lag:
                return
            if not self._lag_episode:
                # one typed event per episode, not one per blocked record
                self._lag_episode = True
                self._lag_events += 1
                announce = {"stream": self.spec.name, "lag": self._lag(),
                            "max_lag": self.max_lag,
                            "open_window": self._open_id}
        if announce is not None:
            # journal outside the driver lock: the recovery appender has
            # its own write lock, and commit threads journal while this
            # lock is wanted — holding both here is a lock-order cycle
            self.am.history(HistoryEvent(
                HistoryEventType.WINDOW_LAGGING, data=announce))
            log.warning("stream %s: lag %d >= %d, pacing source",
                        self.spec.name, announce["lag"], self.max_lag)
        with self._lock:
            while not self._dead and self._error is None and \
                    self._lag() >= self.max_lag:
                metrics.observe("stream.window.lag", float(self._lag()))
                self._lock.wait(timeout=self.poll_s)
            self._lag_episode = False
        self._check_alive()

    def _check_alive(self) -> None:
        if self._error is not None:
            raise StreamFailedError(
                f"stream {self.spec.name} failed: {self._error}")
        if self._dead or self._retired:
            raise StreamError(f"stream {self.spec.name} is closed")

    # -- window loop (worker thread) ------------------------------------------
    def _run(self) -> None:
        while True:
            try:
                w = self._queue.get(timeout=0.5)
            except queue.Empty:
                if self._dead or self._retired:
                    return
                continue
            if w is None:
                return
            try:
                self._run_window(w)
            except BaseException as e:  # noqa: BLE001 — typed to callers
                if self._dead:
                    return
                log.exception("stream %s: window %d failed",
                              self.spec.name, w)
                self._abort_window(w, repr(e))
                with self._lock:
                    self._error = f"window {w}: {e!r}"
                    self._lock.notify_all()
                return

    def _window_plan(self, w: int) -> DAGPlan:
        t = self.spec.plan
        dag_conf = dict(t.dag_conf)
        dag_conf[C.STREAM_ID.name] = self.spec.name
        dag_conf[C.STREAM_WINDOW_ID.name] = w
        dag_conf[C.STREAM_INPUT.name] = os.path.join(self.dir, spool_name(w))
        dag_conf[C.STREAM_OUTPUT_DIR.name] = self.spec.output_dir
        return dataclasses.replace(t, name=f"{self.spec.name}@w{w}",
                                   dag_conf=dag_conf)

    def _run_window(self, w: int) -> None:
        if w in self._committed:       # resume belt-and-braces: sealed
            return                     # forever, never re-run
        # fence registration: from here on, any straggler stamped with an
        # earlier window of this stream is stale at every seam
        epoch_registry.register_window(self.am.app_id, self.spec.name, w)
        replay = w in self._replayed
        plan = self._window_plan(w)
        dag_id = self.am.submit_dag(plan)
        final = self._wait(dag_id)
        if final is not DAGState.SUCCEEDED:
            raise StreamError(f"window DAG {plan.name} finished "
                              f"{getattr(final, 'name', final)}")
        self._commit_window(w, str(dag_id), replay=replay)

    def _wait(self, dag_id: Any) -> Any:
        deadline = clock.mono_s() + self.window_timeout
        while True:
            try:
                return self.am.wait_for_dag(dag_id, timeout=0.5)
            except TimeoutError:
                if self._dead:
                    raise StreamError("AM crashed mid-window") from None
                if clock.mono_s() >= deadline:
                    raise

    def _commit_window(self, w: int, dag_id: str, replay: bool = False) -> None:
        """The exactly-once ledger bracket.  STARTED and FINISHED are
        fsync'd summary records; the renames between them are idempotent,
        so replaying an open bracket after a crash rolls forward without
        double-publishing."""
        self.am.history(HistoryEvent(
            HistoryEventType.WINDOW_COMMIT_STARTED,
            dag_id=dag_id,
            data={"stream": self.spec.name, "window_id": w}))
        # the chaos lever: a fail rule here IS the mid-commit crash window
        faults.fire("stream.window.commit", detail=f"{self.spec.name}@w{w}")
        published = self._publish_window(w)
        self.am.history(HistoryEvent(
            HistoryEventType.WINDOW_COMMIT_FINISHED,
            dag_id=dag_id,
            data={"stream": self.spec.name, "window_id": w,
                  "parts": published, "replayed": replay}))
        with self._lock:
            self._committed.add(w)
            cut_at = self._cut_monotonic.pop(w, None)
            self._lock.notify_all()
        if cut_at is not None:
            ms = (clock.mono_s() - cut_at) * 1000.0
            metrics.observe("stream.window.latency", ms)
            # per-stream twin of the aggregate: the series the SLO
            # watchdog checks (and burn-evaluates) per stream, split into
            # a stream= label at exposition — why "window" is a reserved
            # stream name (__init__ guard)
            metrics.observe(f"stream.{self.spec.name}.window.latency", ms)
        metrics.set_gauge(f"stream.{self.spec.name}.committed", float(w))
        self._tick_slo()

    def _publish_window(self, w: int) -> int:
        """Atomic tmp->final renames; idempotent (a final that already
        exists means a prior bracket published it — drop the tmp)."""
        out = self.spec.output_dir
        published = 0
        for tmp in sorted(glob.glob(
                os.path.join(out, f".w{w:0{_W}d}.part*.tmp"))):
            base = os.path.basename(tmp)
            final = os.path.join(out, base[1:-len(".tmp")])
            if os.path.exists(final):
                os.remove(tmp)
            else:
                os.rename(tmp, final)
            published += 1
        return published

    def _abort_window(self, w: int, reason: str) -> None:
        try:
            self.am.history(HistoryEvent(
                HistoryEventType.WINDOW_COMMIT_ABORTED,
                data={"stream": self.spec.name, "window_id": w,
                      "reason": reason}))
            # roll back: drop this window's unpublished tmp files
            for tmp in glob.glob(os.path.join(
                    self.spec.output_dir, f".w{w:0{_W}d}.part*.tmp")):
                os.remove(tmp)
            with self._lock:
                self._aborted.add(w)
                self._lock.notify_all()
        except Exception:  # noqa: BLE001 — abort is best-effort cleanup
            log.exception("stream %s: abort of window %d failed",
                          self.spec.name, w)

    def _tick_slo(self) -> None:
        # re-sweep AFTER the latency observation lands — the admission
        # tick at DAG-finish ran before the commit bracket closed
        try:
            self.am.admission._slo_tick()
        except Exception:  # noqa: BLE001 — diagnostics never fail commits
            log.exception("stream SLO tick failed")

    # -- drain / retire --------------------------------------------------------
    def drain(self, timeout: float = 120.0) -> Dict[str, Any]:
        """Cut the final partial window, wait for every sealed window to
        commit, journal STREAM_RETIRED.  Returns the final status."""
        self._check_alive()
        if self._open_count > 0:
            self._cut_window()
        deadline = clock.mono_s() + timeout
        with self._lock:
            while len(self._committed) + len(self._aborted) < self._cut:
                if self._error is not None:
                    raise StreamFailedError(
                        f"stream {self.spec.name} failed: {self._error}")
                if self._dead:
                    raise StreamError("AM crashed during drain")
                if not self._lock.wait(timeout=0.2) and \
                        clock.mono_s() >= deadline:
                    raise TimeoutError(
                        f"stream {self.spec.name}: {self._cut - len(self._committed) - len(self._aborted)} "
                        f"window(s) still uncommitted after {timeout}s")
            self._retired = True
        self._queue.put(None)
        self.am.history(HistoryEvent(
            HistoryEventType.STREAM_RETIRED,
            data={"stream": self.spec.name,
                  "windows_committed": len(self._committed),
                  "windows_aborted": len(self._aborted)}))
        if self._worker is not None:
            self._worker.join(timeout=5.0)
        return self.status()

    def status(self) -> Dict[str, Any]:
        with self._lock:
            return {"stream": self.spec.name,
                    "cut": self._cut,
                    "open_window": self._open_id,
                    "open_records": self._open_count,
                    "committed": sorted(self._committed),
                    "aborted": sorted(self._aborted),
                    "replayed": sorted(self._replayed),
                    "lag": self._lag(),
                    "lag_episodes": self._lag_events,
                    "retired": self._retired,
                    "error": self._error}

    # -- crash recovery --------------------------------------------------------
    def _resume_from(self, rec: Dict[str, Any]) -> None:
        """Rebuild position from the ledger + surviving spools.

        Committed windows are sealed forever.  Every sealed-but-uncommitted
        spool re-enters the run queue in order (window-exact replay: same
        spool, same window id, lineage salted identically — sealed store
        outputs from the crashed incarnation are reusable).  An ``.open``
        spool becomes the open window again, its ingested records intact."""
        self._committed = set(rec.get("committed") or ())
        self._aborted = set(rec.get("aborted") or ())
        sealed = sorted(
            int(os.path.basename(p)[1:1 + _W])
            for p in glob.glob(os.path.join(self.dir, "w" + "[0-9]" * _W
                                            + ".spool")))
        open_spools = glob.glob(
            os.path.join(self.dir, "w" + "[0-9]" * _W + ".spool.open"))
        self._cut = max(sealed) if sealed else 0
        self._open_id = self._cut + 1
        if open_spools:
            path = sorted(open_spools)[-1]
            self._open_id = max(self._open_id,
                                int(os.path.basename(path)[1:1 + _W]))
            self._open_count = len(read_spool(path))
        for w in sealed:
            if w in self._committed or w in self._aborted:
                continue
            self._replayed.add(w)
            self._cut_monotonic[w] = clock.mono_s()
            self._queue.put(w)
        if self._replayed:
            log.info("stream %s: resuming — %d committed, replaying "
                     "window(s) %s, open window %d (%d record(s))",
                     self.spec.name, len(self._committed),
                     sorted(self._replayed), self._open_id,
                     self._open_count)

    @classmethod
    def resume(cls, am: Any, rec: Dict[str, Any]) -> Optional["StreamDriver"]:
        """Build + start a driver from a RecoveryParser.stream_records()
        entry; None when the stream was retired or the spec is missing."""
        if rec.get("retired") or not rec.get("spec"):
            return None
        spec = StreamSpec.from_journal(rec["spec"])
        return cls(am, spec, resume=rec).start()
