"""DAG-level priority assignment + scheduling gate variants.

Reference parity: tez-dag/.../dag/impl/DAGSchedulerNaturalOrder.java:75 —
priority = topological depth (deeper vertices run at lower priority so
upstream work drains first) — and DAGSchedulerNaturalOrderControlled.java:54,
which additionally HOLDS BACK a vertex's task scheduling until every
SEQUENTIAL source vertex has scheduled all of its own tasks (downstream
tasks must not grab slots before their sources are even queued).
Selected via tez.am.dag.scheduler.class.
"""
from __future__ import annotations

from typing import TYPE_CHECKING, Dict

if TYPE_CHECKING:
    from tez_tpu.am.dag_impl import DAGImpl


def _assign_depth_priorities(dag: "DAGImpl") -> None:
    """Longest-path-from-root depth, priority = (depth+1)*3 with the +/-1
    band reserved for retries/speculation (reference multiplies by 3 to give
    each vertex a priority band)."""
    order = []
    indeg = {v.name: 0 for v in dag.plan.vertices}
    adj: Dict[str, list] = {v.name: [] for v in dag.plan.vertices}
    for e in dag.plan.edges:
        adj[e.input_vertex].append(e.output_vertex)
        indeg[e.output_vertex] += 1
    ready = [n for n, d in indeg.items() if d == 0]
    depth = {n: 0 for n in ready}
    while ready:
        n = ready.pop()
        order.append(n)
        for m in adj[n]:
            depth[m] = max(depth.get(m, 0), depth[n] + 1)
            indeg[m] -= 1
            if indeg[m] == 0:
                ready.append(m)

    for name, v in dag.vertices.items():
        v.distance_from_root = depth.get(name, 0)
        v.priority = (depth.get(name, 0) + 1) * 3


class DAGSchedulerNaturalOrder:
    """Priorities only; vertex managers decide when tasks schedule."""

    controlled = False

    def apply(self, dag: "DAGImpl") -> None:
        _assign_depth_priorities(dag)
        for v in dag.vertices.values():
            v.controlled_scheduling = self.controlled


class DAGSchedulerNaturalOrderControlled(DAGSchedulerNaturalOrder):
    """Same priorities + the sources-fully-scheduled gate
    (VertexImpl.schedule_tasks defers until every SEQUENTIAL source vertex
    has scheduled all of its tasks)."""

    controlled = True


def apply_dag_scheduler(dag: "DAGImpl") -> None:
    from tez_tpu.common import config as C
    from tez_tpu.common.payload import resolve_class
    resolve_class(dag.conf.get(C.DAG_SCHEDULER_CLASS))().apply(dag)
