"""DAG-level priority assignment + scheduling gate variants.

Reference parity: tez-dag/.../dag/impl/DAGSchedulerNaturalOrder.java:75 —
priority = topological depth (deeper vertices run at lower priority so
upstream work drains first) — and DAGSchedulerNaturalOrderControlled.java:54,
which additionally HOLDS BACK a vertex's task scheduling until every
SEQUENTIAL source vertex has scheduled all of its own tasks (downstream
tasks must not grab slots before their sources are even queued).
Selected via tez.am.dag.scheduler.class.
"""
from __future__ import annotations

from typing import TYPE_CHECKING, Dict

if TYPE_CHECKING:
    from tez_tpu.am.dag_impl import DAGImpl


def _assign_depth_priorities(dag: "DAGImpl") -> None:
    """Longest-path-from-root depth, priority = (depth+1)*3 with the +/-1
    band reserved for retries/speculation (reference multiplies by 3 to give
    each vertex a priority band)."""
    order = []
    indeg = {v.name: 0 for v in dag.plan.vertices}
    adj: Dict[str, list] = {v.name: [] for v in dag.plan.vertices}
    for e in dag.plan.edges:
        adj[e.input_vertex].append(e.output_vertex)
        indeg[e.output_vertex] += 1
    ready = [n for n, d in indeg.items() if d == 0]
    depth = {n: 0 for n in ready}
    while ready:
        n = ready.pop()
        order.append(n)
        for m in adj[n]:
            depth[m] = max(depth.get(m, 0), depth[n] + 1)
            indeg[m] -= 1
            if indeg[m] == 0:
                ready.append(m)

    for name, v in dag.vertices.items():
        v.distance_from_root = depth.get(name, 0)
        v.priority = (depth.get(name, 0) + 1) * 3


def _push_coschedule(dag: "DAGImpl") -> None:
    """Map-wave / merge-wave co-scheduling for push-based shuffle.

    With eager push enabled, scatter-gather consumers run in INGEST mode:
    the ShuffleVertexManager releases them at the push start fraction, and
    here they get (a) priority bumped one notch INTO the vertex's reserved
    +/-1 band — deeper-than-source but ahead of the source's retry band —
    so released reducers interleave with the still-running map wave
    instead of queueing strictly behind it, and (b) the controlled gate
    lifted (the sources-fully-scheduled hold-back is exactly the barrier
    push exists to break).  Priorities elsewhere are untouched.
    """
    from tez_tpu.common import config as C
    push_on = dag.conf.get(C.PUSH_ENABLED.name, C.PUSH_ENABLED.default)
    if isinstance(push_on, str):
        push_on = push_on.lower() in ("1", "true", "yes")
    if not push_on:
        return
    from tez_tpu.dag.edge_property import DataMovementType
    consumers = {e.output_vertex for e in dag.plan.edges
                 if e.edge_property.data_movement_type in (
                     DataMovementType.SCATTER_GATHER,
                     DataMovementType.CUSTOM)}
    for name in consumers:
        v = dag.vertices.get(name)
        if v is None:
            continue
        v.priority -= 1
        v.controlled_scheduling = False


class DAGSchedulerNaturalOrder:
    """Priorities only; vertex managers decide when tasks schedule."""

    controlled = False

    def apply(self, dag: "DAGImpl") -> None:
        _assign_depth_priorities(dag)
        for v in dag.vertices.values():
            v.controlled_scheduling = self.controlled
        _push_coschedule(dag)


class DAGSchedulerNaturalOrderControlled(DAGSchedulerNaturalOrder):
    """Same priorities + the sources-fully-scheduled gate
    (VertexImpl.schedule_tasks defers until every SEQUENTIAL source vertex
    has scheduled all of its tasks)."""

    controlled = True


def apply_dag_scheduler(dag: "DAGImpl") -> None:
    from tez_tpu.common import config as C
    from tez_tpu.common.payload import resolve_class
    resolve_class(dag.conf.get(C.DAG_SCHEDULER_CLASS))().apply(dag)
