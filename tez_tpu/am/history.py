"""History subsystem: every lifecycle transition emits a typed HistoryEvent.

Reference parity: tez-dag/.../dag/history/ (~25 event classes,
HistoryEvents.proto), HistoryEventHandler.java:46 fanning out to the recovery
journal and a pluggable HistoryLoggingService; SimpleHistoryLoggingService and
ProtoHistoryLoggingService analogs (here: in-memory and JSONL-file loggers —
the JSONL journal doubles as the analyzer/trace input, SURVEY.md §5.1).
"""
from __future__ import annotations

import dataclasses
import enum
import json
import os
import threading
import time
from typing import Any, Dict, List, Optional

from tez_tpu.common import clock
from tez_tpu.common.payload import resolve_class


class HistoryEventType(enum.Enum):
    APP_LAUNCHED = enum.auto()
    AM_LAUNCHED = enum.auto()
    AM_STARTED = enum.auto()
    DAG_SUBMITTED = enum.auto()
    DAG_INITIALIZED = enum.auto()
    DAG_STARTED = enum.auto()
    DAG_COMMIT_STARTED = enum.auto()
    # commit-ledger terminals: STARTED..FINISHED bracket the window where
    # committers may have mutated the filesystem; ABORTED records that the
    # partial commit was rolled back.  All three are summary events (fsync'd
    # before the next ledger state can be reached).
    DAG_COMMIT_FINISHED = enum.auto()
    DAG_COMMIT_ABORTED = enum.auto()
    DAG_FINISHED = enum.auto()
    DAG_KILL_REQUEST = enum.auto()
    # multi-tenant admission ledger: QUEUED marks a submission parked in
    # the bounded FIFO behind the concurrency cap (its plan rides in
    # data, so a crashed queue consumer never loses an accepted submit);
    # ADMISSION_SHED records the typed RETRY-AFTER verdict returned to
    # the client.  Every QUEUED submission must later reach
    # DAG_SUBMITTED or DAG_ADMISSION_SHED — the lossless-admission
    # contract (docs/multitenancy.md).
    DAG_QUEUED = enum.auto()
    DAG_ADMISSION_SHED = enum.auto()
    # AM crash survival (docs/recovery.md): REQUEUED_ON_RECOVERY marks a
    # journaled-but-unpromoted submission re-entering the admission queue
    # under a successor AM incarnation (it carries the plan again, so a
    # second crash replays from IT); ATTEMPT_FENCED records a zombie task
    # attempt from a superseded incarnation rejected at the umbilical.
    DAG_REQUEUED_ON_RECOVERY = enum.auto()
    ATTEMPT_FENCED = enum.auto()
    VERTEX_INITIALIZED = enum.auto()
    VERTEX_STARTED = enum.auto()
    VERTEX_CONFIGURE_DONE = enum.auto()
    VERTEX_COMMIT_STARTED = enum.auto()
    VERTEX_GROUP_COMMIT_STARTED = enum.auto()
    VERTEX_GROUP_COMMIT_FINISHED = enum.auto()
    VERTEX_FINISHED = enum.auto()
    TASK_STARTED = enum.auto()
    TASK_FINISHED = enum.auto()
    TASK_ATTEMPT_STARTED = enum.auto()
    TASK_ATTEMPT_FINISHED = enum.auto()
    CONTAINER_LAUNCHED = enum.auto()
    CONTAINER_STOPPED = enum.auto()
    # robustness transitions (AMNodeImpl state changes): node_id rides in
    # data — nodes are hosts, not DAG-scoped entities
    NODE_BLACKLISTED = enum.auto()
    NODE_FORCED_ACTIVE = enum.auto()
    # SLO watchdog (obs/slo.py): one event per latched breach episode
    # (tenant, kind, stream, observed, target ride in data) so chaos/soak
    # can assert on breaches straight from the journal
    TENANT_SLO_BREACH = enum.auto()
    # burn-rate alerting (obs/slo.py evaluate_burn, docs/telemetry.md):
    # one event per latched burn episode — a fast-window p95 crossed
    # threshold x target while the cumulative histogram was still under
    # target, i.e. the series is TRENDING toward breach.  Carries the
    # same tenant/kind/stream labels as TENANT_SLO_BREACH so doctor can
    # join alert -> breach per stream.  Summary event: the chaos leg
    # asserts journal ordering (alert strictly before breach), so the
    # alert must be on disk when it fires, not when the buffer drains.
    SLO_BURN_ALERT = enum.auto()
    # live telemetry plane (am/telemetry.py): the sampler's ring/overflow
    # accounting journaled once at AM stop, so counter_diff can flag
    # eviction/scrape-error growth across runs without scraping a live AM
    TELEMETRY_SNAPSHOT = enum.auto()
    # streaming mode (am/streaming.py, docs/streaming.md): STREAM_OPENED
    # journals the resident stream's full spec (so a successor AM can
    # rebuild the window driver after a crash); STREAM_RETIRED seals it —
    # no window commit may ever follow.  WINDOW_COMMIT_STARTED/FINISHED/
    # ABORTED are the per-window exactly-once ledger, the windowed analog
    # of the DAG_COMMIT_* records (stream + window_id ride in data);
    # WINDOW_LAGGING types one backpressure episode (ingest blocked at
    # tez.runtime.stream.max-lag).  All summary events: each must be on
    # disk before the stream advances past it.
    STREAM_OPENED = enum.auto()
    STREAM_RETIRED = enum.auto()
    WINDOW_COMMIT_STARTED = enum.auto()
    WINDOW_COMMIT_FINISHED = enum.auto()
    WINDOW_COMMIT_ABORTED = enum.auto()
    WINDOW_LAGGING = enum.auto()
    # relational query layer (tez_tpu/query, docs/query.md):
    # QUERY_SUBMITTED records one planned query per run — the logical
    # fingerprint, per-join physical strategy, the vertex -> operator
    # attribution map, and the store's lineage cache-hit delta — so
    # counter_diff can count plans/cache hits per session journal.
    # QUERY_REPLANNED types one PlanFeedback decision (strategy flip or
    # reducer bump) with the doctor plane it blamed; summary event so
    # the decision is on disk before the replanned DAG submits — the
    # doctor must be able to blame the planner itself after a crash.
    QUERY_SUBMITTED = enum.auto()
    QUERY_REPLANNED = enum.auto()


#: Events whose loss recovery cannot tolerate — flushed synchronously.
#: Reference: SummaryEvent handling in RecoveryService (hflush :246-250).
SUMMARY_EVENT_TYPES = frozenset({
    HistoryEventType.DAG_SUBMITTED,
    HistoryEventType.DAG_STARTED,
    HistoryEventType.DAG_COMMIT_STARTED,
    HistoryEventType.DAG_COMMIT_FINISHED,
    HistoryEventType.DAG_COMMIT_ABORTED,
    HistoryEventType.VERTEX_COMMIT_STARTED,
    HistoryEventType.VERTEX_GROUP_COMMIT_STARTED,
    HistoryEventType.VERTEX_GROUP_COMMIT_FINISHED,
    HistoryEventType.DAG_FINISHED,
    HistoryEventType.DAG_KILL_REQUEST,
    HistoryEventType.DAG_QUEUED,
    HistoryEventType.DAG_ADMISSION_SHED,
    HistoryEventType.DAG_REQUEUED_ON_RECOVERY,
    HistoryEventType.ATTEMPT_FENCED,
    HistoryEventType.TENANT_SLO_BREACH,
    HistoryEventType.SLO_BURN_ALERT,
    HistoryEventType.TELEMETRY_SNAPSHOT,
    HistoryEventType.STREAM_OPENED,
    HistoryEventType.STREAM_RETIRED,
    HistoryEventType.WINDOW_COMMIT_STARTED,
    HistoryEventType.WINDOW_COMMIT_FINISHED,
    HistoryEventType.WINDOW_COMMIT_ABORTED,
    HistoryEventType.WINDOW_LAGGING,
    HistoryEventType.QUERY_SUBMITTED,
    HistoryEventType.QUERY_REPLANNED,
})


@dataclasses.dataclass
class HistoryEvent:
    event_type: HistoryEventType
    # entity ids as strings for serializability; None where not applicable
    dag_id: Optional[str] = None
    vertex_id: Optional[str] = None
    task_id: Optional[str] = None
    attempt_id: Optional[str] = None
    container_id: Optional[str] = None
    timestamp: float = 0.0
    data: Dict[str, Any] = dataclasses.field(default_factory=dict)

    def __post_init__(self) -> None:
        if not self.timestamp:
            self.timestamp = clock.wall_s()

    @property
    def is_summary(self) -> bool:
        return self.event_type in SUMMARY_EVENT_TYPES

    def to_json(self) -> str:
        d = dataclasses.asdict(self)
        d["event_type"] = self.event_type.name
        return json.dumps(d, default=str)

    @staticmethod
    def from_json(line: str) -> "HistoryEvent":
        d = json.loads(line)
        d["event_type"] = HistoryEventType[d["event_type"]]
        return HistoryEvent(**d)


class HistoryLoggingService:
    """SPI (reference: HistoryLoggingService.java)."""

    def start(self) -> None: ...

    def stop(self) -> None: ...

    def handle(self, event: HistoryEvent) -> None:
        raise NotImplementedError


class InMemoryHistoryLoggingService(HistoryLoggingService):
    def __init__(self, conf: Any = None):
        self.events: List[HistoryEvent] = []
        self._lock = threading.Lock()

    def handle(self, event: HistoryEvent) -> None:
        with self._lock:
            self.events.append(event)

    def of_type(self, t: HistoryEventType) -> List[HistoryEvent]:
        with self._lock:
            return [e for e in self.events if e.event_type is t]


class JsonlHistoryLoggingService(HistoryLoggingService):
    """Date/app-partitioned JSONL store — the ProtoHistoryLoggingService
    analog (reference: ProtoHistoryLoggingService.java:47 writing
    date-partitioned proto files scanned by a manifest reader).

    Layout: `<log-dir>/date=YYYY-MM-DD/app_<app_id>_<pid>.jsonl`.  The
    writer rolls to a new partition when the (UTC) date changes mid-run;
    readers discover journals with `scan_history_store` (optionally
    bounded to a date range, so a long-lived store never needs a full
    directory walk)."""

    def __init__(self, conf: Any = None, log_dir: str = "",
                 app_id: str = ""):
        if not log_dir and conf is not None:
            log_dir = conf.get("tez.history.logging.log-dir") or ""
        self.log_dir = log_dir or "/tmp/tez-tpu-history"
        self.app_id = app_id or \
            ((conf.get("tez.app.id") if conf else "") or
             f"app_{int(clock.wall_s())}")
        self._fh = None
        self._date: Optional[str] = None
        self._lock = threading.Lock()

    def _roll(self) -> None:
        """Under lock: open (or roll to) today's partition file."""
        today = time.strftime("%Y-%m-%d", time.gmtime())
        if self._fh is not None and self._date == today:
            return
        if self._fh is not None:
            self._fh.flush()
            self._fh.close()
        part = os.path.join(self.log_dir, f"date={today}")
        os.makedirs(part, exist_ok=True)
        self._fh = open(os.path.join(
            part, f"app_{self.app_id}_{os.getpid()}.jsonl"), "a")
        self._date = today

    def start(self) -> None:
        with self._lock:
            self._roll()

    def handle(self, event: HistoryEvent) -> None:
        with self._lock:
            self._roll()
            self._fh.write(event.to_json() + "\n")
            if event.is_summary:
                self._fh.flush()
                os.fsync(self._fh.fileno())

    def stop(self) -> None:
        with self._lock:
            if self._fh:
                self._fh.flush()
                self._fh.close()
                self._fh = None
                self._date = None


def scan_history_store(log_dir: str, date_from: Optional[str] = None,
                       date_to: Optional[str] = None) -> List[str]:
    """Manifest scan of a history store: every journal file under the
    date-partitioned layout (`date=YYYY-MM-DD/*.jsonl`), optionally
    bounded to [date_from, date_to] (inclusive, `YYYY-MM-DD` strings —
    lexicographic compare IS date order).  Flat legacy `*.jsonl` files
    directly under log_dir are included unless a date bound excludes the
    unknown-date files explicitly (they carry no partition)."""
    if not os.path.isdir(log_dir):
        return []
    out: List[str] = []
    bounded = date_from is not None or date_to is not None
    for name in sorted(os.listdir(log_dir)):
        path = os.path.join(log_dir, name)
        if name.startswith("date=") and os.path.isdir(path):
            day = name[len("date="):]
            if date_from is not None and day < date_from:
                continue
            if date_to is not None and day > date_to:
                continue
            out.extend(sorted(
                os.path.join(path, f) for f in os.listdir(path)
                if f.endswith(".jsonl")))
        elif name.endswith(".jsonl") and not bounded:
            out.append(path)   # legacy flat layout
    return out


class DevNullHistoryLoggingService(HistoryLoggingService):
    def __init__(self, conf: Any = None):
        pass

    def handle(self, event: HistoryEvent) -> None:
        pass


class HistoryEventHandler:
    """Fans history events out to the logging service and recovery journal.

    Reference: HistoryEventHandler.java:46.
    """

    def __init__(self, logging_service: HistoryLoggingService,
                 recovery_service: "Any | None" = None,
                 conf: "Any | None" = None):
        self.logging_service = logging_service
        self.recovery_service = recovery_service
        # master switch + per-DAG switch (reference: HistoryEventHandler
        # .shouldLogEvent — TEZ_AM_HISTORY_LOGGING_ENABLED /
        # TEZ_DAG_HISTORY_LOGGING_ENABLED; recovery journaling is NOT
        # affected, only the logging service)
        self.am_logging_enabled = bool(conf.get(
            "tez.am.history.logging.enabled", True)) if conf else True
        self._dag_logging_disabled: "set[str]" = set()

    def set_dag_conf(self, dag_id: Any, dag_conf: Any) -> None:
        """Record the per-DAG logging switch at submission."""
        if dag_conf is not None and not bool(dag_conf.get(
                "tez.dag.history.logging.enabled", True)):
            self._dag_logging_disabled.add(str(dag_id))

    def handle(self, event: HistoryEvent) -> None:
        if self.recovery_service is not None:
            self.recovery_service.handle(event)
        dag_id = getattr(event, "dag_id", None) or \
            (event.data.get("dag_id") if isinstance(
                getattr(event, "data", None), dict) else None)
        suppressed = dag_id is not None and \
            str(dag_id) in self._dag_logging_disabled
        # DAG over: drop its switch so a session AM serving many DAGs
        # doesn't accumulate entries forever — BEFORE the master-switch
        # early return, which otherwise leaks one entry per DAG when
        # am_logging_enabled is off
        if suppressed and event.event_type is HistoryEventType.DAG_FINISHED:
            self._dag_logging_disabled.discard(str(dag_id))
        if not self.am_logging_enabled or suppressed:
            return
        self.logging_service.handle(event)

    @staticmethod
    def create_logging_service(conf: Any,
                               app_id: str = "") -> HistoryLoggingService:
        cls_name = conf.get("tez.history.logging.service.class") if conf else None
        if not cls_name:
            return InMemoryHistoryLoggingService()
        cls = resolve_class(cls_name)
        try:
            return cls(conf, app_id=app_id)
        except TypeError:
            return cls(conf)   # custom services with a conf-only signature
