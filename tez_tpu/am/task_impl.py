"""Task and TaskAttempt state machines.

Reference parity: tez-dag/.../dag/impl/TaskImpl.java:114 (retry counting,
commit arbitration, output-failure re-run, speculation hooks) and
TaskAttemptImpl.java:126 (schedule -> container assignment -> RUNNING ->
terminal).  Transition tables are explicit like the reference's
StateMachineFactory declarations, with the container-allocation sub-states
collapsed (the runner pool pulls work, so allocation == queue pop).
"""
from __future__ import annotations

import enum
import logging
from typing import Any, Dict, List, Optional, TYPE_CHECKING

from tez_tpu.common import clock
from tez_tpu.am.events import (SchedulerEvent, SchedulerEventType, TaskEvent,
                               TaskAttemptEvent, TaskAttemptEventType,
                               TaskEventType, VertexEvent, VertexEventType)
from tez_tpu.am.history import HistoryEvent, HistoryEventType
from tez_tpu.common.counters import DAGCounter, TezCounters
from tez_tpu.common.ids import TaskAttemptId, TaskId
from tez_tpu.common.statemachine import StateMachineFactory

if TYPE_CHECKING:
    from tez_tpu.am.vertex_impl import VertexImpl

log = logging.getLogger(__name__)


class TaskState(enum.Enum):
    NEW = enum.auto()
    SCHEDULED = enum.auto()
    RUNNING = enum.auto()
    SUCCEEDED = enum.auto()
    FAILED = enum.auto()
    KILLED = enum.auto()


class TaskAttemptState(enum.Enum):
    NEW = enum.auto()
    SUBMITTED = enum.auto()     # queued at the scheduler
    RUNNING = enum.auto()       # runner picked it up
    SUCCEEDED = enum.auto()
    FAILED = enum.auto()
    KILLED = enum.auto()


TERMINAL_ATTEMPT_STATES = frozenset(
    {TaskAttemptState.SUCCEEDED, TaskAttemptState.FAILED, TaskAttemptState.KILLED})
TERMINAL_TASK_STATES = frozenset(
    {TaskState.SUCCEEDED, TaskState.FAILED, TaskState.KILLED})


class TaskAttemptImpl:
    """One execution attempt of a task."""

    _factory: StateMachineFactory = None  # built below

    def __init__(self, attempt_id: TaskAttemptId, vertex: "VertexImpl"):
        self.attempt_id = attempt_id
        self.vertex = vertex
        self.ctx = vertex.ctx
        self.counters = TezCounters()
        self.diagnostics: List[str] = []
        self.container_id: Any = None
        self.node_id: str = ""
        self.progress: float = 0.0
        self.launch_time: float = 0.0
        self.finish_time: float = 0.0
        self.creation_time: float = clock.wall_s()
        self.is_speculative = False
        self.is_rescheduled = False   # re-run after output loss
        self.output_failure_reports: Dict[int, int] = {}  # consumer task -> count
        self.first_output_failure_time = 0.0
        # (edge dest vertex name, event) pairs this attempt produced — journaled
        # on success so AM recovery can re-route them without re-running the
        # task (reference: TaskAttemptFinishedEvent taGeneratedEvents).
        self.generated_events: List[tuple] = []
        self.sm = self._factory.make(self)

    @property
    def state(self) -> TaskAttemptState:
        return self.sm.state

    def handle(self, event: TaskAttemptEvent) -> None:
        if self.state in TERMINAL_ATTEMPT_STATES:
            # Late/racing events against finished attempts are dropped, with
            # one exception: output-failure against a SUCCEEDED attempt.
            if (event.event_type is TaskAttemptEventType.TA_OUTPUT_FAILED
                    and self.state is TaskAttemptState.SUCCEEDED):
                self._on_output_failed(event)
            return
        if not self.sm.can_handle(event.event_type):
            log.debug("attempt %s: ignoring %s in %s", self.attempt_id,
                      event.event_type, self.state)
            return
        self.sm.handle(event)

    # -- transition hooks ----------------------------------------------------
    def _on_schedule(self, event: TaskAttemptEvent) -> None:
        # a reschedule after output loss blocks live consumers: boost it
        # ahead of its vertex's normal work (reference:
        # TEZ_AM_TASK_RESCHEDULE_HIGHER_PRIORITY; lower value = sooner)
        priority = self.vertex.priority
        if self.is_rescheduled and bool(self.vertex.conf.get(
                "tez.am.task.reschedule.higher.priority", True)):
            priority -= 1
        self.ctx.dispatch(SchedulerEvent(
            SchedulerEventType.S_TA_LAUNCH_REQUEST,
            attempt_id=self.attempt_id, task_spec=event.task_spec,
            priority=priority))

    def _on_started(self, event: TaskAttemptEvent) -> None:
        self.container_id = getattr(event, "container_id", None)
        self.node_id = getattr(event, "node_id", "")
        self.launch_time = clock.wall_s()
        self.ctx.history(HistoryEvent(
            HistoryEventType.TASK_ATTEMPT_STARTED,
            dag_id=str(self.attempt_id.dag_id),
            vertex_id=str(self.attempt_id.vertex_id),
            task_id=str(self.attempt_id.task_id),
            attempt_id=str(self.attempt_id),
            container_id=str(self.container_id),
            data={"vertex_name": self.vertex.name,
                  "node_id": self.node_id}))
        self.ctx.dispatch(TaskEvent(TaskEventType.T_ATTEMPT_LAUNCHED,
                                    self.attempt_id.task_id,
                                    attempt_id=self.attempt_id))

    def _on_status_update(self, event: TaskAttemptEvent) -> None:
        self.progress = getattr(event, "progress", self.progress)
        counters = getattr(event, "counters", None)
        if counters is not None:
            self.counters = counters

    def _on_done(self, event: TaskAttemptEvent) -> None:
        self.finish_time = clock.wall_s()
        counters = getattr(event, "counters", None)
        if counters is not None:
            self.counters = counters
        self.progress = 1.0
        self._finish_history("SUCCEEDED")
        self.ctx.dispatch(TaskEvent(TaskEventType.T_ATTEMPT_SUCCEEDED,
                                    self.attempt_id.task_id,
                                    attempt_id=self.attempt_id))
        self._notify_scheduler_ended()

    def _on_failed(self, event: TaskAttemptEvent) -> None:
        self.finish_time = clock.wall_s()
        diag = getattr(event, "diagnostics", "")
        if diag:
            self.diagnostics.append(diag)
        self.failure_fatal = getattr(event, "fatal", False)
        self._finish_history("FAILED")
        self.ctx.dispatch(TaskEvent(TaskEventType.T_ATTEMPT_FAILED,
                                    self.attempt_id.task_id,
                                    attempt_id=self.attempt_id,
                                    fatal=self.failure_fatal))
        self._notify_scheduler_ended(failed=True)

    def _on_killed(self, event: TaskAttemptEvent) -> None:
        self.finish_time = clock.wall_s()
        diag = getattr(event, "diagnostics", "")
        if diag:
            self.diagnostics.append(diag)
        self.ctx.kill_attempt_in_runner(self.attempt_id)
        self._finish_history("KILLED")
        self.ctx.dispatch(TaskEvent(TaskEventType.T_ATTEMPT_KILLED,
                                    self.attempt_id.task_id,
                                    attempt_id=self.attempt_id))
        self._notify_scheduler_ended()

    def _on_output_failed(self, event: TaskAttemptEvent) -> None:
        """A consumer could not read this attempt's output.  Mirrors
        TaskAttemptImpl output-failure accounting: enough distinct failures
        (or a local-fetch/source-disk error) fail the SUCCEEDED attempt so
        the task re-runs (reference: SURVEY.md §3.5 fetch-failure path)."""
        consumer = getattr(event, "consumer_task_index", -1)
        if not self.output_failure_reports:
            self.first_output_failure_time = clock.wall_s()
        self.output_failure_reports[consumer] = \
            self.output_failure_reports.get(consumer, 0) + 1
        max_failures = self.vertex.conf.get("tez.am.max.allowed.output.failures", 10)
        num_consumers = max(1, self.vertex.downstream_consumer_count(
            self.attempt_id.task_id.id))
        fraction = len(self.output_failure_reports) / num_consumers
        max_fraction = self.vertex.conf.get(
            "tez.am.max.allowed.output.failures.fraction", 0.1)
        # reports persisting past this window fail the output regardless of
        # counts — consumers have been stuck on it for too long (reference:
        # TEZ_AM_MAX_ALLOWED_TIME_FOR_TASK_READ_ERROR_SEC)
        max_window = float(self.vertex.conf.get(
            "tez.am.max.allowed.time-sec.for-read-error", 300))
        window_expired = \
            clock.wall_s() - self.first_output_failure_time > max_window
        local_fetch = getattr(event, "is_local_fetch", False)
        disk_error = getattr(event, "is_disk_error_at_source", False)
        total = sum(self.output_failure_reports.values())
        if local_fetch or disk_error or total >= max_failures or \
                fraction > max_fraction or window_expired:
            log.info("attempt %s: output lost (%d reports) -> re-running task",
                     self.attempt_id, total)
            self.sm.force_state(TaskAttemptState.FAILED)
            self.diagnostics.append(
                f"output lost: {total} fetch failures reported")
            self.ctx.dispatch(TaskEvent(TaskEventType.T_ATTEMPT_FAILED,
                                        self.attempt_id.task_id,
                                        attempt_id=self.attempt_id,
                                        was_succeeded=True))

    def _finish_history(self, final_state: str) -> None:
        data = {"state": final_state,
                "vertex_name": self.vertex.name,
                "time_taken": self.finish_time - (self.launch_time or
                                                  self.finish_time),
                "diagnostics": "; ".join(self.diagnostics),
                "counters": self.counters.to_dict()}
        if final_state == "SUCCEEDED" and self.generated_events:
            from tez_tpu.am.recovery import event_to_wire
            data["generated_events"] = [
                [name, event_to_wire(ev)] for name, ev in self.generated_events]
        self.ctx.history(HistoryEvent(
            HistoryEventType.TASK_ATTEMPT_FINISHED,
            dag_id=str(self.attempt_id.dag_id),
            vertex_id=str(self.attempt_id.vertex_id),
            task_id=str(self.attempt_id.task_id),
            attempt_id=str(self.attempt_id),
            data=data))

    def _notify_scheduler_ended(self, failed: bool = False) -> None:
        self.ctx.dispatch(SchedulerEvent(SchedulerEventType.S_TA_ENDED,
                                         attempt_id=self.attempt_id,
                                         failed=failed,
                                         node_id=self.node_id))


def _build_attempt_factory() -> StateMachineFactory:
    S, E = TaskAttemptState, TaskAttemptEventType
    f = StateMachineFactory(S.NEW)
    f.add(S.NEW, S.SUBMITTED, E.TA_SCHEDULE, TaskAttemptImpl._on_schedule)
    f.add(S.NEW, S.KILLED, E.TA_KILL_REQUEST, TaskAttemptImpl._on_killed)
    f.add(S.SUBMITTED, S.RUNNING, E.TA_STARTED_REMOTELY, TaskAttemptImpl._on_started)
    f.add(S.SUBMITTED, S.KILLED, E.TA_KILL_REQUEST, TaskAttemptImpl._on_killed)
    f.add(S.SUBMITTED, S.FAILED, E.TA_FAILED, TaskAttemptImpl._on_failed)
    f.add(S.RUNNING, S.RUNNING, E.TA_STATUS_UPDATE, TaskAttemptImpl._on_status_update)
    f.add(S.RUNNING, S.SUCCEEDED, E.TA_DONE, TaskAttemptImpl._on_done)
    f.add(S.RUNNING, S.FAILED, E.TA_FAILED, TaskAttemptImpl._on_failed)
    f.add(S.RUNNING, S.FAILED, E.TA_TIMED_OUT, TaskAttemptImpl._on_failed)
    f.add(S.RUNNING, S.KILLED, E.TA_KILL_REQUEST, TaskAttemptImpl._on_killed)
    f.add(S.RUNNING, S.FAILED, E.TA_CONTAINER_TERMINATED, TaskAttemptImpl._on_failed)
    return f


TaskAttemptImpl._factory = _build_attempt_factory()


class TaskImpl:
    """Task: a retry/speculation envelope over attempts."""

    _factory: StateMachineFactory = None

    def __init__(self, task_id: TaskId, vertex: "VertexImpl"):
        self.task_id = task_id
        self.vertex = vertex
        self.ctx = vertex.ctx
        self.attempts: Dict[int, TaskAttemptImpl] = {}
        self.next_attempt_number = 0
        self.failed_attempts = 0
        self.killed_attempts = 0
        self.commit_attempt: Optional[TaskAttemptId] = None
        self.successful_attempt: Optional[TaskAttemptId] = None
        self.scheduled_time = 0.0
        self.finish_time = 0.0
        self.sm = self._factory.make(self)

    @property
    def state(self) -> TaskState:
        return self.sm.state

    @property
    def max_failed_attempts(self) -> int:
        return self.vertex.conf.get("tez.am.task.max.failed.attempts", 4)

    def handle(self, event: TaskEvent) -> None:
        if self.state in TERMINAL_TASK_STATES:
            if (event.event_type is TaskEventType.T_ATTEMPT_FAILED
                    and getattr(event, "was_succeeded", False)
                    and self.state is TaskState.SUCCEEDED):
                self._reschedule_after_output_loss(event)
            return
        if not self.sm.can_handle(event.event_type):
            log.debug("task %s: ignoring %s in %s", self.task_id,
                      event.event_type, self.state)
            return
        self.sm.handle(event)

    def attempt(self, attempt_id: TaskAttemptId) -> Optional[TaskAttemptImpl]:
        return self.attempts.get(attempt_id.id)

    # -- commit arbitration (reference: TaskImpl.canCommit) ------------------
    def can_commit(self, attempt_id: TaskAttemptId) -> bool:
        if self.state is TaskState.SUCCEEDED:
            return self.successful_attempt == attempt_id
        if self.commit_attempt is None:
            att = self.attempts.get(attempt_id.id)
            if att is None or att.state is not TaskAttemptState.RUNNING:
                return False
            self.commit_attempt = attempt_id
        return self.commit_attempt == attempt_id

    # -- hooks ---------------------------------------------------------------
    def _spawn_attempt(self, speculative: bool = False,
                       rescheduled: bool = False) -> TaskAttemptImpl:
        n = self.next_attempt_number
        self.next_attempt_number += 1
        att = TaskAttemptImpl(self.task_id.attempt(n), self.vertex)
        att.is_speculative = speculative
        att.is_rescheduled = rescheduled
        self.attempts[n] = att
        spec = self.vertex.build_task_spec(att.attempt_id)
        att.handle(TaskAttemptEvent(TaskAttemptEventType.TA_SCHEDULE,
                                    att.attempt_id, task_spec=spec))
        self.ctx.dag_counters.increment(DAGCounter.TOTAL_LAUNCHED_TASKS)
        if speculative:
            self.ctx.dag_counters.increment(DAGCounter.NUM_SPECULATIONS)
        return att

    def _on_schedule(self, event: TaskEvent) -> None:
        self.scheduled_time = clock.wall_s()
        self.ctx.history(HistoryEvent(
            HistoryEventType.TASK_STARTED,
            dag_id=str(self.task_id.dag_id),
            vertex_id=str(self.task_id.vertex_id),
            task_id=str(self.task_id),
            data={"vertex_name": self.vertex.name}))
        self._spawn_attempt()

    def _on_attempt_launched(self, event: TaskEvent) -> None:
        pass

    def _on_recover(self, event: TaskEvent) -> None:
        """AM recovery: restore this task as SUCCEEDED from journal data and
        re-route its successful attempt's DataMovementEvents into the out-
        edges, without launching anything (reference: RecoveryParser short-
        circuit of TaskFinished/TaskAttemptFinished events, SURVEY.md §5.4).

        If the restored output data turns out to be gone (runner died with
        the AM), consumers report InputReadErrorEvents and the normal output-
        loss path re-runs the task — same guarantee the reference gets when
        a node is lost after recovery."""
        rec: Dict[str, Any] = event.recovered
        att_str: str = rec["attempt"]
        try:
            n = int(att_str.rsplit("_", 1)[1])
        except (ValueError, IndexError):
            n = 0
        self.next_attempt_number = max(self.next_attempt_number, n + 1)
        att = TaskAttemptImpl(self.task_id.attempt(n), self.vertex)
        att.sm.force_state(TaskAttemptState.SUCCEEDED)
        now = clock.wall_s()
        att.progress = 1.0
        att.launch_time = att.finish_time = now
        counters = rec.get("counters")
        if counters:
            att.counters = TezCounters.from_dict(counters)
        self.attempts[n] = att
        self.successful_attempt = att.attempt_id
        self.scheduled_time = self.finish_time = now
        # events were decoded (and the pickle trust gate enforced) by
        # VertexImpl._load_recovered_tasks — a task reaches T_RECOVER only
        # when every journaled event replayed cleanly
        for edge_name, ev in rec["decoded_events"]:
            edge = self.vertex.out_edges.get(edge_name)
            if edge is None:
                continue
            edge.add_source_event(self.task_id.id, n, ev)
            att.generated_events.append((edge_name, ev))
            self.vertex.dag.notify_new_edge_events(edge)
        self.ctx.dag_counters.increment(DAGCounter.NUM_SUCCEEDED_TASKS)
        # Re-journal so the *next* AM attempt can recover from this journal
        # alone (recovery is idempotent across attempts).
        att._finish_history("SUCCEEDED")
        self._finish_history("SUCCEEDED")
        self.ctx.dispatch(VertexEvent(
            VertexEventType.V_TASK_COMPLETED, self.task_id.vertex_id,
            task_id=self.task_id, final_state=TaskState.SUCCEEDED,
            attempt_id=att.attempt_id))

    def _on_add_spec_attempt(self, event: TaskEvent) -> None:
        if len(self.live_attempts()) < 2:
            self._spawn_attempt(speculative=True)

    def live_attempts(self) -> List[TaskAttemptImpl]:
        return [a for a in self.attempts.values()
                if a.state not in TERMINAL_ATTEMPT_STATES]

    def _on_attempt_succeeded(self, event: TaskEvent) -> None:
        self.successful_attempt = event.attempt_id
        self.finish_time = clock.wall_s()
        # Kill other live attempts (speculation losers).
        for att in self.live_attempts():
            att.handle(TaskAttemptEvent(
                TaskAttemptEventType.TA_KILL_REQUEST, att.attempt_id,
                diagnostics="other attempt succeeded"))
        self.ctx.dag_counters.increment(DAGCounter.NUM_SUCCEEDED_TASKS)
        self._finish_history("SUCCEEDED")
        self.ctx.dispatch(VertexEvent(
            VertexEventType.V_TASK_COMPLETED, self.task_id.vertex_id,
            task_id=self.task_id, final_state=TaskState.SUCCEEDED,
            attempt_id=event.attempt_id))

    def _on_attempt_failed(self, event: TaskEvent) -> "TaskState":
        if self.commit_attempt == event.attempt_id:
            self.commit_attempt = None   # commit right dies with the attempt
        self.failed_attempts += 1
        fatal = getattr(event, "fatal", False)
        if not fatal and self.failed_attempts < self.max_failed_attempts:
            log.info("task %s: attempt %s failed (%d/%d), retrying",
                     self.task_id, event.attempt_id, self.failed_attempts,
                     self.max_failed_attempts)
            self._spawn_attempt()
            return TaskState.RUNNING
        self.finish_time = clock.wall_s()
        self.ctx.dag_counters.increment(DAGCounter.NUM_FAILED_TASKS)
        self._finish_history("FAILED")
        self.ctx.dispatch(VertexEvent(
            VertexEventType.V_TASK_COMPLETED, self.task_id.vertex_id,
            task_id=self.task_id, final_state=TaskState.FAILED,
            attempt_id=event.attempt_id,
            diagnostics=self._attempt_diagnostics(event)))
        return TaskState.FAILED

    def _attempt_diagnostics(self, event: TaskEvent) -> str:
        att = self.attempts.get(event.attempt_id.id)
        return "; ".join(att.diagnostics) if att else ""

    def _on_attempt_killed(self, event: TaskEvent) -> "TaskState":
        if self.commit_attempt == event.attempt_id:
            self.commit_attempt = None
        # Killed attempts don't count against retries (reference semantics);
        # spawn a replacement unless the task itself is terminating.
        if self._terminating:
            if not self.live_attempts():
                self.killed_attempts += 1
                self.finish_time = clock.wall_s()
                self.ctx.dag_counters.increment(DAGCounter.NUM_KILLED_TASKS)
                self._finish_history("KILLED")
                self.ctx.dispatch(VertexEvent(
                    VertexEventType.V_TASK_COMPLETED, self.task_id.vertex_id,
                    task_id=self.task_id, final_state=TaskState.KILLED,
                    attempt_id=event.attempt_id))
                return TaskState.KILLED
            return TaskState.RUNNING
        att = self.attempts.get(event.attempt_id.id)
        if att is not None and att.is_speculative:
            return TaskState.RUNNING
        self._spawn_attempt()
        return TaskState.RUNNING

    _terminating = False

    def _on_terminate(self, event: TaskEvent) -> "TaskState":
        self._terminating = True
        live = self.live_attempts()
        if not live:
            self.ctx.dag_counters.increment(DAGCounter.NUM_KILLED_TASKS)
            self._finish_history("KILLED")
            self.ctx.dispatch(VertexEvent(
                VertexEventType.V_TASK_COMPLETED, self.task_id.vertex_id,
                task_id=self.task_id, final_state=TaskState.KILLED,
                attempt_id=None))
            return TaskState.KILLED
        for att in live:
            att.handle(TaskAttemptEvent(
                TaskAttemptEventType.TA_KILL_REQUEST, att.attempt_id,
                diagnostics=getattr(event, "diagnostics", "task terminated")))
        return TaskState.RUNNING

    def _reschedule_after_output_loss(self, event: TaskEvent) -> None:
        """SUCCEEDED task whose output was lost: re-run (reference:
        TaskImpl output-failure retroactive transition)."""
        log.info("task %s: output lost, rescheduling", self.task_id)
        failed_version = event.attempt_id.id
        self.successful_attempt = None
        self.commit_attempt = None
        self.sm.force_state(TaskState.RUNNING)
        self.ctx.dispatch(VertexEvent(
            VertexEventType.V_TASK_RESCHEDULED, self.task_id.vertex_id,
            task_id=self.task_id, failed_version=failed_version))
        self._spawn_attempt(rescheduled=True)

    def _finish_history(self, final_state: str) -> None:
        data = {"state": final_state, "vertex_name": self.vertex.name,
                "time_taken": self.finish_time - self.scheduled_time}
        if final_state == "SUCCEEDED" and self.successful_attempt is not None:
            data["successful_attempt"] = str(self.successful_attempt)
        self.ctx.history(HistoryEvent(
            HistoryEventType.TASK_FINISHED,
            dag_id=str(self.task_id.dag_id),
            vertex_id=str(self.task_id.vertex_id),
            task_id=str(self.task_id),
            data=data))

    def successful_attempt_impl(self) -> Optional[TaskAttemptImpl]:
        if self.successful_attempt is None:
            return None
        return self.attempts.get(self.successful_attempt.id)


def _build_task_factory() -> StateMachineFactory:
    S, E = TaskState, TaskEventType
    f = StateMachineFactory(S.NEW)
    f.add(S.NEW, S.SCHEDULED, E.T_SCHEDULE, TaskImpl._on_schedule)
    f.add(S.NEW, S.SUCCEEDED, E.T_RECOVER, TaskImpl._on_recover)
    f.add_multi(S.NEW, (S.RUNNING, S.KILLED), E.T_TERMINATE,
                TaskImpl._on_terminate)
    f.add(S.SCHEDULED, S.RUNNING, E.T_ATTEMPT_LAUNCHED, TaskImpl._on_attempt_launched)
    f.add_multi(S.SCHEDULED, (S.RUNNING, S.FAILED), E.T_ATTEMPT_FAILED,
                TaskImpl._on_attempt_failed)
    f.add_multi(S.SCHEDULED, (S.RUNNING, S.KILLED), E.T_ATTEMPT_KILLED,
                TaskImpl._on_attempt_killed)
    f.add_multi(S.SCHEDULED, (S.RUNNING, S.KILLED), E.T_TERMINATE,
                TaskImpl._on_terminate)
    f.add(S.SCHEDULED, S.SUCCEEDED, E.T_ATTEMPT_SUCCEEDED, TaskImpl._on_attempt_succeeded)
    f.add(S.RUNNING, S.RUNNING, E.T_ATTEMPT_LAUNCHED, TaskImpl._on_attempt_launched)
    f.add(S.RUNNING, S.RUNNING, E.T_ADD_SPEC_ATTEMPT, TaskImpl._on_add_spec_attempt)
    f.add(S.RUNNING, S.SUCCEEDED, E.T_ATTEMPT_SUCCEEDED, TaskImpl._on_attempt_succeeded)
    f.add_multi(S.RUNNING, (S.RUNNING, S.FAILED), E.T_ATTEMPT_FAILED,
                TaskImpl._on_attempt_failed)
    f.add_multi(S.RUNNING, (S.RUNNING, S.KILLED), E.T_ATTEMPT_KILLED,
                TaskImpl._on_attempt_killed)
    f.add_multi(S.RUNNING, (S.RUNNING, S.KILLED), E.T_TERMINATE,
                TaskImpl._on_terminate)
    return f


TaskImpl._factory = _build_task_factory()
