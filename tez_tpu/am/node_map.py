"""Per-node health tracking and blacklisting.

Reference parity: tez-dag/.../app/rm/node/ (AMNodeImpl / AMNodeTracker) —
task-attempt failures accumulate per NODE (not just per container); crossing
tez.am.maxtaskfailures.per.node blacklists the node so no new work lands
there, and when more than tez.am.node.blacklisting.ignore.threshold of the
cluster is blacklisted, blacklisting is IGNORED (nodes go FORCED_ACTIVE)
rather than deadlocking the app on its own pessimism.

Here a "node" is one runner host: "local-0" for the in-process pool, the
--node-id of each runner process for the subprocess pool (one per TPU host
in a pod deployment).
"""
from __future__ import annotations

import enum
import logging
import threading
from typing import Any, Callable, Dict, List, Optional, Tuple

log = logging.getLogger(__name__)


class NodeState(enum.Enum):
    ACTIVE = enum.auto()
    BLACKLISTED = enum.auto()
    FORCED_ACTIVE = enum.auto()    # blacklisted but ignore-threshold crossed


class AMNodeTracker:
    def __init__(self, conf: Any):
        from tez_tpu.common import config as C
        self.max_failures = int(conf.get(C.NODE_MAX_TASK_FAILURES))
        self.enabled = bool(conf.get(C.NODE_BLACKLISTING_ENABLED))
        self.ignore_threshold = float(
            conf.get(C.NODE_BLACKLISTING_FAILURE_THRESHOLD)) / 100.0
        self._lock = threading.Lock()
        self._failures: Dict[str, int] = {}
        self._states: Dict[str, NodeState] = {}
        self._ignoring = False
        #: observer for state transitions: (node_id, new_state, failures).
        #: Invoked OUTSIDE the lock (the AM's handler emits history events,
        #: which may re-enter tracker queries).
        self.on_transition: Optional[
            Callable[[str, NodeState, int], None]] = None

    def _notify(self, transitions: List[Tuple[str, NodeState, int]]) -> None:
        """Fire collected transitions after the lock is released."""
        cb = self.on_transition
        if cb is None:
            return
        for node, state, failures in transitions:
            try:
                cb(node, state, failures)
            except Exception:  # noqa: BLE001 — observers must not wedge
                log.exception("node transition observer failed")

    # -- bookkeeping ---------------------------------------------------------
    def node_seen(self, node_id: str) -> None:
        if not node_id:
            return
        transitions: List[Tuple[str, NodeState, int]] = []
        with self._lock:
            if node_id not in self._states:
                self._states[node_id] = NodeState.ACTIVE
                # fleet grew: the blacklisted fraction changed, so a
                # FORCED_ACTIVE node may have to revert to BLACKLISTED
                self._recompute_ignore_locked(transitions)
        self._notify(transitions)

    def on_attempt_failed(self, node_id: str) -> None:
        if not node_id or not self.enabled:
            return
        transitions: List[Tuple[str, NodeState, int]] = []
        with self._lock:
            self._states.setdefault(node_id, NodeState.ACTIVE)
            n = self._failures.get(node_id, 0) + 1
            self._failures[node_id] = n
            if n >= self.max_failures and \
                    self._states[node_id] is NodeState.ACTIVE:
                self._states[node_id] = NodeState.BLACKLISTED
                log.warning("node %s blacklisted after %d task failures",
                            node_id, n)
                transitions.append((node_id, NodeState.BLACKLISTED, n))
                self._recompute_ignore_locked(transitions)
        self._notify(transitions)

    def on_attempt_succeeded(self, node_id: str) -> None:
        """Reference semantics: success does not clear the failure count
        (AMNodeImpl only counts failures); kept as a hook for health probes."""

    def node_gone(self, node_id: str) -> None:
        """A node left the fleet (host decommissioned): drop its state so
        stale blacklist entries don't skew the ignore-threshold math."""
        transitions: List[Tuple[str, NodeState, int]] = []
        with self._lock:
            self._states.pop(node_id, None)
            self._failures.pop(node_id, None)
            self._recompute_ignore_locked(transitions)
        self._notify(transitions)

    def _recompute_ignore_locked(
            self, transitions: Optional[
                List[Tuple[str, NodeState, int]]] = None) -> None:
        total = len(self._states)
        blacklisted = sum(1 for s in self._states.values()
                          if s in (NodeState.BLACKLISTED,
                                   NodeState.FORCED_ACTIVE))
        ignore = total > 0 and blacklisted / total > self.ignore_threshold
        if ignore and not self._ignoring:
            log.warning("%d/%d nodes blacklisted (> %.0f%%): ignoring "
                        "blacklists to keep the app alive", blacklisted,
                        total, self.ignore_threshold * 100)
        self._ignoring = ignore
        for node, s in self._states.items():
            if ignore and s is NodeState.BLACKLISTED:
                self._states[node] = NodeState.FORCED_ACTIVE
            elif not ignore and s is NodeState.FORCED_ACTIVE:
                self._states[node] = NodeState.BLACKLISTED
            else:
                continue
            if transitions is not None:
                transitions.append((node, self._states[node],
                                    self._failures.get(node, 0)))

    # -- queries -------------------------------------------------------------
    def is_usable(self, node_id: str) -> bool:
        if not self.enabled or not node_id:
            return True
        with self._lock:
            return self._states.get(node_id, NodeState.ACTIVE) is not \
                NodeState.BLACKLISTED

    def state(self, node_id: str) -> NodeState:
        with self._lock:
            return self._states.get(node_id, NodeState.ACTIVE)

    def snapshot(self) -> Dict[str, Dict[str, Any]]:
        with self._lock:
            return {n: {"state": s.name,
                        "failures": self._failures.get(n, 0)}
                    for n, s in self._states.items()}
