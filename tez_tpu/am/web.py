"""Live AM web UI: JSON REST surface + single-page app.

Reference parity: tez-dag/.../app/web/{AMWebController.java:69,
WebUIService.java} (the live REST surface) plus the tez-ui SPA feature set
(tez-ui/src/main/webapp/app/: DAG/vertex/task/attempt browsing, counters,
DAG graph view, swimlane) — rebuilt as a zero-dependency single page served
by the AM itself instead of an Ember app reading ATS.

Endpoints:
  GET /                 single-page app (tabs: overview, graph, tasks,
                        counters, config, swimlane, history, analyzers)
  GET /status           JSON DAG status (DAGClient schema)
  GET /dags             JSON all DAGs this session (session mode)
  GET /graph            JSON DAG structure (vertices + typed edges)
  GET /tasks?vertex=N   JSON per-task/attempt detail for one vertex
  GET /attempt?id=A     JSON one attempt's counters/diagnostics/timing
  GET /counters         JSON aggregated DAG counters
  GET /counters?vertex=N  per-vertex counter aggregation
  GET /conf             JSON effective DAG configuration (secrets redacted)
  GET /history          JSON recent history events (in-memory logger only)
  GET /analyzers        JSON analyzer suite run over live history
  GET /swimlane.svg     container swimlane SVG
  GET /trace            Chrome/Perfetto trace_event JSON (span buffer, or
                        history-derived when tracing was disarmed)
  GET /metrics          Prometheus text: counters, latency histograms,
                        running-task/queued-fetch/epoch gauges; dynamic
                        series split into tenant=/stream=/lane= labels
                        (?tenant=X / ?stream=Y drill down)
  GET /metrics.json     structured JSON exposition + windowed aggregates
                        from the live time-series rings (?window=SECONDS)
  GET /doctor/live      continuous doctor: incremental per-plane blame,
                        tenants, streams, queue depth, lane occupancy
"""
from __future__ import annotations

import http.server
import json
import logging
import threading
import urllib.parse
from typing import Any, Dict, List, Optional

from tez_tpu.common import clock

log = logging.getLogger(__name__)

_PAGE = """<!doctype html><html><head><title>tez_tpu AM</title><style>
body{font-family:monospace;margin:1.5em;background:#fafafa}
table{border-collapse:collapse;margin-top:8px}
td,th{border:1px solid #bbb;padding:3px 9px;text-align:left;font-size:13px}
th{background:#eee}
.bar{background:#ddd;width:200px;display:inline-block}
.fill{background:#4e79a7;height:11px}
.tabs button{font-family:monospace;padding:6px 14px;border:1px solid #999;
 background:#eee;cursor:pointer;margin-right:4px}
.tabs button.on{background:#4e79a7;color:#fff}
#panel{margin-top:12px}
.SUCCEEDED{color:#2a7d2a}.FAILED{color:#c0392b}.RUNNING{color:#2471a3}
.KILLED{color:#8e44ad}
svg text{font-family:monospace;font-size:12px}
.hl{font-weight:bold}
</style></head><body>
<h2 id="t">tez_tpu AM</h2>
<div class="tabs" id="tabs"></div><div id="panel"></div>
<script>
const TABS = ["overview","graph","tasks","counters","config","swimlane",
              "history","analyzers"];
let cur = "overview", selVertex = null, timer = null, gen = 0;
let selAttempt = null, taskFilter = "", counterVertex = "";
const $ = id => document.getElementById(id);
const esc = s => String(s).replace(/[&<>]/g,
  c => ({'&':'&amp;','<':'&lt;','>':'&gt;'}[c]));
function tabbar() {
  $('tabs').innerHTML = TABS.map(t =>
    `<button class="${t===cur?'on':''}" onclick="go('${t}')">${t}</button>`
  ).join('');
}
function go(t) { cur = t; tabbar(); render(); }
async function j(path) { return (await fetch(path)).json(); }
async function head() {
  const s = await j('/status');
  $('t').innerHTML = s.name ?
    `${esc(s.name)} — <span class="${esc(s.state)}">${esc(s.state)}</span>` +
    ` (${Math.round(s.progress*100)}%)` : 'tez_tpu AM — idle';
  return s;
}
async function render() {
  // generation counter: a tab switch mid-fetch invalidates this render so it
  // neither clobbers the new tab's panel nor schedules a duplicate poll loop
  const g = ++gen;
  clearTimeout(timer);
  try {
  const s = await head();
  if (g !== gen) return;
  if (cur === 'overview') {
    let h = '<table><tr><th>vertex</th><th>state</th><th>tasks</th>' +
            '<th>progress</th></tr>';
    for (const [n,v] of Object.entries(s.vertices || {}))
      h += `<tr><td>${esc(n)}</td><td class="${esc(v.state)}">${esc(v.state)}` +
           `</td><td>${v.succeeded}/${v.total_tasks}</td>` +
           `<td><div class="bar"><div class="fill" style="width:` +
           `${Math.round(v.progress*200)}px"></div></div></td></tr>`;
    h += '</table>';
    const dags = await j('/dags');
    if (g !== gen) return;
    if (dags.length > 1) {
      h += '<h3>session DAGs</h3><table><tr><th>dag</th><th>state</th></tr>';
      for (const d of dags)
        h += `<tr><td>${esc(d.dag_id)} (${esc(d.name)})</td>` +
             `<td class="${esc(d.state)}">${esc(d.state)}</td></tr>`;
      h += '</table>';
    }
    $('panel').innerHTML = h;
  } else if (cur === 'graph') {
    const gr = await j('/graph');
    if (g !== gen) return;
    $('panel').innerHTML = drawGraph(gr);
  } else if (cur === 'tasks') {
    const names = Object.keys(s.vertices || {});
    if (!selVertex || !names.includes(selVertex)) selVertex = names[0];
    let h = 'vertex: ' +
      '<select onchange="selVertex=this.value;selAttempt=null;render()">' +
      names.map(n =>
        `<option ${n===selVertex?'selected':''}>${esc(n)}</option>`).join('') +
      '</select> state filter: ' +
      '<select onchange="taskFilter=this.value;render()">' +
      ['','RUNNING','SUCCEEDED','FAILED','KILLED','SCHEDULED'].map(f =>
        `<option ${f===taskFilter?'selected':''}>${f}</option>`).join('') +
      '</select>';
    if (selVertex) {
      const rows = await j('/tasks?vertex=' + encodeURIComponent(selVertex));
      if (g !== gen) return;
      h += '<table><tr><th>task</th><th>state</th><th>attempt</th>' +
           '<th>attempt state</th><th>node</th><th>duration</th></tr>';
      for (const t of rows) {
        if (taskFilter && t.state !== taskFilter &&
            !t.attempts.some(a => a.state === taskFilter)) continue;
        if (!t.attempts.length)
          h += `<tr><td>${t.index}</td><td class="${esc(t.state)}">` +
               `${esc(t.state)}</td><td colspan=4></td></tr>`;
        for (const a of t.attempts)
          h += `<tr><td>${t.index}</td><td class="${esc(t.state)}">` +
               `${esc(t.state)}</td>` +
               `<td><a href="#" onclick="selAttempt='${esc(a.id)}';` +
               `render();return false">${esc(a.id)}</a></td>` +
               `<td class="${esc(a.state)}">${esc(a.state)}</td>` +
               `<td>${esc(a.node)}</td><td>${a.duration_s}s</td></tr>`;
      }
      h += '</table>';
      if (selAttempt) {
        const d = await j('/attempt?id=' + encodeURIComponent(selAttempt));
        if (g !== gen) return;
        if (!d.error) {
          h += `<h3>${esc(d.id)} — <span class="${esc(d.state)}">` +
               `${esc(d.state)}</span> on ${esc(d.node)} ` +
               `(${d.duration_s}s) ` +
               `<button onclick="selAttempt=null;render()">close</button>` +
               `</h3>`;
          if (d.diagnostics.length)
            h += '<pre style="color:#c0392b">' +
                 d.diagnostics.map(esc).join('\\n') + '</pre>';
          for (const [grp, cs] of Object.entries(d.counters)) {
            h += `<h4>${esc(grp)}</h4><table>`;
            for (const [k,v] of Object.entries(cs))
              h += `<tr><td>${esc(k)}</td>` +
                   `<td style="text-align:right">${v}</td></tr>`;
            h += '</table>';
          }
        }
      }
    }
    $('panel').innerHTML = h;
  } else if (cur === 'counters') {
    const names = Object.keys(s.vertices || {});
    let h = 'scope: <select onchange="counterVertex=this.value;render()">' +
      ['<option value="">DAG total</option>'].concat(names.map(n =>
        `<option value="${esc(n)}" ${n===counterVertex?'selected':''}>` +
        `${esc(n)}</option>`)).join('') + '</select>';
    const c = await j('/counters' + (counterVertex ?
      '?vertex=' + encodeURIComponent(counterVertex) : ''));
    if (g !== gen) return;
    for (const [grp, cs] of Object.entries(c)) {
      h += `<h3>${esc(grp)}</h3><table>`;
      for (const [k,v] of Object.entries(cs))
        h += `<tr><td>${esc(k)}</td><td style="text-align:right">${v}</td></tr>`;
      h += '</table>';
    }
    $('panel').innerHTML = h;
  } else if (cur === 'config') {
    const c = await j('/conf');
    if (g !== gen) return;
    let h = '<table><tr><th>key</th><th>value</th></tr>';
    for (const k of Object.keys(c).sort())
      h += `<tr><td>${esc(k)}</td><td>${esc(JSON.stringify(c[k]))}</td></tr>`;
    $('panel').innerHTML = h + '</table>';
  } else if (cur === 'swimlane') {
    $('panel').innerHTML =
      `<img src="/swimlane.svg?ts=${Date.now()}" style="max-width:100%">`;
  } else if (cur === 'history') {
    const evs = await j('/history');
    if (g !== gen) return;
    let h = '<table><tr><th>time</th><th>event</th><th>entity</th></tr>';
    for (const e of evs.slice(-80).reverse())
      h += `<tr><td>${new Date(e.timestamp*1000).toLocaleTimeString()}</td>` +
           `<td>${esc(e.event_type)}</td>` +
           `<td>${esc(e.attempt_id||e.task_id||e.vertex_id||e.dag_id||'')}` +
           `</td></tr>`;
    $('panel').innerHTML = h + '</table>';
  } else if (cur === 'analyzers') {
    const rs = await j('/analyzers');
    if (g !== gen) return;
    let h = '<table><tr><th>analyzer</th><th>headline</th></tr>';
    for (const r of rs)
      h += `<tr><td class="hl">${esc(r.analyzer)}</td>` +
           `<td>${esc(r.headline)}</td></tr>`;
    $('panel').innerHTML = h + '</table>';
  }
  } catch (e) {
    // a transient fetch error (AM busy, DAG transition) must not kill the
    // poll loop; show it and keep polling
    if (g !== gen) return;
    $('panel').innerHTML = '<i>fetch failed, retrying: ' + esc(e) + '</i>';
  }
  if (g !== gen) return;
  timer = setTimeout(render, cur === 'overview' || cur === 'graph' ||
                             cur === 'tasks' ? 2000 : 10000);
}
function drawGraph(g) {
  // layered layout by distance-from-root
  const lanes = {};
  for (const v of g.vertices)
    (lanes[v.distance] = lanes[v.distance] || []).push(v);
  const pos = {}, W = 190, H = 90;
  let maxX = 0, maxY = 0;
  for (const [d, vs] of Object.entries(lanes))
    vs.forEach((v, i) => {
      pos[v.name] = {x: 40 + i*W, y: 40 + d*H};
      maxX = Math.max(maxX, 40 + i*W); maxY = Math.max(maxY, 40 + d*H);
    });
  let s = `<svg width="${maxX+220}" height="${maxY+90}" ` +
          `xmlns="http://www.w3.org/2000/svg">`;
  s += '<defs><marker id="ar" viewBox="0 0 10 10" refX="9" refY="5" ' +
       'markerWidth="7" markerHeight="7" orient="auto-start-reverse">' +
       '<path d="M 0 0 L 10 5 L 0 10 z" fill="#666"/></marker></defs>';
  for (const e of g.edges) {
    const a = pos[e.src], b = pos[e.dst];
    if (!a || !b) continue;
    const x1=a.x+80, y1=a.y+44, x2=b.x+80, y2=b.y;
    s += `<line x1="${x1}" y1="${y1}" x2="${x2}" y2="${y2}" stroke="#666" ` +
         `marker-end="url(#ar)"/>` +
         `<text x="${(x1+x2)/2+4}" y="${(y1+y2)/2}" fill="#888">` +
         `${esc(e.movement.toLowerCase())}</text>`;
  }
  const fill = {SUCCEEDED:'#d5e8d4',FAILED:'#f8cecc',RUNNING:'#dae8fc',
                KILLED:'#e1d5e7'};
  for (const v of g.vertices) {
    const p = pos[v.name];
    s += `<rect x="${p.x}" y="${p.y}" width="160" height="44" rx="6" ` +
         `fill="${fill[v.state]||'#eee'}" stroke="#666"/>` +
         `<text x="${p.x+10}" y="${p.y+18}">${esc(v.name)}</text>` +
         `<text x="${p.x+10}" y="${p.y+35}" fill="#555">${esc(v.state)} ` +
         `${v.succeeded}/${v.tasks}</text>`;
  }
  return s + '</svg>';
}
tabbar(); render();
</script></body></html>"""


class _Handler(http.server.BaseHTTPRequestHandler):
    def log_message(self, *args: Any) -> None:
        pass

    def do_GET(self) -> None:  # noqa: N802 — stdlib naming
        am = self.server.am  # type: ignore[attr-defined]
        parsed = urllib.parse.urlparse(self.path)
        path, query = parsed.path, urllib.parse.parse_qs(parsed.query)
        try:
            self._route(am, path, query)
        except BrokenPipeError:
            pass
        except Exception as e:  # noqa: BLE001 — a UI bug must not kill the AM
            log.exception("web ui error for %s", self.path)
            try:
                self._send(500, json.dumps({"error": repr(e)}).encode())
            except Exception:  # noqa: BLE001
                pass

    def _route(self, am: Any, path: str, query: Dict[str, List[str]]) -> None:
        if path == "/":
            self._send(200, _PAGE.encode(), "text/html")
        elif path == "/status":
            dag = am.current_dag
            body = dag.status_dict() if dag is not None else {
                "name": None, "state": "IDLE", "progress": 0, "vertices": {}}
            self._send(200, json.dumps(body, default=str).encode())
        elif path == "/dags":
            self._send(200, json.dumps(self._dags(am)).encode())
        elif path == "/queue":
            self._send(200, json.dumps(self._queue(am),
                                       default=str).encode())
        elif path == "/graph":
            self._send(200, json.dumps(self._graph(am), default=str).encode())
        elif path == "/tasks":
            name = (query.get("vertex") or [""])[0]
            self._send(200, json.dumps(self._tasks(am, name),
                                       default=str).encode())
        elif path == "/counters":
            vertex = (query.get("vertex") or [""])[0]
            self._send(200, json.dumps(
                self._counters(am, vertex)).encode())
        elif path == "/attempt":
            aid = (query.get("id") or [""])[0]
            self._send(200, json.dumps(self._attempt(am, aid),
                                       default=str).encode())
        elif path == "/conf":
            self._send(200, json.dumps(self._conf(am),
                                       default=str).encode())
        elif path == "/swimlane.svg":
            from tez_tpu.tools.swimlane import render_svg
            dag = self._parsed_dag(am)
            if dag is not None:
                svg = render_svg(dag)
                self._send(200, svg.encode(), "image/svg+xml")
            else:
                self._send(404, b'{"error": "no DAG yet"}')
        elif path == "/nodes":
            tracker = getattr(am, "node_tracker", None)
            body = tracker.snapshot() if tracker is not None else {}
            self._send(200, json.dumps(body).encode())
        elif path == "/history":
            events = getattr(am.logging_service, "events", [])
            body = [json.loads(e.to_json()) for e in events[-200:]]
            self._send(200, json.dumps(body).encode())
        elif path == "/analyzers":
            self._send(200, json.dumps(self._analyzers(am),
                                       default=str).encode())
        elif path == "/trace":
            self._send(200, json.dumps(self._trace(am)).encode())
        elif path == "/slo":
            self._send(200, json.dumps(self._slo(am)).encode())
        elif path in ("/metrics", "/metrics.json"):
            from tez_tpu.common import config as C
            conf = getattr(am, "conf", None)
            if conf is not None and not bool(conf.get(C.METRICS_ENABLED)):
                self._send(404, b'{"error": "tez.metrics.enabled is off"}')
                return
            tenant = (query.get("tenant") or [None])[0]
            stream = (query.get("stream") or [None])[0]
            try:
                if path == "/metrics":
                    body, ctype = (self._metrics(am, tenant, stream)
                                   .encode(),
                                   "text/plain; version=0.0.4; "
                                   "charset=utf-8")
                else:
                    window = float((query.get("window") or ["0"])[0] or 0)
                    body, ctype = (json.dumps(self._metrics_json(
                        am, tenant, stream, window)).encode(),
                        "application/json")
            except Exception:
                # scrape-error accounting lives in the plane that broke,
                # so counter_diff can flag scrape health across runs
                from tez_tpu.obs import timeseries
                timeseries.registry().note_scrape_error()
                raise
            self._send(200, body, ctype)
        elif path == "/doctor/live":
            sampler = getattr(am, "telemetry", None)
            if sampler is None:
                self._send(404, b'{"error": "no telemetry sampler"}')
            else:
                window = float((query.get("window") or ["0"])[0] or 0)
                self._send(200, json.dumps(
                    sampler.live_status(window or None),
                    default=str).encode())
        else:
            self._send(404, b'{"error": "not found"}')

    @staticmethod
    def _dags(am: Any) -> List[Dict[str, Any]]:
        names = getattr(am, "completed_dag_names", {})
        # list() snapshots are atomic under the GIL; these dicts are mutated
        # by the dispatcher thread while we serve
        out = [{"dag_id": d, "name": names.get(d, ""), "state": s.name}
               for d, s in list(am.completed_dags.items())]
        live = list(getattr(am, "live_dags", {}).values())
        if not live and am.current_dag is not None:
            live = [am.current_dag]
        for dag in live:
            if str(dag.dag_id) not in am.completed_dags:
                out.append({"dag_id": str(dag.dag_id), "name": dag.name,
                            "state": dag.state.name,
                            "tenant": getattr(dag, "tenant", "")})
        return out

    @staticmethod
    def _queue(am: Any) -> Dict[str, Any]:
        """Admission/queue snapshot: queue depth, per-tenant in-flight/
        queued/shed counts, plus per-tenant resident store bytes."""
        status = am.queue_status() if hasattr(am, "queue_status") else {}
        from tez_tpu.store import local_buffer_store
        store = local_buffer_store()
        if store is not None:
            status["store_tenant_bytes"] = store.tenant_bytes()
        return status

    @staticmethod
    def _slo(am: Any) -> Dict[str, Any]:
        """SLO watchdog surface: declared targets, latched active
        breaches, and the bounded breach/clear transition log."""
        wd = getattr(am, "slo_watchdog", None)
        if wd is None:
            return {"enabled": False, "targets": {}, "active": [],
                    "total_breaches": 0, "log": []}
        return wd.status()

    @staticmethod
    def _graph(am: Any) -> Dict[str, Any]:
        dag = am.current_dag
        if dag is None:
            return {"vertices": [], "edges": []}
        vertices = [{
            "name": v.name, "state": v.state.name,
            "tasks": v.num_tasks, "succeeded": v.succeeded_tasks,
            "distance": v.distance_from_root,
        } for v in list(dag.vertices.values())]
        edges = [{
            "src": e.source_vertex.name, "dst": e.destination_vertex.name,
            "movement": e.edge_property.data_movement_type.name,
        } for e in list(dag.edges.values())]
        return {"vertices": vertices, "edges": edges}

    @staticmethod
    def _attempt_dict(a: Any) -> Dict[str, Any]:
        """The one serialization of an attempt row (shared by the task
        table and the drill-down)."""
        end = a.finish_time or clock.wall_s()
        return {
            "id": str(a.attempt_id), "state": a.state.name,
            "node": a.node_id or str(a.container_id or ""),
            "duration_s": round(max(0.0, end - a.launch_time), 2)
            if a.launch_time else 0.0,
        }

    @staticmethod
    def _tasks(am: Any, vertex_name: str) -> List[Dict[str, Any]]:
        dag = am.current_dag
        v = dag.vertex_by_name(vertex_name) if dag is not None else None
        if v is None:
            return []
        rows = []
        tasks = dict(v.tasks)
        for i in sorted(tasks):
            t = tasks[i]
            task_attempts = dict(t.attempts)
            attempts = [_Handler._attempt_dict(task_attempts[n])
                        for n in sorted(task_attempts)]
            rows.append({"index": i, "state": t.state.name,
                         "attempts": attempts})
        return rows

    @staticmethod
    def _counters(am: Any, vertex_name: str = "") -> Dict[str, Any]:
        """DAG-total counters, or one vertex's aggregation over its
        attempts' counters (tez-ui's per-vertex counters view)."""
        dag = am.current_dag
        if dag is None:
            return {}
        if not vertex_name:
            return dag.counters.to_dict()
        v = dag.vertex_by_name(vertex_name)
        if v is None:
            return {}
        from tez_tpu.common.counters import TezCounters
        agg = TezCounters()
        for t in list(v.tasks.values()):
            # mirror the canonical roll-up (vertex_impl._finish_succeeded):
            # one attempt per task — the successful one when it exists,
            # else the latest — so retries never double-count I/O
            attempts = dict(t.attempts)
            if not attempts:
                continue
            chosen = next((a for a in attempts.values()
                           if a.state.name == "SUCCEEDED"),
                          attempts[max(attempts)])
            agg.aggregate(chosen.counters)
        return agg.to_dict()

    @staticmethod
    def _trace(am: Any) -> Dict[str, Any]:
        """Perfetto trace_event JSON: live span buffer when the tracing
        plane has recorded anything, else a post-mortem trace derived from
        history events (works even for a DAG traced by a crashed AM whose
        journal was replayed)."""
        from tez_tpu.common import tracing
        from tez_tpu.tools import trace_export
        spans = tracing.snapshot()
        if spans:
            return trace_export.spans_to_trace(spans)
        events = getattr(am.logging_service, "events", [])
        if events:
            from tez_tpu.tools.history_parser import parse_history_events
            dags = parse_history_events(events)
            if dags:
                dag = dags[sorted(dags)[-1]]
                return trace_export.history_to_trace(dag)
        return {"traceEvents": [], "displayTimeUnit": "ms"}

    @staticmethod
    def _metrics(am: Any, tenant: Optional[str] = None,
                 stream: Optional[str] = None) -> str:
        """Prometheus text scrape: process-global latency histograms +
        running-task/queued-fetch/epoch gauges + DAG counters, dynamic
        series names split into tenant=/stream=/lane= labels
        (obs/exposition.py); ?tenant= / ?stream= drill down."""
        from tez_tpu.common import metrics
        # every live DAG contributes (concurrent session AM); an idle AM
        # falls back to the most recently retired DAG so post-completion
        # scrapes still see the final counters.  The registry snapshot is
        # taken under the AM's _dag_done lock: a DAG retiring between an
        # unlocked live_dags read and the fallback read could vanish from
        # BOTH maps mid-scrape and silently drop its counters.
        lock = getattr(am, "_dag_done", None)
        if lock is not None:
            with lock:
                dags = list(am.live_dags.values())
                if not dags:
                    dags = list(am.retired_dags.values())[-1:]
        else:
            dags = list(getattr(am, "live_dags", {}).values())
        if not dags and am.current_dag is not None:
            dags = [am.current_dag]
        running = 0
        counters_dict: Dict[str, Dict[str, int]] = {}
        if len(dags) == 1:
            counters_dict = dags[0].counters.to_dict()
        elif dags:
            from tez_tpu.common.counters import TezCounters
            agg = TezCounters()
            for dag in dags:
                agg.aggregate(dag.counters)
            counters_dict = agg.to_dict()
        for dag in dags:
            for v in list(dag.vertices.values()):
                running += sum(1 for t in list(v.tasks.values())
                               if t.state.name == "RUNNING")
        gauges = metrics.registry().gauges()
        gauges["running_tasks"] = float(running)
        gauges["am_epoch"] = float(getattr(am, "attempt", 0) or 0)
        gauges.setdefault("shuffle.queued_fetches", 0.0)
        from tez_tpu.obs import exposition
        return exposition.render_text(
            metrics.registry().histograms(), gauges, counters_dict,
            tenant=tenant, stream=stream)

    @staticmethod
    def _metrics_json(am: Any, tenant: Optional[str],
                      stream: Optional[str],
                      window_s: float) -> Dict[str, Any]:
        """GET /metrics.json: the same families as the text format plus
        windowed rate/p50/p95/p99 from the live rings and the telemetry
        plane's overflow accounting."""
        from tez_tpu.common import metrics
        from tez_tpu.obs import exposition, timeseries
        sampler = getattr(am, "telemetry", None)
        if not window_s:
            window_s = sampler.window_s if sampler is not None else 10.0
        reg = timeseries.registry()
        return exposition.render_json(
            metrics.registry().histograms(), metrics.registry().gauges(),
            windows=reg.windows(window_s), accounting=reg.accounting(),
            window_s=window_s, tenant=tenant, stream=stream)

    @staticmethod
    def _attempt(am: Any, attempt_id: str) -> Dict[str, Any]:
        """One attempt's full drill-down: counters, diagnostics, timing
        (tez-ui attempt page)."""
        dag = am.current_dag
        if dag is None:
            return {"error": "no DAG"}
        for v in list(dag.vertices.values()):
            for t in list(v.tasks.values()):
                for a in list(t.attempts.values()):
                    if str(a.attempt_id) != attempt_id:
                        continue
                    d = _Handler._attempt_dict(a)
                    d.update({
                        "vertex": v.name,
                        "launch_time": a.launch_time,
                        "finish_time": a.finish_time,
                        "diagnostics": list(a.diagnostics),
                        "counters": a.counters.to_dict(),
                    })
                    return d
        return {"error": f"unknown attempt {attempt_id}"}

    #: conf keys whose VALUES must never reach a browser
    _SECRET_MARKERS = ("token", "secret", "password", "ssl.key")

    @classmethod
    def _conf(cls, am: Any) -> Dict[str, Any]:
        """Effective DAG configuration (tez-ui configurations view),
        secrets redacted by key pattern."""
        dag = am.current_dag
        conf = getattr(dag, "conf", None) if dag is not None else am.conf
        if conf is None:
            return {}
        out = {}
        for k, v in dict(conf).items():
            lk = str(k).lower()
            out[k] = "<redacted>" if any(m in lk for m in
                                         cls._SECRET_MARKERS) else v
        return out

    def _parsed_dag(self, am: Any) -> Optional[Any]:
        """Parse the in-memory history into the latest DagInfo, cached on the
        event count — polling tabs must not re-parse an ever-growing event
        list on every request."""
        events = list(getattr(am.logging_service, "events", []))
        if not events:
            return None
        srv = self.server
        cached = getattr(srv, "_parse_cache", None)
        if cached is not None and cached[0] == len(events):
            return cached[1]
        from tez_tpu.tools.history_parser import parse_history_events
        dags = parse_history_events(events)
        dag = list(dags.values())[-1] if dags else None
        srv._parse_cache = (len(events), dag)  # type: ignore[attr-defined]
        return dag

    def _analyzers(self, am: Any) -> List[Dict[str, Any]]:
        n_events = len(getattr(am.logging_service, "events", []))
        srv = self.server
        cached = getattr(srv, "_analyzer_cache", None)
        if cached is not None and cached[0] == n_events:
            return cached[1]
        dag = self._parsed_dag(am)
        if dag is None:
            return []
        from tez_tpu.tools.analyzers import analyze_dag
        out = [r.to_dict() for r in analyze_dag(dag)]
        srv._analyzer_cache = (n_events, out)  # type: ignore[attr-defined]
        return out

    def _send(self, code: int, body: bytes,
              ctype: str = "application/json") -> None:
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)


class WebUIService:
    def __init__(self, am: Any, host: str = "127.0.0.1", port: int = 0):
        self.am = am
        self._httpd = http.server.ThreadingHTTPServer((host, port), _Handler)
        self._httpd.am = am  # type: ignore[attr-defined]
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True, name="am-web")

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    @property
    def url(self) -> str:
        return f"http://127.0.0.1:{self.port}/"

    def start(self) -> "WebUIService":
        self._thread.start()
        log.info("AM web UI at %s", self.url)
        return self

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
