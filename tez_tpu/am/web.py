"""Live AM status endpoint.

Reference parity: tez-dag/.../app/web/{AMWebController.java:69,
WebUIService.java} — the REST surface the Tez UI polls for live DAG/vertex
progress.  Endpoints:
  GET /            tiny HTML progress page (auto-refresh)
  GET /status      JSON DAG status (DAGClient schema)
  GET /counters    JSON aggregated DAG counters
  GET /history     JSON recent history events (in-memory logger only)
"""
from __future__ import annotations

import http.server
import json
import logging
import threading
from typing import Any, Optional

log = logging.getLogger(__name__)

_PAGE = """<!doctype html><html><head><title>tez_tpu AM</title>
<meta http-equiv="refresh" content="2"><style>
body{font-family:monospace;margin:2em} table{border-collapse:collapse}
td,th{border:1px solid #999;padding:4px 10px;text-align:left}
.bar{background:#ddd;width:240px}.fill{background:#4e79a7;height:12px}
</style></head><body><h2 id="t"></h2><div id="c"></div>
<script>
fetch('/status').then(r=>r.json()).then(s=>{
 document.getElementById('t').textContent =
   s.name + ' — ' + s.state + ' (' + Math.round(s.progress*100) + '%)';
 let h = '<table><tr><th>vertex</th><th>state</th><th>tasks</th>' +
         '<th>progress</th></tr>';
 for (const [n,v] of Object.entries(s.vertices)) {
   h += '<tr><td>'+n+'</td><td>'+v.state+'</td><td>'+v.succeeded+'/'+
        v.total_tasks+'</td><td><div class="bar"><div class="fill" '+
        'style="width:'+Math.round(v.progress*240)+'px"></div></div></td></tr>';
 }
 document.getElementById('c').innerHTML = h + '</table>';
});
</script></body></html>"""


class _Handler(http.server.BaseHTTPRequestHandler):
    def log_message(self, *args: Any) -> None:
        pass

    def do_GET(self) -> None:  # noqa: N802 — stdlib naming
        am = self.server.am  # type: ignore[attr-defined]
        if self.path == "/":
            self._send(200, _PAGE.encode(), "text/html")
            return
        if self.path == "/status":
            dag = am.current_dag
            body = dag.status_dict() if dag is not None else {
                "name": None, "state": "IDLE", "progress": 0, "vertices": {}}
            self._send(200, json.dumps(body, default=str).encode())
            return
        if self.path == "/counters":
            dag = am.current_dag
            body = dag.counters.to_dict() if dag is not None else {}
            self._send(200, json.dumps(body).encode())
            return
        if self.path == "/swimlane.svg":
            events = list(getattr(am.logging_service, "events", []))
            from tez_tpu.tools.history_parser import parse_history_events
            from tez_tpu.tools.swimlane import render_svg
            dags = parse_history_events(events)
            if dags:
                svg = render_svg(list(dags.values())[-1])
                self._send(200, svg.encode(), "image/svg+xml")
            else:
                self._send(404, b'{"error": "no DAG yet"}')
            return
        if self.path == "/history":
            events = getattr(am.logging_service, "events", [])
            body = [json.loads(e.to_json()) for e in events[-200:]]
            self._send(200, json.dumps(body).encode())
            return
        self._send(404, b'{"error": "not found"}')

    def _send(self, code: int, body: bytes,
              ctype: str = "application/json") -> None:
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)


class WebUIService:
    def __init__(self, am: Any, host: str = "127.0.0.1", port: int = 0):
        self.am = am
        self._httpd = http.server.ThreadingHTTPServer((host, port), _Handler)
        self._httpd.am = am  # type: ignore[attr-defined]
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True, name="am-web")

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    @property
    def url(self) -> str:
        return f"http://127.0.0.1:{self.port}/"

    def start(self) -> "WebUIService":
        self._thread.start()
        log.info("AM web UI at %s", self.url)
        return self

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
