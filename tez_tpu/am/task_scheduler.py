"""Task scheduling onto the runner pool.

Reference parity: tez-dag/.../app/rm/ — TaskSchedulerManager.java:99
multiplexing pluggable TaskSchedulers; here the stock scheduler is the
LocalTaskSchedulerService analog: a priority queue of launch requests that
runner "containers" pull from (the pull IS the allocation — mirrors
TezChild.getTask).  Container reuse falls out naturally: a runner keeps
pulling until the idle timeout.

The TaskScheduler SPI seam (schedule/deallocate/total_slots) is what a
TPU-pod or GKE scheduler plugin would implement instead
(reference: tez-api serviceplugins TaskScheduler).
"""
from __future__ import annotations

import heapq
import itertools
import logging
import threading
from typing import Any, Dict, List, Optional, Set, Tuple, TYPE_CHECKING

from tez_tpu.common import clock
from tez_tpu.am.events import (SchedulerEvent, SchedulerEventType,
                               TaskAttemptEvent, TaskAttemptEventType)
from tez_tpu.common.ids import ContainerId, TaskAttemptId
from tez_tpu.runtime.task_spec import TaskSpec

log = logging.getLogger(__name__)


class TaskSchedulerService:
    """SPI: how execution slots are acquired (reference:
    serviceplugins/api/TaskScheduler)."""

    def schedule(self, attempt_id: TaskAttemptId, task_spec: TaskSpec,
                 priority: int) -> None:
        raise NotImplementedError

    def deallocate(self, attempt_id: TaskAttemptId,
                   failed: bool = False) -> None:
        """failed=True feeds container-health accounting (blacklisting)."""
        raise NotImplementedError

    def total_slots(self) -> int:
        raise NotImplementedError

    def shutdown(self) -> None:
        pass


class LocalTaskSchedulerService(TaskSchedulerService):
    """Priority queue + pull model (reference: LocalTaskSchedulerService.java:54
    merged with the container-side getTask loop)."""

    #: container failure count that triggers blacklisting (reference:
    #: AMNodeImpl blacklisting via tez.am.maxtaskfailures.per.node)
    MAX_FAILURES_PER_CONTAINER = 3

    def __init__(self, ctx: Any, num_slots: int):
        self.ctx = ctx
        self.num_slots = num_slots
        self._lock = threading.Lock()
        self._available = threading.Condition(self._lock)
        self._heap: List[Any] = []
        self._seq = itertools.count()
        self._queued: Dict[TaskAttemptId, float] = {}   # -> enqueue time
        self._priorities: Dict[TaskAttemptId, int] = {}
        self._running: Dict[TaskAttemptId, ContainerId] = {}
        self._preempting: Set[TaskAttemptId] = set()
        self._last_preempt_round = 0.0
        self._preempt_retry: "threading.Timer | None" = None
        self._vertex_running: Dict[Any, int] = {}   # vertex_id -> count
        self._container_failures: Dict[Any, int] = {}
        self._blacklisted: Set[Any] = set()
        self._shutdown = False
        # -- tenant fair-share (deficit round-robin, docs/multitenancy.md):
        # queued-work counts per tenant, the DRR rotation + credits, and
        # the weights parsed once from tez.am.session.tenant.weights
        from tez_tpu.common import config as C
        conf = getattr(ctx, "conf", None)
        self._fair_share = bool(conf.get(C.AM_SESSION_FAIR_SHARE)) \
            if conf is not None else True
        self._tenant_weights = self._parse_weights(
            str(conf.get(C.AM_SESSION_TENANT_WEIGHTS) or "")
            if conf is not None else "")
        self._queued_tenant: Dict[TaskAttemptId, str] = {}
        self._tenant_queued: Dict[str, int] = {}
        self._rr_order: List[str] = []
        self._rr_idx = 0
        self._tenant_deficit: Dict[str, float] = {}

    @staticmethod
    def _parse_weights(spec: str) -> Dict[str, float]:
        out: Dict[str, float] = {}
        for part in spec.split(","):
            part = part.strip()
            if not part:
                continue
            name, _, w = part.partition("=")
            try:
                out[name.strip()] = max(0.001, float(w or 1.0))
            except ValueError:
                log.warning("bad tenant weight %r ignored", part)
        return out

    def _weight(self, tenant: str) -> float:
        return self._tenant_weights.get(tenant, 1.0)

    def schedule(self, attempt_id: TaskAttemptId, task_spec: TaskSpec,
                 priority: int) -> None:
        tenant = getattr(task_spec, "tenant", "") or ""
        with self._lock:
            heapq.heappush(self._heap,
                           (priority, next(self._seq), attempt_id, task_spec))
            self._queued[attempt_id] = clock.wall_s()
            self._priorities[attempt_id] = priority
            self._queued_tenant[attempt_id] = tenant
            self._tenant_queued[tenant] = \
                self._tenant_queued.get(tenant, 0) + 1
            if tenant not in self._rr_order:
                self._rr_order.append(tenant)
            self._available.notify()
        self.ctx.ensure_runners(self.backlog())
        self._maybe_preempt()

    def _drop_queued_tenant_locked(self, attempt_id: TaskAttemptId) -> None:
        tenant = self._queued_tenant.pop(attempt_id, None)
        if tenant is not None:
            n = self._tenant_queued.get(tenant, 0) - 1
            if n > 0:
                self._tenant_queued[tenant] = n
            else:
                self._tenant_queued.pop(tenant, None)

    def _maybe_preempt(self) -> None:
        """Higher-priority work waiting with every slot busy on strictly
        lower-priority attempts: kill the lowest-priority running attempts
        (up to tez.am.preemption.percentage of slots).  Killed attempts
        respawn and re-queue — reference: YarnTaskSchedulerService
        preemption (lower priority VALUE = more important, heap order)."""
        with self._lock:
            # a _preempt_retry Timer can fire after shutdown() cancelled it
            # (cancel() does not stop a Timer already past its wait): never
            # dispatch TA_KILL_REQUEST into a stopping AM
            if self._shutdown:
                return
            # cheap common-path exit BEFORE any heap scan: a free slot (or
            # empty queue) means nothing to preempt — schedule() stays O(1)
            if len(self._running) < self.num_slots or not self._queued:
                return
        from tez_tpu.common import config as C
        conf = getattr(self.ctx, "conf", None)
        pct = int(conf.get(C.AM_PREEMPTION_PERCENTAGE)) \
            if conf is not None else 10
        if pct <= 0:
            return   # preemption disabled
        limit = max(1, self.num_slots * pct // 100)
        with self._lock:
            if self._shutdown or len(self._running) < self.num_slots:
                return
            # best waiting priority from the heap head, lazily discarding
            # entries cancelled while queued
            best_waiting = None
            best_att = None
            while self._heap:
                p, _s, a, _spec = self._heap[0]
                if a in self._queued:
                    best_waiting = p
                    best_att = a
                    break
                heapq.heappop(self._heap)
            if best_waiting is None:
                return
            # pacing (reference: heartbeats-between-preemptions x the AM-RM
            # heartbeat period): preemption rounds keep a minimum spacing so
            # one burst of schedule() calls doesn't serially kill a slot's
            # whole complement — UNLESS the top request has waited past
            # max.wait-time-ms, which forces a round
            now = clock.wall_s()
            hb_between = int(conf.get(C.AM_PREEMPTION_HEARTBEATS_BETWEEN)) \
                if conf is not None else 3
            max_wait_ms = int(conf.get(C.AM_PREEMPTION_MAX_WAIT_MS)) \
                if conf is not None else 60_000
            spacing = hb_between * 0.25   # 250 ms AM heartbeat period analog
            waited = now - self._queued.get(best_att, now)
            if self._last_preempt_round and \
                    now - self._last_preempt_round < spacing and \
                    waited * 1000 < max_wait_ms:
                # paced out — but _maybe_preempt only runs from schedule(),
                # so arm a one-shot retry or the deferred round (and the
                # max-wait force) would never fire without new submissions
                if self._preempt_retry is None:
                    delay = spacing - (now - self._last_preempt_round)

                    def _retry() -> None:
                        with self._lock:
                            self._preempt_retry = None
                        self._maybe_preempt()

                    t = threading.Timer(max(delay, 0.05), _retry)
                    t.daemon = True
                    self._preempt_retry = t
                    t.start()
                return
            self._preempting &= set(self._running)
            budget = limit - len(self._preempting)
            if budget <= 0:
                return
            eligible = self._victim_filter(self._queued)
            victims = sorted(
                ((self._priorities.get(att, 0), att)
                 for att in self._running
                 if self._priorities.get(att, 0) > best_waiting
                 and att not in self._preempting
                 and eligible(att)),
                key=lambda x: -x[0])[:budget]
            self._preempting.update(att for _, att in victims)
            if victims:
                self._last_preempt_round = now
        for prio, att in victims:
            log.info("preempting %s (priority %d) for waiting priority %d",
                     att, prio, best_waiting)
            self.ctx.dispatch(TaskAttemptEvent(
                TaskAttemptEventType.TA_KILL_REQUEST, att,
                diagnostics=f"preempted: priority-{best_waiting} work "
                            "waiting for a slot"))

    def _victim_filter(self, waiting: "Set[TaskAttemptId]"):
        """Hook: which running attempts MAY be preempted, given the set of
        queued attempts.  The stock policy allows any; the DAG-aware
        subclass restricts to descendants of the waiting vertices."""
        return lambda att: True

    def deallocate(self, attempt_id: TaskAttemptId,
                   failed: bool = False) -> None:
        with self._lock:
            if self._queued.pop(attempt_id, None) is not None:
                self._drop_queued_tenant_locked(attempt_id)
            self._preempting.discard(attempt_id)
            self._priorities.pop(attempt_id, None)
            container = self._running.pop(attempt_id, None)
            if container is not None:
                vid = attempt_id.vertex_id
                n = self._vertex_running.get(vid, 0) - 1
                if n > 0:
                    self._vertex_running[vid] = n
                else:
                    self._vertex_running.pop(vid, None)
                # a finished attempt may unblock work deferred by the
                # vertex concurrency cap: wake waiting runners to re-pop
                self._available.notify_all()
            if failed and container is not None:
                n = self._container_failures.get(container, 0) + 1
                self._container_failures[container] = n
                if n >= self.MAX_FAILURES_PER_CONTAINER and \
                        container not in self._blacklisted:
                    self._blacklisted.add(container)
                    log.warning("container %s blacklisted after %d failures",
                                container, n)

    def is_blacklisted(self, container_id: Any) -> bool:
        with self._lock:
            return container_id in self._blacklisted

    def backlog(self) -> int:
        with self._lock:
            return len(self._queued)

    def total_slots(self) -> int:
        return self.num_slots

    def get_task(self, container_id: ContainerId,
                 timeout: float) -> Optional[TaskSpec]:
        """Runner pull (the allocation point).  Returns None on idle timeout,
        shutdown, or when this container is blacklisted (the runner exits
        and the pool replaces it — container loss recovery)."""
        conf = getattr(self.ctx, "conf", None)
        max_conc = int(conf.get("tez.am.vertex.max-task-concurrency", -1)) \
            if conf is not None else -1
        with self._lock:
            if container_id in self._blacklisted:
                return None
            while True:
                # deficit round-robin tenant pick: with >1 tenant queued,
                # prefer the tenant whose credit is due; a tenant with no
                # poppable entry (concurrency cap) falls back to the best
                # other entry — fair, but always work-conserving
                want = self._drr_pick_locked()
                deferred: List[Any] = []
                handout = None
                fallback = None          # first poppable non-want entry
                while self._heap:
                    entry = heapq.heappop(self._heap)
                    prio, seq, attempt_id, spec = entry
                    if attempt_id not in self._queued:
                        continue  # cancelled while queued
                    if max_conc > 0 and self._vertex_running.get(
                            attempt_id.vertex_id, 0) >= max_conc:
                        # vertex at its concurrency cap
                        # (tez.am.vertex.max-task-concurrency): skip, try
                        # the next entry, re-queue the skipped ones
                        deferred.append(entry)
                        continue
                    tenant = self._queued_tenant.get(attempt_id, "")
                    if want is not None and tenant != want:
                        deferred.append(entry)
                        if fallback is None:
                            fallback = entry
                        continue
                    handout = entry
                    break
                if handout is None and fallback is not None:
                    handout = fallback
                    deferred.remove(fallback)
                for entry in deferred:
                    heapq.heappush(self._heap, entry)
                if handout is not None:
                    prio, seq, attempt_id, spec = handout
                    tenant = self._queued_tenant.get(attempt_id, "")
                    self._queued.pop(attempt_id, None)
                    self._drop_queued_tenant_locked(attempt_id)
                    self._running[attempt_id] = container_id
                    self._vertex_running[attempt_id.vertex_id] = \
                        self._vertex_running.get(attempt_id.vertex_id, 0) + 1
                    if want is not None:
                        # charge whichever tenant actually got the slot
                        d = self._tenant_deficit.get(tenant, 0.0)
                        self._tenant_deficit[tenant] = max(0.0, d - 1.0)
                    return spec
                if self._shutdown:
                    return None
                if not self._available.wait(timeout):
                    return None

    def _drr_pick_locked(self) -> Optional[str]:
        """Next tenant owed a slot (deficit round-robin): visiting a tenant
        replenishes its credit by its weight; a tenant is served while its
        credit lasts, then the rotation advances.  None = fair-share off or
        only one tenant has queued work (plain priority order)."""
        if not self._fair_share:
            return None
        eligible = {t for t, n in self._tenant_queued.items() if n > 0}
        if len(eligible) <= 1:
            return None
        order = self._rr_order
        for _ in range(4 * len(order) + 4):
            t = order[self._rr_idx % len(order)]
            if t in eligible and self._tenant_deficit.get(t, 0.0) >= 1.0:
                return t                 # still in service on this turn
            # t's turn is over (no queued work, or credit spent): the
            # rotation advances and the tenant it ARRIVES at earns its
            # quantum.  Replenishing before advancing would hand the
            # current tenant fresh credit every pick — a monopoly, not
            # round-robin.
            if t not in eligible:
                self._tenant_deficit[t] = 0.0   # empty queue loses credit
            self._rr_idx += 1
            nt = order[self._rr_idx % len(order)]
            if nt in eligible:
                # cap the burst a long-idle tenant could otherwise bank
                self._tenant_deficit[nt] = min(
                    self._tenant_deficit.get(nt, 0.0) + self._weight(nt),
                    4.0 * self._weight(nt))
        return next(iter(sorted(eligible)))    # unreachable-in-practice guard


    def shutdown(self) -> None:
        with self._lock:
            self._shutdown = True
            if self._preempt_retry is not None:
                self._preempt_retry.cancel()
                self._preempt_retry = None
            self._available.notify_all()


class DagAwareTaskSchedulerService(LocalTaskSchedulerService):
    """DAG-topology-aware preemption (reference:
    DagAwareYarnTaskScheduler.java:96, maybePreempt:1172).

    The stock scheduler preempts ANY strictly-lower-priority running
    attempt when better work waits.  That can kill unrelated branch work
    whose eviction cannot unblock the waiting request — and whose re-run
    throws away progress.  Here victims must be DESCENDANTS of a vertex
    with requests waiting at the best priority (the reference's
    blocked-set ∩ assigned-vertices rule): preempting a descendant is
    always productive, because the descendant cannot finish before its
    blocked ancestor anyway."""

    def __init__(self, ctx: Any, num_slots: int):
        super().__init__(ctx, num_slots)
        self._descendants_cache: Dict[str, Dict[str, Set[str]]] = {}

    # ----------------------------------------------------------- topology
    def _dag_for(self, attempt_id: TaskAttemptId) -> Any:
        """Resolve the attempt's DAG through the live registry (concurrent
        session DAGs); falls back to current_dag for older contexts."""
        find = getattr(self.ctx, "find_dag", None)
        if find is not None:
            return find(attempt_id.vertex_id.dag_id)
        return getattr(self.ctx, "current_dag", None)

    def _descendants(self, dag: Any) -> Dict[str, Set[str]]:
        """vertex name -> set of (transitive) descendant vertex names for
        one DAG (reference: vertexDescendants BitSets); cached per dag_id
        since several DAGs stay live at once."""
        if dag is None:
            return {}
        key = str(dag.dag_id)
        cached = self._descendants_cache.get(key)
        if cached is not None:
            return cached
        children = {name: [e.destination_vertex.name
                           for e in v.out_edges.values()]
                    for name, v in dag.vertices.items()}
        memo: Dict[str, Set[str]] = {}

        def desc(name: str) -> Set[str]:
            got = memo.get(name)
            if got is not None:
                return got
            memo[name] = out = set()   # pre-seed: DAG => no cycles, but a
            # partially-built entry keeps this robust anyway
            for c in children.get(name, ()):
                out.add(c)
                out |= desc(c)
            return out

        result = {name: desc(name) for name in children}
        if len(self._descendants_cache) > 16:    # bound the session cache
            self._descendants_cache.clear()
        self._descendants_cache[key] = result
        return result

    def _vertex_name(self, attempt_id: TaskAttemptId) -> str:
        dag = self._dag_for(attempt_id)
        if dag is None:
            return ""
        v = dag.vertex_by_id(attempt_id.vertex_id)
        return v.name if v is not None else ""

    def _victim_filter(self, waiting: "Set[TaskAttemptId]"):
        """Victims must be descendants of ANY vertex with queued requests
        (the reference's blocked-set ∩ assigned-vertices rule) — evicting a
        descendant always helps, because it cannot finish before its
        blocked ancestor anyway.  Blocked entries are (dag_id, vertex)
        pairs: vertex names may collide across concurrent DAGs, and
        cross-DAG preemption through a name collision would be unfair."""
        blocked: Set[Tuple[str, str]] = set()
        for a in waiting:
            dag = self._dag_for(a)
            if dag is None:
                continue
            descendants = self._descendants(dag)
            for name in descendants.get(self._vertex_name(a), set()):
                blocked.add((str(dag.dag_id), name))

        def _is_victim(att: TaskAttemptId) -> bool:
            # resolve the victim's DAG through the same registry the
            # blocked set was built from, so both sides agree on the id
            dag = self._dag_for(att)
            if dag is None:
                return False
            return (str(dag.dag_id), self._vertex_name(att)) in blocked

        return _is_victim


def create_task_scheduler(ctx: Any, num_slots: int) -> TaskSchedulerService:
    """tez.am.task.scheduler.class: 'local' | 'dag-aware' | module:Class."""
    from tez_tpu.common import config as C
    name = ctx.conf.get(C.AM_TASK_SCHEDULER_CLASS) if ctx.conf is not None \
        else "local"
    if name in ("", "local", None):
        return LocalTaskSchedulerService(ctx, num_slots)
    if name == "dag-aware":
        return DagAwareTaskSchedulerService(ctx, num_slots)
    from tez_tpu.common.payload import resolve_class
    return resolve_class(name)(ctx, num_slots)


class TaskSchedulerManager:
    """Dispatcher-facing façade (reference: TaskSchedulerManager.java:99)."""

    def __init__(self, ctx: Any, scheduler: TaskSchedulerService):
        self.ctx = ctx
        self.scheduler = scheduler

    def handle(self, event: SchedulerEvent) -> None:
        if event.event_type is SchedulerEventType.S_TA_LAUNCH_REQUEST:
            self.scheduler.schedule(event.attempt_id, event.task_spec,
                                    event.priority)
        elif event.event_type is SchedulerEventType.S_TA_ENDED:
            failed = getattr(event, "failed", False)
            self.scheduler.deallocate(event.attempt_id, failed=failed)
            tracker = getattr(self.ctx, "node_tracker", None)
            node = getattr(event, "node_id", "")
            if tracker is not None and node:
                if failed:
                    tracker.on_attempt_failed(node)
                else:
                    tracker.on_attempt_succeeded(node)
