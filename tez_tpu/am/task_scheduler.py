"""Task scheduling onto the runner pool.

Reference parity: tez-dag/.../app/rm/ — TaskSchedulerManager.java:99
multiplexing pluggable TaskSchedulers; here the stock scheduler is the
LocalTaskSchedulerService analog: a priority queue of launch requests that
runner "containers" pull from (the pull IS the allocation — mirrors
TezChild.getTask).  Container reuse falls out naturally: a runner keeps
pulling until the idle timeout.

The TaskScheduler SPI seam (schedule/deallocate/total_slots) is what a
TPU-pod or GKE scheduler plugin would implement instead
(reference: tez-api serviceplugins TaskScheduler).
"""
from __future__ import annotations

import heapq
import itertools
import logging
import threading
import time
from typing import Any, Dict, List, Optional, Set, TYPE_CHECKING

from tez_tpu.am.events import (SchedulerEvent, SchedulerEventType,
                               TaskAttemptEvent, TaskAttemptEventType)
from tez_tpu.common.ids import ContainerId, TaskAttemptId
from tez_tpu.runtime.task_spec import TaskSpec

log = logging.getLogger(__name__)


class TaskSchedulerService:
    """SPI: how execution slots are acquired (reference:
    serviceplugins/api/TaskScheduler)."""

    def schedule(self, attempt_id: TaskAttemptId, task_spec: TaskSpec,
                 priority: int) -> None:
        raise NotImplementedError

    def deallocate(self, attempt_id: TaskAttemptId,
                   failed: bool = False) -> None:
        """failed=True feeds container-health accounting (blacklisting)."""
        raise NotImplementedError

    def total_slots(self) -> int:
        raise NotImplementedError

    def shutdown(self) -> None:
        pass


class LocalTaskSchedulerService(TaskSchedulerService):
    """Priority queue + pull model (reference: LocalTaskSchedulerService.java:54
    merged with the container-side getTask loop)."""

    #: container failure count that triggers blacklisting (reference:
    #: AMNodeImpl blacklisting via tez.am.maxtaskfailures.per.node)
    MAX_FAILURES_PER_CONTAINER = 3

    def __init__(self, ctx: Any, num_slots: int):
        self.ctx = ctx
        self.num_slots = num_slots
        self._lock = threading.Lock()
        self._available = threading.Condition(self._lock)
        self._heap: List[Any] = []
        self._seq = itertools.count()
        self._queued: Dict[TaskAttemptId, float] = {}   # -> enqueue time
        self._priorities: Dict[TaskAttemptId, int] = {}
        self._running: Dict[TaskAttemptId, ContainerId] = {}
        self._preempting: Set[TaskAttemptId] = set()
        self._last_preempt_round = 0.0
        self._preempt_retry: "threading.Timer | None" = None
        self._vertex_running: Dict[Any, int] = {}   # vertex_id -> count
        self._container_failures: Dict[Any, int] = {}
        self._blacklisted: Set[Any] = set()
        self._shutdown = False

    def schedule(self, attempt_id: TaskAttemptId, task_spec: TaskSpec,
                 priority: int) -> None:
        with self._lock:
            heapq.heappush(self._heap,
                           (priority, next(self._seq), attempt_id, task_spec))
            self._queued[attempt_id] = time.time()
            self._priorities[attempt_id] = priority
            self._available.notify()
        self.ctx.ensure_runners(self.backlog())
        self._maybe_preempt()

    def _maybe_preempt(self) -> None:
        """Higher-priority work waiting with every slot busy on strictly
        lower-priority attempts: kill the lowest-priority running attempts
        (up to tez.am.preemption.percentage of slots).  Killed attempts
        respawn and re-queue — reference: YarnTaskSchedulerService
        preemption (lower priority VALUE = more important, heap order)."""
        with self._lock:
            # a _preempt_retry Timer can fire after shutdown() cancelled it
            # (cancel() does not stop a Timer already past its wait): never
            # dispatch TA_KILL_REQUEST into a stopping AM
            if self._shutdown:
                return
            # cheap common-path exit BEFORE any heap scan: a free slot (or
            # empty queue) means nothing to preempt — schedule() stays O(1)
            if len(self._running) < self.num_slots or not self._queued:
                return
        from tez_tpu.common import config as C
        conf = getattr(self.ctx, "conf", None)
        pct = int(conf.get(C.AM_PREEMPTION_PERCENTAGE)) \
            if conf is not None else 10
        if pct <= 0:
            return   # preemption disabled
        limit = max(1, self.num_slots * pct // 100)
        with self._lock:
            if self._shutdown or len(self._running) < self.num_slots:
                return
            # best waiting priority from the heap head, lazily discarding
            # entries cancelled while queued
            best_waiting = None
            best_att = None
            while self._heap:
                p, _s, a, _spec = self._heap[0]
                if a in self._queued:
                    best_waiting = p
                    best_att = a
                    break
                heapq.heappop(self._heap)
            if best_waiting is None:
                return
            # pacing (reference: heartbeats-between-preemptions x the AM-RM
            # heartbeat period): preemption rounds keep a minimum spacing so
            # one burst of schedule() calls doesn't serially kill a slot's
            # whole complement — UNLESS the top request has waited past
            # max.wait-time-ms, which forces a round
            now = time.time()
            hb_between = int(conf.get(C.AM_PREEMPTION_HEARTBEATS_BETWEEN)) \
                if conf is not None else 3
            max_wait_ms = int(conf.get(C.AM_PREEMPTION_MAX_WAIT_MS)) \
                if conf is not None else 60_000
            spacing = hb_between * 0.25   # 250 ms AM heartbeat period analog
            waited = now - self._queued.get(best_att, now)
            if self._last_preempt_round and \
                    now - self._last_preempt_round < spacing and \
                    waited * 1000 < max_wait_ms:
                # paced out — but _maybe_preempt only runs from schedule(),
                # so arm a one-shot retry or the deferred round (and the
                # max-wait force) would never fire without new submissions
                if self._preempt_retry is None:
                    delay = spacing - (now - self._last_preempt_round)

                    def _retry() -> None:
                        with self._lock:
                            self._preempt_retry = None
                        self._maybe_preempt()

                    t = threading.Timer(max(delay, 0.05), _retry)
                    t.daemon = True
                    self._preempt_retry = t
                    t.start()
                return
            self._preempting &= set(self._running)
            budget = limit - len(self._preempting)
            if budget <= 0:
                return
            eligible = self._victim_filter(self._queued)
            victims = sorted(
                ((self._priorities.get(att, 0), att)
                 for att in self._running
                 if self._priorities.get(att, 0) > best_waiting
                 and att not in self._preempting
                 and eligible(att)),
                key=lambda x: -x[0])[:budget]
            self._preempting.update(att for _, att in victims)
            if victims:
                self._last_preempt_round = now
        for prio, att in victims:
            log.info("preempting %s (priority %d) for waiting priority %d",
                     att, prio, best_waiting)
            self.ctx.dispatch(TaskAttemptEvent(
                TaskAttemptEventType.TA_KILL_REQUEST, att,
                diagnostics=f"preempted: priority-{best_waiting} work "
                            "waiting for a slot"))

    def _victim_filter(self, waiting: "Set[TaskAttemptId]"):
        """Hook: which running attempts MAY be preempted, given the set of
        queued attempts.  The stock policy allows any; the DAG-aware
        subclass restricts to descendants of the waiting vertices."""
        return lambda att: True

    def deallocate(self, attempt_id: TaskAttemptId,
                   failed: bool = False) -> None:
        with self._lock:
            self._queued.pop(attempt_id, None)
            self._preempting.discard(attempt_id)
            self._priorities.pop(attempt_id, None)
            container = self._running.pop(attempt_id, None)
            if container is not None:
                vid = attempt_id.vertex_id
                n = self._vertex_running.get(vid, 0) - 1
                if n > 0:
                    self._vertex_running[vid] = n
                else:
                    self._vertex_running.pop(vid, None)
                # a finished attempt may unblock work deferred by the
                # vertex concurrency cap: wake waiting runners to re-pop
                self._available.notify_all()
            if failed and container is not None:
                n = self._container_failures.get(container, 0) + 1
                self._container_failures[container] = n
                if n >= self.MAX_FAILURES_PER_CONTAINER and \
                        container not in self._blacklisted:
                    self._blacklisted.add(container)
                    log.warning("container %s blacklisted after %d failures",
                                container, n)

    def is_blacklisted(self, container_id: Any) -> bool:
        with self._lock:
            return container_id in self._blacklisted

    def backlog(self) -> int:
        with self._lock:
            return len(self._queued)

    def total_slots(self) -> int:
        return self.num_slots

    def get_task(self, container_id: ContainerId,
                 timeout: float) -> Optional[TaskSpec]:
        """Runner pull (the allocation point).  Returns None on idle timeout,
        shutdown, or when this container is blacklisted (the runner exits
        and the pool replaces it — container loss recovery)."""
        conf = getattr(self.ctx, "conf", None)
        max_conc = int(conf.get("tez.am.vertex.max-task-concurrency", -1)) \
            if conf is not None else -1
        with self._lock:
            if container_id in self._blacklisted:
                return None
            while True:
                deferred: List[Any] = []
                handout = None
                while self._heap:
                    entry = heapq.heappop(self._heap)
                    prio, seq, attempt_id, spec = entry
                    if attempt_id not in self._queued:
                        continue  # cancelled while queued
                    if max_conc > 0 and self._vertex_running.get(
                            attempt_id.vertex_id, 0) >= max_conc:
                        # vertex at its concurrency cap
                        # (tez.am.vertex.max-task-concurrency): skip, try
                        # the next entry, re-queue the skipped ones
                        deferred.append(entry)
                        continue
                    self._queued.pop(attempt_id, None)
                    self._running[attempt_id] = container_id
                    self._vertex_running[attempt_id.vertex_id] = \
                        self._vertex_running.get(attempt_id.vertex_id, 0) + 1
                    handout = spec
                    break
                for entry in deferred:
                    heapq.heappush(self._heap, entry)
                if handout is not None:
                    return handout
                if self._shutdown:
                    return None
                if not self._available.wait(timeout):
                    return None


    def shutdown(self) -> None:
        with self._lock:
            self._shutdown = True
            if self._preempt_retry is not None:
                self._preempt_retry.cancel()
                self._preempt_retry = None
            self._available.notify_all()


class DagAwareTaskSchedulerService(LocalTaskSchedulerService):
    """DAG-topology-aware preemption (reference:
    DagAwareYarnTaskScheduler.java:96, maybePreempt:1172).

    The stock scheduler preempts ANY strictly-lower-priority running
    attempt when better work waits.  That can kill unrelated branch work
    whose eviction cannot unblock the waiting request — and whose re-run
    throws away progress.  Here victims must be DESCENDANTS of a vertex
    with requests waiting at the best priority (the reference's
    blocked-set ∩ assigned-vertices rule): preempting a descendant is
    always productive, because the descendant cannot finish before its
    blocked ancestor anyway."""

    def __init__(self, ctx: Any, num_slots: int):
        super().__init__(ctx, num_slots)
        self._descendants_cache: Dict[str, Dict[str, Set[str]]] = {}

    # ----------------------------------------------------------- topology
    def _descendants(self) -> Dict[str, Set[str]]:
        """vertex name -> set of (transitive) descendant vertex names for
        the current DAG (reference: vertexDescendants BitSets)."""
        dag = getattr(self.ctx, "current_dag", None)
        if dag is None:
            return {}
        key = str(dag.dag_id)
        cached = self._descendants_cache.get(key)
        if cached is not None:
            return cached
        children = {name: [e.destination_vertex.name
                           for e in v.out_edges.values()]
                    for name, v in dag.vertices.items()}
        memo: Dict[str, Set[str]] = {}

        def desc(name: str) -> Set[str]:
            got = memo.get(name)
            if got is not None:
                return got
            memo[name] = out = set()   # pre-seed: DAG => no cycles, but a
            # partially-built entry keeps this robust anyway
            for c in children.get(name, ()):
                out.add(c)
                out |= desc(c)
            return out

        result = {name: desc(name) for name in children}
        self._descendants_cache = {key: result}   # one DAG at a time
        return result

    def _vertex_name(self, attempt_id: TaskAttemptId) -> str:
        dag = getattr(self.ctx, "current_dag", None)
        if dag is None:
            return ""
        v = dag.vertex_by_id(attempt_id.vertex_id)
        return v.name if v is not None else ""

    def _victim_filter(self, waiting: "Set[TaskAttemptId]"):
        """Victims must be descendants of ANY vertex with queued requests
        (the reference's blocked-set ∩ assigned-vertices rule) — evicting a
        descendant always helps, because it cannot finish before its
        blocked ancestor anyway."""
        descendants = self._descendants()
        blocked: Set[str] = set()
        for a in waiting:
            blocked |= descendants.get(self._vertex_name(a), set())
        return lambda att: self._vertex_name(att) in blocked


def create_task_scheduler(ctx: Any, num_slots: int) -> TaskSchedulerService:
    """tez.am.task.scheduler.class: 'local' | 'dag-aware' | module:Class."""
    from tez_tpu.common import config as C
    name = ctx.conf.get(C.AM_TASK_SCHEDULER_CLASS) if ctx.conf is not None \
        else "local"
    if name in ("", "local", None):
        return LocalTaskSchedulerService(ctx, num_slots)
    if name == "dag-aware":
        return DagAwareTaskSchedulerService(ctx, num_slots)
    from tez_tpu.common.payload import resolve_class
    return resolve_class(name)(ctx, num_slots)


class TaskSchedulerManager:
    """Dispatcher-facing façade (reference: TaskSchedulerManager.java:99)."""

    def __init__(self, ctx: Any, scheduler: TaskSchedulerService):
        self.ctx = ctx
        self.scheduler = scheduler

    def handle(self, event: SchedulerEvent) -> None:
        if event.event_type is SchedulerEventType.S_TA_LAUNCH_REQUEST:
            self.scheduler.schedule(event.attempt_id, event.task_spec,
                                    event.priority)
        elif event.event_type is SchedulerEventType.S_TA_ENDED:
            failed = getattr(event, "failed", False)
            self.scheduler.deallocate(event.attempt_id, failed=failed)
            tracker = getattr(self.ctx, "node_tracker", None)
            node = getattr(event, "node_id", "")
            if tracker is not None and node:
                if failed:
                    tracker.on_attempt_failed(node)
                else:
                    tracker.on_attempt_succeeded(node)
