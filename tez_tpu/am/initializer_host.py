"""Runs InputInitializers on the AM executor, feeding events back through
the dispatcher.

Reference parity: tez-dag/.../dag/impl/RootInputInitializerManager.java:82.
"""
from __future__ import annotations

import logging
from typing import Any, TYPE_CHECKING

from tez_tpu.api.events import InputInitializerEvent
from tez_tpu.api.initializer import InputInitializer, InputInitializerContext
from tez_tpu.am.events import VertexEvent, VertexEventType
from tez_tpu.common.payload import UserPayload
from tez_tpu.dag.plan import RootInputSpec

if TYPE_CHECKING:
    from tez_tpu.am.vertex_impl import VertexImpl

log = logging.getLogger(__name__)


class _InitializerContext(InputInitializerContext):
    def __init__(self, vertex: "VertexImpl", spec: RootInputSpec):
        self._vertex = vertex
        self._spec = spec

    @property
    def input_name(self) -> str:
        return self._spec.name

    @property
    def vertex_name(self) -> str:
        return self._vertex.name

    @property
    def dag_name(self) -> str:
        return self._vertex.dag.name

    @property
    def user_payload(self) -> UserPayload:
        # The initializer's own payload, unconditionally (reference:
        # InputInitializerContext.getUserPayload); the input descriptor's
        # payload is exposed separately as input_user_payload.
        if self._spec.initializer_descriptor is not None:
            return self._spec.initializer_descriptor.payload
        return UserPayload()

    @property
    def input_user_payload(self) -> UserPayload:
        return self._spec.input_descriptor.payload

    @property
    def conf(self) -> Any:
        return self._vertex.conf

    @property
    def num_tasks(self) -> int:
        return self._vertex.num_tasks

    def get_total_available_resource(self) -> int:
        return self._vertex.ctx.total_slots()

    def get_vertex_num_tasks(self, vertex_name: str) -> int:
        v = self._vertex.dag.vertex_by_name(vertex_name)
        return v.num_tasks if v is not None else -1

    def register_for_vertex_state_updates(self, vertex_name: str,
                                          states: Any) -> None:
        self._vertex.dag.register_state_updates(
            vertex_name, self._vertex.initializers.get(self._spec.name), states)


def run_initializer(vertex: "VertexImpl", spec: RootInputSpec) -> None:
    ctx = _InitializerContext(vertex, spec)
    try:
        initializer: InputInitializer = \
            spec.initializer_descriptor.instantiate(ctx)
    except BaseException as e:  # noqa: BLE001
        vertex.ctx.dispatch(VertexEvent(
            VertexEventType.V_ROOT_INPUT_FAILED, vertex.vertex_id,
            input_name=spec.name, diagnostics=repr(e)))
        return
    vertex.initializers[spec.name] = initializer

    def _run() -> None:
        try:
            events = initializer.initialize()
            vertex.ctx.dispatch(VertexEvent(
                VertexEventType.V_ROOT_INPUT_INITIALIZED, vertex.vertex_id,
                input_name=spec.name, events=events))
        except BaseException as e:  # noqa: BLE001
            log.exception("initializer %s/%s failed", vertex.name, spec.name)
            vertex.ctx.dispatch(VertexEvent(
                VertexEventType.V_ROOT_INPUT_FAILED, vertex.vertex_id,
                input_name=spec.name, diagnostics=repr(e)))

    vertex.ctx.submit_to_executor(_run)


def deliver_initializer_event(vertex: "VertexImpl",
                              event: InputInitializerEvent) -> None:
    init = vertex.initializers.get(event.target_input_name)
    if init is not None:
        try:
            init.handle_input_initializer_event([event])
        except BaseException:  # noqa: BLE001
            log.exception("initializer event handler failed")
