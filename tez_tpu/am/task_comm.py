"""The umbilical: runner <-> AM control protocol.

Reference parity: tez-runtime-internals/.../common/TezTaskUmbilicalProtocol.java:42
(getTask / heartbeat / canCommit) + tez-dag TaskCommunicatorManager.java:220
(heartbeat event routing) and TezTaskCommunicatorImpl (getTask :311).

In local mode this is a plain in-process object; a multi-host deployment puts
a gRPC server in front of the same interface (the TaskCommunicator service
plugin seam).  Heartbeats batch task events up and pull routed input events
down, exactly like TezHeartbeatRequest/Response.
"""
from __future__ import annotations

import dataclasses
import logging
import threading
from typing import Any, Dict, List, Optional, Sequence, Set, Tuple

from tez_tpu.api.events import TezAPIEvent, TezEvent
from tez_tpu.am.events import (TaskAttemptEvent, TaskAttemptEventType,
                               VertexEvent, VertexEventType)
from tez_tpu.common import clock, epoch as epoch_registry
from tez_tpu.common import faults, tracing
from tez_tpu.common.counters import TezCounters
from tez_tpu.common.ids import ContainerId, TaskAttemptId
from tez_tpu.runtime.task_spec import TaskSpec

log = logging.getLogger(__name__)


@dataclasses.dataclass
class HeartbeatRequest:
    attempt_id: TaskAttemptId
    events: List[TezEvent]
    counters: Optional[TezCounters] = None
    progress: float = 0.0
    #: AM epoch stamped into the runner's TaskSpec (0 = unstamped/legacy)
    epoch: int = 0
    #: Streaming window coordinate of the generalized fence (0 = batch)
    window_id: int = 0
    #: Stream identity the window belongs to ("" = not streaming)
    stream: str = ""


@dataclasses.dataclass
class HeartbeatResponse:
    events: List[TezAPIEvent]
    should_die: bool = False


class _AttemptSession:
    __slots__ = ("edge_seqs", "killed", "last_heartbeat", "custom_events",
                 "custom_seq", "last_progress", "last_activity")

    def __init__(self) -> None:
        self.edge_seqs: Dict[str, int] = {}
        self.killed = False
        self.last_heartbeat = clock.wall_s()
        self.custom_events: List[TezAPIEvent] = []
        # progress-stuck detection (TaskHeartbeatHandler progress check):
        # an attempt that heartbeats but whose progress never moves and
        # which generates no events is hung, not alive
        self.last_progress = -1.0
        self.last_activity = clock.wall_s()


class TaskCommunicatorManager:
    """AM side of the umbilical."""

    def __init__(self, ctx: Any):
        self.ctx = ctx
        self._sessions: Dict[TaskAttemptId, _AttemptSession] = {}
        self._lock = threading.Lock()
        # epoch fencing: this comm serves exactly one AM incarnation; it
        # rejects messages stamped with an older epoch AND stops arbitrating
        # once a newer incarnation registers (zombie-AM self-fencing)
        self.epoch = int(getattr(ctx, "attempt", 0) or 0)
        from tez_tpu.common import config as C
        conf = getattr(ctx, "conf", None)
        self._fencing = bool(conf.get(C.AM_EPOCH_FENCING_ENABLED)) \
            if conf is not None else True
        #: fencing rejections served by this incarnation (status surface;
        #: counter_diff reads the journaled ATTEMPT_FENCED records instead)
        self.fenced_count = 0
        if self.epoch > 0:
            epoch_registry.register(getattr(ctx, "app_id", ""), self.epoch)

    def _fenced(self, msg_epoch: int, detail: str,
                window_id: int = 0, stream: str = "") -> bool:
        """True when the caller (or this AM itself) is from a stale epoch,
        or — streaming mode — from a *known-older window* of a live stream
        (the ``(attempt_epoch, window_id)`` fence generalization)."""
        if not self._fencing or self.epoch <= 0:
            return False
        app_id = getattr(self.ctx, "app_id", "")
        if epoch_registry.is_stale_window(app_id, stream, window_id):
            faults.fire("fence.stale_window", detail=detail)
            tracing.event("fence.stale_window", seam="umbilical",
                          reason="stale_window", window_id=window_id,
                          stream=stream,
                          current=epoch_registry.current_window(
                              app_id, stream),
                          detail=detail)
            log.warning("fenced stale-window message (%s window %d < %d): %s",
                        stream, window_id,
                        epoch_registry.current_window(app_id, stream), detail)
            self._record_fence("stale_window", msg_epoch, detail,
                               window_id=window_id, stream=stream)
            return True
        if 0 < msg_epoch < self.epoch:
            faults.fire("fence.stale_epoch", detail=detail)
            tracing.event("fence.stale_epoch", seam="umbilical",
                          reason="stale_sender", msg_epoch=msg_epoch,
                          am_epoch=self.epoch, detail=detail)
            log.warning("fenced stale-epoch message (epoch %d < %d): %s",
                        msg_epoch, self.epoch, detail)
            self._record_fence("stale_sender", msg_epoch, detail)
            return True
        if epoch_registry.is_stale(app_id, self.epoch):
            faults.fire("fence.stale_epoch", detail=detail)
            tracing.event("fence.stale_epoch", seam="umbilical",
                          reason="superseded_am", am_epoch=self.epoch,
                          current=epoch_registry.current(app_id),
                          detail=detail)
            log.warning("AM epoch %d superseded by %d; refusing: %s",
                        self.epoch, epoch_registry.current(app_id), detail)
            self._record_fence("superseded_am", msg_epoch, detail)
            return True
        return False

    def _record_fence(self, reason: str, msg_epoch: int, detail: str,
                      window_id: int = 0, stream: str = "") -> None:
        """Make every fencing rejection forensically visible: a flight MARK
        (acceptance surface for chaos --am-kill) plus an ATTEMPT_FENCED
        journal record (counter_diff's zombie-fenced tally).  Rare by
        construction — a zombie runner dies on its first fenced heartbeat —
        so a journal record per rejection is cheap."""
        self.fenced_count += 1
        from tez_tpu.obs import flight
        flight.record(flight.MARK, "fence.stale_epoch", detail,
                      a=msg_epoch, b=self.epoch)
        history = getattr(self.ctx, "history", None)
        if history is None:
            return
        try:
            from tez_tpu.am.history import HistoryEvent, HistoryEventType
            data = {"reason": reason, "msg_epoch": msg_epoch,
                    "am_epoch": self.epoch, "detail": detail}
            if stream:
                data["stream"] = stream
                data["window_id"] = window_id
            history(HistoryEvent(HistoryEventType.ATTEMPT_FENCED, data=data))
        except Exception:  # noqa: BLE001 — forensics never block fencing
            log.exception("ATTEMPT_FENCED journaling failed")

    # -- runner-facing API (called from runner threads) ----------------------
    def get_task(self, container_id: ContainerId, timeout: float = 1.0,
                 node_id: str = "") -> Optional[TaskSpec]:
        node = node_id or self.ctx.node_id
        tracker = getattr(self.ctx, "node_tracker", None)
        if tracker is not None:
            tracker.node_seen(node)
            if not tracker.is_usable(node):
                # blacklisted node: starve its runner so it exits and the
                # pool replaces it elsewhere (AMNodeImpl blacklisting)
                return None
        spec = self.ctx.task_scheduler.get_task(container_id, timeout)
        if spec is None:
            return None
        with self._lock:
            self._sessions[spec.attempt_id] = _AttemptSession()
        self.ctx.dispatch(TaskAttemptEvent(
            TaskAttemptEventType.TA_STARTED_REMOTELY, spec.attempt_id,
            container_id=container_id, node_id=node))
        return spec

    def heartbeat(self, request: HeartbeatRequest) -> HeartbeatResponse:
        # delay mode here starves the liveness monitor (the runner's
        # heartbeat thread stalls before the AM sees the beat); fail mode
        # surfaces as an umbilical fault on the runner side
        faults.fire("am.heartbeat", detail=str(request.attempt_id))
        if self._fenced(getattr(request, "epoch", 0),
                        f"heartbeat {request.attempt_id}",
                        window_id=getattr(request, "window_id", 0),
                        stream=getattr(request, "stream", "")):
            # a zombie runner must stop, not keep feeding a dead (or wrong)
            # incarnation's state machines
            return HeartbeatResponse(events=[], should_die=True)
        session = self._session(request.attempt_id)
        session.last_heartbeat = clock.wall_s()
        if request.events or request.progress != session.last_progress:
            session.last_progress = request.progress
            session.last_activity = session.last_heartbeat
        if request.events:
            self._route_events(request.attempt_id, request.events)
        if request.counters is not None or request.progress:
            self.ctx.dispatch(TaskAttemptEvent(
                TaskAttemptEventType.TA_STATUS_UPDATE, request.attempt_id,
                counters=request.counters, progress=request.progress))
        events = self._pull_events(request.attempt_id, session)
        return HeartbeatResponse(events=events, should_die=session.killed)

    def can_commit(self, attempt_id: TaskAttemptId, epoch: int = 0,
                   window_id: int = 0, stream: str = "") -> bool:
        # commit arbitration is the last line of exactly-once defense: a
        # zombie attempt (or this comm itself, once superseded) never wins,
        # and neither does a straggler from a sealed streaming window
        if self._fenced(epoch, f"can_commit {attempt_id}",
                        window_id=window_id, stream=stream):
            return False
        vertex = self._vertex_for(attempt_id)
        if vertex is None:
            return False
        task = vertex.tasks.get(attempt_id.task_id.id)
        if task is None:
            return False
        with self._lock:  # serialize commit arbitration
            return task.can_commit(attempt_id)

    def task_done(self, attempt_id: TaskAttemptId, events: List[TezEvent],
                  counters: Optional[TezCounters], epoch: int = 0,
                  window_id: int = 0, stream: str = "") -> None:
        if self._fenced(epoch, f"task_done {attempt_id}",
                        window_id=window_id, stream=stream):
            return
        if events:
            self._route_events(attempt_id, events)
        self.ctx.dispatch(TaskAttemptEvent(
            TaskAttemptEventType.TA_DONE, attempt_id, counters=counters))
        self._drop_session(attempt_id)

    def task_failed(self, attempt_id: TaskAttemptId, diagnostics: str,
                    fatal: bool = False,
                    counters: Optional[TezCounters] = None) -> None:
        if self._fenced(0, f"task_failed {attempt_id}"):
            return
        self.ctx.dispatch(TaskAttemptEvent(
            TaskAttemptEventType.TA_FAILED, attempt_id,
            diagnostics=diagnostics, fatal=fatal, counters=counters))
        self._drop_session(attempt_id)

    def task_killed(self, attempt_id: TaskAttemptId, diagnostics: str) -> None:
        if self._fenced(0, f"task_killed {attempt_id}"):
            return
        self.ctx.dispatch(TaskAttemptEvent(
            TaskAttemptEventType.TA_KILL_REQUEST, attempt_id,
            diagnostics=diagnostics))
        self._drop_session(attempt_id)

    def should_die(self, attempt_id: TaskAttemptId) -> bool:
        with self._lock:
            s = self._sessions.get(attempt_id)
        return s.killed if s is not None else True

    # -- AM-facing -----------------------------------------------------------
    def kill_attempt(self, attempt_id: TaskAttemptId) -> None:
        with self._lock:
            s = self._sessions.get(attempt_id)
            if s is not None:
                s.killed = True

    def deliver_custom_events(self, attempt_id: TaskAttemptId,
                              events: Sequence[TezAPIEvent]) -> None:
        with self._lock:
            s = self._sessions.get(attempt_id)
            if s is not None:
                s.custom_events.extend(events)

    def sessions_snapshot(self) -> Dict[TaskAttemptId, float]:
        """Excludes sessions already marked to die — the monitor must not
        re-fire on an attempt whose teardown is in flight."""
        with self._lock:
            return {a: s.last_heartbeat for a, s in self._sessions.items()
                    if not s.killed}

    def activity_snapshot(self) -> Dict[TaskAttemptId, float]:
        """attempt -> last time its progress moved or it produced events
        (the progress-stuck detector's input); killed sessions excluded."""
        with self._lock:
            return {a: s.last_activity for a, s in self._sessions.items()
                    if not s.killed}

    # -- internals -----------------------------------------------------------
    def _session(self, attempt_id: TaskAttemptId) -> _AttemptSession:
        with self._lock:
            s = self._sessions.get(attempt_id)
            if s is None:
                s = self._sessions[attempt_id] = _AttemptSession()
            return s

    def _drop_session(self, attempt_id: TaskAttemptId) -> None:
        # Session survives until the attempt's terminal event is processed;
        # dropping immediately is fine because routed events were flushed.
        with self._lock:
            self._sessions.pop(attempt_id, None)

    def _route_events(self, attempt_id: TaskAttemptId,
                      events: List[TezEvent]) -> None:
        vertex_id = attempt_id.vertex_id
        for tez_event in events:
            self.ctx.dispatch(VertexEvent(
                VertexEventType.V_ROUTE_EVENT, vertex_id,
                tez_event=tez_event))

    def _vertex_for(self, attempt_id: TaskAttemptId) -> Any:
        """Resolve through the live-DAG registry — the attempt's id chain
        names its DAG, so concurrent DAGs never cross wires here."""
        find = getattr(self.ctx, "find_dag", None)
        dag = find(attempt_id.vertex_id.dag_id) if find is not None \
            else getattr(self.ctx, "current_dag", None)
        return dag.vertex_by_id(attempt_id.vertex_id) \
            if dag is not None else None

    def _pull_events(self, attempt_id: TaskAttemptId,
                     session: _AttemptSession) -> List[TezAPIEvent]:
        vertex = self._vertex_for(attempt_id)
        if vertex is None:
            return []
        # bound one heartbeat response (tez.task.max-event-backlog): a
        # 10k-source fan-in must stream events across heartbeats, not ship
        # one giant response that stalls the umbilical
        from tez_tpu.common import config as C
        max_events = int(self.ctx.conf.get(C.TASK_MAX_EVENT_BACKLOG)) \
            if getattr(self.ctx, "conf", None) is not None else 0
        out = vertex.get_task_events(attempt_id.task_id.id,
                                     session.edge_seqs,
                                     max_events=max_events)
        with self._lock:
            if session.custom_events:
                out.extend(("__custom__", ev) for ev in session.custom_events)
                session.custom_events = []
        return out
