"""DAG state machine: vertex bookkeeping, commit orchestration.

Reference parity: tez-dag/.../dag/impl/DAGImpl.java:161 — states
NEW -> INITED -> RUNNING -> COMMITTING -> SUCCEEDED/FAILED/KILLED/ERROR,
all-or-nothing commit at DAG success (default), vertex rerun pulls a
SUCCEEDED DAG-in-waiting back to RUNNING.
"""
from __future__ import annotations

import enum
import logging
from typing import Any, Dict, List, Optional, Sequence, TYPE_CHECKING

from tez_tpu.common import clock
from tez_tpu.am.edge import EdgeImpl
from tez_tpu.am.events import (DAGEvent, DAGEventType, VertexEvent,
                               VertexEventType)
from tez_tpu.am.history import HistoryEvent, HistoryEventType
from tez_tpu.am.vertex_impl import (TERMINAL_VERTEX_STATES, VertexImpl,
                                    VertexState)
from tez_tpu.api.vertex_manager import VertexStateUpdate
from tez_tpu.common.counters import TezCounters
from tez_tpu.common.ids import DAGId, VertexId
from tez_tpu.common.statemachine import StateMachineFactory
from tez_tpu.dag.plan import DAGPlan

log = logging.getLogger(__name__)


class DAGState(enum.Enum):
    NEW = enum.auto()
    INITED = enum.auto()
    RUNNING = enum.auto()
    COMMITTING = enum.auto()
    SUCCEEDED = enum.auto()
    FAILED = enum.auto()
    KILLED = enum.auto()
    ERROR = enum.auto()


TERMINAL_DAG_STATES = frozenset(
    {DAGState.SUCCEEDED, DAGState.FAILED, DAGState.KILLED, DAGState.ERROR})


class DAGImpl:
    _factory: StateMachineFactory = None

    def __init__(self, dag_id: DAGId, plan: DAGPlan, ctx: Any,
                 recovery_data: Any = None):
        self.dag_id = dag_id
        self.plan = plan
        self.name = plan.name
        self.ctx = ctx
        # DAGRecoveryData from a prior AM attempt's journal, or None.
        # Vertices consult this to short-circuit journaled SUCCEEDED tasks.
        self.recovery_data = recovery_data
        self.conf = ctx.conf.merged(plan.dag_conf)
        self.vertices: Dict[str, VertexImpl] = {}
        self.vertices_by_id: Dict[VertexId, VertexImpl] = {}
        self.edges: Dict[str, EdgeImpl] = {}
        self.counters = TezCounters()
        self.diagnostics: List[str] = []
        self.start_time = 0.0
        self.finish_time = 0.0
        self.completed_vertices = 0
        self.succeeded_vertices = 0
        self.failed_vertices = 0
        self.killed_vertices = 0
        self._terminating = False
        self._committed = False
        self._state_update_registry: Dict[str, List[Any]] = {}
        self.sm = self._factory.make(self)

    @property
    def state(self) -> DAGState:
        return self.sm.state

    def handle(self, event: DAGEvent) -> None:
        if self.state in TERMINAL_DAG_STATES:
            return
        if not self.sm.can_handle(event.event_type):
            log.debug("dag %s: ignoring %s in %s", self.name,
                      event.event_type, self.state)
            return
        self.sm.handle(event)

    # -- lookups -------------------------------------------------------------
    def vertex_by_name(self, name: str) -> Optional[VertexImpl]:
        return self.vertices.get(name)

    def vertex_by_id(self, vid: VertexId) -> Optional[VertexImpl]:
        return self.vertices_by_id.get(vid)

    # -- construction (DAG_INIT) ---------------------------------------------
    def _on_init(self, event: DAGEvent) -> None:
        from tez_tpu.am.dag_scheduler import apply_dag_scheduler
        # Per-vertex commit mode cannot drive a vertex-group SHARED sink:
        # the first member to finish would commit an output its siblings are
        # still writing (the reference rejects this combination too).
        if not self.conf.get("tez.am.commit-all-outputs-on-dag-success", True):
            for g in self.plan.vertex_groups:
                if g.outputs:   # the plan records ACTUAL shared sinks
                    raise ValueError(
                        f"vertex group '{g.name}' shares output(s) "
                        f"{sorted(g.outputs)}: commit-on-vertex-success is "
                        "incompatible with group-shared sinks")
        for i, vplan in enumerate(self.plan.vertices):
            vid = self.dag_id.vertex(i)
            v = VertexImpl(vid, vplan, self)
            self.vertices[vplan.name] = v
            self.vertices_by_id[vid] = v
        for eplan in self.plan.edges:
            src = self.vertices[eplan.input_vertex]
            dst = self.vertices[eplan.output_vertex]
            edge = EdgeImpl(eplan.id, eplan.edge_property, src, dst)
            self.edges[eplan.id] = edge
            src.out_edges[dst.name] = edge
            dst.in_edges[src.name] = edge
        # group inputs
        from tez_tpu.runtime.task_spec import GroupInputSpec
        for gplan in self.plan.group_edges:
            v = self.vertices[gplan.output_vertex]
            v.group_input_specs.append(GroupInputSpec(
                gplan.group_name,
                tuple(self._group_members(gplan.group_name)),
                gplan.merged_input))
        apply_dag_scheduler(self)
        for edge in self.edges.values():
            edge.initialize()
        self.ctx.history(HistoryEvent(
            HistoryEventType.DAG_INITIALIZED, dag_id=str(self.dag_id),
            data={"dag_name": self.name,
                  "vertices": [v.name for v in self.vertices.values()]}))
        for v in self.vertices.values():
            self.ctx.dispatch(VertexEvent(VertexEventType.V_INIT, v.vertex_id))

    def _group_members(self, group_name: str) -> Sequence[str]:
        for g in self.plan.vertex_groups:
            if g.name == group_name:
                return g.members
        return ()

    def _on_start(self, event: DAGEvent) -> None:
        self.start_time = clock.wall_s()
        self.ctx.history(HistoryEvent(
            HistoryEventType.DAG_STARTED, dag_id=str(self.dag_id),
            data={"dag_name": self.name}))
        for v in self.vertices.values():
            self.ctx.dispatch(VertexEvent(VertexEventType.V_START, v.vertex_id))

    # -- vertex callbacks (invoked on dispatcher thread) ---------------------
    def on_vertex_inited(self, vertex: VertexImpl) -> None:
        self._notify_state_update(vertex.name, "CONFIGURED")

    def on_vertex_rerunning(self, vertex: VertexImpl) -> None:
        self.completed_vertices -= 1
        self.succeeded_vertices -= 1
        self.ctx.dispatch(DAGEvent(DAGEventType.DAG_VERTEX_RERUNNING,
                                   self.dag_id, vertex_name=vertex.name))

    def on_vertex_completed(self, vertex: VertexImpl,
                            final_state: VertexState) -> None:
        self.completed_vertices += 1
        if final_state is VertexState.SUCCEEDED:
            self.succeeded_vertices += 1
        elif final_state is VertexState.FAILED:
            self.failed_vertices += 1
        else:
            self.killed_vertices += 1
        self._notify_state_update(vertex.name, final_state.name)
        self.ctx.dispatch(DAGEvent(DAGEventType.DAG_VERTEX_COMPLETED,
                                   self.dag_id, vertex_name=vertex.name,
                                   final_state=final_state))

    def _on_vertex_completed(self, event: DAGEvent) -> DAGState:
        final_state: VertexState = event.final_state
        if final_state is VertexState.FAILED and not self._terminating:
            self.diagnostics.append(
                f"vertex {event.vertex_name} failed")
            self._terminate_vertices("DAG failing: vertex failed")
        if self.completed_vertices == len(self.vertices):
            return self._finish()
        return DAGState.RUNNING

    def _on_vertex_rerunning(self, event: DAGEvent) -> DAGState:
        return DAGState.RUNNING

    def _finish(self) -> DAGState:
        if self.succeeded_vertices == len(self.vertices):
            return self._start_commit()
        self.finish_time = clock.wall_s()
        final = DAGState.FAILED if self.failed_vertices else DAGState.KILLED
        self._finish_history(final)
        return final

    # -- commit (reference: DAGImpl commit orchestration) --------------------
    def _start_commit(self) -> DAGState:
        committers = self._collect_committers()
        if not committers:
            self.finish_time = clock.wall_s()
            self._finish_history(DAGState.SUCCEEDED)
            return DAGState.SUCCEEDED
        # ledger record 1/2: COMMIT_STARTED is fsync'd (summary event,
        # synchronous ctx.history) BEFORE any committer mutates the
        # filesystem — the write-ahead half of the two-phase commit
        self.ctx.history(HistoryEvent(
            HistoryEventType.DAG_COMMIT_STARTED, dag_id=str(self.dag_id),
            data={"dag_name": self.name}))

        def _commit() -> None:
            from tez_tpu.common import epoch as epoch_registry
            from tez_tpu.common import faults
            from tez_tpu.common.epoch import EpochFencedError
            app_id = getattr(self.ctx, "app_id", "")
            my_epoch = int(getattr(self.ctx, "attempt", 0) or 0)
            try:
                for name, committer in committers:
                    # a zombie commit thread (its AM superseded while this
                    # ran, or while a delay fault held it) must stop before
                    # each publish, not after the damage
                    if my_epoch > 0 and \
                            epoch_registry.is_stale(app_id, my_epoch):
                        faults.fire("fence.stale_epoch",
                                    detail=f"dag_commit {name}")
                        raise EpochFencedError(
                            f"AM epoch {my_epoch} superseded by "
                            f"{epoch_registry.current(app_id)} mid-commit")
                    committer.commit_output()
                self.ctx.dispatch(DAGEvent(DAGEventType.DAG_COMMIT_COMPLETED,
                                           self.dag_id, succeeded=True))
            except EpochFencedError as e:
                log.warning("dag %s: commit fenced: %s", self.name, e)
                self.ctx.dispatch(DAGEvent(DAGEventType.DAG_COMMIT_COMPLETED,
                                           self.dag_id, succeeded=False,
                                           fenced=True, diagnostics=repr(e)))
            except BaseException as e:  # noqa: BLE001
                log.exception("dag %s: commit failed", self.name)
                self.ctx.dispatch(DAGEvent(DAGEventType.DAG_COMMIT_COMPLETED,
                                           self.dag_id, succeeded=False,
                                           diagnostics=repr(e)))

        self.ctx.submit_to_executor(_commit)
        return DAGState.COMMITTING

    def _collect_committers(self) -> List[Any]:
        if not self.conf.get("tez.am.commit-all-outputs-on-dag-success", True):
            return []   # per-vertex mode: each vertex committed on success
        out = []
        for v in self.vertices.values():
            for name, committer in getattr(v, "committers", {}).items():
                out.append((f"{v.name}:{name}", committer))
        return out

    def _on_commit_completed(self, event: DAGEvent) -> DAGState:
        self.finish_time = clock.wall_s()
        if getattr(event, "fenced", False):
            # A superseded incarnation owns nothing anymore: it must not
            # journal to the ledger (the live AM writes it), must not abort
            # committers (the live AM may be publishing right now), and must
            # not tear down process-global services the live AM is using.
            self.diagnostics.append(
                f"commit fenced: {getattr(event, 'diagnostics', '')}")
            self.ctx.on_dag_finished(self, DAGState.FAILED, fenced=True)
            return DAGState.FAILED
        if self._kill_requested:
            self._ledger_abort("kill requested during commit")
            self._abort_committers()
            self._finish_history(DAGState.KILLED)
            return DAGState.KILLED
        if event.succeeded:
            # ledger record 2/2: committers are done and durable — fsync'd
            # before the DAG's terminal record so a crash after this point
            # rolls FORWARD to SUCCEEDED, never re-runs or aborts
            self.ctx.history(HistoryEvent(
                HistoryEventType.DAG_COMMIT_FINISHED,
                dag_id=str(self.dag_id), data={"dag_name": self.name}))
            self._finish_history(DAGState.SUCCEEDED)
            return DAGState.SUCCEEDED
        self.diagnostics.append(
            f"commit failed: {getattr(event, 'diagnostics', '')}")
        self._ledger_abort(getattr(event, "diagnostics", ""))
        self._abort_committers()
        self._finish_history(DAGState.FAILED)
        return DAGState.FAILED

    def _ledger_abort(self, reason: str) -> None:
        """COMMIT_ABORTED is written (and fsync'd) BEFORE the rollback runs:
        once durable, recovery never rolls this commit forward — it re-runs
        the idempotent aborts instead."""
        self.ctx.history(HistoryEvent(
            HistoryEventType.DAG_COMMIT_ABORTED, dag_id=str(self.dag_id),
            data={"dag_name": self.name, "reason": reason}))

    def _abort_committers(self) -> None:
        for name, committer in self._collect_committers():
            try:
                committer.abort_output("FAILED")
            except BaseException:  # noqa: BLE001
                log.exception("abort of %s failed", name)

    # -- kill ----------------------------------------------------------------
    def _on_kill(self, event: DAGEvent) -> DAGState:
        self.diagnostics.append(getattr(event, "diagnostics", "DAG killed"))
        self.ctx.history(HistoryEvent(
            HistoryEventType.DAG_KILL_REQUEST, dag_id=str(self.dag_id)))
        if self.state is DAGState.COMMITTING:
            # Let the in-flight commit thread finish; _on_commit_completed
            # aborts and reports KILLED (all-or-nothing commit contract).
            self._kill_requested = True
            return DAGState.COMMITTING
        if not self._any_live_vertices():
            self.finish_time = clock.wall_s()
            self._finish_history(DAGState.KILLED)
            return DAGState.KILLED
        self._terminate_vertices("DAG kill requested")
        return DAGState.RUNNING

    _kill_requested = False

    def _on_internal_error(self, event: DAGEvent) -> DAGState:
        self.diagnostics.append(
            f"internal error: {getattr(event, 'diagnostics', '')}")
        self._terminate_vertices("internal error")
        self.finish_time = clock.wall_s()
        self._finish_history(DAGState.ERROR)
        return DAGState.ERROR

    def _terminate_vertices(self, reason: str) -> None:
        self._terminating = True
        for v in self.vertices.values():
            if v.state not in TERMINAL_VERTEX_STATES:
                self.ctx.dispatch(VertexEvent(
                    VertexEventType.V_TERMINATE, v.vertex_id,
                    diagnostics=reason))

    def _any_live_vertices(self) -> bool:
        return any(v.state not in TERMINAL_VERTEX_STATES
                   for v in self.vertices.values())

    def _finish_history(self, final: DAGState) -> None:
        self.counters = TezCounters()
        for v in self.vertices.values():
            self.counters.aggregate(v.counters)
        self.ctx.history(HistoryEvent(
            HistoryEventType.DAG_FINISHED, dag_id=str(self.dag_id),
            data={"dag_name": self.name, "state": final.name,
                  "time_taken": self.finish_time - (self.start_time or
                                                    self.finish_time),
                  "diagnostics": "; ".join(self.diagnostics),
                  "counters": self.counters.to_dict()}))
        self.ctx.on_dag_finished(self, final)

    # -- misc hooks used by vertices/managers --------------------------------
    def notify_new_edge_events(self, edge: EdgeImpl) -> None:
        """New producer events available; wake any waiting consumers (local
        mode: consumers poll via heartbeat, so this is a no-op hook point)."""

    def send_custom_events_to_tasks(self, vertex: VertexImpl,
                                    events: Sequence[Any],
                                    task_indices: Sequence[int]) -> None:
        self.ctx.deliver_processor_events(vertex, events, task_indices)

    def register_state_updates(self, vertex_name: str, listener: Any,
                               states: Sequence[str]) -> None:
        self._state_update_registry.setdefault(vertex_name, []).append(listener)
        # deliver the latest state immediately if already reached (reference
        # semantics: register delivers the current state)
        v = self.vertex_by_name(vertex_name)
        if v is None or listener is None:
            return
        if v.state is VertexState.INITED:
            self._deliver_state_update(listener, vertex_name, "CONFIGURED")
        elif v.state is VertexState.RUNNING:
            self._deliver_state_update(listener, vertex_name, "CONFIGURED")
            self._deliver_state_update(listener, vertex_name, "RUNNING")
        elif v.state in TERMINAL_VERTEX_STATES:
            self._deliver_state_update(listener, vertex_name, v.state.name)

    def _notify_state_update(self, vertex_name: str, state: str) -> None:
        for listener in self._state_update_registry.get(vertex_name, []):
            self._deliver_state_update(listener, vertex_name, state)

    @staticmethod
    def _deliver_state_update(listener: Any, vertex_name: str,
                              state: str) -> None:
        try:
            listener.on_vertex_state_updated(
                VertexStateUpdate(vertex_name, state))
        except BaseException:  # noqa: BLE001
            log.exception("state update listener failed")

    # -- status --------------------------------------------------------------
    def status_dict(self) -> Dict[str, Any]:
        total = sum(len(v.tasks) for v in self.vertices.values())
        succeeded = sum(v.succeeded_tasks for v in self.vertices.values())
        return {
            "name": self.name, "state": self.state.name,
            "progress": (succeeded / total) if total else 0.0,
            "diagnostics": list(self.diagnostics),
            "vertices": {v.name: v.status_dict()
                         for v in self.vertices.values()},
        }


def _build_dag_factory() -> StateMachineFactory:
    S, E = DAGState, DAGEventType
    f = StateMachineFactory(S.NEW)
    f.add(S.NEW, S.INITED, E.DAG_INIT, DAGImpl._on_init)
    f.add(S.INITED, S.RUNNING, E.DAG_START, DAGImpl._on_start)
    f.add_multi(S.INITED, (S.RUNNING, S.KILLED), E.DAG_KILL, DAGImpl._on_kill)
    # init/start-time failures (e.g. an invalid plan rejected in _on_init)
    # must terminate the DAG, not strand it in NEW forever
    f.add_multi(S.NEW, (S.ERROR,), E.INTERNAL_ERROR,
                DAGImpl._on_internal_error)
    f.add_multi(S.INITED, (S.ERROR,), E.INTERNAL_ERROR,
                DAGImpl._on_internal_error)
    f.add_multi(S.RUNNING,
                (S.RUNNING, S.COMMITTING, S.SUCCEEDED, S.FAILED, S.KILLED),
                E.DAG_VERTEX_COMPLETED, DAGImpl._on_vertex_completed)
    f.add_multi(S.RUNNING, (S.RUNNING,), E.DAG_VERTEX_RERUNNING,
                DAGImpl._on_vertex_rerunning)
    f.add_multi(S.RUNNING, (S.RUNNING, S.KILLED), E.DAG_KILL, DAGImpl._on_kill)
    f.add_multi(S.RUNNING, (S.ERROR,), E.INTERNAL_ERROR,
                DAGImpl._on_internal_error)
    f.add_multi(S.COMMITTING, (S.SUCCEEDED, S.FAILED, S.KILLED),
                E.DAG_COMMIT_COMPLETED, DAGImpl._on_commit_completed)
    f.add_multi(S.COMMITTING, (S.COMMITTING, S.RUNNING, S.KILLED), E.DAG_KILL,
                DAGImpl._on_kill)
    f.add_multi(S.COMMITTING,
                (S.RUNNING, S.COMMITTING, S.SUCCEEDED, S.FAILED, S.KILLED),
                E.DAG_VERTEX_COMPLETED, DAGImpl._on_vertex_completed)
    f.add_multi(S.COMMITTING, (S.ERROR,), E.INTERNAL_ERROR,
                DAGImpl._on_internal_error)
    return f


DAGImpl._factory = _build_dag_factory()
