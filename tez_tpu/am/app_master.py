"""DAGAppMaster: composite service wiring every orchestrator subsystem.

Reference parity: tez-dag/.../app/DAGAppMaster.java:226 (serviceInit:423
registers dispatchers/scheduler/launcher/history; session mode runs multiple
DAGs; shutdown/error funnel) + LocalDAGAppMaster.  Here the AM always runs
in-process ("local mode"); a multi-host deployment wraps this object with
gRPC endpoints for the client and umbilical protocols.
"""
from __future__ import annotations

import logging
import os
import threading
import concurrent.futures
from typing import Any, Dict, List, Optional, Sequence

from tez_tpu.am.dag_impl import DAGImpl, DAGState, TERMINAL_DAG_STATES
from tez_tpu.am.events import (DAGEvent, DAGEventType, SchedulerEvent,
                               SchedulerEventType, TaskAttemptEvent,
                               TaskAttemptEventType, TaskEvent, TaskEventType,
                               VertexEvent, VertexEventType)
from tez_tpu.am.history import (HistoryEvent, HistoryEventHandler,
                                HistoryEventType)
from tez_tpu.am.launcher import RunnerPool
from tez_tpu.am.task_comm import TaskCommunicatorManager
from tez_tpu.am.task_scheduler import (TaskSchedulerManager,
                                       create_task_scheduler)
from tez_tpu.common import clock, config as C
from tez_tpu.common.counters import TezCounters
from tez_tpu.common.dispatcher import Dispatcher
from tez_tpu.common.ids import DAGId, TaskAttemptId
from tez_tpu.dag.plan import DAGPlan

log = logging.getLogger(__name__)


class DAGAppMaster:
    """The single-controller orchestrator."""

    def __init__(self, app_id: str, conf: C.TezConfiguration,
                 attempt: int = 1):
        self.app_id = app_id
        self.attempt = attempt
        self.conf = conf
        max_attempts = int(conf.get(C.AM_MAX_APP_ATTEMPTS) or 0)
        if max_attempts > 0 and attempt > max_attempts:
            # the RM-side restart budget (reference: tez.am.max.app.attempts
            # via YARN's ApplicationSubmissionContext): a supervisor looping
            # AM restarts must stop re-running a persistently-crashing app
            raise RuntimeError(
                f"AM attempt {attempt} exceeds tez.am.max.app.attempts="
                f"{max_attempts}; refusing to restart {app_id}")
        self.node_id = "local-0"
        self.work_dir = os.path.join(
            conf.get(C.STAGING_DIR), app_id, "work")
        os.makedirs(self.work_dir, exist_ok=True)
        shards = int(conf.get(C.AM_CONCURRENT_DISPATCHER_SHARDS) or 0)
        if shards > 1:
            from tez_tpu.common.dispatcher import ShardedDispatcher
            self.dispatcher = ShardedDispatcher(f"am-{app_id}",
                                                num_shards=shards)
        else:
            self.dispatcher = Dispatcher(f"am-{app_id}")
        self.dag_counters = TezCounters()
        from tez_tpu.common.counters import Limits
        Limits.configure(conf)
        num_slots = conf.get(C.AM_NUM_CONTAINERS) or max(2, os.cpu_count() or 2)
        self.task_scheduler = create_task_scheduler(self, num_slots)
        self.scheduler_manager = TaskSchedulerManager(self, self.task_scheduler)
        self.task_comm = TaskCommunicatorManager(self)
        from tez_tpu.common.security import JobTokenSecretManager
        token_hex = conf.get("tez.job.token", "")
        self.secrets = JobTokenSecretManager(
            bytes.fromhex(token_hex) if token_hex else None)
        self.umbilical_server = None
        runner_mode = conf.get(C.RUNNER_MODE)
        if runner_mode in ("subprocess", "pods"):
            from tez_tpu.am.umbilical_server import UmbilicalServer
            from tez_tpu.common.tls import server_context
            self.umbilical_server = UmbilicalServer(
                self.task_comm, self.secrets,
                host=conf.get(C.UMBILICAL_BIND_HOST),
                ssl_context=server_context(conf))
            if runner_mode == "subprocess":
                from tez_tpu.am.launcher import SubprocessRunnerPool
                self.runner_pool = SubprocessRunnerPool(self, num_slots)
            else:
                # external cluster binding: the AM acquires runner pods
                # from a cluster driver (YarnTaskSchedulerService/NMClient
                # analog — am/cluster_binding.py)
                from tez_tpu.am.cluster_binding import create_pod_pool
                self.runner_pool = create_pod_pool(self, num_slots)
        else:
            self.runner_pool = RunnerPool(self, num_slots)
        logging_service = HistoryEventHandler.create_logging_service(
            conf, app_id=app_id)
        from tez_tpu.am.recovery import RecoveryService
        recovery_enabled = conf.get(C.DAG_RECOVERY_ENABLED)
        self.recovery_service = RecoveryService(self, attempt) \
            if recovery_enabled else None
        from tez_tpu.am.node_map import AMNodeTracker
        self.node_tracker = AMNodeTracker(conf)
        self.node_tracker.on_transition = self._on_node_transition
        from tez_tpu.am.heartbeat import HeartbeatMonitor
        self.heartbeat_monitor = HeartbeatMonitor(self)
        from tez_tpu.runtime.diagnostics import ThreadDumpHelper
        self.thread_dumper = ThreadDumpHelper(
            int(conf.get(C.THREAD_DUMP_INTERVAL_MS) or 0),
            label=f"am-{app_id}")
        self.web_ui = None
        if conf.get(C.AM_WEB_ENABLED):
            from tez_tpu.am.web import WebUIService
            self.web_ui = WebUIService(self, port=conf.get(C.AM_WEB_PORT))
        self.history_handler = HistoryEventHandler(
            logging_service, self.recovery_service, conf=conf)
        self.logging_service = logging_service
        self.executor = concurrent.futures.ThreadPoolExecutor(
            max_workers=8, thread_name_prefix=f"am-exec-{app_id}")
        #: live (non-terminal) DAGs keyed by str(dag_id), in submit order —
        #: the session runs tez.am.session.max-concurrent-dags of them at
        #: once; events route here by the dag_id their id chains carry
        self.live_dags: Dict[str, DAGImpl] = {}
        #: recently finished DAGImpls, bounded — kept so dag_status /
        #: counters stay queryable after completion (events never route
        #: here: a terminal DAG's trailing events are dropped instead)
        self.retired_dags: Dict[str, DAGImpl] = {}
        self.completed_dags: Dict[str, DAGState] = {}
        self.completed_dag_names: Dict[str, str] = {}
        #: dag name -> latest dag_id that ran under it (client re-attach
        #: resolves recovered DAGs by name — dag ids are AM-assigned and a
        #: successor incarnation reassigns them deterministically, but the
        #: NAME is the client-stable handle; docs/recovery.md)
        self.dag_ids_by_name: Dict[str, str] = {}
        self._dag_seq = 0
        self._dag_done = threading.Condition()
        from tez_tpu.obs import slo as _slo
        #: None unless some tez.am.slo.* target is declared; the admission
        #: controller ticks it on every completion/shed/queue-promotion
        self.slo_watchdog = _slo.from_conf(conf, journal=self.history)
        from tez_tpu.am.admission import AdmissionController
        self.admission = AdmissionController(self)
        from tez_tpu.am.telemetry import TelemetrySampler
        #: the live telemetry plane: periodic ring sampler + burn-rate SLO
        #: evaluation + the GET /doctor/live surface (docs/telemetry.md)
        self.telemetry = TelemetrySampler(self)
        #: resident stream drivers keyed by stream name (streaming mode,
        #: docs/streaming.md); populated by open_stream and by recovery
        self.streams: Dict[str, Any] = {}
        self._register_handlers()
        self._started = False

    @property
    def current_dag(self) -> Optional[DAGImpl]:
        """Most recently started live DAG (single-DAG compat surface; the
        web UI and tests predating multi-tenancy read it)."""
        # lock-free: callers include dispatcher + web threads; a racing
        # registry mutation just means retrying the snapshot.  Falls back
        # to the most recently retired DAG — the historical slot kept the
        # finished DAG visible, and status/counters readers rely on that.
        while True:
            try:
                vals = list(self.live_dags.values())
                if not vals:
                    vals = list(self.retired_dags.values())
                return vals[-1] if vals else None
            except RuntimeError:      # dict mutated during iteration
                continue

    def find_dag(self, dag_id: Any,
                 include_retired: bool = False) -> Optional[DAGImpl]:
        dag = self.live_dags.get(str(dag_id))
        if dag is None and include_retired:
            dag = self.retired_dags.get(str(dag_id))
        return dag

    def find_dag_id_by_name(self, name: str) -> Optional[str]:
        """Latest dag_id that ran (or finished) under `name`; falls back to
        the completed-name registry so recovery roll-forwards — which never
        re-instantiate a DAGImpl — are still re-attachable."""
        dag_id = self.dag_ids_by_name.get(name)
        if dag_id is not None:
            return dag_id
        for did, dag_name in self.completed_dag_names.items():
            if dag_name == name:
                dag_id = did    # latest wins (insertion order)
        return dag_id

    def queued_dag_names(self) -> List[str]:
        """Names currently parked in the admission queue (re-attach probes
        these before declaring a DAG lost)."""
        return self.admission.queued_names()

    def _retire_dag_locked(self, dag: DAGImpl) -> None:
        self.live_dags.pop(str(dag.dag_id), None)
        self.retired_dags[str(dag.dag_id)] = dag
        while len(self.retired_dags) > 16:
            self.retired_dags.pop(next(iter(self.retired_dags)))

    # -- service lifecycle ---------------------------------------------------
    def start(self) -> None:
        if self._started:
            return
        self.logging_service.start()
        if self.recovery_service is not None:
            self.recovery_service.start()
        self.dispatcher.on_error = self._on_dispatcher_error
        self.dispatcher.start()
        self.heartbeat_monitor.start()
        self.thread_dumper.start()
        if self.umbilical_server is not None:
            self.umbilical_server.start()
        if self.web_ui is not None:
            self.web_ui.start()
        self.telemetry.start()
        self._started = True
        self.history(HistoryEvent(HistoryEventType.AM_STARTED,
                                  data={"app_id": self.app_id,
                                        "attempt": self.attempt}))

    def stop(self) -> None:
        # first: the TELEMETRY_SNAPSHOT summary event needs the history
        # plane still up, and the final accounting should see the session
        # as the scrapers last did
        self.telemetry.stop()
        if self.web_ui is not None:
            self.web_ui.stop()
        self.thread_dumper.stop()
        self.heartbeat_monitor.stop()
        for driver in list(self.streams.values()):
            driver.crash()   # un-drained streams resume on the successor
        self.admission.stop()
        for dag in list(self.live_dags.values()):
            speculator = getattr(dag, "speculator", None)
            if speculator is not None:
                speculator.stop()
        self.task_scheduler.shutdown()
        self.runner_pool.shutdown()
        if self.umbilical_server is not None:
            self.umbilical_server.stop()
        self.dispatcher.stop()
        self.executor.shutdown(wait=False)
        if self.recovery_service is not None:
            self.recovery_service.stop()
        self.logging_service.stop()
        self._started = False

    def crash(self) -> None:
        """SIGKILL analog (tests/chaos): die WITHOUT the graceful niceties.

        Unlike stop(), nothing terminal is journaled, queued submissions are
        abandoned with AMCrashedError instead of resolved, and no deletion
        tracking runs — live DAGs' journals stay exactly as the crash left
        them, which is what recover_and_resume on the successor incarnation
        (attempt+1) is built to consume.  The `am.crash` fault point fires
        first so chaos specs can widen the kill window deterministically."""
        from tez_tpu.common import faults
        try:
            faults.fire("am.crash", detail=f"attempt={self.attempt}")
        except BaseException:  # noqa: BLE001 — a fail rule still crashes us
            pass
        self.telemetry.crash()   # no TELEMETRY_SNAPSHOT: SIGKILL analog
        if self.web_ui is not None:
            self.web_ui.stop()
        self.thread_dumper.stop()
        self.heartbeat_monitor.stop()
        # abandon — not resolve — the admission queue: parked submitters
        # get AMCrashedError and must re-attach; their DAG_QUEUED records
        # stay unresolved in the journal, which is the replay contract
        for driver in list(self.streams.values()):
            driver.crash()   # window loop dies mid-bracket; ledger decides
        self.admission.crash()
        for dag in list(self.live_dags.values()):
            speculator = getattr(dag, "speculator", None)
            if speculator is not None:   # thread hygiene, not graceful state
                speculator.stop()
        self.task_scheduler.shutdown()
        self.runner_pool.shutdown()
        if self.umbilical_server is not None:
            self.umbilical_server.stop()
        self.dispatcher.stop()
        self.executor.shutdown(wait=False)
        # the in-process close flushes buffered journal lines — a superset
        # of what a real SIGKILL leaves; recovery only ever depends on the
        # fsync'd summary prefix, so the extra tail is harmless
        if self.recovery_service is not None:
            self.recovery_service.stop()
        self.logging_service.stop()
        self._started = False
        log.warning("AM %s attempt %d: CRASHED (simulated SIGKILL)",
                    self.app_id, self.attempt)

    def _register_handlers(self) -> None:
        from tez_tpu.am.events import (DAGEventType, LauncherEventType,
                                       SchedulerEventType, SpeculatorEventType,
                                       TaskAttemptEventType, TaskEventType,
                                       VertexEventType)
        d = self.dispatcher
        d.register(DAGEventType, self._handle_dag_event)
        d.register(VertexEventType, self._handle_vertex_event)
        d.register(TaskEventType, self._handle_task_event)
        d.register(TaskAttemptEventType, self._handle_attempt_event)
        d.register(SchedulerEventType, self.scheduler_manager.handle)

    # -- event handlers (dispatcher thread): every event's id chain names
    # its DAG, so concurrent DAGs route without any ambient "current" slot
    def _handle_dag_event(self, event: DAGEvent) -> None:
        dag = self.find_dag(event.dag_id)
        if dag is not None:
            dag.handle(event)

    def _handle_vertex_event(self, event: VertexEvent) -> None:
        dag = self.find_dag(event.vertex_id.dag_id)
        if dag is None:
            return
        v = dag.vertex_by_id(event.vertex_id)
        if v is not None:
            v.handle(event)

    def _handle_task_event(self, event: TaskEvent) -> None:
        dag = self.find_dag(event.task_id.dag_id)
        if dag is None:
            return
        v = dag.vertex_by_id(event.task_id.vertex_id)
        if v is None:
            return
        t = v.tasks.get(event.task_id.id)
        if t is not None:
            t.handle(event)

    def _handle_attempt_event(self, event: TaskAttemptEvent) -> None:
        dag = self.find_dag(event.attempt_id.dag_id)
        if dag is None:
            return
        v = dag.vertex_by_id(event.attempt_id.vertex_id)
        if v is None:
            return
        t = v.tasks.get(event.attempt_id.task_id.id)
        att = t.attempt(event.attempt_id) if t is not None else None
        if att is not None:
            att.handle(event)

    def _on_dispatcher_error(self, exc: BaseException, event: Any) -> None:
        """AM error funnel (reference: DAGAppMaster error handling —
        unhandled dispatcher error fails the DAG(s), not the process)."""
        for dag in list(self.live_dags.values()):
            if dag.state not in TERMINAL_DAG_STATES:
                self.dispatch(DAGEvent(DAGEventType.INTERNAL_ERROR,
                                       dag.dag_id, diagnostics=repr(exc)))

    # -- AMContext surface used by components --------------------------------
    def dispatch(self, event: Any) -> None:
        self.dispatcher.dispatch(event)

    def history(self, event: HistoryEvent) -> None:
        self.history_handler.handle(event)

    def _on_node_transition(self, node_id: str, state: Any,
                            failures: int) -> None:
        """AMNodeTracker observer: make blacklist flaps attributable in the
        history stream (chaos-run forensics + NodeHealthAnalyzer input)."""
        from tez_tpu.am.node_map import NodeState
        kind = {
            NodeState.BLACKLISTED: HistoryEventType.NODE_BLACKLISTED,
            NodeState.FORCED_ACTIVE: HistoryEventType.NODE_FORCED_ACTIVE,
        }.get(state)
        if kind is None:
            return   # ACTIVE reverts carry no dedicated event (yet)
        dag = self.current_dag
        self.history(HistoryEvent(
            kind, dag_id=str(dag.dag_id) if dag is not None else None,
            data={"node_id": node_id, "failures": failures}))

    def history_vertex_configured(self, vertex: Any) -> None:
        data = {"vertex_name": vertex.name, "num_tasks": vertex.num_tasks}
        reconfig = getattr(vertex, "_reconfig_journal", None)
        if reconfig is not None:
            # enough to REPLAY the manager's decision on AM restart
            data["reconfig"] = reconfig
        self.history(HistoryEvent(
            HistoryEventType.VERTEX_CONFIGURE_DONE,
            dag_id=str(vertex.vertex_id.dag_id),
            vertex_id=str(vertex.vertex_id),
            data=data))

    def submit_to_executor(self, fn: Any) -> None:
        self.executor.submit(fn)

    def total_slots(self) -> int:
        return self.task_scheduler.total_slots()

    def prewarm(self) -> None:
        """Spin runners up before the first DAG (reference: TezClient
        preWarm:897 submitting a pre-warm DAG; the runner-pool model just
        needs the pool filled)."""
        self.ensure_runners(self.total_slots())

    def ensure_runners(self, backlog: int) -> None:
        self.runner_pool.ensure_runners(backlog)

    def kill_attempt_in_runner(self, attempt_id: TaskAttemptId) -> None:
        self.task_comm.kill_attempt(attempt_id)

    def deliver_processor_events(self, vertex: Any, events: Sequence[Any],
                                 task_indices: Sequence[int]) -> None:
        for idx in task_indices:
            task = vertex.tasks.get(idx)
            if task is None:
                continue
            for att in task.attempts.values():
                self.task_comm.deliver_custom_events(
                    att.attempt_id, list(events))

    def on_dag_finished(self, dag: DAGImpl, final: DAGState,
                        fenced: bool = False) -> None:
        if fenced:
            # this incarnation was superseded mid-flight: the dag_id (and its
            # shuffle data, mesh edges, fault rules) now belongs to the LIVE
            # AM — tearing any of it down here would sabotage the successor.
            # Only release local waiters.
            log.warning("dag %s: finished FENCED (%s); skipping "
                        "process-global cleanup", dag.dag_id, final.name)
            with self._dag_done:
                self._retire_dag_locked(dag)
                self.completed_dags[str(dag.dag_id)] = final
                self.completed_dag_names[str(dag.dag_id)] = dag.name
                self._dag_done.notify_all()
            self._notify_admission(dag, final)
            return
        # deletion tracking: drop the finished DAG's shuffle data
        # (reference: ContainerLauncherManager DeletionTracker).  A store-
        # backed session seals lineage-tagged outputs FIRST so identical
        # recurring DAGs reuse them after this DAG's keys are released.
        from tez_tpu.shuffle.service import local_shuffle_service
        store = local_shuffle_service().buffer_store()
        if store is not None and final is DAGState.SUCCEEDED:
            sealed = store.seal_lineage(str(dag.dag_id))
            if sealed:
                log.info("dag %s: sealed %d outputs for lineage reuse",
                         dag.dag_id, sealed)
        n = local_shuffle_service().unregister_prefix(str(dag.dag_id))
        if n:
            log.info("dag %s: released %d shuffle outputs", dag.dag_id, n)
        from tez_tpu.parallel.coordinator import mesh_coordinator
        m = mesh_coordinator().cleanup_dag(str(dag.dag_id))
        if m:
            log.info("dag %s: released %d mesh exchange edges", dag.dag_id, m)
        speculator = getattr(dag, "speculator", None)
        if speculator is not None:
            speculator.stop()
        from tez_tpu.common import faults
        faults.clear(str(dag.dag_id))
        from tez_tpu.common import lockorder
        lockorder.disarm(str(dag.dag_id))
        from tez_tpu.common import tracing
        sp = getattr(dag, "trace_span", None)
        if sp is not None:
            sp.annotate(final_state=final.name)
            sp.finish()
        tracing.clear(str(dag.dag_id))
        from tez_tpu.obs import flight
        if flight.armed():
            flight.record(flight.MARK, f"dag.finished:{final.name}",
                          str(dag.dag_id))
            if final is not DAGState.SUCCEEDED:
                flight.auto_dump(f"dag.{final.name.lower()}",
                                 scope=str(dag.dag_id))
        flight.clear(str(dag.dag_id))
        with self._dag_done:
            self._retire_dag_locked(dag)
            self.completed_dags[str(dag.dag_id)] = final
            self.completed_dag_names[str(dag.dag_id)] = dag.name
            self._dag_done.notify_all()
        self._notify_admission(dag, final)

    def _notify_admission(self, dag: DAGImpl, final: DAGState) -> None:
        """Release the DAG's admission slot (promotes the queue head) and
        record its per-tenant completion latency.  Outside _dag_done — the
        admission lock never nests inside it."""
        elapsed_s = (clock.mono_s()
                     - getattr(dag, "submit_monotonic", clock.mono_s()))
        self.admission.on_dag_finished(
            getattr(dag, "tenant", ""), final.name, elapsed_s * 1000.0)

    # -- DAG submission (client-facing) --------------------------------------
    def submit_dag(self, plan: DAGPlan, recovery_data: Any = None) -> DAGId:
        """Admission-controlled submit: ACCEPT starts the DAG now, QUEUE
        blocks until the FIFO consumer promotes it, SHED raises a typed
        DAGRejectedError carrying the RETRY-AFTER hint."""
        assert self._started, "AM not started"
        return self.admission.submit(plan, recovery_data)

    def _start_dag(self, plan: DAGPlan, recovery_data: Any,
                   tenant: str, sub_id: str = "") -> DAGId:
        """Instantiate + start an admitted DAG (AdmissionController only)."""
        with self._dag_done:
            self._dag_seq += 1
            dag_id = DAGId(self.app_id, self._dag_seq)
        plan_hex = plan.serialize().hex()
        # per-DAG logging switch must be known before the first dag event
        self.history_handler.set_dag_conf(dag_id, plan.dag_conf)
        submit_data = {"dag_name": plan.name, "tenant": tenant,
                       "plan": plan_hex}
        if sub_id:
            # resolves the DAG_QUEUED / DAG_REQUEUED_ON_RECOVERY admission
            # record: the journal now proves this submission was promoted,
            # so a successor AM must NOT requeue it (and journal_fsck can
            # pair the records like commit-ledger brackets)
            submit_data["sub_id"] = sub_id
        self.history(HistoryEvent(
            HistoryEventType.DAG_SUBMITTED, dag_id=str(dag_id),
            data=submit_data))
        dag = DAGImpl(dag_id, plan, self, recovery_data=recovery_data)
        dag.tenant = tenant
        dag.submit_monotonic = clock.mono_s()
        with self._dag_done:
            self.live_dags[str(dag_id)] = dag
            self.dag_ids_by_name[plan.name] = str(dag_id)
        # DAG-scoped knob: per-DAG conf overrides the AM conf
        if dag.conf.get(C.GENERATE_DEBUG_ARTIFACTS):
            # reference: the AM writes the expanded dag plan text into
            # staging for postmortems (TezUtilsInternal debug artifacts)
            try:
                import json as _json
                path = os.path.join(self.work_dir,
                                    f"{dag_id}-plan-debug.json")
                with open(path, "w") as fh:
                    _json.dump({"name": plan.name,
                                "vertices": sorted(
                                    v.name for v in plan.vertices),
                                "plan_hex": plan_hex},
                               fh, indent=1)
                log.info("debug artifact: %s", path)
            except Exception:  # noqa: BLE001 — diagnostics must not fail
                log.exception("debug artifact write failed")
        if dag.conf.get(C.SPECULATION_ENABLED):
            from tez_tpu.am.speculation import Speculator
            dag.speculator = Speculator(dag)
            dag.speculator.start()
        # tiered buffer store: created on the first DAG that enables it and
        # shared by the whole session; lineage hashes let recurring DAGs
        # reuse sealed outputs (computed per-submit — they depend only on
        # the plan, not the dag id)
        from tez_tpu.store import ensure_store
        if ensure_store(dag.conf) is not None and \
                dag.conf.get(C.STORE_LINEAGE_REUSE):
            from tez_tpu.store.lineage import vertex_lineage_hashes
            dag.lineage_hashes = vertex_lineage_hashes(plan)
        # fault plane (test/chaos only): rules arm with the DAG and disarm
        # with it in on_dag_finished — per-DAG scoping
        from tez_tpu.common import faults
        faults.install_from_conf(dag.conf, scope=str(dag_id))
        # lock-order witness (tez.debug.lockorder): armed per-DAG like the
        # fault plane; disarmed in on_dag_finished, observations retained
        from tez_tpu.common import lockorder
        lockorder.install_from_conf(dag.conf, scope=str(dag_id))
        # tracing plane: armed with the DAG like faults; the DAG root span
        # stays open until on_dag_finished and every TaskSpec carries its
        # context so attempt/fetch spans land on the same trace id
        from tez_tpu.common import tracing
        if tracing.install_from_conf(dag.conf, scope=str(dag_id)):
            sp = tracing.start_span(
                f"dag:{plan.name}", cat="dag",
                dag_id=str(dag_id), am_epoch=self.attempt)
            dag.trace_span = sp
            dag.trace_carrier = sp.context.carrier()
        # flight recorder: armed per-DAG like the planes above; the ring
        # survives disarm so tools/doctor.py and GET-time snapshots can
        # read it after the run
        from tez_tpu.obs import flight
        if flight.install_from_conf(dag.conf, scope=str(dag_id)):
            flight.record(flight.MARK, f"dag:{plan.name}", str(dag_id))
        self.dispatch(DAGEvent(DAGEventType.DAG_INIT, dag_id))
        self.dispatch(DAGEvent(DAGEventType.DAG_START, dag_id))
        return dag_id

    # -- streaming mode (docs/streaming.md) ----------------------------------
    def open_stream(self, spec: Any) -> Any:
        """Open a resident windowed stream: journal the rebuildable spec
        (STREAM_OPENED, fsync'd — the successor incarnation's resume
        contract), start the driver, hand the ingest surface back."""
        assert self._started, "AM not started"
        from tez_tpu.am.streaming import StreamDriver
        if spec.name in self.streams:
            raise ValueError(f"stream {spec.name!r} already open")
        self.history(HistoryEvent(
            HistoryEventType.STREAM_OPENED, data=spec.journal_data()))
        driver = StreamDriver(self, spec).start()
        self.streams[spec.name] = driver
        return driver

    def _resume_streams(self, parser: Any) -> None:
        """Resume every non-retired journaled stream (recovery): sealed
        windows are served from the ledger, the first uncommitted window
        re-runs from its surviving spool (StreamDriver._resume_from)."""
        from tez_tpu.am.streaming import StreamDriver
        for stream_id, rec in parser.stream_records().items():
            if stream_id in self.streams:
                continue
            driver = StreamDriver.resume(self, rec)
            if driver is not None:
                self.streams[stream_id] = driver
                log.info("stream %s: resumed after AM restart", stream_id)

    @staticmethod
    def _is_window_plan(plan: Optional[DAGPlan]) -> bool:
        """True for a per-window DAG cloned by a StreamDriver — its
        replay belongs to the stream's ledger, never the generic DAG
        recovery path (re-running window N outside the driver would race
        the resumed stream and break exactly-once)."""
        if plan is None:
            return False
        return bool((plan.dag_conf or {}).get("tez.runtime.stream.id"))

    def wait_for_dag(self, dag_id: DAGId,
                     timeout: Optional[float] = None) -> DAGState:
        with self._dag_done:
            ok = self._dag_done.wait_for(
                lambda: str(dag_id) in self.completed_dags, timeout)
            if not ok:
                raise TimeoutError(f"DAG {dag_id} still running")
            return self.completed_dags[str(dag_id)]

    def kill_dag(self, dag_id: DAGId, reason: str = "killed by client") -> None:
        self.dispatch(DAGEvent(DAGEventType.DAG_KILL, dag_id,
                               diagnostics=reason))

    # -- AM-crash recovery (reference: DAGAppMaster serviceInit recovery
    # path + RecoveryParser.parseRecoveryData:658) ---------------------------
    def recover_and_resume(self) -> Optional[DAGId]:
        """Parse prior attempts' journals; re-run the last in-progress DAG.

        The commit ledger (DAG_COMMIT_STARTED/FINISHED/ABORTED, all fsync'd)
        decides what happens to a DAG that crashed during commit:

        - FINISHED: the committers completed; only the terminal DAG record
          was lost.  Roll forward to SUCCEEDED — never re-run or abort.
        - ABORTED: the rollback was declared durable.  Re-run the idempotent
          aborts (a crash may have interrupted the cleanup), record FAILED.
        - STARTED with tez.am.commit.recovery.policy=resume (default):
          re-run ONLY the idempotent committers and roll the commit forward
          (_resume_commit).  Resubmitting the whole DAG would be unsafe —
          a TASK_FINISHED record lost in the crash would re-run a task
          whose output the interrupted commit may already have published.
        - STARTED with policy=fail (reference semantics), or per-vertex /
          group commits pending: FAILED — partial commits can't be trusted.

        An in-flight DAG that never reached commit is resubmitted with its
        journaled SUCCEEDED tasks short-circuited — their generated
        DataMovementEvents replay into the edges instead of re-running
        (RecoveryParser.parseRecoveryData:658 semantics; if the restored
        output data died with the runner, the fetch-failure -> producer-rerun
        path recovers, as it does in the reference on node loss).

        A session AM may die with SEVERAL DAGs live plus a parked admission
        queue; every journaled DAG is recovered in submit order and every
        unresolved DAG_QUEUED record is re-parked (admission replay,
        docs/recovery.md).  Returns the last recovered dag_id — the
        single-DAG surface older callers expect.
        """
        from tez_tpu.am.recovery import RecoveryParser
        parser = RecoveryParser(self.conf.get(C.STAGING_DIR), self.app_id)
        last: Optional[DAGId] = None
        for data in parser.parse_all():
            if data.dag_state is not None:
                # finished before the crash: nothing to re-run, but two
                # things must survive into this incarnation.  First the id
                # sequence — a replayed queued submission must never be
                # assigned a dead DAG's id, or its journal records alias.
                try:
                    seq = int(data.dag_id.rsplit("_", 1)[1])
                    self._dag_seq = max(self._dag_seq, seq)
                except (ValueError, IndexError):
                    pass
                # Second the journaled verdict — a client handle re-bound
                # by reattach() resolves against completed_dags, so
                # DAGLostError keeps meaning "never reached a replayable
                # state", not "finished too early"
                try:
                    final = DAGState[data.dag_state]
                except KeyError:
                    continue
                with self._dag_done:
                    self.completed_dags.setdefault(data.dag_id, final)
                    if data.plan is not None:
                        self.completed_dag_names.setdefault(
                            data.dag_id, data.plan.name)
                    self._dag_done.notify_all()
                continue
            if self._is_window_plan(data.plan):
                # a stream's in-flight window DAG: keep the id sequence
                # monotonic but leave the re-run to the resumed driver —
                # the window-commit ledger, not DAG recovery, decides
                # whether window N runs again (docs/streaming.md)
                try:
                    seq = int(data.dag_id.rsplit("_", 1)[1])
                    self._dag_seq = max(self._dag_seq, seq)
                except (ValueError, IndexError):
                    pass
                log.info("dag %s: window DAG of stream %s — deferring to "
                         "stream resume", data.dag_id,
                         data.plan.dag_conf.get("tez.runtime.stream.id"))
                continue
            recovered = self._recover_one(data)
            if recovered is not None:
                last = recovered
        self._replay_admission_queue(parser)
        self._resume_streams(parser)
        return last

    def _recover_one(self, data: Any) -> Optional[DAGId]:
        """Recover a single journaled DAG (see recover_and_resume)."""
        try:
            seq = int(data.dag_id.rsplit("_", 1)[1])
        except (ValueError, IndexError):
            seq = self._dag_seq + 1
        dag_id = DAGId(self.app_id, seq)
        if data.commit_state == "FINISHED":
            log.info("dag %s: commit had FINISHED before AM crash; rolling "
                     "forward to SUCCEEDED", data.dag_id)
            self._dag_seq = max(self._dag_seq, seq)
            self._finish_recovered(
                data.dag_id, DAGState.SUCCEEDED,
                "commit finished before AM failure; rolled forward",
                name=data.plan.name if data.plan is not None else "")
            return dag_id
        if data.commit_state == "ABORTED":
            log.warning("dag %s: commit had ABORTED before AM crash; "
                        "re-running aborts -> FAILED", data.dag_id)
            self._dag_seq = max(self._dag_seq, seq)
            self._abort_recovered(data)
            self._finish_recovered(
                data.dag_id, DAGState.FAILED,
                "commit aborted before AM failure",
                name=data.plan.name if data.plan is not None else "")
            return dag_id
        policy = str(self.conf.get(C.AM_COMMIT_RECOVERY_POLICY) or "resume")
        if data.commit_state == "STARTED" and policy == "resume" and \
                data.plan is not None:
            self._dag_seq = max(self._dag_seq, seq)
            return self._resume_commit(data, dag_id)
        if data.commit_in_flight:
            log.warning("dag %s: commit was in flight at AM crash -> FAILED "
                        "(policy=%s)", data.dag_id, policy)
            if data.commit_state == "STARTED":
                # policy=fail (or no plan): declare the abort in the ledger,
                # then roll the partial commit back so no half-published
                # output survives
                self.history(HistoryEvent(
                    HistoryEventType.DAG_COMMIT_ABORTED, dag_id=data.dag_id,
                    data={"reason": "commit in flight during AM failure"}))
                self._abort_recovered(data)
            self._finish_recovered(
                data.dag_id, DAGState.FAILED,
                "commit in flight during AM failure",
                name=data.plan.name if data.plan is not None else "")
            self._dag_seq = max(self._dag_seq, seq)
            return dag_id
        if data.plan is None:
            log.warning("dag %s: no plan in journal, cannot recover",
                        data.dag_id)
            return None
        log.info("recovering dag %s (attempt %d): resubmitting "
                 "(%d vertices finished, %d tasks restorable)", data.dag_id,
                 self.attempt, len(data.completed_vertices),
                 len(data.task_data))
        self._dag_seq = seq - 1
        data.events = []   # only task_data/vertex_num_tasks are consulted;
        # don't pin the whole prior journal in AM memory for the DAG lifetime
        return self.submit_dag(data.plan, recovery_data=data)

    def _replay_admission_queue(self, parser: Any) -> None:
        """Re-park every unresolved admission record from prior attempts.

        The lossless-admission contract (docs/multitenancy.md) journals a
        DAG_QUEUED record — plan included — BEFORE the submitter blocks, and
        the `unresolved()` window covers popped-but-unstarted submissions
        too (the am.queue.delay lever).  Here the successor incarnation
        cashes that contract in: each unresolved record re-enters the queue
        with its ORIGINAL sub_id, tenant, and arrival order, under a
        DAG_REQUEUED_ON_RECOVERY event (docs/recovery.md)."""
        if not bool(self.conf.get(C.AM_RECOVERY_QUEUE_REPLAY)):
            return
        for rec in parser.queued_submissions():
            if rec.get("decode_error"):
                # flagged, not silently dropped: journal_fsck reports the
                # same record, and the submitter's re-attach gets DAGLost
                log.error("queued submission %s (%s): plan undecodable, "
                          "cannot replay: %s", rec["sub_id"],
                          rec.get("dag_name") or "<unnamed>",
                          rec["decode_error"])
                continue
            plan = DAGPlan.deserialize(bytes.fromhex(rec["plan"]))
            if self._is_window_plan(plan):
                # the resumed StreamDriver resubmits its own windows;
                # requeueing here would double-run the window
                log.info("queued submission %s (%s): window DAG, deferring "
                         "to stream resume", rec["sub_id"], plan.name)
                continue
            self.admission.requeue(plan, rec.get("tenant") or "",
                                   rec["sub_id"])

    def _finish_recovered(self, dag_id: str, final: DAGState,
                          diagnostics: str, name: str = "") -> None:
        """Journal the terminal record for a DAG resolved during recovery
        (it never re-instantiates as a DAGImpl), run the same deletion
        tracking a normally-finished DAG gets — the crashed attempt's
        shuffle registrations, mesh edges, and fault rules die with it —
        and release waiters."""
        self.history(HistoryEvent(
            HistoryEventType.DAG_FINISHED, dag_id=dag_id,
            data={"state": final.name, "diagnostics": diagnostics}))
        if name:
            with self._dag_done:
                self.completed_dag_names[dag_id] = name
        from tez_tpu.shuffle.service import local_shuffle_service
        n = local_shuffle_service().unregister_prefix(dag_id)
        if n:
            log.info("dag %s: released %d shuffle outputs", dag_id, n)
        from tez_tpu.parallel.coordinator import mesh_coordinator
        mesh_coordinator().cleanup_dag(dag_id)
        from tez_tpu.common import faults
        faults.clear(dag_id)
        with self._dag_done:
            self.completed_dags[dag_id] = final
            self._dag_done.notify_all()

    def _recovered_committers(self, plan: DAGPlan) -> List[Any]:
        """Rebuild leaf-output committers straight from the plan (mirrors
        VertexImpl._create_committers) for commit roll-forward/rollback.
        setup_output is deliberately NOT called — the output trees already
        exist from the crashed attempt and must be inspected, not reset."""
        from tez_tpu.api.initializer import SimpleCommitterContext
        out: List[Any] = []
        for vplan in plan.vertices:
            for sink in vplan.leaf_outputs:
                if sink.committer_descriptor is None:
                    continue
                ctx = SimpleCommitterContext(
                    sink.name, vplan.name, sink.committer_descriptor.payload,
                    app_id=self.app_id, am_epoch=self.attempt)
                committer = sink.committer_descriptor.instantiate(ctx)
                committer.initialize()
                out.append((f"{vplan.name}:{sink.name}", committer))
        return out

    def _abort_recovered(self, data: Any) -> None:
        if data.plan is None:
            return
        for name, committer in self._recovered_committers(data.plan):
            try:
                committer.abort_output("FAILED")
            except BaseException:  # noqa: BLE001
                log.exception("recovery abort of %s failed", name)

    def _resume_commit(self, data: Any, dag_id: DAGId) -> DAGId:
        """Roll a mid-commit DAG forward (tez.am.commit.recovery.policy=
        resume).  COMMIT_STARTED is only journaled once every vertex has
        succeeded, so the tasks' work is complete — only the committers'
        publish step is in doubt, and they are idempotent/resumable."""
        committers = self._recovered_committers(data.plan)
        log.info("dag %s: resuming interrupted commit (%d committers)",
                 data.dag_id, len(committers))
        try:
            for name, committer in committers:
                committer.commit_output()
        except BaseException as e:  # noqa: BLE001
            log.exception("dag %s: commit resume failed; aborting",
                          data.dag_id)
            self.history(HistoryEvent(
                HistoryEventType.DAG_COMMIT_ABORTED, dag_id=data.dag_id,
                data={"reason": f"commit resume failed: {e!r}"}))
            for name, committer in committers:
                try:
                    committer.abort_output("FAILED")
                except BaseException:  # noqa: BLE001
                    log.exception("recovery abort of %s failed", name)
            self._finish_recovered(data.dag_id, DAGState.FAILED,
                                   f"commit resume failed: {e!r}",
                                   name=data.plan.name)
            return dag_id
        self.history(HistoryEvent(
            HistoryEventType.DAG_COMMIT_FINISHED, dag_id=data.dag_id,
            data={"resumed": True}))
        self._finish_recovered(
            data.dag_id, DAGState.SUCCEEDED,
            "commit resumed and rolled forward after AM restart",
            name=data.plan.name)
        return dag_id

    def dag_status(self, dag_id: DAGId) -> Dict[str, Any]:
        dag = self.find_dag(dag_id, include_retired=True)
        if dag is None:
            state = self.completed_dags.get(str(dag_id))
            name = self.completed_dag_names.get(str(dag_id), "?")
            return {"name": name, "state": state.name if state else "UNKNOWN",
                    "progress": 1.0 if state else 0.0, "vertices": {},
                    "diagnostics": []}
        return dag.status_dict()

    def queue_status(self) -> Dict[str, Any]:
        """Admission/queue snapshot (client RPC + GET /queue)."""
        st = self.admission.status()
        st["live_dags"] = {did: d.name for did, d in
                           list(self.live_dags.items())}
        return st
