"""Canned DAG topologies ("model" shapes) built on the public DSL.

See tez_tpu.models.shapes for the tez-tests dag-shape analogs
(SimpleTestDAG, V / reverse-V, multi-level failing DAGs, MultiAttemptDAG).
"""

from tez_tpu.models import shapes

__all__ = ["shapes"]
