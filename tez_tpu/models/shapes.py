"""Canned DAG topologies — the framework's reusable "model" shapes.

Reference role: the tez-tests canned DAGs used by every fault-tolerance and
recovery suite — SimpleTestDAG / SimpleTestDAG3Vertices
(tez-tests/src/test/java/org/apache/tez/test/SimpleTestDAG.java),
SimpleVTestDAG / SimpleReverseVTestDAG / MultiAttemptDAG / FailingDagBuilder
(tez-tests/src/test/java/org/apache/tez/test/dag/FailingDagBuilder.java:62).

Every builder wires the fault-injectable TestInput/TestProcessor/TestOutput
doubles (tez_tpu/library/test_components.py), so any shape can be turned
into a failure scenario by passing the shared `payload` dict (do_fail,
failing_task_indices, ...).  The same topologies double as user-facing
skeletons: swap the descriptors for real processors via the `processor`,
`input_descriptor`, `output_descriptor` hooks.
"""
from __future__ import annotations

from typing import Any, Dict, Optional

from tez_tpu.common.payload import (InputDescriptor, OutputDescriptor,
                                    ProcessorDescriptor)
from tez_tpu.dag.dag import DAG, Edge, Vertex
from tez_tpu.dag.edge_property import (DataMovementType, DataSourceType,
                                       EdgeProperty, SchedulingType)

_TEST_PROC = "tez_tpu.library.test_components:TestProcessor"
_TEST_IN = "tez_tpu.library.test_components:TestInput"
_TEST_OUT = "tez_tpu.library.test_components:TestOutput"


class ShapeBuilder:
    """Fluent canned-DAG builder (FailingDagBuilder analog).

    >>> dag = (ShapeBuilder("diamond", payload={"do_fail": True})
    ...        .vertex("a", 2).vertex("b", 3).vertex("c", 3).vertex("d", 2)
    ...        .edge("a", "b").edge("a", "c")
    ...        .edge("b", "d").edge("c", "d").build())
    """

    def __init__(self, name: str,
                 payload: Optional[Dict[str, Any]] = None,
                 processor: str = _TEST_PROC,
                 input_descriptor: str = _TEST_IN,
                 output_descriptor: str = _TEST_OUT):
        self.name = name
        self.payload = dict(payload or {})
        self.processor = processor
        self.input_descriptor = input_descriptor
        self.output_descriptor = output_descriptor
        self._vertices: Dict[str, Vertex] = {}
        self._edges = []

    def vertex(self, name: str, parallelism: int = 1,
               payload: Optional[Dict[str, Any]] = None) -> "ShapeBuilder":
        self._vertices[name] = Vertex.create(
            name, ProcessorDescriptor.create(
                self.processor, payload=payload if payload is not None
                else self.payload), parallelism)
        return self

    def edge(self, src: str, dst: str,
             movement: DataMovementType = DataMovementType.SCATTER_GATHER,
             payload: Optional[Dict[str, Any]] = None) -> "ShapeBuilder":
        p = payload if payload is not None else self.payload
        self._edges.append(Edge.create(
            self._vertices[src], self._vertices[dst], EdgeProperty.create(
                movement, DataSourceType.PERSISTED,
                SchedulingType.SEQUENTIAL,
                OutputDescriptor.create(self.output_descriptor, payload=p),
                InputDescriptor.create(self.input_descriptor, payload=p))))
        return self

    def build(self) -> DAG:
        dag = DAG.create(self.name)
        for v in self._vertices.values():
            dag.add_vertex(v)
        for e in self._edges:
            dag.add_edge(e)
        return dag


def simple_dag(name: str = "SimpleTestDAG", parallelism: int = 2,
               payload: Optional[Dict[str, Any]] = None) -> DAG:
    """v1 -SG-> v2 (SimpleTestDAG.java)."""
    return (ShapeBuilder(name, payload)
            .vertex("v1", parallelism).vertex("v2", parallelism)
            .edge("v1", "v2").build())


def simple_dag_3_vertices(name: str = "SimpleTestDAG3Vertices",
                          parallelism: int = 2,
                          payload: Optional[Dict[str, Any]] = None) -> DAG:
    """v1 -SG-> v2 -SG-> v3 (SimpleTestDAG3Vertices.java)."""
    return (ShapeBuilder(name, payload)
            .vertex("v1", parallelism).vertex("v2", parallelism)
            .vertex("v3", parallelism)
            .edge("v1", "v2").edge("v2", "v3").build())


def simple_v_dag(name: str = "SimpleVTestDAG", parallelism: int = 2,
                 payload: Optional[Dict[str, Any]] = None) -> DAG:
    """v1, v2 -SG-> v3 fan-in (SimpleVTestDAG.java:51)."""
    return (ShapeBuilder(name, payload)
            .vertex("v1", parallelism).vertex("v2", parallelism)
            .vertex("v3", parallelism)
            .edge("v1", "v3").edge("v2", "v3").build())


def simple_reverse_v_dag(name: str = "SimpleReverseVTestDAG",
                         parallelism: int = 2,
                         payload: Optional[Dict[str, Any]] = None) -> DAG:
    """v1 -SG-> v2, v3 fan-out (SimpleReverseVTestDAG.java:51)."""
    return (ShapeBuilder(name, payload)
            .vertex("v1", parallelism).vertex("v2", parallelism)
            .vertex("v3", parallelism)
            .edge("v1", "v2").edge("v1", "v3").build())


def two_levels_failing_dag(name: str = "TwoLevelsFailingDAG",
                           payload: Optional[Dict[str, Any]] = None) -> DAG:
    """Four independent l1->l2 pairs, one BROADCAST
    (FailingDagBuilder.Levels.TWO, FailingDagBuilder.java:71)."""
    b = ShapeBuilder(name, payload)
    for i, (p1, p2) in enumerate(((1, 1), (2, 3), (3, 2), (2, 3)), start=1):
        b.vertex(f"l1v{i}", p1).vertex(f"l2v{i}", p2)
    for i in range(1, 4):
        b.edge(f"l1v{i}", f"l2v{i}")
    b.edge("l1v4", "l2v4", DataMovementType.BROADCAST)
    return b.build()


def three_levels_failing_dag(name: str = "ThreeLevelsFailingDAG",
                             payload: Optional[Dict[str, Any]] = None) -> DAG:
    """Adds l3v1/l3v2 over the two-level shape with mixed fan-in
    (FailingDagBuilder.Levels.THREE, FailingDagBuilder.java:85)."""
    b = ShapeBuilder(name, payload)
    for i, (p1, p2) in enumerate(((1, 1), (2, 3), (3, 2), (2, 3)), start=1):
        b.vertex(f"l1v{i}", p1).vertex(f"l2v{i}", p2)
    for i in range(1, 4):
        b.edge(f"l1v{i}", f"l2v{i}")
    b.edge("l1v4", "l2v4", DataMovementType.BROADCAST)
    b.vertex("l3v1", 4).vertex("l3v2", 4)
    b.edge("l2v1", "l3v1").edge("l2v2", "l3v1")
    b.edge("l2v2", "l3v2", DataMovementType.BROADCAST)
    b.edge("l2v3", "l3v2").edge("l2v4", "l3v2")
    return b.build()


def multi_attempt_dag(name: str = "MultiAttemptDAG",
                      failing_upto_attempt: int = 1,
                      parallelism: int = 1) -> DAG:
    """Every vertex fails its first `failing_upto_attempt` attempts then
    succeeds — drives retry + recovery paths (MultiAttemptDAG.java)."""
    payload = {"do_fail": True, "failing_task_indices": [-1],
               "failing_upto_attempt": failing_upto_attempt}
    return (ShapeBuilder(name, payload)
            .vertex("v1", parallelism).vertex("v2", parallelism)
            .vertex("v3", parallelism)
            .edge("v1", "v2").edge("v2", "v3").build())
