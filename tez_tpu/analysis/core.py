"""graftlint checker plugin API: context, findings, suppressions, baseline.

Design rules (mirroring how the other planes in this tree work):

- **Stable identities.**  A finding's baseline key is symbolic —
  ``checker:code:path:symbol`` — never a line number, so unrelated edits
  don't churn the committed baseline.  Line numbers are display-only.
- **Inline suppressions.**  ``# graftlint: disable=<code>[,<code>...]``
  on the reported line (or the line above it) suppresses that finding;
  ``disable=all`` suppresses every code on the line.  Suppressions are
  for *triaged* findings — the comment is the audit trail.
- **Committed baseline.**  ``tools/graftlint_baseline.json`` holds the
  identities of known findings; the CLI exits nonzero only on findings
  NOT in the baseline, so every future PR inherits the analysis without
  first paying down historical debt.  ``--update-baseline`` rewrites it
  (sorted, one identity per line — diffs stay reviewable).
"""
from __future__ import annotations

import ast
import dataclasses
import json
import os
import re
from typing import Callable, Dict, Iterable, List, Optional, Sequence, Tuple

#: Inline suppression grammar, anywhere in a comment.
_SUPPRESS_RE = re.compile(r"#\s*graftlint:\s*disable=([a-z0-9_,\-]+|all)")


@dataclasses.dataclass(frozen=True)
class Finding:
    """One lint result.

    ``symbol`` is the stable identity component (knob name, fault point,
    cycle signature, ``Class.attr`` ...); two findings with the same
    (checker, code, path, symbol) are the same finding across edits.
    """
    checker: str
    code: str
    path: str          # repo-relative, forward slashes
    line: int          # 1-based display anchor (0 = whole file)
    symbol: str
    message: str

    @property
    def identity(self) -> str:
        return f"{self.checker}:{self.code}:{self.path}:{self.symbol}"

    def render(self) -> str:
        return (f"{self.path}:{self.line}: {self.code} "
                f"[{self.checker}] {self.message}")


class SourceFile:
    """One parsed source file: AST + raw lines + per-line suppressions."""

    def __init__(self, path: str, rel: str, text: str) -> None:
        self.path = path
        self.rel = rel
        self.text = text
        self.lines = text.splitlines()
        self.tree: Optional[ast.AST] = None
        self.parse_error: Optional[SyntaxError] = None
        try:
            self.tree = ast.parse(text, filename=path)
        except SyntaxError as e:       # reported as a finding, not a crash
            self.parse_error = e
        self._suppressed: Dict[int, set] = {}
        for i, line in enumerate(self.lines, start=1):
            m = _SUPPRESS_RE.search(line)
            if m:
                codes = set(m.group(1).split(","))
                self._suppressed[i] = codes

    def suppressed(self, line: int, code: str) -> bool:
        """A finding at ``line`` is suppressed by a pragma on that line or
        on the line directly above it (for lines that are too long to
        carry a trailing comment)."""
        for ln in (line, line - 1):
            codes = self._suppressed.get(ln)
            if codes and ("all" in codes or code in codes):
                return True
        return False


class Context:
    """Everything a checker may look at, parsed once and shared.

    ``root`` is the repository root (the directory holding ``tez_tpu/``
    and ``docs/``); ``package`` is the package dir analyzed (normally
    ``<root>/tez_tpu``).  Fixture tests point ``package`` at a tmp tree.
    """

    def __init__(self, root: str, package: Optional[str] = None,
                 docs_dir: Optional[str] = None) -> None:
        self.root = os.path.abspath(root)
        self.package = os.path.abspath(package or
                                       os.path.join(self.root, "tez_tpu"))
        self.docs_dir = os.path.abspath(docs_dir or
                                        os.path.join(self.root, "docs"))
        self.files: List[SourceFile] = []
        for dirpath, dirnames, filenames in sorted(os.walk(self.package)):
            dirnames[:] = sorted(d for d in dirnames
                                 if d not in ("__pycache__", ".git"))
            for fn in sorted(filenames):
                if not fn.endswith(".py"):
                    continue
                full = os.path.join(dirpath, fn)
                rel = os.path.relpath(full, self.root).replace(os.sep, "/")
                with open(full, "r", encoding="utf-8") as f:
                    self.files.append(SourceFile(full, rel, f.read()))
        self._docs_cache: Dict[str, str] = {}

    # -- helpers shared by checkers ----------------------------------------

    def doc_text(self, name: str) -> str:
        """Contents of docs/<name>, '' when absent (absence is itself
        reported by the registry checkers)."""
        if name not in self._docs_cache:
            path = os.path.join(self.docs_dir, name)
            try:
                with open(path, "r", encoding="utf-8") as f:
                    self._docs_cache[name] = f.read()
            except OSError:
                self._docs_cache[name] = ""
        return self._docs_cache[name]

    def find_file(self, suffix: str) -> Optional[SourceFile]:
        """The analyzed file whose repo-relative path ends with suffix."""
        suffix = suffix.replace(os.sep, "/")
        for sf in self.files:
            if sf.rel.endswith(suffix):
                return sf
        return None

    def module_name(self, sf: SourceFile) -> str:
        """Dotted module path relative to the analyzed package, e.g.
        ``ops.async_stage`` — the shared naming base for lock names in
        both the static graph and the runtime witness."""
        rel = os.path.relpath(sf.path, self.package).replace(os.sep, "/")
        mod = rel[:-3] if rel.endswith(".py") else rel
        if mod.endswith("/__init__"):
            mod = mod[: -len("/__init__")]
        return mod.replace("/", ".")


@dataclasses.dataclass(frozen=True)
class Checker:
    """A checker plugin: a name plus ``run(ctx) -> findings``.  Adding a
    checker = one module with a ``CHECKER`` constant, listed in
    :func:`tez_tpu.analysis.all_checkers` (docs/static_analysis.md)."""
    name: str
    doc: str
    run: Callable[[Context], List[Finding]]


def _apply_suppressions(ctx: Context,
                        findings: Iterable[Finding]) -> List[Finding]:
    by_rel = {sf.rel: sf for sf in ctx.files}
    out = []
    for f in findings:
        sf = by_rel.get(f.path)
        if sf is not None and f.line > 0 and sf.suppressed(f.line, f.code):
            continue
        out.append(f)
    return out


def run_checkers(ctx: Context,
                 checkers: Sequence[Checker]) -> List[Finding]:
    """Run the checkers and return suppression-filtered findings in a
    stable order (path, line, code, symbol)."""
    findings: List[Finding] = []
    for sf in ctx.files:
        if sf.parse_error is not None:
            findings.append(Finding(
                "core", "parse-error", sf.rel,
                sf.parse_error.lineno or 0, sf.rel,
                f"syntax error: {sf.parse_error.msg}"))
    for checker in checkers:
        findings.extend(checker.run(ctx))
    findings = _apply_suppressions(ctx, findings)
    findings.sort(key=lambda f: (f.path, f.line, f.code, f.symbol))
    return findings


# -- baseline ---------------------------------------------------------------

def load_baseline(path: str) -> List[str]:
    try:
        with open(path, "r", encoding="utf-8") as f:
            data = json.load(f)
    except OSError:
        return []
    return list(data.get("findings", []))


def save_baseline(path: str, findings: Sequence[Finding]) -> None:
    data = {
        "comment": "graftlint suppression baseline: known-finding "
                   "identities (checker:code:path:symbol).  Regenerate "
                   "with --update-baseline; keep diffs reviewed.",
        "findings": sorted({f.identity for f in findings}),
    }
    with open(path, "w", encoding="utf-8") as f:
        json.dump(data, f, indent=2, sort_keys=True)
        f.write("\n")


def partition_by_baseline(findings: Sequence[Finding],
                          baseline: Sequence[str]
                          ) -> Tuple[List[Finding], List[Finding], List[str]]:
    """(new, known, stale-baseline-entries)."""
    base = set(baseline)
    new = [f for f in findings if f.identity not in base]
    known = [f for f in findings if f.identity in base]
    current = {f.identity for f in findings}
    stale = sorted(b for b in base if b not in current)
    return new, known, stale
