"""Static lock-order analysis: discover locks, build the acquisition
graph, report cycles as potential deadlocks.

Model (ThreadSanitizer-style, but source-level):

1. **Lock discovery.**  ``self.X = threading.Lock()/RLock()/Condition()``
   inside a class names instance lock ``<module>.<Class>.X``;
   ``X = threading.Lock()`` at module level names ``<module>.X``.
   ``threading.Condition(self.Y)`` is an *alias*: acquiring the
   condition acquires lock ``Y``, so both resolve to Y's name — the
   identical naming scheme the runtime witness
   (:mod:`tez_tpu.common.lockorder`) derives from creation frames, which
   is what makes the static/dynamic cross-validation a set comparison.

2. **Acquisition graph.**  Within each function, nested ``with`` blocks
   on resolvable lock expressions produce held->new edges (plus bare
   ``.acquire()`` tracked block-locally).  Function summaries — the set
   of locks a call may (transitively) acquire — propagate edges across
   call sites: holding A while calling ``g()`` adds A->L for every L in
   g's summary.  Calls are resolved through self-methods, instance-attr
   and module-global types, imported modules, and finally by method
   name across every analyzed class (a deliberate over-approximation:
   the runtime witness's observed edges must be a SUBSET of this graph,
   so resolution errs toward more edges, and the cycle report pays for
   it with an occasional triaged false positive in the baseline).

3. **Cycles.**  Strongly connected components of size > 1 (or a
   self-loop through distinct functions) are potential deadlocks; each
   SCC is one finding whose symbol is the sorted node list, so the
   identity survives line churn.
"""
from __future__ import annotations

import ast
import dataclasses
from typing import Dict, List, Optional, Sequence, Set, Tuple

from tez_tpu.analysis.core import Checker, Context, Finding, SourceFile

_LOCK_CTORS = ("Lock", "RLock", "Condition")

#: Method names excluded from the by-name call-resolution fallback:
#: ubiquitous stdlib-collection verbs that would wire every class to
#: every other through dict/list/set receivers the type pass can't see.
_FALLBACK_SKIP = frozenset({
    "get", "set", "add", "pop", "append", "appendleft", "popleft",
    "remove", "clear", "update", "items", "keys", "values", "copy",
    "join", "split", "strip", "startswith", "endswith", "format",
    "encode", "decode", "read", "write", "flush", "sort", "extend",
    "index", "count", "insert", "setdefault", "discard", "put",
    # lock-protocol verbs: ``self.lock.wait()`` on a Condition attribute
    # reaches here as a chain call; the acquisition itself is modeled by
    # the enclosing ``with``, not by resolving wait() to package classes
    "wait", "wait_for", "notify", "notify_all", "locked",
})

#: Generic lifecycle verbs so ubiquitous that a by-name fallback match
#: says nothing about the receiver: candidates for these contribute only
#: their DIRECT acquires to the caller's summary.  Every other fallback
#: name (handle_events, route_*, can_commit, ...) is specific enough to
#: propagate the candidate's full transitive summary — which is what
#: keeps deep chains under untyped dispatch (TaskRunner holding
#: _dispatch_lock across ``inp.handle_events(...)`` into the fetch
#: table and merge manager) inside the graph the runtime witness
#: validates against, without ``stop()``/``close()`` welding every
#: class in the tree into one spurious mega-cycle.
_FALLBACK_DIRECT_ONLY = frozenset({
    "__init__", "initialize", "run", "close", "stop", "start",
    "analyze", "handle", "create", "shutdown", "submit_dag",
})


@dataclasses.dataclass
class _FuncInfo:
    key: Tuple[str, str]                    # (module, qualname)
    sf: SourceFile
    line: int
    #: (lock name, locks held at that point)
    acquires: List[Tuple[str, Tuple[str, ...]]] = \
        dataclasses.field(default_factory=list)
    #: (call descriptor, locks held at that point, lineno)
    calls: List[Tuple[tuple, Tuple[str, ...], int]] = \
        dataclasses.field(default_factory=list)


@dataclasses.dataclass
class _ClassInfo:
    module: str
    name: str                               # qualified within module
    sf: SourceFile
    bases: List[str] = dataclasses.field(default_factory=list)
    #: attr -> canonical lock name (aliases already resolved)
    lock_attrs: Dict[str, str] = dataclasses.field(default_factory=dict)
    #: attr -> (module, class) of the instance assigned to it
    attr_types: Dict[str, Tuple[str, str]] = \
        dataclasses.field(default_factory=dict)
    methods: Dict[str, _FuncInfo] = dataclasses.field(default_factory=dict)
    #: __init__ parameter names, in order (self excluded)
    init_params: List[str] = dataclasses.field(default_factory=list)
    #: attr -> __init__ param it was assigned from (``self.x = x`` /
    #: ``self.x = x or default``): the stored-callback seam
    attr_from_param: Dict[str, str] = \
        dataclasses.field(default_factory=dict)


@dataclasses.dataclass
class _ModuleInfo:
    name: str
    sf: SourceFile
    #: local alias -> analyzed-module dotted name (tez_tpu imports only)
    imports: Dict[str, str] = dataclasses.field(default_factory=dict)
    #: local name -> (module, function) for from-imports of functions
    func_imports: Dict[str, Tuple[str, str]] = \
        dataclasses.field(default_factory=dict)
    #: local name -> (module, class) for from-imports of classes
    class_imports: Dict[str, Tuple[str, str]] = \
        dataclasses.field(default_factory=dict)
    threading_aliases: Set[str] = dataclasses.field(default_factory=set)
    classes: Dict[str, _ClassInfo] = dataclasses.field(default_factory=dict)
    functions: Dict[str, _FuncInfo] = dataclasses.field(default_factory=dict)
    global_locks: Dict[str, str] = dataclasses.field(default_factory=dict)
    global_types: Dict[str, Tuple[str, str]] = \
        dataclasses.field(default_factory=dict)


class _Model:
    """The whole-package model all passes share."""

    def __init__(self) -> None:
        self.modules: Dict[str, _ModuleInfo] = {}
        self.functions: Dict[Tuple[str, str], _FuncInfo] = {}
        #: method name -> [(module, class)] across every analyzed class
        self.methods_by_name: Dict[str, List[Tuple[str, str]]] = {}
        #: class simple name -> [(module, class)]
        self.classes_by_name: Dict[str, List[Tuple[str, str]]] = {}
        #: (module, class, ctor param) -> bound methods / functions passed
        #: as that argument at any constructor call site — resolving
        #: stored-callback invocations like ``self._on_complete(...)``
        #: back to e.g. ``DeviceSorter._async_complete``
        self.callback_bindings: Dict[Tuple[str, str, str],
                                     Set[Tuple[str, str]]] = {}


# --------------------------------------------------------------------------
# Pass 1: per-module discovery
# --------------------------------------------------------------------------

def _is_lock_ctor(node: ast.expr, mi: _ModuleInfo) -> Optional[str]:
    """'Lock'/'RLock'/'Condition' when node constructs one, else None."""
    if not isinstance(node, ast.Call):
        return None
    f = node.func
    if isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name) \
            and f.value.id in mi.threading_aliases \
            and f.attr in _LOCK_CTORS:
        return f.attr
    if isinstance(f, ast.Name):
        target = mi.func_imports.get(f.id)
        if target is not None and target[0] == "threading" \
                and target[1] in _LOCK_CTORS:
            return target[1]
    return None


def _collect_imports(tree: ast.AST, mi: _ModuleInfo, pkg: str) -> None:
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                name, asname = alias.name, alias.asname or alias.name
                if name == "threading":
                    mi.threading_aliases.add(asname)
                elif name.startswith(pkg + "."):
                    mi.imports[asname.split(".")[0] if not alias.asname
                               else asname] = name[len(pkg) + 1:]
        elif isinstance(node, ast.ImportFrom) and node.module:
            mod = node.module
            if mod == "threading":
                for alias in node.names:
                    mi.func_imports[alias.asname or alias.name] = \
                        ("threading", alias.name)
            elif mod == pkg or mod.startswith(pkg + "."):
                sub = mod[len(pkg) + 1:] if mod != pkg else ""
                for alias in node.names:
                    local = alias.asname or alias.name
                    # module import (from tez_tpu.common import metrics)
                    # vs symbol import — disambiguated in the link pass,
                    # record both candidate meanings here
                    dotted = f"{sub}.{alias.name}" if sub else alias.name
                    mi.imports.setdefault(local, dotted)
                    if sub:
                        mi.func_imports.setdefault(local, (sub, alias.name))
                        mi.class_imports.setdefault(local, (sub, alias.name))


def _resolve_class_ctor(node: ast.expr, mi: _ModuleInfo
                        ) -> Optional[Tuple[str, str]]:
    """(module, class) when node is ``Class(...)`` / ``mod.Class(...)``
    over names importable from the analyzed package; linked later."""
    if not isinstance(node, ast.Call):
        return None
    f = node.func
    if isinstance(f, ast.Name):
        if f.id in mi.classes:
            return (mi.name, f.id)
        if f.id in mi.class_imports:
            return mi.class_imports[f.id]
    if isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name) \
            and f.value.id in mi.imports:
        return (mi.imports[f.value.id], f.attr)
    return None


def _discover_module(ctx: Context, sf: SourceFile, model: _Model) -> None:
    mi = _ModuleInfo(ctx.module_name(sf), sf)
    model.modules[mi.name] = mi
    assert sf.tree is not None
    _collect_imports(sf.tree, mi, "tez_tpu")

    def walk_class(cnode: ast.ClassDef, prefix: str) -> None:
        cname = f"{prefix}{cnode.name}"
        ci = _ClassInfo(mi.name, cname, sf)
        ci.bases = [b.id for b in cnode.bases if isinstance(b, ast.Name)]
        mi.classes[cname] = ci
        model.classes_by_name.setdefault(cnode.name, []).append(
            (mi.name, cname))
        pending_alias: List[Tuple[str, str]] = []    # (attr, target attr)
        for item in cnode.body:
            if isinstance(item, ast.ClassDef):
                walk_class(item, f"{cname}.")
            elif isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                fi = _FuncInfo((mi.name, f"{cname}.{item.name}"), sf,
                               item.lineno)
                ci.methods[item.name] = fi
                model.functions[fi.key] = fi
                model.methods_by_name.setdefault(item.name, []).append(
                    (mi.name, cname))
                if item.name == "__init__":
                    ci.init_params = [
                        a.arg for a in (item.args.args[1:]
                                        + item.args.kwonlyargs)]
                for sub in ast.walk(item):
                    if not isinstance(sub, ast.Assign):
                        continue
                    for tgt in sub.targets:
                        if not (isinstance(tgt, ast.Attribute)
                                and isinstance(tgt.value, ast.Name)
                                and tgt.value.id == "self"):
                            continue
                        kind = _is_lock_ctor(sub.value, mi)
                        if kind == "Condition" and sub.value.args:
                            arg = sub.value.args[0]
                            if isinstance(arg, ast.Attribute) and \
                                    isinstance(arg.value, ast.Name) and \
                                    arg.value.id == "self":
                                pending_alias.append((tgt.attr, arg.attr))
                                continue
                        if kind is not None:
                            ci.lock_attrs[tgt.attr] = \
                                f"{mi.name}.{cname}.{tgt.attr}"
                            continue
                        typ = _resolve_class_ctor(sub.value, mi)
                        if typ is not None:
                            ci.attr_types[tgt.attr] = typ
                            continue
                        if item.name == "__init__":
                            val = sub.value
                            if isinstance(val, ast.BoolOp) and val.values:
                                val = val.values[0]   # ``param or default``
                            if isinstance(val, ast.Name) and \
                                    val.id in ci.init_params:
                                ci.attr_from_param[tgt.attr] = val.id
        for attr, target in pending_alias:
            ci.lock_attrs[attr] = ci.lock_attrs.get(
                target, f"{mi.name}.{cname}.{target}")

    globals_decls: Set[str] = set()
    for node in sf.tree.body:
        if isinstance(node, ast.ClassDef):
            walk_class(node, "")
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            fi = _FuncInfo((mi.name, node.name), sf, node.lineno)
            mi.functions[node.name] = fi
            model.functions[fi.key] = fi
            for sub in ast.walk(node):
                if isinstance(sub, ast.Global):
                    globals_decls.update(sub.names)
        elif isinstance(node, ast.Assign):
            for tgt in node.targets:
                if not isinstance(tgt, ast.Name):
                    continue
                if _is_lock_ctor(node.value, mi):
                    mi.global_locks[tgt.id] = f"{mi.name}.{tgt.id}"
                else:
                    typ = _resolve_class_ctor(node.value, mi)
                    if typ is not None:
                        mi.global_types[tgt.id] = typ
    # module globals assigned from inside functions (singleton factories):
    # NAME = Class(...) under a ``global NAME`` declaration
    for node in ast.walk(sf.tree):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                isinstance(node.targets[0], ast.Name) and \
                node.targets[0].id in globals_decls:
            typ = _resolve_class_ctor(node.value, mi)
            if typ is not None:
                mi.global_types.setdefault(node.targets[0].id, typ)


# --------------------------------------------------------------------------
# Pass 2: per-function acquisition + call events
# --------------------------------------------------------------------------

class _FuncWalker:
    """Walk one function body tracking the held-lock stack through
    nested ``with`` statements and block-local bare ``.acquire()``s."""

    def __init__(self, model: _Model, mi: _ModuleInfo,
                 ci: Optional[_ClassInfo], fi: _FuncInfo) -> None:
        self.model = model
        self.mi = mi
        self.ci = ci
        self.fi = fi

    # -- lock expression resolution -----------------------------------------
    def lock_of(self, node: ast.expr) -> Optional[str]:
        if isinstance(node, ast.Attribute) and \
                isinstance(node.value, ast.Name):
            base, attr = node.value.id, node.attr
            if base == "self" and self.ci is not None:
                return self.ci.lock_attrs.get(attr)
            if base in self.mi.imports:
                target = self.model.modules.get(self.mi.imports[base])
                if target is not None:
                    return target.global_locks.get(attr)
            return None
        if isinstance(node, ast.Name):
            return self.mi.global_locks.get(node.id)
        return None

    # -- call descriptor extraction ------------------------------------------
    def call_ref(self, node: ast.Call) -> Optional[tuple]:
        f = node.func
        if isinstance(f, ast.Name):
            return ("name", f.id)
        if isinstance(f, ast.Attribute):
            m = f.attr
            v = f.value
            if isinstance(v, ast.Name):
                if v.id == "self":
                    return ("self", m)
                if v.id in self.mi.imports:
                    return ("mod", self.mi.imports[v.id], m)
                if v.id in self.mi.global_types:
                    return ("typed", self.mi.global_types[v.id], m)
                return ("chain", m)
            if isinstance(v, ast.Attribute) and \
                    isinstance(v.value, ast.Name) and v.value.id == "self" \
                    and self.ci is not None and \
                    v.attr in self.ci.attr_types:
                return ("typed", self.ci.attr_types[v.attr], m)
            return ("chain", m)
        return None

    # -- body walking ---------------------------------------------------------
    def walk_body(self, body: Sequence[ast.stmt],
                  held: Tuple[str, ...]) -> None:
        extra: List[str] = []          # block-local bare .acquire()s
        for stmt in body:
            self.walk_stmt(stmt, held + tuple(extra), extra)

    def _record_acquire(self, lock: str, held: Tuple[str, ...]) -> None:
        if lock not in held:
            self.fi.acquires.append((lock, held))

    def _bare_lock_call(self, stmt: ast.stmt) -> Optional[Tuple[str, str]]:
        """(lock, 'acquire'|'release') for ``X.acquire()`` statements."""
        if not (isinstance(stmt, ast.Expr)
                and isinstance(stmt.value, ast.Call)
                and isinstance(stmt.value.func, ast.Attribute)
                and stmt.value.func.attr in ("acquire", "release")):
            return None
        lock = self.lock_of(stmt.value.func.value)
        return (lock, stmt.value.func.attr) if lock else None

    def walk_stmt(self, stmt: ast.stmt, held: Tuple[str, ...],
                  extra: List[str]) -> None:
        bare = self._bare_lock_call(stmt)
        if bare is not None:
            lock, op = bare
            if op == "acquire":
                self._record_acquire(lock, held)
                if lock not in held:
                    extra.append(lock)
            elif lock in extra:
                extra.remove(lock)
            return
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            inner = held
            for item in stmt.items:
                self.scan_calls(item.context_expr, held)
                lock = self.lock_of(item.context_expr)
                if lock is not None:
                    self._record_acquire(lock, inner)
                    if lock not in inner:
                        inner = inner + (lock,)
            self.walk_body(stmt.body, inner)
            return
        # non-with compound statements: recurse with the same held set
        for field in ("body", "orelse", "finalbody"):
            sub = getattr(stmt, field, None)
            if sub:
                self.walk_body(sub, held)
        for handler in getattr(stmt, "handlers", []) or []:
            self.walk_body(handler.body, held)
        if isinstance(stmt, (ast.If, ast.While)):
            self.scan_calls(stmt.test, held)
        elif isinstance(stmt, ast.For):
            self.scan_calls(stmt.iter, held)
        elif not hasattr(stmt, "body"):
            self.scan_calls(stmt, held)

    def scan_calls(self, node: ast.AST, held: Tuple[str, ...]) -> None:
        for sub in ast.walk(node):
            if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.Lambda)):
                continue        # nested defs analyzed when called, not here
            if isinstance(sub, ast.Call):
                ref = self.call_ref(sub)
                if ref is not None:
                    self.fi.calls.append((ref, held, sub.lineno))
                target = _resolve_class_ctor(sub, self.mi)
                if target is not None:
                    self._record_ctor_bindings(sub, target)

    def _record_ctor_bindings(self, call: ast.Call,
                              target: Tuple[str, str]) -> None:
        """Bound methods / local functions passed as constructor args are
        stored-callback candidates (``on_complete=self._async_complete``):
        remember them per (class, param) so calls through the stored attr
        resolve exactly instead of vanishing from the graph."""
        mod, cls = target
        tmi = self.model.modules.get(mod)
        tci = tmi.classes.get(cls) if tmi is not None else None
        if tci is None:
            return

        def meth_key(expr: ast.expr) -> Optional[Tuple[str, str]]:
            if isinstance(expr, ast.Attribute) and \
                    isinstance(expr.value, ast.Name) and \
                    expr.value.id == "self" and self.ci is not None and \
                    expr.attr in self.ci.methods:
                return (self.mi.name, f"{self.ci.name}.{expr.attr}")
            if isinstance(expr, ast.Name) and expr.id in self.mi.functions:
                return (self.mi.name, expr.id)
            return None

        for i, arg in enumerate(call.args):
            key = meth_key(arg)
            if key is not None and i < len(tci.init_params):
                self.model.callback_bindings.setdefault(
                    (mod, cls, tci.init_params[i]), set()).add(key)
        for kw in call.keywords:
            key = meth_key(kw.value)
            if kw.arg is not None and key is not None:
                self.model.callback_bindings.setdefault(
                    (mod, cls, kw.arg), set()).add(key)


def _walk_functions(model: _Model) -> None:
    for mi in model.modules.values():
        tree = mi.sf.tree
        assert tree is not None

        def handle(fnode, ci: Optional[_ClassInfo], fi: _FuncInfo) -> None:
            _FuncWalker(model, mi, ci, fi).walk_body(fnode.body, ())

        for node in tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                handle(node, None, mi.functions[node.name])
            elif isinstance(node, ast.ClassDef):
                stack = [(node, "")]
                while stack:
                    cnode, prefix = stack.pop()
                    cname = f"{prefix}{cnode.name}"
                    ci = mi.classes[cname]
                    for item in cnode.body:
                        if isinstance(item, ast.ClassDef):
                            stack.append((item, f"{cname}."))
                        elif isinstance(item, (ast.FunctionDef,
                                               ast.AsyncFunctionDef)):
                            handle(item, ci, ci.methods[item.name])


# --------------------------------------------------------------------------
# Pass 3: call resolution + summary fixed point + edge generation
# --------------------------------------------------------------------------

def _resolve_call(model: _Model, mi: _ModuleInfo, fi: _FuncInfo,
                  ref: tuple) -> List[Tuple[str, str, bool]]:
    """Candidate callees as (module, qualname, exact).  ``exact`` callees
    (resolved through self/types/imports) always propagate their full
    transitive acquisition summary.  Name-fallback candidates do too
    *unless* the method name is a generic lifecycle verb
    (:data:`_FALLBACK_DIRECT_ONLY`), where candidates contribute only
    their direct acquires — keeping chains like
    ``counters.group(g).find_counter(n).increment()`` and untyped
    dispatch like ``inp.handle_events(...)`` in the graph without
    letting every ``stop()``/``start()`` in the tree weld all classes
    into one spurious mega-cycle."""
    kind = ref[0]
    out: List[Tuple[str, str, bool]] = []

    def class_method(mod: str, cls: str, meth: str) -> bool:
        cmi = model.modules.get(mod)
        if cmi is None:
            return False
        ci = cmi.classes.get(cls)
        if ci is None:
            return False
        if meth in ci.methods:
            out.append((mod, f"{cls}.{meth}", True))
            return True
        for base in ci.bases:      # single-level inheritance walk
            for bmod, bcls in model.classes_by_name.get(base, []):
                bmi = model.modules.get(bmod)
                if bmi and meth in bmi.classes[bcls].methods:
                    out.append((bmod, f"{bcls}.{meth}", True))
                    return True
        return False

    def fallback(meth: str) -> None:
        if meth in _FALLBACK_SKIP:
            return
        for mod, cls in model.methods_by_name.get(meth, []):
            out.append((mod, f"{cls}.{meth}", False))

    if kind == "self":
        meth = ref[1]
        cls = fi.key[1].rsplit(".", 1)[0] if "." in fi.key[1] else None
        if cls is not None and class_method(mi.name, cls, meth):
            return out
        # ``self.X(...)`` where X is not a method: a stored callback.
        # When X was assigned from an __init__ param, resolve to every
        # bound method any constructor call site passed for that param —
        # exact, so full summaries flow (the _complete_lock -> sorter /
        # merge-manager chains the runtime witness observes).
        ci = model.modules[mi.name].classes.get(cls) if cls else None
        param = ci.attr_from_param.get(meth) if ci is not None else None
        bound = model.callback_bindings.get((mi.name, cls, param)) \
            if param is not None else None
        if bound:
            out.extend((m, q, True) for m, q in sorted(bound))
        else:
            fallback(meth)
    elif kind == "typed":
        (mod, cls), meth = ref[1], ref[2]
        if not class_method(mod, cls, meth):
            fallback(meth)
    elif kind == "mod":
        mod, name = ref[1], ref[2]
        tmi = model.modules.get(mod)
        if tmi is not None:
            if name in tmi.functions:
                out.append((mod, name, True))
            elif name in tmi.classes:
                class_method(mod, name, "__init__")
        if not out:
            # ``from tez_tpu.x import y`` records y under imports too;
            # ref ("mod", "x.y", name) may really be class/func y's attr
            fallback(name)
    elif kind == "name":
        name = ref[1]
        if name in mi.functions:
            out.append((mi.name, name, True))
        elif name in mi.classes:
            class_method(mi.name, name, "__init__")
        elif name in mi.func_imports:
            mod, orig = mi.func_imports[name]
            tmi = model.modules.get(mod)
            if tmi is not None:
                if orig in tmi.functions:
                    out.append((mod, orig, True))
                elif orig in tmi.classes:
                    class_method(mod, orig, "__init__")
    elif kind == "chain":
        fallback(ref[1])
    return out


def build_graph(ctx: Context) -> Tuple[
        Dict[Tuple[str, str], Tuple[str, int]], Set[str]]:
    """The static lock graph: {(held, acquired): (function key, line)}
    plus the set of every discovered lock name."""
    model = _Model()
    for sf in ctx.files:
        if sf.tree is not None:
            _discover_module(ctx, sf, model)
    _walk_functions(model)

    resolved_calls: Dict[Tuple[str, str],
                         List[Tuple[List[Tuple[str, str, bool]],
                                    Tuple[str, ...], int]]] = {}
    for key, fi in model.functions.items():
        mi = model.modules[key[0]]
        resolved_calls[key] = [
            (_resolve_call(model, mi, fi, ref), held, line)
            for ref, held, line in fi.calls]

    # fixed point: transitive acquisition summary per function.  Exact
    # callees and name-specific fallbacks propagate their whole summary;
    # generic-verb fallbacks contribute only their direct acquires (see
    # _resolve_call / _FALLBACK_DIRECT_ONLY).
    direct: Dict[Tuple[str, str], Set[str]] = {
        key: {lock for lock, _ in fi.acquires}
        for key, fi in model.functions.items()}
    summary: Dict[Tuple[str, str], Set[str]] = {
        key: set(acq) for key, acq in direct.items()}

    def source_for(qual: str, exact: bool) -> Dict[Tuple[str, str], Set[str]]:
        if exact or qual.rsplit(".", 1)[-1] not in _FALLBACK_DIRECT_ONLY:
            return summary
        return direct

    changed = True
    while changed:
        changed = False
        for key in model.functions:
            acc = summary[key]
            before = len(acc)
            for callees, _, _ in resolved_calls[key]:
                for mod, qual, exact in callees:
                    acc |= source_for(qual, exact).get((mod, qual), set())
            if len(acc) != before:
                changed = True

    edges: Dict[Tuple[str, str], Tuple[str, int]] = {}

    def add(a: str, b: str, where: str, line: int) -> None:
        if a != b:
            edges.setdefault((a, b), (where, line))

    for key, fi in model.functions.items():
        where = f"{key[0]}.{key[1]}"
        for lock, held in fi.acquires:
            for h in held:
                add(h, lock, where, fi.line)
        for callees, held, line in resolved_calls[key]:
            if not held:
                continue
            for mod, qual, exact in callees:
                for lock in source_for(qual, exact).get((mod, qual), ()):
                    for h in held:
                        add(h, lock, where, line)

    locks: Set[str] = set()
    for mi in model.modules.values():
        locks.update(mi.global_locks.values())
        for ci in mi.classes.values():
            locks.update(ci.lock_attrs.values())
    return edges, locks


def lock_graph(ctx: Context) -> Set[Tuple[str, str]]:
    """Edge set only — what the runtime witness validates against."""
    return set(build_graph(ctx)[0])


# --------------------------------------------------------------------------
# Cycle reporting
# --------------------------------------------------------------------------

def _sccs(nodes: Set[str],
          adj: Dict[str, Set[str]]) -> List[List[str]]:
    """Tarjan SCCs, iterative (the graph is small but recursion-free
    keeps pathological fixtures safe)."""
    index: Dict[str, int] = {}
    low: Dict[str, int] = {}
    on: Set[str] = set()
    stack: List[str] = []
    out: List[List[str]] = []
    counter = [0]

    for root in sorted(nodes):
        if root in index:
            continue
        work = [(root, iter(sorted(adj.get(root, ()))))]
        index[root] = low[root] = counter[0]
        counter[0] += 1
        stack.append(root)
        on.add(root)
        while work:
            v, it = work[-1]
            advanced = False
            for w in it:
                if w not in index:
                    index[w] = low[w] = counter[0]
                    counter[0] += 1
                    stack.append(w)
                    on.add(w)
                    work.append((w, iter(sorted(adj.get(w, ())))))
                    advanced = True
                    break
                if w in on:
                    low[v] = min(low[v], index[w])
            if advanced:
                continue
            work.pop()
            if work:
                pv = work[-1][0]
                low[pv] = min(low[pv], low[v])
            if low[v] == index[v]:
                comp = []
                while True:
                    w = stack.pop()
                    on.discard(w)
                    comp.append(w)
                    if w == v:
                        break
                if len(comp) > 1:
                    out.append(sorted(comp))
    return out


def run(ctx: Context) -> List[Finding]:
    edges, _locks = build_graph(ctx)
    adj: Dict[str, Set[str]] = {}
    nodes: Set[str] = set()
    for a, b in edges:
        adj.setdefault(a, set()).add(b)
        nodes.add(a)
        nodes.add(b)
    findings: List[Finding] = []
    for comp in _sccs(nodes, adj):
        inside = [(a, b) for (a, b) in edges
                  if a in comp and b in comp]
        inside.sort()
        samples = "; ".join(
            f"{a}->{b} at {edges[(a, b)][0]}:{edges[(a, b)][1]}"
            for a, b in inside[:4])
        where, line = edges[inside[0]]
        mod = where.split(".")[0]
        sf = None
        for cand in ctx.files:
            if ctx.module_name(cand) == where.rsplit(".", 2)[0] or \
                    ctx.module_name(cand).startswith(mod):
                sf = cand
                break
        findings.append(Finding(
            "lockorder", "lock-cycle",
            sf.rel if sf is not None else "tez_tpu",
            line, "<->".join(comp),
            f"potential deadlock: lock acquisition cycle "
            f"{' -> '.join(comp + comp[:1])} ({samples})"))
    return findings


CHECKER = Checker(
    "lockorder",
    "lock acquisition graph cycles (potential deadlocks), "
    "cross-validated by the tez.debug.lockorder runtime witness",
    run)
