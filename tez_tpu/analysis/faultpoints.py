"""Fault-point consistency: fire()/should_corrupt()/corrupt_bytes() call
sites vs the canonical ``faults.KNOWN_POINTS`` table vs
docs/fault_injection.md.

``faults.fire`` deliberately accepts any point name (new seams need no
central edit at runtime) — this checker is the compile-time closure of
that openness:

- ``fault-unregistered`` — a literal point fired somewhere but absent
  from KNOWN_POINTS: invisible to the chaos storm menu and the docs.
- ``fault-unfired`` — a KNOWN_POINTS entry whose name appears nowhere
  else in the package: a seam that was removed (or renamed) without
  updating the table.
- ``fault-undocumented`` — KNOWN_POINTS entry missing from
  docs/fault_injection.md.
- ``fault-doc-stale`` — a backticked point in the doc's table that is no
  longer in KNOWN_POINTS.
"""
from __future__ import annotations

import ast
import re
from typing import Dict, List, Tuple

from tez_tpu.analysis.core import Checker, Context, Finding

_FAULTS_SUFFIX = "common/faults.py"
_FIRE_FUNCS = ("fire", "should_corrupt", "corrupt_bytes")
#: backticked point names inside the doc's markdown table
_DOC_POINT_RE = re.compile(r"^\|\s*`([a-z0-9._-]+)`", re.MULTILINE)


def _known_points(ctx: Context) -> Tuple[Dict[str, int], str]:
    sf = ctx.find_file(_FAULTS_SUFFIX)
    if sf is None or sf.tree is None:
        return {}, ""
    for node in ast.walk(sf.tree):
        if isinstance(node, ast.AnnAssign) and \
                isinstance(node.target, ast.Name) and \
                node.target.id == "KNOWN_POINTS" and \
                isinstance(node.value, ast.Dict):
            return {k.value: k.lineno for k in node.value.keys
                    if isinstance(k, ast.Constant)}, sf.rel
        if isinstance(node, ast.Assign) and node.targets and \
                isinstance(node.targets[0], ast.Name) and \
                node.targets[0].id == "KNOWN_POINTS" and \
                isinstance(node.value, ast.Dict):
            return {k.value: k.lineno for k in node.value.keys
                    if isinstance(k, ast.Constant)}, sf.rel
    return {}, sf.rel


def _is_fire_call(node: ast.Call) -> bool:
    f = node.func
    if isinstance(f, ast.Name):
        return f.id in _FIRE_FUNCS
    if isinstance(f, ast.Attribute):
        # faults.fire(...), plane().fire(...), self._faults.fire(...)
        return f.attr in _FIRE_FUNCS
    return False


def run(ctx: Context) -> List[Finding]:
    known, faults_rel = _known_points(ctx)
    findings: List[Finding] = []
    if not known:
        return findings

    fired: Dict[str, Tuple[str, int]] = {}
    mentioned: Dict[str, Tuple[str, int]] = {}
    for sf in ctx.files:
        if sf.tree is None or sf.rel.endswith(_FAULTS_SUFFIX):
            continue
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.Call) and _is_fire_call(node) and \
                    node.args and isinstance(node.args[0], ast.Constant) \
                    and isinstance(node.args[0].value, str):
                fired.setdefault(node.args[0].value, (sf.rel, node.lineno))
            # any literal occurrence counts as "the seam still exists"
            # (split-retry and chaos-menu sites pass points via variables)
            if isinstance(node, ast.Constant) and \
                    isinstance(node.value, str) and node.value in known:
                mentioned.setdefault(node.value, (sf.rel, node.lineno))

    doc = ctx.doc_text("fault_injection.md")
    # the doc also tables the tez.test.fault.* conf knobs — those belong
    # to the knobs checker, not here
    doc_points = {p for p in _DOC_POINT_RE.findall(doc)
                  if not p.startswith("tez.")} if doc else set()

    for point, (rel, line) in sorted(fired.items()):
        if point not in known:
            findings.append(Finding(
                "faultpoints", "fault-unregistered", rel, line, point,
                f"fault point {point!r} fired here but missing from "
                f"faults.KNOWN_POINTS"))
    for point, line in sorted(known.items()):
        if point not in mentioned:
            findings.append(Finding(
                "faultpoints", "fault-unfired", faults_rel, line, point,
                f"KNOWN_POINTS entry {point!r} never referenced outside "
                f"common/faults.py — dead seam?"))
        if doc and point not in doc_points:
            findings.append(Finding(
                "faultpoints", "fault-undocumented", faults_rel, line,
                point,
                f"KNOWN_POINTS entry {point!r} missing from "
                f"docs/fault_injection.md"))
    for point in sorted(doc_points - set(known)):
        findings.append(Finding(
            "faultpoints", "fault-doc-stale", "docs/fault_injection.md",
            0, point,
            f"docs/fault_injection.md lists {point!r} which is not in "
            f"faults.KNOWN_POINTS"))
    return findings


CHECKER = Checker(
    "faultpoints",
    "fault-injection call sites vs faults.KNOWN_POINTS vs "
    "docs/fault_injection.md",
    run)
