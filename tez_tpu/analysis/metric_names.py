"""Metric-name consistency: instrumentation sites vs
``metrics.WELL_KNOWN_HISTOGRAMS`` vs the ``tools/counter_diff.py``
report sections vs docs/observability.md.

Codes:

- ``hist-unregistered`` — ``metrics.observe(name)`` / ``timer(name)``
  with a literal name missing from WELL_KNOWN_HISTOGRAMS (it records
  fine at runtime but is invisible to /metrics consumers that iterate
  the well-known list and to the bench diff sections).
- ``hist-unused`` — a WELL_KNOWN_HISTOGRAMS entry whose name appears
  nowhere else in the package.
- ``hist-undocumented`` — WELL_KNOWN entry not in docs/observability.md.
- ``diff-stale-hist`` — a ``*_HISTS`` section tuple in
  tools/counter_diff.py naming a histogram that is not well-known.
- ``gauge-undocumented`` — a literal ``set_gauge`` name missing from
  docs/observability.md (dynamic f-string gauges are out of scope).
"""
from __future__ import annotations

import ast
from typing import Dict, List, Tuple

from tez_tpu.analysis.core import Checker, Context, Finding

_METRICS_SUFFIX = "common/metrics.py"
_DIFF_SUFFIX = "tools/counter_diff.py"


def _well_known(ctx: Context) -> Tuple[Dict[str, int], str]:
    sf = ctx.find_file(_METRICS_SUFFIX)
    if sf is None or sf.tree is None:
        return {}, ""
    for node in ast.walk(sf.tree):
        if isinstance(node, ast.Assign) and node.targets and \
                isinstance(node.targets[0], ast.Name) and \
                node.targets[0].id == "WELL_KNOWN_HISTOGRAMS" and \
                isinstance(node.value, (ast.Tuple, ast.List)):
            return {e.value: e.lineno for e in node.value.elts
                    if isinstance(e, ast.Constant)}, sf.rel
    return {}, sf.rel


def _literal_arg(node: ast.Call) -> str:
    if node.args and isinstance(node.args[0], ast.Constant) and \
            isinstance(node.args[0].value, str):
        return node.args[0].value
    return ""


def run(ctx: Context) -> List[Finding]:
    well_known, metrics_rel = _well_known(ctx)
    findings: List[Finding] = []
    if not well_known:
        return findings

    observed: Dict[str, Tuple[str, int]] = {}
    gauges: Dict[str, Tuple[str, int]] = {}
    mentioned: Dict[str, Tuple[str, int]] = {}
    for sf in ctx.files:
        if sf.tree is None or sf.rel.endswith(_METRICS_SUFFIX):
            continue
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Attribute):
                name = _literal_arg(node)
                if name:
                    if node.func.attr in ("observe", "timer"):
                        observed.setdefault(name, (sf.rel, node.lineno))
                    elif node.func.attr == "set_gauge":
                        gauges.setdefault(name, (sf.rel, node.lineno))
            if isinstance(node, ast.Constant) and \
                    isinstance(node.value, str) and \
                    node.value in well_known:
                mentioned.setdefault(node.value, (sf.rel, node.lineno))

    doc = ctx.doc_text("observability.md")

    for name, (rel, line) in sorted(observed.items()):
        if name not in well_known:
            findings.append(Finding(
                "metric_names", "hist-unregistered", rel, line, name,
                f"histogram {name!r} observed here but missing from "
                f"metrics.WELL_KNOWN_HISTOGRAMS"))
    for name, line in sorted(well_known.items()):
        if name not in mentioned:
            findings.append(Finding(
                "metric_names", "hist-unused", metrics_rel, line, name,
                f"WELL_KNOWN_HISTOGRAMS entry {name!r} never referenced "
                f"outside common/metrics.py"))
        if doc and f"`{name}`" not in doc:
            findings.append(Finding(
                "metric_names", "hist-undocumented", metrics_rel, line,
                name,
                f"well-known histogram {name!r} missing from "
                f"docs/observability.md"))

    diff_sf = ctx.find_file(_DIFF_SUFFIX)
    if diff_sf is not None and diff_sf.tree is not None:
        for node in ast.walk(diff_sf.tree):
            if isinstance(node, ast.Assign) and node.targets and \
                    isinstance(node.targets[0], ast.Name) and \
                    node.targets[0].id.endswith("_HISTS") and \
                    isinstance(node.value, (ast.Tuple, ast.List)):
                for e in node.value.elts:
                    if isinstance(e, ast.Constant) and \
                            e.value not in well_known:
                        findings.append(Finding(
                            "metric_names", "diff-stale-hist",
                            diff_sf.rel, e.lineno, str(e.value),
                            f"counter_diff section lists histogram "
                            f"{e.value!r} which is not in "
                            f"WELL_KNOWN_HISTOGRAMS"))

    for name, (rel, line) in sorted(gauges.items()):
        if doc and f"`{name}`" not in doc:
            findings.append(Finding(
                "metric_names", "gauge-undocumented", rel, line, name,
                f"gauge {name!r} set here but missing from "
                f"docs/observability.md"))
    return findings


CHECKER = Checker(
    "metric_names",
    "histogram/gauge names at instrumentation sites vs metrics.py vs "
    "counter_diff sections vs docs/observability.md",
    run)
