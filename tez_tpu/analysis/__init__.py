"""graftlint: AST-based static-analysis suite for the tez_tpu tree.

The control plane is four interlocking threaded planes (async span
pipeline, merge lane, tiered buffer store, fetch-session referee) and
three string-keyed registries (``tez.*`` conf knobs, fault points,
metric names) that drift silently from code and docs.  This package is
the correctness tooling that scales with that codebase:

- :mod:`core` — the checker plugin API: parsed-source context, findings
  with stable identities, inline suppressions, committed baseline.
- :mod:`lockorder` — discovers named lock attributes per class, builds
  the inter-module lock acquisition graph from nested ``with`` blocks
  and call edges, and reports cycles as potential deadlocks.  The
  static graph is cross-validated at runtime by the lock-order witness
  (:mod:`tez_tpu.common.lockorder`, armed via ``tez.debug.lockorder``).
- :mod:`knobs` — every ``tez.*`` literal read in code must be
  registered in ``common/config.py`` and documented, and every
  registered knob must be read somewhere.
- :mod:`faultpoints` — fault-injection call sites vs the canonical
  ``faults.KNOWN_POINTS`` table vs ``docs/fault_injection.md``.
- :mod:`metric_names` — histogram/gauge/counter names at
  instrumentation sites vs ``common/metrics.py`` vs the
  ``tools/counter_diff.py`` sections vs ``docs/observability.md``.
- :mod:`jax_hazards` — ``jax.jit`` recompile churn, implicit host
  syncs in pipeline hot paths, non-daemon threads, bare ``.acquire()``.
- :mod:`rawtime` — raw ``time.time()`` / ``time.monotonic()`` in
  ``am/`` and ``obs/``: every stamp in the control and observability
  planes must come off the shared injectable clock
  (``common/clock.py``) or flight/journal/time-series timelines fork.

CLI: ``python -m tez_tpu.tools.graftlint`` (or ``make lint``); see
docs/static_analysis.md.
"""
from tez_tpu.analysis.core import (Checker, Context, Finding,  # noqa: F401
                                   load_baseline, run_checkers,
                                   save_baseline)


def all_checkers():
    """The six shipped checkers, in report order."""
    from tez_tpu.analysis import (faultpoints, jax_hazards, knobs,
                                  lockorder, metric_names, rawtime)
    return [lockorder.CHECKER, knobs.CHECKER, faultpoints.CHECKER,
            metric_names.CHECKER, jax_hazards.CHECKER, rawtime.CHECKER]
