"""Raw-clock hygiene for the control and observability planes.

Every timestamp the AM journals, exposes, or correlates must come off
the shared injectable clock (``common/clock.py``): ``clock.wall_s()``
for wall time, ``clock.mono_s()`` / ``clock.mono_ns()`` for monotonic
time.  A raw ``time.time()`` or ``time.monotonic()`` inside ``am/`` or
``obs/`` silently forks the timebase — flight records, time-series
samples, and journal events stop being mutually orderable, and the
deterministic chaos/replay harnesses (which drive the clock) cannot
reach the call site.  This checker bans the raw calls in those two
packages so the drift is a lint error, not an archaeology project.

Codes:

- ``raw-clock-call`` — ``time.time()`` / ``time.monotonic()`` /
  ``time.monotonic_ns()`` called in ``tez_tpu/am/`` or ``tez_tpu/obs/``
  outside ``common/clock.py`` itself.
- ``raw-clock-import`` — ``from time import time|monotonic|...`` in
  scope (aliasing hides the call sites from the call check).

``time.sleep`` / ``time.perf_counter`` / formatting helpers are fine:
they measure or wait, they don't *stamp*.  Triaged exceptions carry the
usual ``# graftlint: disable=raw-clock-call`` pragma.
"""
from __future__ import annotations

import ast
from typing import List

from tez_tpu.analysis.core import Checker, Context, Finding

#: packages (repo-relative prefixes) where raw clocks are banned
_SCOPE_PREFIXES = ("tez_tpu/am/", "tez_tpu/obs/")

#: banned time-module attributes: these STAMP a moment
_BANNED = frozenset({"time", "monotonic", "monotonic_ns"})

_REMEDY = ("use the shared injectable clock instead: clock.wall_s() / "
           "clock.mono_s() / clock.mono_ns() from tez_tpu.common.clock")


def _in_scope(rel: str) -> bool:
    return rel.startswith(_SCOPE_PREFIXES)


class _Visitor(ast.NodeVisitor):
    def __init__(self, rel: str) -> None:
        self.rel = rel
        self.stack: List[str] = []
        self.findings: List[Finding] = []
        self._seen: dict = {}

    def _qual(self) -> str:
        return ".".join(self.stack) or "<module>"

    def _add(self, code: str, line: int, what: str, msg: str) -> None:
        # identity stays line-free: disambiguate repeats within one scope
        base = f"{self._qual()}:{what}"
        n = self._seen.get((code, base), 0)
        self._seen[(code, base)] = n + 1
        symbol = base if n == 0 else f"{base}#{n}"
        self.findings.append(Finding(
            "rawtime", code, self.rel, line, symbol, msg))

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        self.stack.append(node.name)
        self.generic_visit(node)
        self.stack.pop()

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        self.stack.append(node.name)
        self.generic_visit(node)
        self.stack.pop()

    def visit_AsyncFunctionDef(self, node: ast.AsyncFunctionDef) -> None:
        self.stack.append(node.name)
        self.generic_visit(node)
        self.stack.pop()

    def visit_Call(self, node: ast.Call) -> None:
        f = node.func
        if isinstance(f, ast.Attribute) and f.attr in _BANNED and \
                isinstance(f.value, ast.Name) and f.value.id == "time":
            self._add("raw-clock-call", node.lineno, f"time.{f.attr}",
                      f"raw time.{f.attr}() in the {self.rel.split('/')[1]}"
                      f" plane; {_REMEDY}")
        self.generic_visit(node)

    def visit_ImportFrom(self, node: ast.ImportFrom) -> None:
        if node.module == "time" and node.level == 0:
            for alias in node.names:
                if alias.name in _BANNED:
                    self._add(
                        "raw-clock-import", node.lineno,
                        f"from-time-import-{alias.name}",
                        f"'from time import {alias.name}' aliases a raw "
                        f"clock past the call check; {_REMEDY}")
        self.generic_visit(node)


def run(ctx: Context) -> List[Finding]:
    findings: List[Finding] = []
    for sf in ctx.files:
        if sf.tree is None or not _in_scope(sf.rel):
            continue
        v = _Visitor(sf.rel)
        v.visit(sf.tree)
        findings.extend(v.findings)
    return findings


CHECKER = Checker(
    "rawtime",
    "raw time.time()/time.monotonic() in am/ and obs/ vs the shared "
    "injectable clock (common/clock.py)",
    run)
