"""JAX and threading hazards that type checkers don't see.

Codes:

- ``jit-in-loop`` — ``jax.jit`` constructed inside a ``for``/``while``
  body: a fresh jit wrapper per iteration defeats XLA's compile cache
  keying and churns recompiles.  The blessed shapes are a module-level
  jit, an ``@functools.lru_cache`` builder, or a builder that *returns*
  the jitted callable (ops/device.py, parallel/exchange.py).
- ``jit-immediate`` — ``jax.jit(f)(args)`` called and invoked in one
  expression: the wrapper is rebuilt (and its traces re-keyed) on every
  call.
- ``host-sync`` — ``.item()`` inside the device data plane's hot-path
  modules: an implicit D2H sync that serializes the async pipeline.
- ``thread-nondaemon`` — ``threading.Thread`` constructed without
  ``daemon=True``: every helper thread in this tree must not block
  interpreter shutdown (the watchdog/failover planes assume it).
- ``bare-acquire`` — ``<lock>.acquire()`` as a bare statement outside
  ``with``: invisible to context-managed cleanup and to the lock-order
  witness discipline.  (Block-local acquire/release pairs are still
  modeled by the static lock graph, but new code should use ``with``.)
"""
from __future__ import annotations

import ast
from typing import List, Optional

from tez_tpu.analysis.core import Checker, Context, Finding

#: Modules whose code runs per-span / per-batch on the device data
#: plane — where one stray host sync stalls the whole overlap schedule.
_HOT_PATH_MODULES = (
    "ops/async_stage.py", "ops/device_pipeline.py", "ops/device.py",
    "parallel/exchange.py", "parallel/coordinator.py",
)


def _is_jit(node: ast.expr) -> bool:
    if isinstance(node, ast.Attribute) and node.attr == "jit" and \
            isinstance(node.value, ast.Name) and node.value.id == "jax":
        return True
    return isinstance(node, ast.Name) and node.id == "jit"


def _is_thread_ctor(node: ast.Call) -> bool:
    f = node.func
    if isinstance(f, ast.Attribute) and f.attr == "Thread" and \
            isinstance(f.value, ast.Name) and f.value.id == "threading":
        return True
    return isinstance(f, ast.Name) and f.id == "Thread"


def _receiver_looks_like_lock(node: ast.expr) -> bool:
    name: Optional[str] = None
    if isinstance(node, ast.Attribute):
        name = node.attr
    elif isinstance(node, ast.Name):
        name = node.id
    return name is not None and "lock" in name.lower()


def run(ctx: Context) -> List[Finding]:
    findings: List[Finding] = []
    for sf in ctx.files:
        if sf.tree is None or "analysis/" in sf.rel:
            continue
        hot = any(sf.rel.endswith(m) for m in _HOT_PATH_MODULES)

        # jit inside loop bodies
        for node in ast.walk(sf.tree):
            if isinstance(node, (ast.For, ast.While)):
                for sub in ast.walk(node):
                    if sub is node:
                        continue
                    if isinstance(sub, ast.Call) and _is_jit(sub.func):
                        findings.append(Finding(
                            "jax_hazards", "jit-in-loop", sf.rel,
                            sub.lineno, f"L{sub.lineno}",
                            "jax.jit constructed inside a loop body — "
                            "hoist to module level or an lru_cache "
                            "builder"))
            # jax.jit(f)(args) in one expression
            if isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Call) and \
                    _is_jit(node.func.func):
                findings.append(Finding(
                    "jax_hazards", "jit-immediate", sf.rel, node.lineno,
                    f"L{node.lineno}",
                    "jax.jit(f)(...) built and invoked per call — cache "
                    "the jitted callable"))
            # host syncs in hot-path modules
            if hot and isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Attribute) and \
                    node.func.attr == "item" and not node.args:
                findings.append(Finding(
                    "jax_hazards", "host-sync", sf.rel, node.lineno,
                    f"L{node.lineno}",
                    ".item() in a device hot path is an implicit D2H "
                    "sync — keep values on device or batch the readback"))
            # non-daemon threads
            if isinstance(node, ast.Call) and _is_thread_ctor(node):
                kw = {k.arg: k.value for k in node.keywords}
                daemon = kw.get("daemon")
                if not (isinstance(daemon, ast.Constant) and
                        daemon.value is True):
                    findings.append(Finding(
                        "jax_hazards", "thread-nondaemon", sf.rel,
                        node.lineno, f"L{node.lineno}",
                        "threading.Thread without daemon=True — helper "
                        "threads must not block interpreter shutdown"))
            # bare .acquire() statements
            if isinstance(node, ast.Expr) and \
                    isinstance(node.value, ast.Call) and \
                    isinstance(node.value.func, ast.Attribute) and \
                    node.value.func.attr == "acquire" and \
                    _receiver_looks_like_lock(node.value.func.value):
                findings.append(Finding(
                    "jax_hazards", "bare-acquire", sf.rel, node.lineno,
                    f"L{node.lineno}",
                    "bare .acquire() — use `with` (or try/finally) so "
                    "release is guaranteed and the witness sees scoping"))
    return findings


CHECKER = Checker(
    "jax_hazards",
    "jit recompile churn, hot-path host syncs, non-daemon threads, "
    "bare lock acquires",
    run)
