"""Config-knob consistency: ``tez.*`` literals vs the common/config.py
registry vs docs/configuration.md.

Codes:

- ``knob-unregistered`` — a ``tez.*`` string literal is read somewhere in
  the package but never registered through ``_key()`` in
  ``common/config.py``; such a knob is invisible to ``make docs``, to
  scope filtering, and to defaulting.
- ``knob-undocumented`` — registered but missing from
  ``docs/configuration.md`` (the doc is generated — this means someone
  edited the registry without rerunning ``make docs``).
- ``knob-unread`` — registered but its ConfKey constant (and its literal
  name) never appears outside ``common/config.py``: dead configuration
  surface.  Knobs kept for registry compatibility carry an inline
  ``# graftlint: disable=knob-unread`` with the reason.
"""
from __future__ import annotations

import ast
import re
from typing import Dict, List, Set, Tuple

from tez_tpu.analysis.core import Checker, Context, Finding

#: A full knob name: dotted, lowercase, no trailing dot.  Prefix strings
#: (``"tez.runtime."``) and spec fragments deliberately don't match.
_KNOB_RE = re.compile(r"^tez(\.[a-z0-9_-]+){2,}$")

_CONFIG_SUFFIX = "common/config.py"


def _registry(ctx: Context) -> Tuple[Dict[str, Tuple[str, int]], str]:
    """{knob name: (ConfKey var name, line)} from common/config.py."""
    sf = ctx.find_file(_CONFIG_SUFFIX)
    if sf is None or sf.tree is None:
        return {}, ""
    out: Dict[str, Tuple[str, int]] = {}
    for node in ast.walk(sf.tree):
        if not (isinstance(node, ast.Assign) and
                isinstance(node.value, ast.Call) and
                isinstance(node.value.func, ast.Name) and
                node.value.func.id == "_key" and node.value.args and
                isinstance(node.value.args[0], ast.Constant) and
                isinstance(node.value.args[0].value, str)):
            continue
        var = node.targets[0].id if node.targets and \
            isinstance(node.targets[0], ast.Name) else ""
        out[node.value.args[0].value] = (var, node.lineno)
    return out, sf.rel


def run(ctx: Context) -> List[Finding]:
    registered, config_rel = _registry(ctx)
    findings: List[Finding] = []
    if not registered:
        return findings

    #: knob literal -> first read site outside config.py
    reads: Dict[str, Tuple[str, int]] = {}
    #: every identifier (Name id / Attribute attr) per non-config file —
    #: how we tell a ConfKey constant is referenced at all
    used_idents: Set[str] = set()
    for sf in ctx.files:
        if sf.tree is None or sf.rel.endswith(_CONFIG_SUFFIX):
            continue
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.Constant) and \
                    isinstance(node.value, str) and \
                    _KNOB_RE.match(node.value):
                reads.setdefault(node.value, (sf.rel, node.lineno))
            elif isinstance(node, ast.Name):
                used_idents.add(node.id)
            elif isinstance(node, ast.Attribute):
                used_idents.add(node.attr)

    doc = ctx.doc_text("configuration.md")

    for knob, (rel, line) in sorted(reads.items()):
        if knob not in registered:
            findings.append(Finding(
                "knobs", "knob-unregistered", rel, line, knob,
                f"tez knob {knob!r} is read here but not registered via "
                f"_key() in common/config.py"))

    cfg_line = 0
    for knob, (var, line) in sorted(registered.items()):
        if doc and f"`{knob}`" not in doc:
            findings.append(Finding(
                "knobs", "knob-undocumented", config_rel, line, knob,
                f"registered knob {knob!r} missing from "
                f"docs/configuration.md — rerun `make docs`"))
        if knob not in reads and (not var or var not in used_idents):
            findings.append(Finding(
                "knobs", "knob-unread", config_rel, line, knob,
                f"registered knob {knob!r} ({var or 'unnamed'}) is never "
                f"read outside common/config.py"))
        cfg_line = max(cfg_line, line)
    return findings


CHECKER = Checker(
    "knobs",
    "tez.* conf literals vs the config.py registry vs "
    "docs/configuration.md",
    run)
