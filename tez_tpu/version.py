"""Framework version, importable without side effects.

Reference role: the Maven project version stamped into every artifact by
tez-dist (tez-dist/pom.xml) and surfaced at runtime through TezUtilsInternal.
"""

__version__ = "0.2.0"
