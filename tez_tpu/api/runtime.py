"""Task-side runtime SPI: the Input / Processor / Output plugin boundary.

Reference parity: tez-api/.../runtime/api/ — LogicalInput, LogicalOutput,
LogicalIOProcessor, AbstractLogicalInput/Output/Processor, Reader, Writer,
InputContext/OutputContext/ProcessorContext, MergedLogicalInput,
MemoryUpdateCallback, ObjectRegistry.  This is the exact seam the TPU data
plane plugs into (SURVEY.md §2.1 "Runtime SPI").
"""
from __future__ import annotations

import abc
from typing import Any, Dict, Iterable, List, Optional, Sequence

from tez_tpu.api.events import TezAPIEvent
from tez_tpu.common.counters import TezCounters
from tez_tpu.common.payload import UserPayload


class Reader(abc.ABC):
    """Reference: Reader.java — marker base for input readers."""


class Writer(abc.ABC):
    """Reference: Writer.java."""


class KeyValueReader(Reader):
    """Iterate (key, value) records (reference: KeyValueReader.java)."""

    @abc.abstractmethod
    def __iter__(self):
        ...


class KeyValuesReader(Reader):
    """Iterate (key, iterable-of-values) groups (reference:
    KeyValuesReader.java, backed by ValuesIterator grouping)."""

    @abc.abstractmethod
    def __iter__(self):
        ...


class KeyValueWriter(Writer):
    @abc.abstractmethod
    def write(self, key: Any, value: Any) -> None:
        ...


class KeyValuesWriter(KeyValueWriter):
    """Also accepts (key, [values]) (reference: KeyValuesWriter.java)."""

    def write_key_values(self, key: Any, values: Iterable[Any]) -> None:
        for v in values:
            self.write(key, v)


class TaskContext(abc.ABC):
    """Shared context surface (reference: TaskContext.java)."""

    @property
    @abc.abstractmethod
    def task_attempt_id(self) -> Any: ...

    @property
    @abc.abstractmethod
    def task_index(self) -> int: ...

    @property
    @abc.abstractmethod
    def task_attempt_number(self) -> int: ...

    @property
    @abc.abstractmethod
    def vertex_name(self) -> str: ...

    @property
    @abc.abstractmethod
    def dag_name(self) -> str: ...

    @property
    @abc.abstractmethod
    def vertex_parallelism(self) -> int: ...

    @property
    @abc.abstractmethod
    def counters(self) -> TezCounters: ...

    @property
    @abc.abstractmethod
    def user_payload(self) -> UserPayload: ...

    @abc.abstractmethod
    def send_events(self, events: Sequence[TezAPIEvent]) -> None: ...

    @abc.abstractmethod
    def request_initial_memory(self, size: int,
                               callback: "MemoryUpdateCallback | None",
                               component_type: str = "OTHER") -> None:
        """Ask for task memory; component_type weights oversubscription
        scaling (see runtime.memory.DEFAULT_WEIGHTS)."""

    @abc.abstractmethod
    def notify_progress(self) -> None: ...

    @abc.abstractmethod
    def set_progress(self, progress: float) -> None: ...

    @abc.abstractmethod
    def fatal_error(self, exc: Optional[BaseException], message: str) -> None: ...

    @property
    @abc.abstractmethod
    def work_dirs(self) -> List[str]: ...

    @abc.abstractmethod
    def get_service_provider_metadata(self, service: str) -> Any: ...

    @property
    @abc.abstractmethod
    def object_registry(self) -> "ObjectRegistry": ...


class InputContext(TaskContext):
    @property
    @abc.abstractmethod
    def source_vertex_name(self) -> str: ...

    @property
    @abc.abstractmethod
    def input_index(self) -> int: ...


class OutputContext(TaskContext):
    @property
    @abc.abstractmethod
    def destination_vertex_name(self) -> str: ...

    @property
    @abc.abstractmethod
    def output_index(self) -> int: ...


class ProcessorContext(TaskContext):
    @abc.abstractmethod
    def can_commit(self) -> bool:
        """Commit arbitration with the AM (reference:
        ProcessorContext.canCommit -> umbilical canCommit)."""


class MemoryUpdateCallback(abc.ABC):
    """Reference: MemoryUpdateCallback.java — grant delivered before start()."""

    @abc.abstractmethod
    def memory_assigned(self, assigned_size: int) -> None: ...


class LogicalInput(abc.ABC):
    """Reference: AbstractLogicalInput.java — lifecycle:
    initialize() -> [start()] -> getReader() -> close()."""

    def __init__(self, context: InputContext, num_physical_inputs: int):
        self.context = context
        self.num_physical_inputs = num_physical_inputs

    @abc.abstractmethod
    def initialize(self) -> List[TezAPIEvent]: ...

    def start(self) -> None:
        """Start fetching; startable inputs are auto-started by the runtime
        task unless the processor opts out."""

    @abc.abstractmethod
    def get_reader(self) -> Reader: ...

    @abc.abstractmethod
    def handle_events(self, events: Sequence[TezAPIEvent]) -> None: ...

    @abc.abstractmethod
    def close(self) -> List[TezAPIEvent]: ...


class LogicalOutput(abc.ABC):
    """Reference: AbstractLogicalOutput.java."""

    def __init__(self, context: OutputContext, num_physical_outputs: int):
        self.context = context
        self.num_physical_outputs = num_physical_outputs

    @abc.abstractmethod
    def initialize(self) -> List[TezAPIEvent]: ...

    def start(self) -> None: ...

    @abc.abstractmethod
    def get_writer(self) -> Writer: ...

    @abc.abstractmethod
    def handle_events(self, events: Sequence[TezAPIEvent]) -> None: ...

    @abc.abstractmethod
    def close(self) -> List[TezAPIEvent]: ...


class LogicalIOProcessor(abc.ABC):
    """Reference: LogicalIOProcessor.java / AbstractLogicalIOProcessor."""

    def __init__(self, context: ProcessorContext):
        self.context = context

    @abc.abstractmethod
    def initialize(self) -> None: ...

    @abc.abstractmethod
    def run(self, inputs: Dict[str, LogicalInput],
            outputs: Dict[str, LogicalOutput]) -> None: ...

    def handle_events(self, events: Sequence[TezAPIEvent]) -> None:
        pass

    @abc.abstractmethod
    def close(self) -> None: ...


class MergedLogicalInput(LogicalInput):
    """Combines several constituent inputs of a vertex-group edge into one
    logical view (reference: MergedLogicalInput.java)."""

    def __init__(self, context: InputContext, inputs: List[LogicalInput]):
        super().__init__(context, len(inputs))
        self.inputs = inputs
        self._ready = 0

    def initialize(self) -> List[TezAPIEvent]:
        return []

    def handle_events(self, events: Sequence[TezAPIEvent]) -> None:
        pass

    def close(self) -> List[TezAPIEvent]:
        return []

    def is_started(self) -> bool:
        return self._ready == len(self.inputs)

    def set_constituent_ready(self) -> None:
        self._ready += 1


class ObjectRegistry:
    """Per-container object cache keyed by lifetime scope.

    Reference: tez-runtime-internals/.../objectregistry/ObjectRegistryImpl —
    cache survives across tasks in a reused container (on TPU: across tasks
    in a runner process, e.g. compiled-kernel caches).
    """
    VERTEX = "vertex"
    DAG = "dag"
    SESSION = "session"

    def __init__(self) -> None:
        self._store: Dict[str, Dict[str, Any]] = {
            self.VERTEX: {}, self.DAG: {}, self.SESSION: {}}

    def add(self, scope: str, key: str, value: Any) -> Any:
        prev = self._store[scope].get(key)
        self._store[scope][key] = value
        return prev

    def get(self, key: str) -> Any:
        for scope in (self.VERTEX, self.DAG, self.SESSION):
            if key in self._store[scope]:
                return self._store[scope][key]
        return None

    def delete(self, key: str) -> bool:
        for scope in (self.VERTEX, self.DAG, self.SESSION):
            if self._store[scope].pop(key, None) is not None:
                return True
        return False

    def clear_scope(self, scope: str) -> None:
        self._store[scope].clear()


class TaskFailureType:
    """Reference: tez-api/.../runtime/api/TaskFailureType.java."""
    NON_FATAL = "NON_FATAL"   # retry up to max attempts
    FATAL = "FATAL"           # fail the task (and DAG) immediately
