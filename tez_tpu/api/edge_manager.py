"""EdgeManagerPlugin SPI — custom routing of events/partitions per edge.

Reference parity: tez-api/.../dag/api/EdgeManagerPlugin.java:36 and
EdgeManagerPluginOnDemand.java:41.  The on-demand (pull) variant is the
primary SPI here — SURVEY.md §7 ("adopt on-demand event routing from the
start, not broadcast routing") — the legacy push variant is layered on top.
"""
from __future__ import annotations

import abc
import dataclasses
from typing import Any, Dict, List, Optional, Sequence

from tez_tpu.common.payload import UserPayload


class EdgeManagerPluginContext(abc.ABC):
    @property
    @abc.abstractmethod
    def source_vertex_name(self) -> str: ...

    @property
    @abc.abstractmethod
    def destination_vertex_name(self) -> str: ...

    @property
    @abc.abstractmethod
    def source_vertex_num_tasks(self) -> int: ...

    @property
    @abc.abstractmethod
    def destination_vertex_num_tasks(self) -> int: ...

    @property
    @abc.abstractmethod
    def user_payload(self) -> UserPayload: ...


@dataclasses.dataclass(frozen=True)
class EventRouteMetadata:
    """Where a source event lands at one destination task.

    Reference: EdgeManagerPluginOnDemand.EventRouteMetadata — num_events
    copies delivered at the given target indices; target_index_to_send is the
    source-output index the consumer should fetch.
    """
    num_events: int
    target_indices: Sequence[int]
    target_index_to_send: Sequence[int] = ()


class EdgeManagerPluginOnDemand(abc.ABC):
    """Pull-based routing: the AM asks, per (source task, dest task) pair,
    how events route — avoiding O(src*dst) event materialization."""

    def __init__(self, context: EdgeManagerPluginContext):
        self.context = context

    @abc.abstractmethod
    def initialize(self) -> None: ...

    def prepare_for_routing(self) -> None:
        pass

    # -- sizing -------------------------------------------------------------
    @abc.abstractmethod
    def get_num_destination_task_physical_inputs(self, dest_task: int) -> int: ...

    @abc.abstractmethod
    def get_num_source_task_physical_outputs(self, src_task: int) -> int: ...

    @abc.abstractmethod
    def get_num_destination_consumer_tasks(self, src_task: int) -> int:
        """How many consumers read src_task's output (failure accounting)."""

    # -- routing ------------------------------------------------------------
    @abc.abstractmethod
    def route_data_movement_event_to_destination(
            self, src_task: int, src_output_index: int,
            dest_task: int) -> Optional[EventRouteMetadata]: ...

    @abc.abstractmethod
    def route_composite_data_movement_event_to_destination(
            self, src_task: int, dest_task: int
    ) -> Optional["CompositeEventRouteMetadata"]: ...

    @abc.abstractmethod
    def route_input_source_task_failed_event_to_destination(
            self, src_task: int, dest_task: int) -> Optional[EventRouteMetadata]: ...

    @abc.abstractmethod
    def route_input_error_event_to_source(self, dest_task: int,
                                          dest_failed_input_index: int) -> int:
        """Map a consumer's failed input index back to the producer task."""


@dataclasses.dataclass(frozen=True)
class CompositeEventRouteMetadata:
    count: int
    target: int      # first target input index at the destination
    source: int      # first source partition index


class EdgeManagerPlugin(EdgeManagerPluginOnDemand):
    """Legacy push-style SPI (reference: EdgeManagerPlugin.java:36) expressed
    over the on-demand base: subclasses implement routeDataMovementEvent...
    writing into a target map."""

    @abc.abstractmethod
    def route_data_movement_event_to_destination_map(
            self, event_source_index: int, src_task: int,
            target: Dict[int, List[int]]) -> None:
        """Fill {dest_task: [input indices]} (reference signature)."""

    def route_data_movement_event_to_destination(
            self, src_task: int, src_output_index: int,
            dest_task: int) -> Optional[EventRouteMetadata]:
        target: Dict[int, List[int]] = {}
        self.route_data_movement_event_to_destination_map(
            src_output_index, src_task, target)
        idx = target.get(dest_task)
        if not idx:
            return None
        return EventRouteMetadata(len(idx), idx)
