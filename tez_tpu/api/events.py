"""Typed events routed AM<->task and task->task (logically).

Reference parity: tez-api/.../runtime/api/events/ (12 classes) and
Events.proto:23-79.  The DataMovementEvent payload carries the shuffle
manifest info (ShufflePayloads.proto DataMovementEventPayloadProto):
host/port identify the producer's runner, path_component names the output,
empty_partitions is a bitmap eliding zero-size partitions client-side
(SURVEY.md §5.8).
"""
from __future__ import annotations

import dataclasses
from typing import Any, Optional, Tuple


class TezAPIEvent:
    """Base for user-visible events (distinct from dispatcher control events)."""


@dataclasses.dataclass
class DataMovementEvent(TezAPIEvent):
    """Producer output ready for one target partition/index.

    Reference: DataMovementEvent.java; source_index = producer's output
    partition number, target_index = consumer input index (filled by the
    AM-side edge manager during routing)."""
    source_index: int
    user_payload: Any = None
    target_index: int = -1
    version: int = 0    # producer attempt number

    def with_target(self, target_index: int) -> "DataMovementEvent":
        return dataclasses.replace(self, target_index=target_index)


@dataclasses.dataclass
class CompositeDataMovementEvent(TezAPIEvent):
    """One event covering a contiguous range of source partitions
    (reference: CompositeDataMovementEvent.java — avoids P events per task)."""
    source_index_start: int
    count: int
    user_payload: Any = None
    version: int = 0

    def expand(self) -> Tuple[DataMovementEvent, ...]:
        return tuple(
            DataMovementEvent(self.source_index_start + i, self.user_payload,
                              version=self.version)
            for i in range(self.count))


@dataclasses.dataclass
class CompositeRoutedDataMovementEvent(TezAPIEvent):
    """Routed composite delivered to a consumer that reads a partition range
    (reference: CompositeRoutedDataMovementEvent.java, on-demand routing)."""
    source_index: int
    target_index_start: int
    count: int
    user_payload: Any = None
    version: int = 0


@dataclasses.dataclass
class InputReadErrorEvent(TezAPIEvent):
    """Consumer failed to fetch a producer output; AM fails the *producer*
    attempt (reference: InputReadErrorEvent.java, Events.proto:38)."""
    diagnostics: str
    index: int            # consumer input index that failed
    version: int          # producer attempt number
    num_failures: int = 1
    is_local_fetch: bool = False
    is_disk_error_at_source: bool = False


@dataclasses.dataclass
class InputFailedEvent(TezAPIEvent):
    """AM -> consumer: a previously announced input is gone (producer being
    re-run); consumer must discard/re-wait (reference: InputFailedEvent.java)."""
    target_index: int
    version: int


@dataclasses.dataclass
class VertexManagerEvent(TezAPIEvent):
    """Task -> its vertex's VertexManagerPlugin (stats for auto-parallelism;
    reference: VertexManagerEvent.java + VertexManagerEventPayloadProto)."""
    target_vertex_name: str
    user_payload: Any
    producer_attempt: Any = None
    producer_vertex_name: str = ""   # filled by the AM during routing


@dataclasses.dataclass
class InputDataInformationEvent(TezAPIEvent):
    """InputInitializer -> root input tasks: one split description each
    (reference: InputDataInformationEvent.java)."""
    source_index: int
    user_payload: Any = None
    target_index: int = -1
    serialized_path: str = ""


@dataclasses.dataclass
class InputInitializerEvent(TezAPIEvent):
    """Running task -> an InputInitializer of another vertex
    (reference: InputInitializerEvent.java)."""
    target_vertex_name: str
    target_input_name: str
    user_payload: Any = None


@dataclasses.dataclass
class CustomProcessorEvent(TezAPIEvent):
    """Processor -> processor free-form event (reference:
    CustomProcessorEvent.java)."""
    user_payload: Any
    version: int = 0


@dataclasses.dataclass
class ErrorEvent(TezAPIEvent):
    """Fatal error reported by a task component."""
    diagnostics: str


@dataclasses.dataclass(frozen=True)
class EventMetaData:
    """Source/destination envelope for a routed event.

    Reference: tez-runtime-internals/.../runtime/api/impl/EventMetaData.java
    (producer_consumer_type, taskVertexName, edgeVertexName, taskAttemptID)."""
    producer_consumer_type: str   # "INPUT"|"PROCESSOR"|"OUTPUT"|"SYSTEM"
    task_vertex_name: str
    edge_vertex_name: str = ""
    task_attempt_id: Any = None


@dataclasses.dataclass
class TezEvent:
    """Wire envelope: user event + routing metadata (reference:
    runtime/api/impl/TezEvent.java:63)."""
    event: TezAPIEvent
    source_info: Optional[EventMetaData] = None
    destination_info: Optional[EventMetaData] = None
    event_received_time: float = 0.0


# ---------------------------------------------------------------------------
# DME payload: the shuffle manifest shipped inside DataMovementEvents.
# Reference: ShufflePayloads.proto DataMovementEventPayloadProto:23 —
# host, port, path_component, run_duration, empty_partitions bitmap,
# + pipelined-shuffle spill bookkeeping (spill_id, last_event).
# ---------------------------------------------------------------------------
@dataclasses.dataclass(frozen=True)
class ShufflePayload:
    host: str
    port: int
    path_component: str          # producer attempt's output id
    empty_partitions: Optional[bytes] = None  # packed bitset; None = none empty
    spill_id: int = -1           # >=0 when pipelined shuffle emits per-spill
    last_event: bool = True
    run_duration: int = 0

    def is_empty(self, partition: int) -> bool:
        bm = self.empty_partitions
        if bm is None:
            return False
        byte_i, bit_i = divmod(partition, 8)
        if byte_i >= len(bm):
            return False
        return bool(bm[byte_i] & (1 << bit_i))


def pack_empty_partitions(flags) -> Optional[bytes]:
    """Pack per-partition emptiness into a bitset; None if nothing empty."""
    if not any(flags):
        return None
    out = bytearray((len(flags) + 7) // 8)
    for i, f in enumerate(flags):
        if f:
            out[i // 8] |= 1 << (i % 8)
    return bytes(out)
