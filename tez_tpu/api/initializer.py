"""InputInitializer and OutputCommitter SPIs.

Reference parity: tez-api/.../runtime/api/InputInitializer.java:36 (AM-side
root-input planning: compute splits -> InputDataInformationEvents + suggested
parallelism) and OutputCommitter.java (per-output commit/abort, optionally
deferred to DAG success).
"""
from __future__ import annotations

import abc
import dataclasses
from typing import Any, List, Optional, Sequence

from tez_tpu.api.events import (InputDataInformationEvent,
                                InputInitializerEvent)
from tez_tpu.common.payload import UserPayload


class InputInitializerContext(abc.ABC):
    @property
    @abc.abstractmethod
    def input_name(self) -> str: ...

    @property
    @abc.abstractmethod
    def vertex_name(self) -> str: ...

    @property
    @abc.abstractmethod
    def dag_name(self) -> str: ...

    @property
    @abc.abstractmethod
    def user_payload(self) -> UserPayload:
        """The initializer descriptor's payload."""

    @property
    def input_user_payload(self) -> UserPayload:
        """The input descriptor's payload (reference:
        InputInitializerContext.getInputUserPayload)."""
        return UserPayload()

    @property
    def conf(self) -> Any:
        """Effective vertex/DAG configuration.  (The reference delivers
        conf to split generators inside MRInputUserPayload; exposing it on
        the context lets tez.grouping.* keys work without payload
        plumbing.)"""
        return {}

    @property
    @abc.abstractmethod
    def num_tasks(self) -> int:
        """Vertex parallelism as declared (-1 = initializer decides)."""

    @abc.abstractmethod
    def get_total_available_resource(self) -> int: ...

    @abc.abstractmethod
    def get_vertex_num_tasks(self, vertex_name: str) -> int: ...

    @abc.abstractmethod
    def register_for_vertex_state_updates(self, vertex_name: str,
                                          states: Sequence[str]) -> None: ...


@dataclasses.dataclass
class InputConfigureVertexTasksEvent:
    """Initializer asks the framework to set parallelism
    (reference: events/InputConfigureVertexTasksEvent.java)."""
    num_tasks: int
    location_hints: Any = None


class InputInitializer(abc.ABC):
    """Reference: InputInitializer.java:36."""

    def __init__(self, context: InputInitializerContext):
        self.context = context

    @abc.abstractmethod
    def initialize(self) -> List[Any]:
        """Returns a list of events: InputDataInformationEvent per split and
        optionally one InputConfigureVertexTasksEvent."""

    def handle_input_initializer_event(
            self, events: List[InputInitializerEvent]) -> None:
        pass

    def on_vertex_state_updated(self, update: Any) -> None:
        pass


class OutputCommitterContext(abc.ABC):
    @property
    @abc.abstractmethod
    def output_name(self) -> str: ...

    @property
    @abc.abstractmethod
    def vertex_name(self) -> str: ...

    @property
    @abc.abstractmethod
    def user_payload(self) -> UserPayload: ...


class SimpleCommitterContext(OutputCommitterContext):
    """Concrete committer identity used by the AM (and by recovery, which
    rebuilds committers straight from the plan).  Carries the owning AM
    incarnation so committers can fence filesystem mutations against a
    restarted AM (0 = unstamped/legacy)."""

    def __init__(self, output_name: str, vertex_name: str, payload: Any,
                 app_id: str = "", am_epoch: int = 0):
        self._o, self._v, self._p = output_name, vertex_name, payload
        self.app_id = app_id
        self.am_epoch = am_epoch

    @property
    def output_name(self) -> str:
        return self._o

    @property
    def vertex_name(self) -> str:
        return self._v

    @property
    def user_payload(self) -> Any:
        return self._p


class OutputCommitter(abc.ABC):
    """Reference: OutputCommitter.java."""

    def __init__(self, context: OutputCommitterContext):
        self.context = context

    @abc.abstractmethod
    def initialize(self) -> None: ...

    @abc.abstractmethod
    def setup_output(self) -> None: ...

    @abc.abstractmethod
    def commit_output(self) -> None: ...

    @abc.abstractmethod
    def abort_output(self, final_state: str) -> None: ...

    def is_task_recovery_supported(self) -> bool:
        return False

    def recover_task(self, task_index: int, previous_dag_attempt: int) -> None:
        pass
