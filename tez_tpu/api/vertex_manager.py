"""VertexManagerPlugin SPI — the AM-side per-vertex brain.

Reference parity: tez-api/.../dag/api/VertexManagerPlugin.java:41 and
VertexManagerPluginContext.java (reconfigureVertex :203/:228/:253,
scheduleTasks, getVertexStatistics...).
"""
from __future__ import annotations

import abc
import dataclasses
from typing import Any, Dict, List, Optional, Sequence

from tez_tpu.api.events import InputDataInformationEvent, VertexManagerEvent
from tez_tpu.common.payload import EdgeManagerPluginDescriptor, UserPayload
from tez_tpu.dag.edge_property import EdgeProperty


@dataclasses.dataclass(frozen=True)
class ScheduleTaskRequest:
    """Reference: VertexManagerPluginContext.ScheduleTaskRequest."""
    task_index: int
    locality_hint: Any = None


@dataclasses.dataclass(frozen=True)
class VertexLocationHint:
    hints: Sequence[Any] = ()


class VertexManagerPluginContext(abc.ABC):
    @property
    @abc.abstractmethod
    def vertex_name(self) -> str: ...

    @property
    @abc.abstractmethod
    def user_payload(self) -> UserPayload: ...

    @abc.abstractmethod
    def get_vertex_num_tasks(self, vertex_name: str) -> int: ...

    @abc.abstractmethod
    def get_input_vertex_edge_properties(self) -> Dict[str, EdgeProperty]: ...

    @abc.abstractmethod
    def get_output_vertex_edge_properties(self) -> Dict[str, EdgeProperty]: ...

    @abc.abstractmethod
    def get_input_vertex_groups(self) -> Dict[str, Sequence[str]]: ...

    @abc.abstractmethod
    def schedule_tasks(self, requests: Sequence[ScheduleTaskRequest]) -> None: ...

    @abc.abstractmethod
    def reconfigure_vertex(self, parallelism: int,
                           location_hint: Optional[VertexLocationHint] = None,
                           source_edge_properties: Optional[
                               Dict[str, EdgeProperty]] = None,
                           root_input_specs: Optional[Dict[str, Any]] = None
                           ) -> None:
        """Change parallelism / edge routing before tasks run
        (reference: VertexManagerPluginContext.java:203)."""

    @abc.abstractmethod
    def vertex_reconfiguration_planned(self) -> None:
        """Tell the framework to defer task creation visibility until
        doneReconfiguringVertex (reference :287)."""

    @abc.abstractmethod
    def done_reconfiguring_vertex(self) -> None: ...

    def vertex_reconfiguration_restored(self) -> bool:
        """True when a recovering AM already re-applied a journaled
        reconfiguration of this vertex — the manager must NOT re-decide
        parallelism (reference: recovered VertexConfigurationDoneEvent)."""
        return False

    def get_vertex_conf(self) -> Dict[str, Any]:
        """The managed vertex's effective configuration (DAG conf merged
        with the vertex plan conf).  Lets payload-less default managers
        honor runtime knobs — e.g. the shuffle manager's push ingest
        mode.  Default: empty (test/standalone contexts)."""
        return {}

    @abc.abstractmethod
    def send_event_to_processor(self, events: Sequence[Any],
                                task_indices: Sequence[int]) -> None: ...

    @abc.abstractmethod
    def add_root_input_events(
            self, input_name: str,
            events: Sequence[InputDataInformationEvent]) -> None: ...

    @abc.abstractmethod
    def get_total_available_resource(self) -> int:
        """Cluster slots available (for wave sizing)."""

    @abc.abstractmethod
    def register_for_vertex_state_updates(self, vertex_name: str,
                                          states: Sequence[str]) -> None: ...


@dataclasses.dataclass(frozen=True)
class TaskAttemptIdentifier:
    """Reference: tez-api TaskAttemptIdentifier."""
    vertex_name: str
    task_index: int
    attempt_number: int


@dataclasses.dataclass(frozen=True)
class VertexStateUpdate:
    vertex_name: str
    state: str      # CONFIGURED | RUNNING | SUCCEEDED | FAILED | KILLED


class VertexManagerPlugin(abc.ABC):
    """Reference: VertexManagerPlugin.java:41."""

    def __init__(self, context: VertexManagerPluginContext):
        self.context = context

    @abc.abstractmethod
    def initialize(self) -> None: ...

    @abc.abstractmethod
    def on_vertex_started(
            self, completions: Sequence[TaskAttemptIdentifier]) -> None: ...

    @abc.abstractmethod
    def on_source_task_completed(
            self, attempt: TaskAttemptIdentifier) -> None: ...

    @abc.abstractmethod
    def on_vertex_manager_event_received(
            self, event: VertexManagerEvent) -> None: ...

    @abc.abstractmethod
    def on_root_vertex_initialized(
            self, input_name: str, input_descriptor: Any,
            events: List[InputDataInformationEvent]) -> None: ...

    def on_vertex_state_updated(self, update: VertexStateUpdate) -> None:
        pass
