"""Metrics exposition: label-aware Prometheus text + structured JSON.

``common/metrics.py`` keeps series under *flat dynamic names* —
``tenant.<t>.dag.latency``, ``stream.<s>.window.latency``,
``mesh.lane.<i>.occupancy`` — because the hot path must not allocate
label dicts.  This module is where those names become labels: at scrape
time (cold path, one caller) each name is split into a base family plus
``tenant=`` / ``stream=`` / ``lane=`` labels, so one dashboard query
aggregates across tenants instead of matching a regex over metric names,
and ``GET /metrics?tenant=a`` serves a per-tenant drill-down without the
planes ever knowing labels exist.

Rendering is deterministic by construction — families sorted by name,
label sets sorted by label items, no timestamps in the text format — so
the exposition is golden-testable byte-for-byte (tests/golden/).
:func:`parse_exposition` is the other half of that contract: the strict
parser the golden test and ``make metrics-smoke`` run against a live
scrape, validating label escaping, cumulative bucket monotonicity and
count/sum consistency.  See docs/telemetry.md.
"""
from __future__ import annotations

from typing import Any, Dict, List, Mapping, Optional, Tuple

from tez_tpu.common.metrics import (BUCKET_BOUNDS_MS, HIST_GROUP_PREFIX,
                                    Histogram, _escape_label, _sanitize,
                                    quantile_from_buckets)

#: ``stream.window.*`` is the session-wide aggregate family the
#: StreamDriver always feeds; every OTHER ``stream.<x>.…`` name is a
#: per-stream series (a stream literally named "window" would collide —
#: the StreamDriver rejects it at open_stream).
_STREAM_AGGREGATE_SEGMENT = "window"


def split_labels(name: str) -> Tuple[str, Dict[str, str]]:
    """Flat dynamic series name -> (base family, labels)."""
    parts = name.split(".")
    if parts[0] == "tenant" and len(parts) >= 3:
        return "tenant." + ".".join(parts[2:]), {"tenant": parts[1]}
    if parts[0] == "stream" and len(parts) >= 3 \
            and parts[1] != _STREAM_AGGREGATE_SEGMENT:
        return "stream." + ".".join(parts[2:]), {"stream": parts[1]}
    if parts[0] == "mesh" and len(parts) >= 4 and parts[1] == "lane":
        return "mesh.lane." + ".".join(parts[3:]), {"lane": parts[2]}
    return name, {}


def _keep(labels: Dict[str, str], tenant: Optional[str],
          stream: Optional[str]) -> bool:
    """Drill-down filter: a tenant= / stream= query keeps only the series
    carrying that label value."""
    if tenant is not None and labels.get("tenant") != tenant:
        return False
    if stream is not None and labels.get("stream") != stream:
        return False
    return True


def _label_str(labels: Mapping[str, str], extra: str = "") -> str:
    parts = [f'{k}="{_escape_label(v)}"'
             for k, v in sorted(labels.items())]
    if extra:
        parts.append(extra)
    return "{" + ",".join(parts) + "}" if parts else ""


# --------------------------------------------------------------------------
# Prometheus text (version 0.0.4), label-aware
# --------------------------------------------------------------------------

def render_text(histograms: Mapping[str, Histogram],
                gauges: Mapping[str, float],
                counters_dict: Optional[Mapping[str,
                                                Mapping[str, int]]] = None,
                tenant: Optional[str] = None,
                stream: Optional[str] = None) -> str:
    """The GET /metrics body: every histogram family with cumulative
    le-buckets, every gauge, every plain counter — dynamic names split
    into labels, everything sorted, nothing time-dependent beyond the
    sampled values themselves."""
    filtering = tenant is not None or stream is not None
    hist_fams: Dict[str, List[Tuple[Dict[str, str], Histogram]]] = {}
    for name in histograms:
        base, labels = split_labels(name)
        if not _keep(labels, tenant, stream):
            continue
        hist_fams.setdefault(base, []).append((labels, histograms[name]))
    gauge_fams: Dict[str, List[Tuple[Dict[str, str], float]]] = {}
    for name in gauges:
        base, labels = split_labels(name)
        if not _keep(labels, tenant, stream):
            continue
        gauge_fams.setdefault(base, []).append((labels, gauges[name]))

    lines: List[str] = []
    for base in sorted(hist_fams):
        metric = f"tez_latency_{_sanitize(base)}_ms"
        lines.append(f"# HELP {metric} latency histogram for {base}")
        lines.append(f"# TYPE {metric} histogram")
        for labels, h in sorted(hist_fams[base],
                                key=lambda lh: sorted(lh[0].items())):
            cum = 0
            for i, bound in enumerate(BUCKET_BOUNDS_MS):
                cum += h.counts[i]
                le = 'le="%g"' % bound
                lines.append(
                    f"{metric}_bucket{_label_str(labels, le)} {cum}")
            cum += h.counts[-1]
            inf = 'le="+Inf"'
            lines.append(
                f"{metric}_bucket{_label_str(labels, inf)} {cum}")
            lines.append(f"{metric}_sum{_label_str(labels)} {h.sum_ms:g}")
            lines.append(f"{metric}_count{_label_str(labels)} {h.count}")
    for base in sorted(gauge_fams):
        metric = f"tez_{_sanitize(base)}"
        lines.append(f"# TYPE {metric} gauge")
        for labels, v in sorted(gauge_fams[base],
                                key=lambda lv: sorted(lv[0].items())):
            lines.append(f"{metric}{_label_str(labels)} {v:g}")
    if counters_dict and not filtering:
        lines.append("# HELP tez_counter Tez counter value")
        lines.append("# TYPE tez_counter gauge")
        for gname in sorted(counters_dict):
            if gname.startswith(HIST_GROUP_PREFIX):
                continue          # rendered as histogram families above
            for cname in sorted(counters_dict[gname]):
                lines.append(
                    f'tez_counter{{group="{_escape_label(gname)}",'
                    f'name="{_escape_label(cname)}"}} '
                    f"{counters_dict[gname][cname]}")
    return "\n".join(lines) + "\n"


# --------------------------------------------------------------------------
# Structured JSON (GET /metrics.json)
# --------------------------------------------------------------------------

def render_json(histograms: Mapping[str, Histogram],
                gauges: Mapping[str, float],
                windows: Optional[Mapping[str, Dict[str, Any]]] = None,
                accounting: Optional[Mapping[str, int]] = None,
                window_s: float = 0.0,
                tenant: Optional[str] = None,
                stream: Optional[str] = None) -> Dict[str, Any]:
    """The GET /metrics.json body: the same families as the text format
    plus derived quantiles, the windowed aggregates the time-series
    registry computed (when given), and the telemetry plane's own
    overflow accounting."""
    hist_rows: List[Dict[str, Any]] = []
    for name in sorted(histograms):
        base, labels = split_labels(name)
        if not _keep(labels, tenant, stream):
            continue
        h = histograms[name]
        row: Dict[str, Any] = {
            "name": base, "labels": labels, "series": name,
            "count": h.count, "sum_ms": round(h.sum_ms, 4),
            "p50": round(quantile_from_buckets(h.counts, 0.50), 4),
            "p95": round(quantile_from_buckets(h.counts, 0.95), 4),
            "p99": round(quantile_from_buckets(h.counts, 0.99), 4),
        }
        if windows is not None and name in windows and windows[name]:
            row["window"] = windows[name]
        hist_rows.append(row)
    gauge_rows: List[Dict[str, Any]] = []
    for name in sorted(gauges):
        base, labels = split_labels(name)
        if not _keep(labels, tenant, stream):
            continue
        row = {"name": base, "labels": labels, "series": name,
               "value": gauges[name]}
        if windows is not None and name in windows and windows[name]:
            row["window"] = windows[name]
        gauge_rows.append(row)
    out: Dict[str, Any] = {"histograms": hist_rows, "gauges": gauge_rows}
    if window_s:
        out["window_s"] = window_s
    if accounting is not None:
        out["accounting"] = dict(accounting)
    return out


# --------------------------------------------------------------------------
# The golden parser (tests/golden + make metrics-smoke)
# --------------------------------------------------------------------------

def _parse_labels(raw: str) -> Dict[str, str]:
    labels: Dict[str, str] = {}
    i = 0
    while i < len(raw):
        eq = raw.index("=", i)
        key = raw[i:eq]
        if raw[eq + 1] != '"':
            raise ValueError(f"unquoted label value after {key!r}")
        j = eq + 2
        val: List[str] = []
        while True:
            ch = raw[j]
            if ch == "\\":
                nxt = raw[j + 1]
                val.append({"n": "\n", "\\": "\\", '"': '"'}.get(nxt, nxt))
                j += 2
            elif ch == '"':
                break
            else:
                val.append(ch)
                j += 1
        labels[key] = "".join(val)
        i = j + 1
        if i < len(raw) and raw[i] == ",":
            i += 1
    return labels


def parse_exposition(text: str) -> Dict[str, Dict[str, Any]]:
    """Strictly parse a Prometheus 0.0.4 text body into
    ``{family: {"type": str, "samples": [(name, labels, value)]}}``,
    validating the invariants the golden test and metrics-smoke rely on:
    every sample's family was TYPE-declared, every histogram's le-buckets
    are cumulative (non-decreasing, ending at ``+Inf`` == ``_count``),
    and label values round-trip the escaping rules.  Raises ValueError on
    the first violation."""
    fams: Dict[str, Dict[str, Any]] = {}
    for lineno, line in enumerate(text.splitlines(), 1):
        if not line.strip():
            continue
        if line.startswith("# TYPE "):
            _, _, rest = line.partition("# TYPE ")
            fam, _, ftype = rest.partition(" ")
            if fam in fams:
                raise ValueError(f"line {lineno}: duplicate TYPE for {fam}")
            fams[fam] = {"type": ftype.strip(), "samples": []}
            continue
        if line.startswith("#"):
            continue
        brace = line.find("{")
        if brace >= 0:
            name = line[:brace]
            close = line.rindex("}")
            labels = _parse_labels(line[brace + 1:close])
            value_str = line[close + 1:].strip()
        else:
            name, _, value_str = line.partition(" ")
            labels = {}
        try:
            value = float(value_str)
        except ValueError:
            raise ValueError(
                f"line {lineno}: unparsable value {value_str!r}") from None
        fam = name
        for suffix in ("_bucket", "_sum", "_count"):
            if name.endswith(suffix) and name[:-len(suffix)] in fams:
                fam = name[:-len(suffix)]
                break
        if fam not in fams:
            raise ValueError(f"line {lineno}: sample {name} has no TYPE")
        fams[fam]["samples"].append((name, labels, value))
    # histogram invariants: per label set, buckets cumulative + consistent
    for fam, info in fams.items():
        if info["type"] != "histogram":
            continue
        by_labelset: Dict[Tuple, Dict[str, Any]] = {}
        for name, labels, value in info["samples"]:
            key = tuple(sorted((k, v) for k, v in labels.items()
                               if k != "le"))
            slot = by_labelset.setdefault(
                key, {"buckets": [], "sum": None, "count": None})
            if name.endswith("_bucket"):
                slot["buckets"].append((labels.get("le", ""), value))
            elif name.endswith("_sum"):
                slot["sum"] = value
            elif name.endswith("_count"):
                slot["count"] = value
        for key, slot in by_labelset.items():
            buckets = slot["buckets"]
            if not buckets or buckets[-1][0] != "+Inf":
                raise ValueError(f"{fam}{dict(key)}: no terminal +Inf bucket")
            values = [v for _, v in buckets]
            if any(b > a for b, a in zip(values, values[1:])):
                raise ValueError(f"{fam}{dict(key)}: buckets not cumulative")
            if slot["count"] is None or slot["sum"] is None:
                raise ValueError(f"{fam}{dict(key)}: missing _count/_sum")
            if slot["count"] != values[-1]:
                raise ValueError(
                    f"{fam}{dict(key)}: _count {slot['count']} != +Inf "
                    f"bucket {values[-1]}")
    return fams
