"""Per-tenant SLO watchdogs: declarative targets evaluated live from the
metrics registry, surfaced as breach gauges, ``GET /slo``, a typed
history event, and flight-recorder entries.

Targets (``tez.am.slo.*``, all disabled at 0):

- ``submit.p95-ms`` — per-tenant p95 submit→finish wall, read from the
  ``tenant.<t>.dag.latency`` histogram the admission controller already
  feeds on every DAG completion;
- ``queue-wait.p95-ms`` — p95 of ``am.admit.queue_wait`` (session-wide:
  the queue is one FIFO, so queue wait is a property of the session, not
  a tenant — reported under tenant ``*``);
- ``shed-rate`` — shed / (accepted + shed) per tenant, from the
  admission controller's live tenant stats;
- ``window.p95-ms`` — p95 window cut→commit latency of streaming mode,
  checked against BOTH the session-wide ``stream.window.latency``
  aggregate AND every per-stream ``stream.<s>.window.latency`` histogram
  the StreamDriver feeds, so one slow stream pages by name instead of
  hiding inside the session aggregate.

Breach evaluation is *edge-triggered and latched*: a (tenant, kind,
stream) triple breaches once when it crosses its target and clears once
when it drops back under, so chaos/soak assertions see one typed
``TENANT_SLO_BREACH`` history event per episode instead of one per DAG.
Every breach record carries ``tenant``/``kind``/``stream`` — the same
labels the time-series plane splits out at exposition — so doctor can
join breaches against burn alerts and windowed series per stream.
``tez.am.slo.min-count`` guards against declaring a breach off a single
observation.

**Burn-rate alerting** (``tez.am.slo.burn.*``, docs/telemetry.md): the
cumulative histograms above answer "is the SLO breached?" but are
dominated by history — a stream that just turned slow will not move a
10-minute-old cumulative p95 for a long time.  :meth:`evaluate_burn`
runs on the telemetry sampler's ticks against the *windowed* aggregates
of :mod:`tez_tpu.obs.timeseries`: when a fast-window p95 crosses
``threshold × target`` it latches a typed ``SLO_BURN_ALERT`` history
event plus a flight MARK — strictly before the cumulative breach on a
ramping workload, which is the point: the alert fires while there is
still error budget left.  The latch clears only when the *slow* window
drops back under the threshold (multi-window hysteresis, SRE-workbook
style), so an oscillating series pages once per episode.  Burn
evaluation covers the p95 latency targets (submit / queue-wait /
window); shed-rate stays breach-only — it is already a rate.

The watchdog is deliberately pull-based — it recomputes from histograms
the planes already maintain, on the admission controller's completion/
shed ticks (breach) and the telemetry sampler's ticks (burn) — so it
adds no new lock ordering and costs nothing between ticks.
"""
from __future__ import annotations

import logging
import threading
from typing import Any, Dict, List, Optional, Tuple

from tez_tpu.common import clock

log = logging.getLogger(__name__)

#: bounded breach/burn transition log kept for GET /slo
_HISTORY_LIMIT = 64

KIND_SUBMIT = "submit_p95_ms"
KIND_QUEUE_WAIT = "queue_wait_p95_ms"
KIND_SHED_RATE = "shed_rate"
KIND_WINDOW = "window_p95_ms"

#: suffix of the per-stream window-latency series the StreamDriver feeds
_STREAM_HIST_SUFFIX = ".window.latency"


class SloWatchdog:
    """Evaluates ``tez.am.slo.*`` targets against live histograms."""

    def __init__(self, conf: Any, journal: Any = None) -> None:
        from tez_tpu.common import config as C
        self.submit_p95_ms = float(conf.get(C.AM_SLO_SUBMIT_P95_MS) or 0.0)
        self.queue_wait_p95_ms = float(
            conf.get(C.AM_SLO_QUEUE_WAIT_P95_MS) or 0.0)
        self.shed_rate = float(conf.get(C.AM_SLO_SHED_RATE) or 0.0)
        self.window_p95_ms = float(conf.get(C.AM_SLO_WINDOW_P95_MS) or 0.0)
        self.min_count = max(1, int(conf.get(C.AM_SLO_MIN_COUNT) or 1))
        self.burn_threshold = float(
            conf.get(C.AM_SLO_BURN_THRESHOLD) or 0.0)
        self.burn_fast_s = float(conf.get(C.AM_SLO_BURN_FAST_S) or 5.0)
        self.burn_slow_s = float(conf.get(C.AM_SLO_BURN_SLOW_S) or 60.0)
        self.burn_min_count = max(
            1, int(conf.get(C.AM_SLO_BURN_MIN_COUNT) or 1))
        self._journal = journal
        self._lock = threading.Lock()
        #: latched active breaches keyed (tenant, kind, stream)
        self._active: Dict[Tuple[str, str, str], Dict[str, Any]] = {}
        #: latched active burn alerts, same key shape
        self._burning: Dict[Tuple[str, str, str], Dict[str, Any]] = {}
        self._log: List[Dict[str, Any]] = []
        self._total = 0
        self._by_kind: Dict[str, int] = {}
        self._burn_total = 0
        self._burn_by_kind: Dict[str, int] = {}
        self._evaluations = 0
        self._burn_evaluations = 0

    def enabled(self) -> bool:
        return (self.submit_p95_ms > 0 or self.queue_wait_p95_ms > 0
                or self.shed_rate > 0 or self.window_p95_ms > 0)

    def burn_enabled(self) -> bool:
        return self.enabled() and self.burn_threshold > 0

    def targets(self) -> Dict[str, float]:
        return {KIND_SUBMIT: self.submit_p95_ms,
                KIND_QUEUE_WAIT: self.queue_wait_p95_ms,
                KIND_SHED_RATE: self.shed_rate,
                KIND_WINDOW: self.window_p95_ms}

    # -- breach evaluation --------------------------------------------------
    def _checks(self, tenant_stats: Dict[str, Dict[str, int]]
                ) -> List[Tuple[str, str, str, float, float]]:
        """(tenant, kind, stream, observed, target) tuples due for
        comparison."""
        from tez_tpu.common import metrics
        hists = metrics.registry().histograms()
        out: List[Tuple[str, str, str, float, float]] = []
        for tenant, ts in sorted(tenant_stats.items()):
            label = tenant or "default"
            if self.submit_p95_ms > 0:
                h = hists.get(f"tenant.{label}.dag.latency")
                if h is not None and h.count >= self.min_count:
                    out.append((label, KIND_SUBMIT, "", h.quantile(0.95),
                                self.submit_p95_ms))
            if self.shed_rate > 0:
                total = int(ts.get("accepted", 0)) + int(ts.get("shed", 0))
                if total >= self.min_count:
                    out.append((label, KIND_SHED_RATE, "",
                                ts.get("shed", 0) / total, self.shed_rate))
        if self.queue_wait_p95_ms > 0:
            h = hists.get("am.admit.queue_wait")
            if h is not None and h.count >= self.min_count:
                out.append(("*", KIND_QUEUE_WAIT, "", h.quantile(0.95),
                            self.queue_wait_p95_ms))
        if self.window_p95_ms > 0:
            for name in sorted(hists):
                stream = _window_series_stream(name)
                if stream is None:
                    continue
                h = hists[name]
                if h.count >= self.min_count:
                    out.append(("*", KIND_WINDOW, stream, h.quantile(0.95),
                                self.window_p95_ms))
        return out

    def evaluate(self, tenant_stats: Dict[str, Dict[str, int]]
                 ) -> List[Dict[str, Any]]:
        """One sweep.  Returns the NEW breaches this sweep latched."""
        if not self.enabled():
            return []
        new: List[Dict[str, Any]] = []
        cleared: List[Dict[str, Any]] = []
        now = clock.wall_s()
        with self._lock:
            self._evaluations += 1
            for tenant, kind, stream, observed, target in \
                    self._checks(tenant_stats):
                key = (tenant, kind, stream)
                over = observed > target
                active = self._active.get(key)
                if over and active is None:
                    breach = {"tenant": tenant, "kind": kind,
                              "stream": stream,
                              "observed": round(observed, 4),
                              "target": target, "since": now}
                    self._active[key] = breach
                    self._total += 1
                    self._by_kind[kind] = self._by_kind.get(kind, 0) + 1
                    new.append(dict(breach))
                elif over:
                    active["observed"] = round(observed, 4)
                elif active is not None:
                    del self._active[key]
                    cleared.append({"tenant": tenant, "kind": kind,
                                    "stream": stream,
                                    "observed": round(observed, 4),
                                    "target": target, "cleared_at": now})
            for entry in new:
                self._log.append(dict(entry, event="breach"))
            for entry in cleared:
                self._log.append(dict(entry, event="clear"))
            del self._log[:-_HISTORY_LIMIT]
            total = self._total
        self._publish(new, cleared, total)
        return new

    def _publish(self, new: List[Dict[str, Any]],
                 cleared: List[Dict[str, Any]], total: int) -> None:
        from tez_tpu.common import metrics
        from tez_tpu.obs import flight
        if new or cleared:
            metrics.set_gauge("slo.breach.total", float(total))
            metrics.set_gauge("slo.breach.active", float(len(self._active)))
        for b in new:
            # a = observed, b = target — micro-units for latencies,
            # basis points for rates, so both fit integer payload slots
            scale = 1000.0 if b["kind"] != KIND_SHED_RATE else 10000.0
            flight.record(flight.SLO, f"slo.breach.{b['kind']}",
                          b["stream"] or b["tenant"],
                          a=int(b["observed"] * scale),
                          b=int(b["target"] * scale))
            log.warning(
                "SLO breach: tenant=%s stream=%s %s observed=%.2f "
                "target=%.2f", b["tenant"], b["stream"] or "-", b["kind"],
                b["observed"], b["target"])
            self._journal_event("TENANT_SLO_BREACH", b)
        for c in cleared:
            flight.record(flight.SLO, f"slo.clear.{c['kind']}",
                          c["stream"] or c["tenant"])

    # -- burn-rate evaluation -----------------------------------------------
    def _burn_series(self) -> List[Tuple[str, str, str, str, float]]:
        """(series, tenant, kind, stream, target) for every latency
        series burn evaluation watches — derived from the time-series
        registry so a series starts being watched on its first sample."""
        from tez_tpu.obs import timeseries
        out: List[Tuple[str, str, str, str, float]] = []
        for name in timeseries.registry().series_names("hist"):
            if self.submit_p95_ms > 0 and name.startswith("tenant.") \
                    and name.endswith(".dag.latency"):
                tenant = name.split(".")[1]
                out.append((name, tenant, KIND_SUBMIT, "",
                            self.submit_p95_ms))
            elif self.queue_wait_p95_ms > 0 \
                    and name == "am.admit.queue_wait":
                out.append((name, "*", KIND_QUEUE_WAIT, "",
                            self.queue_wait_p95_ms))
            elif self.window_p95_ms > 0:
                stream = _window_series_stream(name)
                if stream is not None:
                    out.append((name, "*", KIND_WINDOW, stream,
                                self.window_p95_ms))
        return out

    def evaluate_burn(self, now_ns: Optional[int] = None
                      ) -> List[Dict[str, Any]]:
        """One burn sweep off a telemetry sampler tick.  Returns the NEW
        burn alerts this sweep latched.

        Latch: fast-window p95 ≥ threshold × target (with at least
        ``burn.min-count`` observations inside the fast window).  Clear:
        slow-window p95 back under threshold × target.  A series whose
        breach is already latched never raises a burn alert — the page
        already went out at full severity."""
        if not self.burn_enabled():
            return []
        from tez_tpu.obs import timeseries
        reg = timeseries.registry()
        now = clock.wall_s()
        new: List[Dict[str, Any]] = []
        cleared: List[Dict[str, Any]] = []
        with self._lock:
            self._burn_evaluations += 1
            for series, tenant, kind, stream, target in self._burn_series():
                key = (tenant, kind, stream)
                bar = self.burn_threshold * target
                burning = self._burning.get(key)
                if burning is None:
                    if key in self._active:
                        continue        # already breached: no pre-page
                    fast = reg.window(series, self.burn_fast_s, now_ns)
                    if (fast is None or fast["count"] < self.burn_min_count
                            or fast["p95"] < bar):
                        continue
                    alert = {"tenant": tenant, "kind": kind,
                             "stream": stream, "series": series,
                             "observed": round(fast["p95"], 4),
                             "target": target,
                             "threshold": self.burn_threshold,
                             "window_s": self.burn_fast_s, "since": now}
                    self._burning[key] = alert
                    self._burn_total += 1
                    self._burn_by_kind[kind] = \
                        self._burn_by_kind.get(kind, 0) + 1
                    new.append(dict(alert))
                else:
                    slow = reg.window(series, self.burn_slow_s, now_ns)
                    if (slow is not None and slow["count"] > 0
                            and slow["p95"] < bar):
                        del self._burning[key]
                        cleared.append({
                            "tenant": tenant, "kind": kind,
                            "stream": stream,
                            "observed": round(slow["p95"], 4),
                            "target": target, "cleared_at": now})
                    else:
                        fast = reg.window(series, self.burn_fast_s, now_ns)
                        if fast is not None and fast["count"] > 0:
                            burning["observed"] = round(fast["p95"], 4)
            for entry in new:
                self._log.append(dict(entry, event="burn"))
            for entry in cleared:
                self._log.append(dict(entry, event="burn_clear"))
            del self._log[:-_HISTORY_LIMIT]
        self._publish_burn(new, cleared)
        return new

    def _publish_burn(self, new: List[Dict[str, Any]],
                      cleared: List[Dict[str, Any]]) -> None:
        from tez_tpu.common import metrics
        from tez_tpu.obs import flight
        if new or cleared:
            metrics.set_gauge("slo.burn.total", float(self._burn_total))
            metrics.set_gauge("slo.burn.active", float(len(self._burning)))
        for a in new:
            flight.record(flight.MARK, f"slo.burn.{a['kind']}",
                          a["stream"] or a["tenant"],
                          a=int(a["observed"] * 1000),
                          b=int(a["target"] * 1000))
            log.warning(
                "SLO burn alert: tenant=%s stream=%s %s fast-window "
                "p95=%.2f >= %.0f%% of target %.2f", a["tenant"],
                a["stream"] or "-", a["kind"], a["observed"],
                a["threshold"] * 100, a["target"])
            self._journal_event("SLO_BURN_ALERT", a)
        for c in cleared:
            flight.record(flight.MARK, f"slo.burn_clear.{c['kind']}",
                          c["stream"] or c["tenant"])

    def _journal_event(self, type_name: str, payload: Dict[str, Any]
                       ) -> None:
        if self._journal is None:
            return
        from tez_tpu.am.history import HistoryEvent, HistoryEventType
        try:
            self._journal(HistoryEvent(
                HistoryEventType[type_name], data=dict(payload)))
        except Exception:  # noqa: BLE001 — diagnostics never fail
            log.exception("SLO %s journal write failed", type_name)

    # -- the GET /slo surface ---------------------------------------------
    def status(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "enabled": self.enabled(),
                "targets": self.targets(),
                "min_count": self.min_count,
                "active": [dict(b) for b in self._active.values()],
                "total_breaches": self._total,
                "breaches_by_kind": dict(self._by_kind),
                "evaluations": self._evaluations,
                "burn": {
                    "enabled": self.burn_enabled(),
                    "threshold": self.burn_threshold,
                    "fast_window_s": self.burn_fast_s,
                    "slow_window_s": self.burn_slow_s,
                    "min_count": self.burn_min_count,
                    "active": [dict(a) for a in self._burning.values()],
                    "total_alerts": self._burn_total,
                    "alerts_by_kind": dict(self._burn_by_kind),
                    "evaluations": self._burn_evaluations,
                },
                "log": [dict(e) for e in self._log],
            }


def _window_series_stream(name: str) -> Optional[str]:
    """``stream.window.latency`` -> "" (the session aggregate);
    ``stream.<s>.window.latency`` -> ``<s>``; anything else -> None."""
    if name == "stream" + _STREAM_HIST_SUFFIX:
        return ""
    if name.startswith("stream.") and name.endswith(_STREAM_HIST_SUFFIX):
        stream = name[len("stream."):-len(_STREAM_HIST_SUFFIX)]
        if stream and "." not in stream:
            return stream
    return None


def from_conf(conf: Any, journal: Any = None) -> Optional["SloWatchdog"]:
    """Build a watchdog when any target is declared, else None (the AM
    keeps a None attribute and every tick short-circuits)."""
    wd = SloWatchdog(conf, journal=journal)
    return wd if wd.enabled() else None
