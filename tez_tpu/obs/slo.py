"""Per-tenant SLO watchdogs: declarative targets evaluated live from the
metrics registry, surfaced as breach gauges, ``GET /slo``, a typed
history event, and flight-recorder entries.

Targets (``tez.am.slo.*``, all disabled at 0):

- ``submit.p95-ms`` — per-tenant p95 submit→finish wall, read from the
  ``tenant.<t>.dag.latency`` histogram the admission controller already
  feeds on every DAG completion;
- ``queue-wait.p95-ms`` — p95 of ``am.admit.queue_wait`` (session-wide:
  the queue is one FIFO, so queue wait is a property of the session, not
  a tenant — reported under tenant ``*``);
- ``shed-rate`` — shed / (accepted + shed) per tenant, from the
  admission controller's live tenant stats;
- ``window.p95-ms`` — p95 window cut→commit latency of streaming mode,
  read from the ``stream.window.latency`` histogram the StreamDriver
  feeds on every ``WINDOW_COMMIT_FINISHED`` (session-wide like queue
  wait: reported under tenant ``*``).

Evaluation is *edge-triggered and latched*: a (tenant, kind) pair
breaches once when it crosses its target and clears once when it drops
back under, so chaos/soak assertions see one typed
``TENANT_SLO_BREACH`` history event per episode instead of one per DAG.
``tez.am.slo.min-count`` guards against declaring a breach off a single
observation.

The watchdog is deliberately pull-based — it recomputes from histograms
the planes already maintain, on the admission controller's own
completion/shed ticks — so it adds no new lock ordering and costs
nothing between ticks.
"""
from __future__ import annotations

import logging
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

log = logging.getLogger(__name__)

#: bounded breach-transition log kept for GET /slo
_HISTORY_LIMIT = 64

KIND_SUBMIT = "submit_p95_ms"
KIND_QUEUE_WAIT = "queue_wait_p95_ms"
KIND_SHED_RATE = "shed_rate"
KIND_WINDOW = "window_p95_ms"


class SloWatchdog:
    """Evaluates ``tez.am.slo.*`` targets against live histograms."""

    def __init__(self, conf: Any, journal: Any = None) -> None:
        from tez_tpu.common import config as C
        self.submit_p95_ms = float(conf.get(C.AM_SLO_SUBMIT_P95_MS) or 0.0)
        self.queue_wait_p95_ms = float(
            conf.get(C.AM_SLO_QUEUE_WAIT_P95_MS) or 0.0)
        self.shed_rate = float(conf.get(C.AM_SLO_SHED_RATE) or 0.0)
        self.window_p95_ms = float(conf.get(C.AM_SLO_WINDOW_P95_MS) or 0.0)
        self.min_count = max(1, int(conf.get(C.AM_SLO_MIN_COUNT) or 1))
        self._journal = journal
        self._lock = threading.Lock()
        #: latched active breaches keyed (tenant, kind)
        self._active: Dict[Tuple[str, str], Dict[str, Any]] = {}
        self._log: List[Dict[str, Any]] = []
        self._total = 0
        self._by_kind: Dict[str, int] = {}
        self._evaluations = 0

    def enabled(self) -> bool:
        return (self.submit_p95_ms > 0 or self.queue_wait_p95_ms > 0
                or self.shed_rate > 0 or self.window_p95_ms > 0)

    def targets(self) -> Dict[str, float]:
        return {KIND_SUBMIT: self.submit_p95_ms,
                KIND_QUEUE_WAIT: self.queue_wait_p95_ms,
                KIND_SHED_RATE: self.shed_rate,
                KIND_WINDOW: self.window_p95_ms}

    # -- evaluation --------------------------------------------------------
    def _checks(self, tenant_stats: Dict[str, Dict[str, int]]
                ) -> List[Tuple[str, str, float, float]]:
        """(tenant, kind, observed, target) tuples due for comparison."""
        from tez_tpu.common import metrics
        hists = metrics.registry().histograms()
        out: List[Tuple[str, str, float, float]] = []
        for tenant, ts in sorted(tenant_stats.items()):
            label = tenant or "default"
            if self.submit_p95_ms > 0:
                h = hists.get(f"tenant.{label}.dag.latency")
                if h is not None and h.count >= self.min_count:
                    out.append((label, KIND_SUBMIT, h.quantile(0.95),
                                self.submit_p95_ms))
            if self.shed_rate > 0:
                total = int(ts.get("accepted", 0)) + int(ts.get("shed", 0))
                if total >= self.min_count:
                    out.append((label, KIND_SHED_RATE,
                                ts.get("shed", 0) / total, self.shed_rate))
        if self.queue_wait_p95_ms > 0:
            h = hists.get("am.admit.queue_wait")
            if h is not None and h.count >= self.min_count:
                out.append(("*", KIND_QUEUE_WAIT, h.quantile(0.95),
                            self.queue_wait_p95_ms))
        if self.window_p95_ms > 0:
            h = hists.get("stream.window.latency")
            if h is not None and h.count >= self.min_count:
                out.append(("*", KIND_WINDOW, h.quantile(0.95),
                            self.window_p95_ms))
        return out

    def evaluate(self, tenant_stats: Dict[str, Dict[str, int]]
                 ) -> List[Dict[str, Any]]:
        """One sweep.  Returns the NEW breaches this sweep latched."""
        if not self.enabled():
            return []
        new: List[Dict[str, Any]] = []
        cleared: List[Dict[str, Any]] = []
        now = time.time()
        with self._lock:
            self._evaluations += 1
            for tenant, kind, observed, target in self._checks(tenant_stats):
                key = (tenant, kind)
                over = observed > target
                active = self._active.get(key)
                if over and active is None:
                    breach = {"tenant": tenant, "kind": kind,
                              "observed": round(observed, 4),
                              "target": target, "since": now}
                    self._active[key] = breach
                    self._total += 1
                    self._by_kind[kind] = self._by_kind.get(kind, 0) + 1
                    new.append(dict(breach))
                elif over:
                    active["observed"] = round(observed, 4)
                elif active is not None:
                    del self._active[key]
                    cleared.append({"tenant": tenant, "kind": kind,
                                    "observed": round(observed, 4),
                                    "target": target, "cleared_at": now})
            for entry in new:
                self._log.append(dict(entry, event="breach"))
            for entry in cleared:
                self._log.append(dict(entry, event="clear"))
            del self._log[:-_HISTORY_LIMIT]
            total = self._total
        self._publish(new, cleared, total)
        return new

    def _publish(self, new: List[Dict[str, Any]],
                 cleared: List[Dict[str, Any]], total: int) -> None:
        from tez_tpu.common import metrics
        from tez_tpu.obs import flight
        if new or cleared:
            metrics.set_gauge("slo.breach.total", float(total))
            metrics.set_gauge("slo.breach.active", float(len(self._active)))
        for b in new:
            # a = observed, b = target — micro-units for latencies,
            # basis points for rates, so both fit integer payload slots
            scale = 1000.0 if b["kind"] != KIND_SHED_RATE else 10000.0
            flight.record(flight.SLO, f"slo.breach.{b['kind']}",
                          b["tenant"], a=int(b["observed"] * scale),
                          b=int(b["target"] * scale))
            log.warning("SLO breach: tenant=%s %s observed=%.2f target=%.2f",
                        b["tenant"], b["kind"], b["observed"], b["target"])
            if self._journal is not None:
                from tez_tpu.am.history import (HistoryEvent,
                                                HistoryEventType)
                try:
                    self._journal(HistoryEvent(
                        HistoryEventType.TENANT_SLO_BREACH,
                        data=dict(b)))
                except Exception:  # noqa: BLE001 — diagnostics never fail
                    log.exception("SLO breach journal write failed")
        for c in cleared:
            flight.record(flight.SLO, f"slo.clear.{c['kind']}", c["tenant"])

    # -- the GET /slo surface ---------------------------------------------
    def status(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "enabled": self.enabled(),
                "targets": self.targets(),
                "min_count": self.min_count,
                "active": [dict(b) for b in self._active.values()],
                "total_breaches": self._total,
                "breaches_by_kind": dict(self._by_kind),
                "evaluations": self._evaluations,
                "log": [dict(e) for e in self._log],
            }


def from_conf(conf: Any, journal: Any = None) -> Optional["SloWatchdog"]:
    """Build a watchdog when any target is declared, else None (the AM
    keeps a None attribute and every tick short-circuits)."""
    wd = SloWatchdog(conf, journal=journal)
    return wd if wd.enabled() else None
