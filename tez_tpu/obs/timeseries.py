"""Live time-series telemetry: bounded ring-buffer series sampled on the
shared monotonic clock.

The PR-12 planes (flight ring, metrics registry, SLO watchdog) are all
*point-in-time*: the registry holds cumulative histograms since process
start, the flight ring holds raw events until they are overwritten, and
nothing answers "what happened in the last five seconds?" — the question
every live surface (GET /metrics.json windows, burn-rate SLO alerts,
``graft top``, the continuous doctor) actually asks.  This module adds
the missing axis: a :class:`TimeSeriesRegistry` that, on each sampler
tick (``am/telemetry.py``), snapshots

- every histogram in :mod:`tez_tpu.common.metrics` (cumulative bucket
  counts + count + sum), and
- every gauge, plus any values produced by **registered collectors** —
  the hook ``store/``, ``shuffle/`` and ``parallel/`` use to publish
  occupancy without importing the AM —

into one bounded ring per series.  Windowed aggregation is a pure
function of ring contents and the window bounds (no wall-clock reads, no
randomness): a histogram window is the clamped delta of two cumulative
snapshots, so rate and p50/p95/p99 over "the last N seconds" are exact
and deterministic given the same samples.  Overflow is explicit: every
ring eviction and collector failure is counted and exposed (GET
/metrics.json ``accounting``, the ``TELEMETRY_SNAPSHOT`` journal record,
counter_diff's telemetry section) — a series silently aging out of its
ring is an observability bug, so it is never silent.

Timestamps are ``clock.mono_ns()`` — the flight recorder's axis — so a
windowed p95 can be joined against flight events without re-anchoring
(graftlint's rawtime checker bans the raw ``time.*`` calls here that
made PR-12's hand-written series drift).  See docs/telemetry.md.
"""
from __future__ import annotations

import threading
from typing import Any, Callable, Dict, List, Mapping, Optional, Tuple

from tez_tpu.common import clock

#: default ring capacity per series (samples, not seconds: at the default
#: 250 ms sampler period this is ~2 min of history per series)
DEFAULT_CAPACITY = 512

# -- plane attribution ------------------------------------------------------
# Blame-priority plane order and the histogram-prefix -> plane mapping used
# by the doctor's causal sweep (tools/doctor.py imports these).  They live
# here so the LIVE plane — per-plane series labels, the continuous blame
# sweep in am/telemetry.py — shares one mapping with the post-hoc tool
# instead of drifting from it.  "control" is the uncovered residual.
PLANES: Tuple[str, ...] = ("recovery", "admission", "exchange", "device",
                           "store", "transport", "compute", "control")

#: histogram-name prefix -> plane (first match wins; None = not blamed,
#: e.g. the flight recorder's own dump timer)
PREFIX_PLANE: Tuple[Tuple[str, Optional[str]], ...] = (
    ("am.admit.queue_wait", "admission"),
    ("am.heartbeat", None),
    ("obs.", None),
    ("mesh.", "exchange"),
    ("device.", "device"),
    ("store.", "store"),
    ("spill.", "store"),
    ("commit.", "store"),
    ("shuffle.merge", "compute"),
    ("shuffle.", "transport"),
)


def plane_for_name(name: str) -> Optional[str]:
    for prefix, plane in PREFIX_PLANE:
        if name.startswith(prefix):
            return plane
    return None


# -- series -----------------------------------------------------------------

class Series:
    """One bounded ring of samples.  ``kind`` is ``"gauge"`` (points are
    ``(t_ns, value)``) or ``"hist"`` (points are ``(t_ns, counts_tuple,
    count, sum_ms)`` — cumulative, exactly the registry snapshot).  Not
    self-locking: the owning registry serializes appends and reads."""

    __slots__ = ("name", "kind", "capacity", "points", "evicted")

    def __init__(self, name: str, kind: str, capacity: int) -> None:
        self.name = name
        self.kind = kind
        self.capacity = max(2, int(capacity))
        self.points: List[Tuple] = []
        self.evicted = 0

    def append(self, point: Tuple) -> None:
        if len(self.points) >= self.capacity:
            # explicit overflow accounting: deque(maxlen) would drop the
            # oldest sample silently, and silent drops are the exact
            # failure mode the telemetry section of counter_diff flags
            del self.points[0]
            self.evicted += 1
        self.points.append(point)


def _hist_window(points: List[Tuple], window_ns: int, now_ns: int
                 ) -> Dict[str, Any]:
    """Deterministic windowed aggregate of cumulative histogram samples:
    the clamped per-bucket delta between the newest sample and the newest
    sample at or before the window start (falling back to the oldest
    sample the ring still holds — ``covered`` reports the span actually
    used, so a reader can tell a truncated window from a full one)."""
    from tez_tpu.common import metrics
    if not points:
        return {"count": 0, "rate_per_s": 0.0, "p50": 0.0, "p95": 0.0,
                "p99": 0.0, "sum_ms": 0.0, "covered_s": 0.0}
    newest = points[-1]
    start = now_ns - window_ns
    base = None
    for p in reversed(points):
        if p[0] <= start:
            base = p
            break
    if base is None:
        base = points[0]
    d_counts = [max(0, n - b) for n, b in zip(newest[1], base[1])]
    d_count = max(0, newest[2] - base[2])
    d_sum = max(0.0, newest[3] - base[3])
    span_s = max((newest[0] - base[0]) / 1e9, 1e-9)
    return {
        "count": d_count,
        "rate_per_s": round(d_count / span_s, 4),
        "p50": round(metrics.quantile_from_buckets(d_counts, 0.50), 4),
        "p95": round(metrics.quantile_from_buckets(d_counts, 0.95), 4),
        "p99": round(metrics.quantile_from_buckets(d_counts, 0.99), 4),
        "sum_ms": round(d_sum, 4),
        "covered_s": round(min(span_s, window_ns / 1e9), 4),
    }


def _gauge_window(points: List[Tuple], window_ns: int, now_ns: int
                  ) -> Dict[str, Any]:
    start = now_ns - window_ns
    vals = [v for t, v in points if t > start]
    if not vals:
        last = points[-1][1] if points else 0.0
        return {"n": 0, "last": last, "min": last, "max": last,
                "mean": last}
    return {"n": len(vals), "last": vals[-1], "min": min(vals),
            "max": max(vals),
            "mean": round(sum(vals) / len(vals), 6)}


# -- registry ---------------------------------------------------------------

class TimeSeriesRegistry:
    """Bounded per-series rings + the sampler entry point."""

    def __init__(self, capacity: int = DEFAULT_CAPACITY) -> None:
        self._lock = threading.Lock()
        self.capacity = max(2, int(capacity))
        self._series: Dict[str, Series] = {}
        #: named collectors: fn() -> {gauge_name: float}.  store/, shuffle/
        #: and parallel/ register here so lane occupancy and tier bytes
        #: ride the same rings as the AM's own gauges.
        self._collectors: Dict[str, Callable[[], Mapping[str, float]]] = {}
        self.samples = 0
        self.collector_errors = 0
        self.scrape_errors = 0      # bumped by the web layer on a failed
        #                             exposition render, so scrape health
        #                             is visible in the plane it broke in

    # -- collector hooks ----------------------------------------------------
    def register_collector(self, name: str,
                           fn: Callable[[], Mapping[str, float]]) -> None:
        with self._lock:
            self._collectors[name] = fn

    def unregister_collector(self, name: str) -> None:
        with self._lock:
            self._collectors.pop(name, None)

    def collectors(self) -> List[str]:
        with self._lock:
            return sorted(self._collectors)

    def note_scrape_error(self) -> None:
        with self._lock:
            self.scrape_errors += 1

    # -- sampling -----------------------------------------------------------
    def sample(self, now_ns: Optional[int] = None) -> int:
        """One sweep: snapshot every registry histogram and gauge plus
        every collector into the rings.  Returns the number of series
        touched.  Collector failures are counted, never raised — the
        sampler thread must survive a sick plane."""
        from tez_tpu.common import metrics
        now = clock.mono_ns() if now_ns is None else int(now_ns)
        reg = metrics.registry()
        hists = reg.histograms()
        gauges = dict(reg.gauges())
        with self._lock:
            collectors = list(self._collectors.items())
        errors = 0
        for _cname, fn in collectors:
            try:
                for gname, value in fn().items():
                    gauges[gname] = float(value)
                    # write-through to the point-in-time gauge surface so
                    # GET /metrics shows collector gauges (lane occupancy,
                    # tier bytes) even between mutations of the plane
                    reg.set_gauge(gname, float(value))
            except Exception:  # noqa: BLE001 — a sick collector is counted
                errors += 1
        with self._lock:
            self.samples += 1
            self.collector_errors += errors
            for name, h in hists.items():
                s = self._series.get(name)
                if s is None:
                    s = self._series[name] = Series(
                        name, "hist", self.capacity)
                s.append((now, tuple(h.counts), h.count, h.sum_ms))
            for name, v in gauges.items():
                s = self._series.get(name)
                if s is None:
                    s = self._series[name] = Series(
                        name, "gauge", self.capacity)
                s.append((now, float(v)))
            return len(hists) + len(gauges)

    # -- reads --------------------------------------------------------------
    def series_names(self, kind: Optional[str] = None) -> List[str]:
        with self._lock:
            return sorted(n for n, s in self._series.items()
                          if kind is None or s.kind == kind)

    def window(self, name: str, window_s: float,
               now_ns: Optional[int] = None) -> Optional[Dict[str, Any]]:
        """Deterministic windowed summary of one series (None when the
        series does not exist yet)."""
        with self._lock:
            s = self._series.get(name)
            if s is None:
                return None
            points = list(s.points)
            kind = s.kind
        now = clock.mono_ns() if now_ns is None else int(now_ns)
        win = int(window_s * 1e9)
        out = (_hist_window(points, win, now) if kind == "hist"
               else _gauge_window(points, win, now))
        out["kind"] = kind
        return out

    def windows(self, window_s: float, now_ns: Optional[int] = None,
                kind: Optional[str] = None) -> Dict[str, Dict[str, Any]]:
        """Windowed summaries for every series (optionally one kind)."""
        return {n: self.window(n, window_s, now_ns)
                for n in self.series_names(kind)}

    def plane_busy_ms(self, window_s: float,
                      now_ns: Optional[int] = None) -> Dict[str, float]:
        """Instrumented busy milliseconds per plane over the window — the
        continuous doctor's incremental blame input: each histogram
        series' windowed ``sum_ms`` delta lands on its plane via the same
        PREFIX_PLANE mapping the post-hoc sweep uses."""
        busy = {p: 0.0 for p in PLANES}
        for name in self.series_names("hist"):
            plane = plane_for_name(name)
            if plane is None:
                continue
            w = self.window(name, window_s, now_ns)
            if w and w["sum_ms"] > 0:
                busy[plane] = round(busy[plane] + w["sum_ms"], 4)
        return busy

    def accounting(self) -> Dict[str, int]:
        """Explicit overflow/health accounting: cardinality is reported,
        evictions/drops/errors are the flaggable signals."""
        with self._lock:
            return {
                "samples": self.samples,
                "series": len(self._series),
                "points": sum(len(s.points)
                              for s in self._series.values()),
                "evicted": sum(s.evicted for s in self._series.values()),
                "collector_errors": self.collector_errors,
                "scrape_errors": self.scrape_errors,
            }

    def reset(self) -> None:
        with self._lock:
            self._series.clear()
            self.samples = 0
            self.collector_errors = 0
            self.scrape_errors = 0
            # collectors survive a reset: they are wiring, not data


_REG = TimeSeriesRegistry()


def registry() -> TimeSeriesRegistry:
    return _REG


def reset() -> None:
    """Drop sampled data (tests); registered collectors stay."""
    _REG.reset()
