"""Cross-plane observability: the flight recorder and SLO watchdogs.

- :mod:`tez_tpu.obs.flight` — per-process bounded binary ring journal of
  cross-plane events (span edges, histogram observations, breaker and
  watchdog transitions, admission verdicts, store demotions, push
  admissions, exchange round plans) on one shared monotonic clock, with
  on-demand snapshots and auto-dump on DAG failure / breaker-open /
  watchdog fire / admission shed.
- :mod:`tez_tpu.obs.slo` — declarative per-tenant SLO targets evaluated
  live from the metrics registry, surfaced on ``GET /slo`` and as typed
  history events.

Deliberately empty of imports: ``common/metrics.py`` and
``common/tracing.py`` import ``tez_tpu.obs.flight`` on their hot paths,
so this package must never pull in modules that import them back.
"""
