"""Always-on flight recorder: a bounded binary ring journal of cross-plane
events on one shared monotonic clock.

The PR-3 span plane answers "why was THIS DAG slow" when someone armed
tracing in advance; counters answer "how much" in aggregate.  What neither
gives is the black-box view after an un-anticipated failure: the last N
things the process did, across *all* planes (admission verdicts, device
breaker trips, store demotions, push admissions, exchange round plans),
cheap enough to leave running.  That is this module: an aircraft-style
flight recorder whose ring is overwritten forever and dumped when
something goes wrong.

Mechanics mirror :mod:`tez_tpu.common.faults` / ``tracing``:

- **Disarmed fast path** — every ``record()`` call site checks the module
  flag ``_armed`` first: one attribute load, no allocation, no lock.
- **Arming** — ``tez.obs.flight.enabled`` on a DAG conf arms the plane in
  ``app_master._start_dag`` (scope-refcounted so concurrent DAGs compose);
  ``on_dag_finished`` releases the scope.  The ring SURVIVES disarm so
  post-run snapshots still see the data; ``clear_all()`` drops it.
- **Binary ring, lock-free append** — events are fixed 44-byte records
  (``<qqIIIqq``: seq, t_ns, kind, name_id, scope_id, a, b) packed into a
  preallocated ``bytearray``.  The sequence counter is an
  ``itertools.count`` (``__next__`` is a single C call, atomic under the
  GIL) and ``struct.pack_into`` is likewise one C call, so appends from
  any thread interleave without a lock and without torn records.  Strings
  are interned into an append-only table; the interning dict hit path is
  a plain ``dict.get``.
- **Consistent snapshots** — ``snapshot()`` copies the ring with a single
  ``bytes(buf)`` (one C call: no record can be half-written relative to
  it), THEN copies the string table (append-only, so every id referenced
  by the copied bytes resolves), decodes non-empty slots and sorts by
  seq.  Overwritten slots simply vanish — bounded-journal semantics.
- **Auto-dump** — ``auto_dump(reason)`` writes the snapshot as JSON into
  the configured dump dir, rate-limited per process arm cycle
  (``tez.obs.flight.dump.max``).  Wired to DAG failure (app_master),
  breaker-open and watchdog fire (ops/async_stage), and admission shed
  (am/admission); chaos ``--dump-flight`` attaches dumps to failed
  scenarios.

Feeds: ``tracing.Span.finish`` records span edges, ``metrics.observe``
records every histogram observation (the counter-delta stream), and the
admission / store / push / exchange / breaker seams record their typed
events directly.  All timestamps come from :mod:`tez_tpu.common.clock`
so the doctor can join the ring with history journals and span buffers.
"""
from __future__ import annotations

import itertools
import json
import os
import struct
import threading
from typing import Any, Dict, List, NamedTuple, Optional, Tuple

from tez_tpu.common import clock

#: record layout: seq, t_ns, kind, name_id, scope_id, a, b
_REC = struct.Struct("<qqIIIqq")
RECORD_SIZE = _REC.size                       # 44 bytes

DEFAULT_CAPACITY_EVENTS = 65536
DEFAULT_MAX_DUMPS = 8

# -- event kinds -------------------------------------------------------------
SPAN = 1          # span edge: a = start mono ns, b = duration ns
COUNTER = 2       # histogram observation: a = microseconds observed
BREAKER = 3       # breaker transition: name = new state, a = consecutive
WATCHDOG = 4      # watchdog fire: name = stage, scope = span ids
ADMIT = 5         # admission verdict: name = verdict, a = queue depth
STORE = 6         # store publish/demote/evict: a = nbytes
PUSH = 7          # push send/admit/reject: a = nbytes, b = wait us
EXCHANGE = 8      # exchange round plan: a = round index, b = rows
SLO = 9           # SLO breach/clear: a = observed (us or bp), b = target
MARK = 10         # free-form marks (dump reasons, scenario boundaries)

KIND_NAMES = {SPAN: "span", COUNTER: "counter", BREAKER: "breaker",
              WATCHDOG: "watchdog", ADMIT: "admit", STORE: "store",
              PUSH: "push", EXCHANGE: "exchange", SLO: "slo", MARK: "mark"}

_armed = False          # single-boolean fast path (see common/faults.py)


class FlightEvent(NamedTuple):
    """One decoded ring record."""
    seq: int
    t_ns: int
    kind: int
    name: str
    scope: str
    a: int
    b: int

    @property
    def kind_name(self) -> str:
        return KIND_NAMES.get(self.kind, str(self.kind))

    def wall(self, anchor_pair: Optional[Tuple[float, int]] = None) -> float:
        return clock.mono_to_wall(self.t_ns, anchor_pair)

    def to_dict(self) -> Dict[str, Any]:
        return {"seq": self.seq, "t_ns": self.t_ns,
                "kind": self.kind_name, "name": self.name,
                "scope": self.scope, "a": self.a, "b": self.b}


class FlightSnapshot(NamedTuple):
    """Decoded ring + the clock anchor that projects it onto wall time."""
    events: List[FlightEvent]
    anchor: Tuple[float, int]
    dropped_before: int       # seq of the oldest surviving record - 1

    def to_dict(self) -> Dict[str, Any]:
        return {"anchor_wall_s": self.anchor[0],
                "anchor_mono_ns": self.anchor[1],
                "dropped_before": self.dropped_before,
                "events": [e.to_dict() for e in self.events]}


class FlightPlane:
    """Scope-refcounted arming + the binary ring itself."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._scopes: set = set()
        #: (bytearray, capacity_in_records) captured together so a racing
        #: reinstall can never pair a new buffer with an old capacity
        self._ring: Optional[Tuple[bytearray, int]] = None
        self._seq = itertools.count(1)
        self._names: Dict[str, int] = {"": 0}
        self._names_rev: List[str] = [""]
        self._dump_dir = ""
        self._max_dumps = DEFAULT_MAX_DUMPS
        self._dumps_written = 0
        self._dump_lock = threading.Lock()

    # -- arming ------------------------------------------------------------
    def install(self, scope: str,
                capacity: int = DEFAULT_CAPACITY_EVENTS,
                dump_dir: str = "", max_dumps: int = DEFAULT_MAX_DUMPS
                ) -> None:
        global _armed
        capacity = max(16, int(capacity))
        with self._lock:
            self._scopes.add(scope)
            if self._ring is None or self._ring[1] != capacity:
                self._ring = (bytearray(capacity * RECORD_SIZE), capacity)
            if dump_dir:
                self._dump_dir = dump_dir
            if max_dumps:
                self._max_dumps = int(max_dumps)
            self._dumps_written = 0
            _armed = True

    def clear(self, scope: str) -> None:
        """Release one scope.  The ring is deliberately retained so
        post-run snapshots/dumps still see the recorded events."""
        global _armed
        with self._lock:
            self._scopes.discard(scope)
            if not self._scopes:
                _armed = False

    def clear_all(self) -> None:
        global _armed
        with self._lock:
            self._scopes.clear()
            self._ring = None
            self._seq = itertools.count(1)
            self._names = {"": 0}
            self._names_rev = [""]
            self._dump_dir = ""
            self._max_dumps = DEFAULT_MAX_DUMPS
            self._dumps_written = 0
            _armed = False

    @property
    def scopes(self) -> set:
        with self._lock:
            return set(self._scopes)

    # -- append (hot path) -------------------------------------------------
    def _intern(self, s: str) -> int:
        sid = self._names.get(s)
        if sid is None:
            with self._lock:
                sid = self._names.get(s)
                if sid is None:
                    sid = len(self._names_rev)
                    self._names_rev.append(s)
                    self._names[s] = sid
        return sid

    def record(self, kind: int, name: str, scope: str = "",
               a: int = 0, b: int = 0) -> None:
        ring = self._ring      # local ref: survives a concurrent clear_all
        if ring is None:
            return
        buf, cap = ring
        nid = self._intern(name)
        sid = self._intern(scope) if scope else 0
        seq = next(self._seq)
        _REC.pack_into(buf, ((seq - 1) % cap) * RECORD_SIZE,
                       seq, clock.mono_ns(), kind, nid, sid,
                       int(a), int(b))

    # -- snapshot / dump ---------------------------------------------------
    def snapshot(self) -> FlightSnapshot:
        ring = self._ring
        if ring is None:
            return FlightSnapshot([], clock.anchor(), 0)
        buf, cap = ring
        raw = bytes(buf)             # single C call: no torn records
        names = list(self._names_rev)    # append-only; copied AFTER raw
        events: List[FlightEvent] = []
        for i in range(cap):
            seq, t_ns, kind, nid, sid, a, b = _REC.unpack_from(
                raw, i * RECORD_SIZE)
            if seq <= 0 or nid >= len(names) or sid >= len(names):
                continue             # empty slot (or mid-clear garbage)
            events.append(FlightEvent(seq, t_ns, kind, names[nid],
                                      names[sid], a, b))
        events.sort(key=lambda e: e.seq)
        dropped = events[0].seq - 1 if events else 0
        return FlightSnapshot(events, clock.anchor(), dropped)

    def dump(self, reason: str, scope: str = "") -> Optional[str]:
        """Write a snapshot to the dump dir.  Returns the path, or None
        when the per-arm-cycle dump budget is spent or no dir is set."""
        with self._dump_lock:
            if self._dumps_written >= self._max_dumps:
                return None
            d = self._dump_dir
            if not d:
                return None
            self._dumps_written += 1
            n = self._dumps_written
        self.record(MARK, "flight.dump", scope or reason)
        snap = self.snapshot()
        payload = snap.to_dict()
        payload["reason"] = reason
        payload["scope"] = scope
        payload["pid"] = os.getpid()
        safe = "".join(ch if (ch.isalnum() or ch in "._-") else "_"
                       for ch in reason)[:48]
        path = os.path.join(d, f"flight_{safe}_{os.getpid()}_{n}.json")
        from tez_tpu.common import metrics   # lazy: metrics imports us
        try:
            os.makedirs(d, exist_ok=True)
            with metrics.timer("obs.flight.dump"):
                tmp = path + ".tmp"
                with open(tmp, "w") as fh:
                    json.dump(payload, fh)
                os.replace(tmp, path)
        except OSError:
            return None              # diagnostics must never fail the run
        return path


_PLANE = FlightPlane()


def plane() -> FlightPlane:
    return _PLANE


def armed() -> bool:
    return _armed


def record(kind: int, name: str, scope: str = "",
           a: int = 0, b: int = 0) -> None:
    """Append one event.  Call sites that already hold data in locals may
    instead check ``flight._armed`` themselves and call
    ``plane().record`` — same thing, one call fewer."""
    if not _armed:
        return
    _PLANE.record(kind, name, scope, a, b)


def span_edge(name: str, start_wall_s: float, duration_s: float,
              cat: str = "") -> None:
    """Span-edge feed from ``tracing.Span.finish`` (already armed-gated
    by the caller): a = start on the mono axis, b = duration ns."""
    _PLANE.record(SPAN, name, cat,
                  clock.wall_to_mono_ns(start_wall_s),
                  int(duration_s * 1e9))


def install(scope: str = "manual",
            capacity: int = DEFAULT_CAPACITY_EVENTS,
            dump_dir: str = "", max_dumps: int = DEFAULT_MAX_DUMPS) -> None:
    _PLANE.install(scope, capacity, dump_dir, max_dumps)


def clear(scope: str) -> None:
    _PLANE.clear(scope)


def clear_all() -> None:
    _PLANE.clear_all()


def snapshot() -> FlightSnapshot:
    return _PLANE.snapshot()


def auto_dump(reason: str, scope: str = "") -> Optional[str]:
    """Dump the ring because something went wrong (breaker-open, watchdog
    fire, DAG failure, admission shed).  No-op while disarmed."""
    if not _armed:
        return None
    return _PLANE.dump(reason, scope)


def install_from_conf(conf: Any, scope: str) -> bool:
    """Arm from ``tez.obs.flight.*`` (AM submit path, the exact seam
    tracing.install_from_conf uses).  Returns True when armed."""
    from tez_tpu.common import config as C
    enabled = conf.get(C.OBS_FLIGHT_ENABLED)
    if not (enabled is True or str(enabled) == "True"):
        return False
    _PLANE.install(
        scope,
        capacity=int(conf.get(C.OBS_FLIGHT_BUFFER_EVENTS) or
                     DEFAULT_CAPACITY_EVENTS),
        dump_dir=str(conf.get(C.OBS_FLIGHT_DUMP_DIR) or ""),
        max_dumps=int(conf.get(C.OBS_FLIGHT_DUMP_MAX) or
                      DEFAULT_MAX_DUMPS))
    return True


def load_dump(path: str) -> FlightSnapshot:
    """Read a dump file back into a FlightSnapshot (doctor input)."""
    with open(path) as fh:
        d = json.load(fh)
    kinds = {v: k for k, v in KIND_NAMES.items()}
    events = [FlightEvent(e["seq"], e["t_ns"], kinds.get(e["kind"], MARK),
                          e["name"], e["scope"], e["a"], e["b"])
              for e in d.get("events", [])]
    return FlightSnapshot(events,
                          (d.get("anchor_wall_s", 0.0),
                           d.get("anchor_mono_ns", 0)),
                          d.get("dropped_before", 0))
