"""tez_tpu — a TPU-native DAG data-processing framework.

A re-design of the apache/tez programming model (DAG of vertices joined by
data-movement edges, pluggable Input/Processor/Output task runtimes, a
single orchestrating AppMaster) with the data plane rebuilt for TPU
hardware: device-resident sort/merge via XLA, scatter-gather over ICI
collectives under shard_map, and a host shuffle service for the DCN path.
"""

from tez_tpu.version import __version__

__all__ = ["__version__"]
