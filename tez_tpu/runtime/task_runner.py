"""The per-task harness: build IOs from the TaskSpec, run the processor.

Reference parity: tez-runtime-internals/.../runtime/
LogicalIOProcessorRuntimeTask.java:169 (initialize :234, run :378, close :385)
+ TezTaskRunner2 (kill/abort races) + TaskReporter.java:79 (heartbeat thread
batching events/counters, receiving routed events back).
"""
from __future__ import annotations

import logging
import os
import threading
import time
import traceback
from typing import Any, Dict, List, Optional, Sequence, Tuple

from tez_tpu.api.events import (CustomProcessorEvent, TezAPIEvent, TezEvent)
from tez_tpu.api.runtime import (LogicalIOProcessor, LogicalInput,
                                 LogicalOutput, MergedLogicalInput,
                                 ObjectRegistry)
from tez_tpu.common import faults, metrics, tracing
from tez_tpu.common.counters import TaskCounter, TezCounters
from tez_tpu.runtime.contexts import (TaskKilledError, TezInputContext,
                                      TezOutputContext, TezProcessorContext)
from tez_tpu.runtime.memory import DEFAULT_TASK_BUDGET, MemoryDistributor
from tez_tpu.runtime.task_spec import TaskSpec

log = logging.getLogger(__name__)

HEARTBEAT_INTERVAL = 0.05

#: serializes per-task XLA profiler traces (the profiler is process-global)
_PROFILE_LOCK = threading.Lock()


class TaskRunner:
    """Runs one task attempt to completion and reports to the umbilical."""

    def __init__(self, spec: TaskSpec, umbilical: Any,
                 registry: Optional[ObjectRegistry] = None,
                 work_dir: str = "/tmp", node_id: str = "local",
                 service_metadata: Optional[Dict[str, Any]] = None):
        self.spec = spec
        self.umbilical = umbilical
        self.registry = registry or ObjectRegistry()
        self.work_dir = work_dir
        self.node_id = node_id
        self.counters = TezCounters()
        from tez_tpu.runtime.memory import (RESERVE_FRACTION,
                                            parse_weight_ratios)
        self.memory = MemoryDistributor(
            int(spec.conf.get("tez.task.hbm.budget.bytes",
                              DEFAULT_TASK_BUDGET)),
            weights=parse_weight_ratios(
                str(spec.conf.get("tez.task.scale.memory.ratios", ""))),
            reserve_fraction=float(spec.conf.get(
                "tez.task.scale.memory.reserve-fraction", RESERVE_FRACTION)),
            weighted=str(spec.conf.get(
                "tez.task.scale.memory.allocator.class",
                "weighted")) != "uniform")
        self.progress = 0.0
        self.service_metadata: Dict[str, Any] = service_metadata or {
            "shuffle": {"host": node_id, "port": 0}}
        self.inputs: Dict[str, LogicalInput] = {}
        self.outputs: Dict[str, LogicalOutput] = {}
        self.processor: Optional[LogicalIOProcessor] = None
        self._event_buffer: List[TezEvent] = []
        self._event_lock = threading.Lock()
        self._killed = threading.Event()
        self._done = threading.Event()
        self._fatal: Optional[Tuple[BaseException | None, str]] = None
        # Incoming events arriving before IO initialize() completes are
        # trapped and replayed (reference: TezTrapEventHandler).  The
        # dispatch lock serializes replay vs. new heartbeat deliveries so
        # handle_events is single-threaded and in arrival order.
        self._inputs_ready = threading.Event()
        self._dispatch_lock = threading.Lock()
        self._trapped_incoming: List[Tuple[str, TezAPIEvent]] = []

    # -- called by contexts --------------------------------------------------
    def enqueue_events(self, events: Sequence[TezEvent]) -> None:
        with self._event_lock:
            self._event_buffer.extend(events)

    def check_killed(self) -> None:
        if self._killed.is_set():
            raise TaskKilledError(str(self.spec.attempt_id))

    def fatal_error(self, exc: Optional[BaseException], message: str) -> None:
        self._fatal = (exc, message)
        self._killed.set()

    # -- lifecycle -----------------------------------------------------------
    def run(self) -> str:
        """Returns final state string: SUCCEEDED | FAILED | KILLED."""
        start = time.time()
        from tez_tpu.runtime.diagnostics import (RuntimeStatsUpdater,
                                                 ThreadDumpHelper)
        stats = RuntimeStatsUpdater(self.counters)
        dump_ms = int(self.spec.conf.get("tez.thread.dump.interval.ms", 0))
        dumper = ThreadDumpHelper(dump_ms,
                                  label=str(self.spec.attempt_id)).start()
        reporter = threading.Thread(target=self._heartbeat_loop,
                                    name=f"reporter-{self.spec.attempt_id}",
                                    daemon=True)
        reporter.start()
        from tez_tpu.common import ndc
        try:
            # adopt the AM's trace context (TaskSpec carrier) so the
            # attempt span — and everything under it, including shuffle
            # fetches delivered on other threads — shares the DAG trace id
            with ndc.context(str(self.spec.attempt_id)), \
                    tracing.attached(getattr(self.spec, "trace_context", "")), \
                    tracing.span(f"attempt:{self.spec.attempt_id}",
                                 cat="task",
                                 vertex=self.spec.vertex_name,
                                 task_index=self.spec.task_index,
                                 attempt=self.spec.attempt_number):
                with tracing.span("initialize", cat="task"):
                    self._initialize()
                with tracing.span("run", cat="task"):
                    if self._try_reuse():
                        log.info("task %s: outputs served from store "
                                 "lineage — processor skipped",
                                 self.spec.attempt_id)
                    else:
                        self._run_processor()
                with tracing.span("close", cat="task"):
                    self._close()
            state = "SUCCEEDED"
        except TaskKilledError:
            # fatal_error() funnels through the kill flag; report it as a
            # FATAL failure, not a kill (kills respawn, fatals fail the DAG).
            state = "FAILED" if self._fatal is not None else "KILLED"
        except BaseException as e:  # noqa: BLE001
            log.exception("task %s failed", self.spec.attempt_id)
            state = "FAILED"
            self._failure_diag = (
                f"{type(e).__name__}: {e}\n{traceback.format_exc(limit=20)}")
        finally:
            self._done.set()
            dumper.stop()
            reporter.join(timeout=5)
        stats.update(final=True)
        self.counters.find_counter(TaskCounter.WALL_CLOCK_MILLISECONDS)\
            .set_value(int((time.time() - start) * 1000))
        if state == "SUCCEEDED":
            self.umbilical.task_done(
                self.spec.attempt_id, self._drain_events(), self.counters,
                epoch=getattr(self.spec, "am_epoch", 0),
                window_id=getattr(self.spec, "window_id", 0),
                stream=getattr(self.spec, "stream", ""))
        elif state == "KILLED":
            self.umbilical.task_killed(self.spec.attempt_id,
                                       "killed during execution")
        else:
            fatal = False
            diag = getattr(self, "_failure_diag", "unknown")
            if self._fatal is not None:
                exc, msg = self._fatal
                diag = f"{msg}: {exc!r}"
                fatal = True
            self.umbilical.task_failed(self.spec.attempt_id, diag,
                                       fatal=fatal, counters=self.counters)
        return state

    def _initialize(self) -> None:
        """Create + initialize processor and IOs, then settle memory
        (reference: initialize:234 — parallel init; serialized here for
        determinism, the IO init cost on TPU is kernel compilation which is
        cached in the object registry anyway)."""
        spec = self.spec
        proc_ctx = TezProcessorContext(self, spec.processor_descriptor.payload)
        self.processor = spec.processor_descriptor.instantiate(proc_ctx)

        init_events: List[TezEvent] = []
        for i, ispec in enumerate(spec.inputs):
            ictx = TezInputContext(self, ispec.input_descriptor.payload,
                                   ispec.source_vertex_name, i)
            inp = ispec.input_descriptor.instantiate(
                ictx, ispec.physical_input_count)
            self.inputs[ispec.source_vertex_name] = inp
        for i, ospec in enumerate(spec.outputs):
            octx = TezOutputContext(self, ospec.output_descriptor.payload,
                                    ospec.destination_vertex_name, i)
            out = ospec.output_descriptor.instantiate(
                octx, ospec.physical_output_count)
            self.outputs[ospec.destination_vertex_name] = out

        self.processor.initialize()
        for name, inp in self.inputs.items():
            evs = inp.initialize() or []
            if evs:
                inp.context.send_events(evs)
        for name, out in self.outputs.items():
            evs = out.initialize() or []
            if evs:
                out.context.send_events(evs)

        # group (merged) inputs presented to the processor as one entry
        for g in spec.group_inputs:
            members = [self.inputs[v] for v in g.group_vertices
                       if v in self.inputs]
            ictx = TezInputContext(self, g.merged_input_descriptor.payload,
                                   g.group_name, len(spec.inputs))
            merged = g.merged_input_descriptor.instantiate(ictx, members)
            self.inputs[g.group_name] = merged

        self.memory.make_initial_allocations()

        # auto-start non-merged inputs (reference: startable inputs started
        # by the framework before processor.run)
        for inp in self.inputs.values():
            if not isinstance(inp, MergedLogicalInput):
                inp.start()

        # replay any events trapped while initializing (ready-flag flip and
        # replay are atomic w.r.t. heartbeat deliveries)
        with self._dispatch_lock:
            trapped, self._trapped_incoming = self._trapped_incoming, []
            self._inputs_ready.set()
            if trapped:
                self._dispatch_incoming(trapped)

    def _try_reuse(self) -> bool:
        """Cross-DAG output reuse: when EVERY output reports a sealed store
        run for this task's lineage, alias them under this attempt's path
        and skip the processor entirely.  Any output that can't reuse (leaf
        outputs, pipelined shuffle, lineage off/miss) forces a full run —
        partial reuse would publish a mix of old and new data."""
        self.check_killed()
        outs = list(self.outputs.values())
        if not outs:
            return False
        for out in outs:
            probe = getattr(out, "reuse_available", None)
            if probe is None or not probe():
                return False
        for out in outs:
            evs = out.publish_reused() or []
            if evs:
                out.context.send_events(evs)
        self.counters.find_counter("ShuffleStore",
                                   "store.reuse.tasks").increment(1)
        return True

    def _run_processor(self) -> None:
        self.check_killed()
        # delay mode makes this attempt a straggler (speculation bait);
        # fail mode crashes it into the ordinary TA_FAILED retry path
        faults.fire("task.run", detail=str(self.spec.attempt_id))
        assert self.processor is not None
        # Constituents of a group stay in self.inputs (they receive events)
        # but the processor only sees the merged input (reference:
        # LogicalIOProcessorRuntimeTask hides grouped constituents).
        grouped = {v for g in self.spec.group_inputs for v in g.group_vertices}
        run_inputs = {name: inp for name, inp in self.inputs.items()
                      if name not in grouped}
        # The TPU-native tracing story (SURVEY.md §5.1): a per-task XLA
        # profiler trace (kernel timings, HBM traffic) viewable in
        # TensorBoard/Perfetto, gated off by default
        profile_dir = self.spec.conf.get("tez.task.jax-profile.dir", "")
        if profile_dir:
            import jax
            trace_dir = os.path.join(str(profile_dir),
                                     str(self.spec.attempt_id))
            # the XLA profiler is process-global (one profile at a time);
            # in-process runners share a process, so profiled tasks
            # serialize — a debugging mode, not the hot path
            with _PROFILE_LOCK, jax.profiler.trace(trace_dir):
                self.processor.run(run_inputs, self.outputs)
        else:
            self.processor.run(run_inputs, self.outputs)

    def _close(self) -> None:
        self.check_killed()
        for inp in self.inputs.values():
            evs = inp.close() or []
            if evs and not isinstance(inp, MergedLogicalInput):
                inp.context.send_events(evs)
        for out in self.outputs.values():
            evs = out.close() or []
            if evs:
                out.context.send_events(evs)
        self.processor.close()

    # -- heartbeat -----------------------------------------------------------
    def _drain_events(self) -> List[TezEvent]:
        with self._event_lock:
            out = self._event_buffer
            self._event_buffer = []
            return out

    def _heartbeat_loop(self) -> None:
        from tez_tpu.am.task_comm import HeartbeatRequest
        try:
            interval = float(self.spec.conf.get(
                "tez.task.am.heartbeat.interval-ms",
                HEARTBEAT_INTERVAL * 1000)) / 1000.0
        except (TypeError, ValueError):
            interval = HEARTBEAT_INTERVAL
        while not self._done.wait(interval):
            try:
                self._heartbeat_once()
            except BaseException:  # noqa: BLE001
                log.exception("heartbeat failed for %s", self.spec.attempt_id)
                self._killed.set()
                return
        # final pull-free flush happens via task_done/task_failed

    def _heartbeat_once(self) -> None:
        from tez_tpu.am.task_comm import HeartbeatRequest
        req = HeartbeatRequest(self.spec.attempt_id, self._drain_events(),
                               counters=None, progress=self.progress,
                               epoch=getattr(self.spec, "am_epoch", 0),
                               window_id=getattr(self.spec, "window_id", 0),
                               stream=getattr(self.spec, "stream", ""))
        t0 = time.perf_counter()
        resp = self.umbilical.heartbeat(req)
        metrics.observe("am.heartbeat.rtt",
                        (time.perf_counter() - t0) * 1000.0,
                        counters=self.counters)
        if resp.should_die:
            self._killed.set()
        if resp.events:
            with self._dispatch_lock:
                if not self._inputs_ready.is_set():
                    self._trapped_incoming.extend(resp.events)
                else:
                    self._dispatch_incoming(resp.events)

    def _dispatch_incoming(self, events: List[Tuple[str, TezAPIEvent]]) -> None:
        by_input: Dict[str, List[TezAPIEvent]] = {}
        for input_name, ev in events:
            if isinstance(ev, CustomProcessorEvent):
                self.processor.handle_events([ev])
            else:
                by_input.setdefault(input_name, []).append(ev)
        for name, evs in by_input.items():
            inp = self.inputs.get(name)
            if inp is not None:
                inp.handle_events(evs)
            else:
                log.warning("events for unknown input %s", name)
