"""Out-of-process runner: the TezChild-as-a-process analog.

Reference parity: tez-runtime-internals TezChild.java:214 — a separate
process that connects back to the AM's umbilical, loops getTask, runs tasks,
and dies when told.  Each runner also hosts a ShuffleServer so its outputs
are fetchable across process/host boundaries (the NM-resident ShuffleHandler
role collapses onto the runner host here).

Launch: python -m tez_tpu.runtime.remote_runner
            --am-host H --am-port P --node-id NAME
  with the job token in the TEZ_TPU_JOB_TOKEN env var (hex), mirroring the
  reference's credential handoff via the container environment.
"""
from __future__ import annotations

import argparse
import logging
import os
import sys
import tempfile

log = logging.getLogger(__name__)


def run_loop(am_host: str, am_port: int, node_id: str, token_hex: str,
             idle_timeout: float = 5.0, work_dir: str = "",
             container_id: str = "", advertise_host: str = "127.0.0.1",
             max_tasks: int = 0) -> int:
    from tez_tpu.am.umbilical_server import RemoteUmbilical
    from tez_tpu.api.runtime import ObjectRegistry
    from tez_tpu.common.ids import ContainerId
    from tez_tpu.common.security import JobTokenSecretManager
    from tez_tpu.runtime.task_runner import TaskRunner
    from tez_tpu.shuffle.server import ShuffleServer
    from tez_tpu.shuffle.service import local_shuffle_service

    secrets = JobTokenSecretManager(bytes.fromhex(token_hex))
    umbilical = RemoteUmbilical(am_host, am_port, secrets)
    # consumers on OTHER hosts dial advertise_host: a non-loopback
    # advertisement requires a non-loopback bind (both server flavors)
    bind_host = "127.0.0.1" if advertise_host in ("127.0.0.1", "localhost") \
        else "0.0.0.0"
    from tez_tpu.common.tls import server_context
    shuffle_ssl = server_context(None)   # TEZ_TPU_SSL_* from the launch env
    native_dir = os.environ.get("TEZ_TPU_NATIVE_SHUFFLE_DIR", "")
    shuffle_server = None
    if native_dir and shuffle_ssl is not None:
        # the C++ sendfile server has no TLS; silently serving plaintext
        # when the operator asked for encrypted shuffle would be a
        # downgrade attack on ourselves — refuse loudly, use the TLS
        # Python server
        log.warning("TEZ_TPU_NATIVE_SHUFFLE_DIR ignored: shuffle TLS is "
                    "enabled and the native server speaks plaintext; "
                    "serving via the Python TLS server instead")
        native_dir = ""
    if native_dir:
        # native sendfile data server (ShuffleHandler analog): registered
        # runs are write-through serialized to disk; remote fetches never
        # enter Python.  Falls back to the Python server if the native lib
        # is unavailable on this host.
        try:
            from tez_tpu.shuffle.native_server import (FileShuffleStore,
                                                       NativeShuffleServer)
            store_dir = os.path.join(native_dir, f"runner-{os.getpid()}")
            shuffle_server = NativeShuffleServer(
                secrets, store_dir, host=bind_host).start()
            # attach only after the server is up: a failed native start
            # must not leave every spill double-written for nothing
            local_shuffle_service().attach_store(FileShuffleStore(store_dir))
        except Exception:  # noqa: BLE001
            log.exception("native shuffle server unavailable; "
                          "using the Python server")
            shuffle_server = None
    if shuffle_server is None:
        shuffle_server = ShuffleServer(secrets, local_shuffle_service(),
                                       host=bind_host,
                                       ssl_context=shuffle_ssl).start()
    if not container_id:
        container_id = str(ContainerId(f"app_proc_{node_id}", os.getpid()))
    registry = ObjectRegistry()
    work_dir = work_dir or tempfile.mkdtemp(prefix=f"tez-runner-{node_id}-")
    # advertise_host is what consumers dial for shuffle fetches; on a
    # multi-host deployment pass this worker's reachable address
    shuffle_meta = {"host": advertise_host, "port": shuffle_server.port,
                    "secret": secrets}
    log.info("runner %s up: shuffle port %d, am %s:%d", node_id,
             shuffle_server.port, am_host, am_port)
    tasks_run = 0
    try:
        while True:
            try:
                spec = umbilical.get_task(container_id, timeout=idle_timeout,
                                          node_id=node_id)
            except ConnectionError:
                log.info("umbilical gone; runner exiting")
                break
            if spec is None:
                break  # idle: release this runner (container release)
            runner = TaskRunner(spec, umbilical, registry,
                                work_dir=work_dir, node_id=node_id,
                                service_metadata={"shuffle": shuffle_meta})
            runner.run()
            registry.clear_scope(ObjectRegistry.VERTEX)
            tasks_run += 1
            if max_tasks and tasks_run >= max_tasks:
                # container reuse disabled: one fresh process per task
                # (tez.am.container.reuse.enabled=False; the pool respawns
                # while backlog remains)
                break
    finally:
        shuffle_server.stop()
        umbilical.close()
        log.info("runner %s done after %d tasks", node_id, tasks_run)
    return 0


def _repin_jax_platform() -> None:
    """Honor the caller's JAX_PLATFORMS request verbatim: an ambient
    sitecustomize may have pinned a different platform in jax.config, which
    outranks the env var — a runner handed JAX_PLATFORMS=cpu (e.g. the test
    mesh, or a host-only deployment) must not initialize the TPU backend."""
    env_platforms = os.environ.get("JAX_PLATFORMS", "")
    if not env_platforms:
        return
    try:
        import jax
        jax.config.update("jax_platforms", env_platforms)
    except Exception:  # noqa: BLE001 — backend already initialized / no jax
        pass


def main() -> int:
    _repin_jax_platform()
    parser = argparse.ArgumentParser()
    parser.add_argument("--am-host", default="127.0.0.1")
    parser.add_argument("--am-port", type=int, required=True)
    parser.add_argument("--node-id", default=f"proc-{os.getpid()}")
    parser.add_argument("--container-id", default="")
    parser.add_argument("--advertise-host", default="127.0.0.1")
    parser.add_argument("--idle-timeout", type=float, default=5.0)
    parser.add_argument("--max-tasks", type=int, default=0,
                        help="exit after N tasks; 0 = loop until idle "
                             "(tez.am.container.reuse.enabled=False -> 1)")
    args = parser.parse_args()
    token = os.environ.get("TEZ_TPU_JOB_TOKEN", "")
    if not token:
        print("TEZ_TPU_JOB_TOKEN env var required", file=sys.stderr)
        return 2
    logging.basicConfig(level=os.environ.get("TEZ_TPU_LOG", "INFO"))
    from tez_tpu.common import ndc
    ndc.install()   # every task log line carries its attempt id (%(ndc)s)
    return run_loop(args.am_host, args.am_port, args.node_id, token,
                    idle_timeout=args.idle_timeout,
                    container_id=args.container_id,
                    advertise_host=args.advertise_host,
                    max_tasks=args.max_tasks)


if __name__ == "__main__":
    sys.exit(main())
