"""Runtime context implementations handed to Inputs/Outputs/Processors.

Reference parity: tez-runtime-internals/.../api/impl/{TezInputContextImpl,
TezOutputContextImpl,TezProcessorContextImpl}.java — counters, payloads,
event send, memory requests, progress, fatal-error funnel.
"""
from __future__ import annotations

import threading
from typing import Any, Callable, List, Optional, Sequence, TYPE_CHECKING

from tez_tpu.api.events import (EventMetaData, TezAPIEvent, TezEvent)
from tez_tpu.api.runtime import (InputContext, MemoryUpdateCallback,
                                 ObjectRegistry, OutputContext,
                                 ProcessorContext)
from tez_tpu.common.counters import TezCounters
from tez_tpu.common.payload import UserPayload

if TYPE_CHECKING:
    from tez_tpu.runtime.task_runner import TaskRunner


class TaskKilledError(Exception):
    """Raised inside user code when the AM killed this attempt."""


class _BaseContext:
    def __init__(self, runner: "TaskRunner", payload: UserPayload,
                 producer_consumer_type: str, edge_vertex_name: str = ""):
        self._runner = runner
        self._payload = payload
        self._type = producer_consumer_type
        self._edge_vertex_name = edge_vertex_name

    # -- identity ------------------------------------------------------------
    @property
    def task_attempt_id(self):
        return self._runner.spec.attempt_id

    @property
    def task_index(self) -> int:
        return self._runner.spec.task_index

    @property
    def task_attempt_number(self) -> int:
        return self._runner.spec.attempt_number

    @property
    def vertex_name(self) -> str:
        return self._runner.spec.vertex_name

    @property
    def dag_name(self) -> str:
        return self._runner.spec.dag_name

    @property
    def vertex_parallelism(self) -> int:
        return self._runner.spec.vertex_parallelism

    @property
    def am_epoch(self) -> int:
        """AM incarnation that issued this task's spec (0 = unstamped)."""
        return getattr(self._runner.spec, "am_epoch", 0)

    @property
    def app_id(self) -> str:
        """Application the task belongs to (epoch fencing is per-app)."""
        return self._runner.spec.attempt_id.dag_id.app_id

    @property
    def lineage(self) -> str:
        """Vertex lineage hash for store output reuse ("" = off)."""
        return getattr(self._runner.spec, "lineage", "")

    @property
    def tenant(self) -> str:
        """Tenant the owning DAG was submitted under ("" = anonymous);
        store publishes charge this tenant's byte quotas."""
        return getattr(self._runner.spec, "tenant", "")

    @property
    def window_id(self) -> int:
        """Streaming window this attempt computes (0 = batch/unstamped)."""
        return getattr(self._runner.spec, "window_id", 0)

    @property
    def stream(self) -> str:
        """Stream identity for the window fence ("" = not streaming)."""
        return getattr(self._runner.spec, "stream", "")

    @property
    def counters(self) -> TezCounters:
        return self._runner.counters

    @property
    def user_payload(self) -> UserPayload:
        return self._payload

    @property
    def conf(self) -> dict:
        return self._runner.spec.conf

    # -- events --------------------------------------------------------------
    def _source_meta(self) -> EventMetaData:
        return EventMetaData(
            producer_consumer_type=self._type,
            task_vertex_name=self._runner.spec.vertex_name,
            edge_vertex_name=self._edge_vertex_name,
            task_attempt_id=self._runner.spec.attempt_id)

    def send_events(self, events: Sequence[TezAPIEvent]) -> None:
        meta = self._source_meta()
        self._runner.enqueue_events(
            [TezEvent(ev, source_info=meta) for ev in events])

    # -- memory / progress ---------------------------------------------------
    def request_initial_memory(self, size: int,
                               callback: "MemoryUpdateCallback | None",
                               component_type: str = "OTHER") -> None:
        cb = callback.memory_assigned if callback is not None else None
        self._runner.memory.request_memory(size, cb, requester=repr(self),
                                           component_type=component_type)

    def notify_progress(self) -> None:
        self._runner.check_killed()

    def set_progress(self, progress: float) -> None:
        self._runner.progress = max(0.0, min(1.0, progress))
        self._runner.check_killed()

    def fatal_error(self, exc: Optional[BaseException], message: str) -> None:
        self._runner.fatal_error(exc, message)

    def can_commit(self) -> bool:
        """Commit arbitration with the AM.  Available on every context so
        leaf outputs can gate publishing (reference: canCommit flows through
        the processor, but output commit also honors it).  The spec's AM
        epoch rides along so a zombie attempt from a pre-crash incarnation
        is fenced at the arbitration seam; in streaming mode the window id
        rides along too so a straggler from a sealed window is fenced the
        same way."""
        return self._runner.umbilical.can_commit(
            self._runner.spec.attempt_id, epoch=self.am_epoch,
            window_id=self.window_id, stream=self.stream)

    @property
    def work_dirs(self) -> List[str]:
        return [self._runner.work_dir]

    def get_service_provider_metadata(self, service: str) -> Any:
        return self._runner.service_metadata.get(service)

    @property
    def object_registry(self) -> ObjectRegistry:
        return self._runner.registry


class TezInputContext(_BaseContext, InputContext):
    def __init__(self, runner: "TaskRunner", payload: UserPayload,
                 source_vertex: str, input_index: int):
        super().__init__(runner, payload, "INPUT", source_vertex)
        self._input_index = input_index

    @property
    def source_vertex_name(self) -> str:
        return self._edge_vertex_name

    @property
    def input_index(self) -> int:
        return self._input_index


class TezOutputContext(_BaseContext, OutputContext):
    def __init__(self, runner: "TaskRunner", payload: UserPayload,
                 dest_vertex: str, output_index: int):
        super().__init__(runner, payload, "OUTPUT", dest_vertex)
        self._output_index = output_index

    @property
    def destination_vertex_name(self) -> str:
        return self._edge_vertex_name

    @property
    def output_index(self) -> int:
        return self._output_index


class TezProcessorContext(_BaseContext, ProcessorContext):
    def __init__(self, runner: "TaskRunner", payload: UserPayload):
        super().__init__(runner, payload, "PROCESSOR")

    def can_commit(self) -> bool:
        return self._runner.umbilical.can_commit(
            self._runner.spec.attempt_id, epoch=self.am_epoch,
            window_id=self.window_id, stream=self.stream)
