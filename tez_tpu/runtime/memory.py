"""Cross-I/O memory arbitration for one task.

Reference parity: tez-runtime-internals/.../common/resources/
MemoryDistributor.java:110 + runtime-library WeightedScalingMemoryDistributor:
components request memory during initialize(), grants are scaled to fit the
task budget and delivered via callback before start().

TPU-first delta: the budget is an HBM byte budget per task (device memory is
the scarce resource the sorter/merger spans live in), not a JVM heap.
"""
from __future__ import annotations

import dataclasses
import logging
from typing import Callable, List, Optional

log = logging.getLogger(__name__)

#: Default per-task HBM budget used when the spec doesn't say (bytes).
DEFAULT_TASK_BUDGET = 2 << 30

#: Requests below this are granted in full before scaling (reference:
#: tez.task.scale.memory.reserve-fraction behavior approximated).
RESERVE_FRACTION = 0.05


@dataclasses.dataclass
class _Request:
    requester: str
    requested: int
    callback: Optional[Callable[[int], None]]
    granted: int = 0


class MemoryDistributor:
    def __init__(self, budget_bytes: int = DEFAULT_TASK_BUDGET):
        self.budget = int(budget_bytes * (1 - RESERVE_FRACTION))
        self._requests: List[_Request] = []
        self._allocated = False

    def request_memory(self, size: int, callback: Optional[Callable[[int], None]],
                       requester: str = "") -> None:
        assert not self._allocated, "requests closed after allocation"
        self._requests.append(_Request(requester, int(size), callback))

    def make_initial_allocations(self) -> None:
        """Scale every request proportionally when oversubscribed
        (reference: MemoryDistributor.makeInitialAllocations:120)."""
        total = sum(r.requested for r in self._requests)
        scale = 1.0 if total <= self.budget or total == 0 else \
            self.budget / total
        for r in self._requests:
            r.granted = int(r.requested * scale)
            if r.callback is not None:
                r.callback(r.granted)
        self._allocated = True
        if scale < 1.0:
            log.info("memory oversubscribed: scaled %d requests by %.2f "
                     "(asked %d, budget %d)", len(self._requests), scale,
                     total, self.budget)

    def total_granted(self) -> int:
        return sum(r.granted for r in self._requests)
