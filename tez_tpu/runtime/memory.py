"""Cross-I/O memory arbitration for one task.

Reference parity: tez-runtime-internals/.../common/resources/
MemoryDistributor.java:110 + runtime-library WeightedScalingMemoryDistributor:
components request memory during initialize(), grants are scaled to fit the
task budget and delivered via callback before start().

TPU-first delta: the budget is an HBM byte budget per task (device memory is
the scarce resource the sorter/merger spans live in), not a JVM heap.
"""
from __future__ import annotations

import dataclasses
import logging
from typing import Callable, List, Optional

log = logging.getLogger(__name__)

#: Default per-task HBM budget used when the spec doesn't say (bytes).
DEFAULT_TASK_BUDGET = 2 << 30

#: Requests below this are granted in full before scaling (reference:
#: tez.task.scale.memory.reserve-fraction behavior approximated).
RESERVE_FRACTION = 0.05


#: Component-type weights for oversubscription scaling (reference:
#: WeightedScalingMemoryDistributor ratios — sorted outputs and merged
#: inputs get proportionally more than unsorted buffers).
DEFAULT_WEIGHTS = {
    "PARTITIONED_SORTED_OUTPUT": 3,
    "PARTITIONED_UNSORTED_OUTPUT": 1,
    "SORTED_MERGED_INPUT": 3,
    "UNSORTED_INPUT": 1,
    "PROCESSOR": 1,
    "OTHER": 1,
}


@dataclasses.dataclass
class _Request:
    requester: str
    requested: int
    callback: Optional[Callable[[int], None]]
    component_type: str = "OTHER"
    granted: int = 0


def parse_weight_ratios(spec: str) -> Optional[dict]:
    """Parse 'TYPE=WEIGHT,...' overrides (reference format:
    tez.task.scale.memory.ratios, WeightedScalingMemoryDistributor
    .populateTypeScaleMap).  Unknown types are accepted (custom component
    types weight themselves); malformed specs return None and the caller
    keeps the defaults."""
    if not spec:
        return None
    out = dict(DEFAULT_WEIGHTS)
    try:
        for part in spec.split(","):
            name, _, w = part.strip().partition("=")
            if not name or not w:
                return None
            out[name.strip()] = int(w)
    except ValueError:
        return None
    return out


class MemoryDistributor:
    def __init__(self, budget_bytes: int = DEFAULT_TASK_BUDGET,
                 weights: Optional[dict] = None,
                 reserve_fraction: float = RESERVE_FRACTION,
                 weighted: bool = True):
        reserve_fraction = min(max(float(reserve_fraction), 0.0), 1.0)
        self.budget = int(budget_bytes * (1 - reserve_fraction))
        # weighted=False: uniform scaling (reference ScalingAllocator —
        # every component type scales by the same factor)
        self.weights = (weights or DEFAULT_WEIGHTS) if weighted \
            else {k: 1 for k in DEFAULT_WEIGHTS}
        self._weighted = weighted
        self._requests: List[_Request] = []
        self._allocated = False

    def request_memory(self, size: int, callback: Optional[Callable[[int], None]],
                       requester: str = "",
                       component_type: str = "OTHER") -> None:
        assert not self._allocated, "requests closed after allocation"
        self._requests.append(_Request(requester, int(size), callback,
                                       component_type))

    def make_initial_allocations(self) -> None:
        """Scale requests to fit the budget when oversubscribed, weighted by
        component type (reference: MemoryDistributor.makeInitialAllocations
        :120 + WeightedScalingMemoryDistributor)."""
        total = sum(r.requested for r in self._requests)
        if total <= self.budget or total == 0:
            for r in self._requests:
                r.granted = r.requested
                if r.callback is not None:
                    r.callback(r.granted)
            self._allocated = True
            return
        # oversubscribed: shuffle data the buffer store holds in host RAM
        # competes with the buffers the scaled-down components are about to
        # allocate — demote idle store entries toward disk by the shortfall
        # before squeezing the task's own grants
        try:
            from tez_tpu.store import local_buffer_store
            store = local_buffer_store()
            if store is not None:
                freed = store.relieve_host_pressure(total - self.budget)
                if freed:
                    log.info("memory oversubscribed by %d bytes: store "
                             "demoted %d bytes to disk",
                             total - self.budget, freed)
        except Exception:  # noqa: BLE001 — relief is best-effort
            log.exception("buffer-store pressure relief failed")
        weighted = [(r, self.weights.get(r.component_type, 1))
                    for r in self._requests]
        # iterative weighted fill: capped requests release their surplus to
        # the still-unmet ones (few components per task, so 2-3 rounds)
        remaining = self.budget
        pending = list(weighted)
        grants = {id(r): 0 for r, _ in weighted}
        while pending and remaining > 0:
            total_weight = sum(w * (r.requested - grants[id(r)])
                               for r, w in pending)
            if total_weight <= 0:
                break
            next_pending = []
            progressed = False
            for r, w in pending:
                need = r.requested - grants[id(r)]
                share = int(remaining * (w * need) / total_weight)
                give = min(need, share)
                if give > 0:
                    grants[id(r)] += give
                    progressed = True
                if grants[id(r)] < r.requested:
                    next_pending.append((r, w))
            spent = sum(grants.values())
            remaining = self.budget - spent
            if not progressed:
                break
            pending = next_pending
        for r, _ in weighted:
            r.granted = grants[id(r)]
            if r.callback is not None:
                r.callback(r.granted)
        self._allocated = True
        log.info("memory oversubscribed: weighted-scaled %d requests "
                 "(asked %d, budget %d)", len(self._requests), total,
                 self.budget)

    def total_granted(self) -> int:
        return sum(r.granted for r in self._requests)
