"""Runtime diagnostics: thread dumps + GC/CPU accounting.

Reference parity: tez-runtime-internals TezThreadDumpHelper.java:53 (periodic
jstack per task/AM, attachable via hooks) and tez-common GcTimeUpdater.java:34
(JVM GC time into counters) + TaskCounterUpdater (CPU/memory stats).
"""
from __future__ import annotations

import gc
import logging
import sys
import threading
import time
import traceback
from typing import Any, Optional

from tez_tpu.common.counters import TaskCounter, TezCounters

log = logging.getLogger(__name__)


def dump_thread_stacks(out=None) -> str:
    """All-threads stack dump (the jstack analog)."""
    lines = []
    frames = sys._current_frames()
    for thread in threading.enumerate():
        frame = frames.get(thread.ident)
        lines.append(f'--- Thread "{thread.name}" '
                     f'(daemon={thread.daemon}, id={thread.ident}) ---')
        if frame is not None:
            lines.extend(l.rstrip() for l in traceback.format_stack(frame))
    text = "\n".join(lines)
    if out is not None:
        print(text, file=out)
    return text


class ThreadDumpHelper:
    """Periodic stack dumps while attached (reference: ThreadDumpDAGHook /
    ThreadDumpTaskAttemptHook)."""

    def __init__(self, interval_ms: int, label: str = ""):
        self.interval = interval_ms / 1000.0
        self.label = label
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "ThreadDumpHelper":
        if self.interval <= 0:
            return self
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name=f"thread-dump-{self.label}")
        self._thread.start()
        return self

    def _loop(self) -> None:
        while not self._stop.wait(self.interval):
            log.info("thread dump [%s]:\n%s", self.label,
                     dump_thread_stacks())

    def stop(self) -> None:
        self._stop.set()


class RuntimeStatsUpdater:
    """Snapshot-based CPU/GC counters for one task (reference:
    TaskCounterUpdater + GcTimeUpdater).  GC pause time is measured for
    real via gc callbacks (start/stop timestamps), matching the reference
    counter's milliseconds unit."""

    def __init__(self, counters: TezCounters):
        self.counters = counters
        self._t0 = time.process_time()
        self._gc_ns = 0
        self._gc_start: Optional[int] = None
        self._cb = self._on_gc
        gc.callbacks.append(self._cb)

    def _on_gc(self, phase: str, info: dict) -> None:
        if phase == "start":
            self._gc_start = time.monotonic_ns()
        elif phase == "stop" and self._gc_start is not None:
            self._gc_ns += time.monotonic_ns() - self._gc_start
            self._gc_start = None

    def update(self, final: bool = False) -> None:
        cpu_ms = int((time.process_time() - self._t0) * 1000)
        self.counters.find_counter(TaskCounter.CPU_MILLISECONDS)\
            .set_value(cpu_ms)
        try:
            import resource
            usage = resource.getrusage(resource.RUSAGE_SELF)
            self.counters.find_counter(TaskCounter.PHYSICAL_MEMORY_BYTES)\
                .set_value(usage.ru_maxrss * 1024)
        except ImportError:
            pass
        self.counters.find_counter(TaskCounter.GC_TIME_MILLIS)\
            .set_value(self._gc_ns // 1_000_000)
        if final:
            try:
                gc.callbacks.remove(self._cb)
            except ValueError:
                pass
