"""Serializable task description shipped AM -> runner.

Reference parity: tez-runtime-internals/.../runtime/api/impl/TaskSpec.java
(272 LoC): processor descriptor + one InputSpec/OutputSpec per connected edge
or root-input/leaf-output, plus task conf.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional, Tuple

from tez_tpu.common.ids import TaskAttemptId
from tez_tpu.common.payload import (InputDescriptor, OutputDescriptor,
                                    ProcessorDescriptor)


@dataclasses.dataclass(frozen=True)
class InputSpec:
    """Reference: InputSpec.java — source vertex (or root input) name +
    descriptor + physical input count."""
    source_vertex_name: str
    input_descriptor: InputDescriptor
    physical_input_count: int
    is_root_input: bool = False


@dataclasses.dataclass(frozen=True)
class OutputSpec:
    destination_vertex_name: str
    output_descriptor: OutputDescriptor
    physical_output_count: int
    is_leaf_output: bool = False


@dataclasses.dataclass(frozen=True)
class GroupInputSpec:
    group_name: str
    group_vertices: Tuple[str, ...]
    merged_input_descriptor: Any


@dataclasses.dataclass(frozen=True)
class TaskSpec:
    attempt_id: TaskAttemptId
    dag_name: str
    vertex_name: str
    vertex_parallelism: int
    processor_descriptor: ProcessorDescriptor
    inputs: Tuple[InputSpec, ...]
    outputs: Tuple[OutputSpec, ...]
    group_inputs: Tuple[GroupInputSpec, ...] = ()
    conf: Dict[str, Any] = dataclasses.field(default_factory=dict)
    #: AM incarnation (attempt number) that issued this spec.  Stamped into
    #: umbilical calls and shuffle registrations so a zombie attempt from a
    #: pre-crash AM is rejected at every seam (0 = unstamped/legacy).
    am_epoch: int = 0
    #: W3C-style trace-context carrier ("00-<trace>-<span>-01") linking this
    #: attempt to the DAG's root span when the tracing plane is armed
    #: ("" = tracing disarmed; the runner then starts no spans).
    trace_context: str = ""
    #: Content-addressed lineage hash of this task's vertex (spec + upstream
    #: closure, see tez_tpu.store.lineage).  Outputs publish under
    #: "<hash>/<task_index>/<dest>" so identical recurring DAGs in a session
    #: can reuse sealed store entries ("" = lineage reuse off).
    lineage: str = ""
    #: Tenant id inherited from the DAG plan (multi-tenant session AM):
    #: the task scheduler's deficit round-robin and the buffer store's
    #: byte quotas key on it ("" = the anonymous default tenant).
    tenant: str = ""
    #: Streaming-mode window coordinate: the numbered window this attempt
    #: computes.  Paired with ``am_epoch`` it forms the generalized
    #: ``(attempt_epoch, window_id)`` fence — a straggler from a sealed
    #: window is rejected at every seam a pre-crash zombie would be
    #: (0 = batch/unstamped: never fenced, pre-streaming semantics).
    window_id: int = 0
    #: Stream identity for the window fence registry ("" = not streaming).
    stream: str = ""

    @property
    def task_index(self) -> int:
        return self.attempt_id.task_id.id

    @property
    def attempt_number(self) -> int:
        return self.attempt_id.id
