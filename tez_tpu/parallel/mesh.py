"""Device-mesh helpers for the distributed data plane.

The TPU replacement for the reference's cluster topology: a scatter-gather
edge between two vertices placed on the same slice maps to an XLA all-to-all
over ICI on a 1-D "workers" mesh (SURVEY.md §2.10 bulk-data-plane row);
multi-slice crosses DCN via the shuffle object service instead.
"""
from __future__ import annotations

from typing import Optional, Sequence

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

WORKER_AXIS = "workers"


def make_mesh(n_devices: Optional[int] = None,
              devices: Optional[Sequence] = None) -> Mesh:
    """1-D mesh over the slice's chips; data-parallel shuffle workers."""
    if devices is None:
        devices = jax.devices()
        if n_devices is not None:
            if len(devices) < n_devices:
                raise ValueError(
                    f"asked for {n_devices} devices, have {len(devices)}")
            devices = devices[:n_devices]
    import numpy as np
    return Mesh(np.asarray(devices), (WORKER_AXIS,))


def coded_buddy(partition: int, num_devices: int, offset: int = 1) -> int:
    """Rotation-offset buddy device for the coded (r2) redundant exchange:
    partition p's duplicate copy lands on device (p + offset) % D, so one
    slow or faulted chip never owns both copies of any partition."""
    return (partition + offset) % num_devices


def worker_sharding(mesh: Mesh) -> NamedSharding:
    """Rows sharded across workers (leading axis)."""
    return NamedSharding(mesh, PartitionSpec(WORKER_AXIS))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, PartitionSpec())
