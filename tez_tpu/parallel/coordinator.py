"""Mesh exchange coordinator: SCATTER_GATHER edges over ICI collectives.

This is the framework seam that turns a DAG edge into ONE SPMD program
(reference roles replaced: ShuffleHandler.java:159 server + Fetcher.java:79
clients + MergeManager's final merge all collapse into the jitted
all-to-all exchange of parallel/exchange.py).  Producer tasks register
their encoded spans; when the last producer lands, the coordinator sizes
the exchange from EXACT per-partition counts (so the padded kernel can
never overflow), runs it over the device mesh — multi-round when one round
would exceed the per-device row budget (SURVEY.md §5.7 multi-pass analog)
— and consumer tasks block on their sorted partition.

The exchange plane is skew- and straggler-aware:

* Round sizing comes from the per-(sender, partition) histogram, not the
  global max: each destination's round rows are balanced across senders in
  contiguous arrival-order chunks, so the per-pair CAP shrinks by up to D×
  versus the padded worst case (``plan_rounds``; ``legacy_sizing=True``
  keeps the old formulation as the bench baseline).
* The fair-shuffle splitter is folded in: an edge that keeps arriving with
  one partition over ``max_rows_per_round`` (``split.after`` consecutive
  exchanges, tracked across recurring DAG runs by edge suffix) gets its hot
  partitions re-partitioned across d sub-destinations, with a merge-side
  recombine by the true consumer hash — instead of re-rounding forever.
* Coded r2 mode (Coded TeraSort-style) duplicates every row to its
  destination's rotation buddy and takes the FIRST complete copy at
  readback, masking one slow or faulted chip at 2x send flops.  The
  ``mesh.exchange.delay`` fault point fires per (round, device) on the
  readback threads so chaos can prove the masking.

Single-controller topology: every runner in this process shares one
coordinator (the analog of local_shuffle_service); a multi-host deployment
runs one coordinator per host participating in a global jax mesh, with the
same register/wait surface.
"""
from __future__ import annotations

import logging
import threading
from typing import Dict, List, Optional, Tuple

import numpy as np

from tez_tpu.common import faults
from tez_tpu.common.counters import MESH_EXCHANGE_GROUP
from tez_tpu.obs import flight as _flight
from tez_tpu.ops.keycodec import matrix_to_lanes, pad_to_matrix
from tez_tpu.ops.runformat import KVBatch

log = logging.getLogger(__name__)


class MeshCapacityError(RuntimeError):
    """A single partition exceeds what the mesh exchange can carry even
    multi-round; callers fall back to the fair-shuffle split path."""


def _encode_values(batch: KVBatch, value_width: int) -> np.ndarray:
    """Values -> u32[N, 1 + value_width/4]: word 0 is the true byte length,
    the rest the zero-padded value bytes as big-endian words."""
    vmat, vlens = pad_to_matrix(batch.val_bytes, batch.val_offsets,
                                value_width)
    words = matrix_to_lanes(vmat)
    return np.concatenate([vlens.astype(np.uint32)[:, None],
                           words.astype(np.uint32)], axis=1)


def _decode_rows(lanes: np.ndarray, lengths: np.ndarray, values: np.ndarray,
                 valid: np.ndarray) -> KVBatch:
    """Exchange output -> KVBatch (vectorized byte reconstruction)."""
    from tez_tpu.ops.keycodec import lanes_to_matrix
    sel = np.flatnonzero(valid)
    if sel.size == 0:
        return KVBatch.empty()
    lanes = lanes[sel]
    klens = lengths[sel].astype(np.int64)
    vwords = values[sel]
    n, L = lanes.shape
    kmat = lanes_to_matrix(lanes)
    kmask = np.arange(L * 4)[None, :] < klens[:, None]
    key_bytes = kmat[kmask]
    key_offsets = np.zeros(n + 1, dtype=np.int64)
    np.cumsum(klens, out=key_offsets[1:])

    vlens = vwords[:, 0].astype(np.int64)
    vmat = lanes_to_matrix(np.ascontiguousarray(vwords[:, 1:]))
    vmask = np.arange(vmat.shape[1])[None, :] < vlens[:, None]
    val_bytes = vmat[vmask]
    val_offsets = np.zeros(n + 1, dtype=np.int64)
    np.cumsum(vlens, out=val_offsets[1:])
    return KVBatch(key_bytes, key_offsets, val_bytes, val_offsets)


class _EdgeState:
    def __init__(self, num_producers: int, num_consumers: int,
                 edge_id: str = ""):
        self.edge_id = edge_id
        self.num_producers = num_producers
        self.num_consumers = num_consumers
        self.max_rows_per_round: Optional[int] = None   # per-edge conf
        self.engine: Optional[str] = None     # auto|padded|ragged (per-edge)
        self.coded: Optional[str] = None      # off|r2 (per-edge)
        self.split_after: Optional[int] = None
        self.counters = None                  # triggering producer's sink
        self.spans: Dict[int, Tuple[np.ndarray, np.ndarray, np.ndarray]] = {}
        self.results: Optional[List[KVBatch]] = None
        self.error: Optional[BaseException] = None
        self.executing = False     # an _execute is in flight on some thread
        self.dirty = False         # spans changed while executing: re-run


def plan_rounds(counts: np.ndarray, per_round: int, num_devices: int,
                legacy: bool = False) -> List[Tuple[np.ndarray, int]]:
    """Round plan for an exchange with per-destination row ``counts``:
    a list of (quota, cap) where quota[d] is destination d's rows in that
    round and cap the per-(sender, dest) slot count the kernel compiles
    with.  Round r carries each destination's arrival ranks
    [r*per_round, (r+1)*per_round), so quota = clip(counts - r*per_round,
    0, per_round) and no quota ever exceeds the device budget.

    Legacy sizing pads every pair to the round's largest partition — any
    one sender COULD hold a whole destination's rows.  Histogram sizing
    instead balances each destination's quota across all D senders in
    contiguous chunks (the coordinator owns placement, so it can promise
    this), shrinking cap to ceil(quota.max()/D): up to D× less padded ICI
    traffic under skew.  Power-of-two bucketing keeps compile keys stable.
    """
    from tez_tpu.ops.device import _bucket
    counts = np.asarray(counts, dtype=np.int64)
    max_part = int(counts.max()) if counts.size else 0
    if max_part == 0:
        return []          # nothing to send: no rounds at all
    rounds = -(-max_part // per_round)
    plan: List[Tuple[np.ndarray, int]] = []
    for r in range(rounds):
        quota = np.clip(counts - r * per_round, 0, per_round)
        if legacy:
            cap = min(_bucket(min(max_part, per_round)), per_round)
        else:
            chunk = max(1, -(-int(quota.max()) // num_devices))
            cap = min(_bucket(chunk), per_round)
        plan.append((quota, cap))
    return plan


class MeshExchangeCoordinator:
    """Per-process exchange coordinator (one per runner host)."""

    def __init__(self, mesh=None, max_rows_per_round: int = 1 << 20,
                 engine: str = "auto", legacy_sizing: bool = False,
                 split_after: int = 2):
        self._mesh = mesh
        self.max_rows_per_round = max_rows_per_round
        self.engine = engine            # default; per-edge conf overrides
        self.legacy_sizing = legacy_sizing   # bench baseline: max-part CAP
        self.split_after = split_after  # 0 = splitter disabled
        self.lock = threading.Condition()
        self.edges: Dict[str, _EdgeState] = {}
        # compiled exchange programs keyed by (devices, shape...) — meshes
        # are cached per size below so these keys are stable across edges
        self._compiled: Dict[Tuple[int, int, int, int, int, bool], object] \
            = {}
        self._meshes: Dict[int, object] = {}
        self.exchanges_run = 0
        self.rows_exchanged = 0
        self.multi_round_exchanges = 0
        self.partition_splits = 0
        self.coded_buddy_wins = 0
        self.last_engine: Optional[str] = None
        #: cumulative rows landed per device lane (coded duplicates
        #: included — they occupy the lane), feeding the
        #: ``mesh.lane.<i>.*`` occupancy gauges via telemetry_collector
        self.lane_rows: Dict[int, int] = {}
        # consecutive over-budget streak per recurring edge (keyed by the
        # edge id MINUS the per-run dag prefix, so history survives re-runs)
        self._skew_history: Dict[str, int] = {}

    # ------------------------------------------------------------------ mesh
    def devices_for(self, num_consumers: int) -> int:
        """How many devices carry a W-consumer exchange: the largest device
        count d <= |devices| with W % d == 0.  d < W means each device
        carries W/d consumer partitions (routing hash%d is consistent with
        consumer partition hash%W exactly when d divides W), split apart on
        host after the exchange."""
        import jax
        avail = len(jax.devices())
        d = min(avail, num_consumers)
        while num_consumers % d != 0:
            d -= 1
        if d * 2 <= min(avail, num_consumers):
            # e.g. W=7 consumers on 4 devices -> d=1: the whole exchange
            # funnels through one device and splits on host.  Legal but
            # quietly wasteful — surface it so the operator can pick a
            # consumer count that divides (or is a multiple of) the mesh.
            log.warning(
                "mesh exchange: %d consumers on %d devices routes through "
                "only %d device(s) (largest divisor); consider a consumer "
                "parallelism divisible by the device count", num_consumers,
                avail, d)
        return d

    def mesh_for(self, num_devices: int):
        from tez_tpu.parallel.mesh import make_mesh
        if self._mesh is not None and \
                self._mesh.devices.size == num_devices:
            return self._mesh
        cached = self._meshes.get(num_devices)
        if cached is not None:
            return cached
        mesh = make_mesh(n_devices=num_devices)
        self._meshes[num_devices] = mesh
        return mesh

    # ------------------------------------------------------------- producers
    def register_producer(self, edge_id: str, task_index: int,
                          num_producers: int, num_consumers: int,
                          batch: KVBatch, key_width: int,
                          value_width: int,
                          max_rows_per_round: Optional[int] = None,
                          max_key_bytes: int = 256,
                          max_value_bytes: int = 1024,
                          engine: Optional[str] = None,
                          coded: Optional[str] = None,
                          split_after: Optional[int] = None,
                          counters=None) -> None:
        """Record one producer span (encoded).  The LAST registration runs
        the exchange inline on that producer's thread — the gang barrier:
        by then every producer's data is resident, which is exactly the
        gang-scheduling condition CONCURRENT edges declare.

        Widths AUTO-WIDEN to the span's actual max key/value (rounded to
        whole u32 lanes) up to the hard caps — the configured widths are
        slot-size hints, not limits (VERDICT r2 item 5; reference carries
        arbitrary KV, IFile.java:67).  Spans with different widths zero-pad
        to the edge max at exchange time (zero lanes == absent bytes, so
        ordering is unaffected).  Beyond the caps the record belongs on the
        host shuffle edge — HBM slots are per-row, so a single huge record
        would tax every row."""
        if len(batch.key_offsets) > 1:
            max_key = int(np.max(np.diff(batch.key_offsets)))
            if max_key > max_key_bytes:
                raise MeshCapacityError(
                    f"mesh edge carries keys up to "
                    f"tez.runtime.tpu.mesh.max.key.bytes={max_key_bytes}B, "
                    f"found {max_key}B; use the host shuffle edge for "
                    f"records this large")
            key_width = max(key_width, ((max_key + 3) // 4) * 4)
            max_val = int(np.max(np.diff(batch.val_offsets)))
            if max_val > max_value_bytes:
                raise MeshCapacityError(
                    f"mesh edge carries values up to "
                    f"tez.runtime.tpu.mesh.max.value.bytes="
                    f"{max_value_bytes}B, found {max_val}B; use the host "
                    f"shuffle edge for records this large")
            value_width = max(value_width, ((max_val + 3) // 4) * 4)
        kmat, klens = pad_to_matrix(batch.key_bytes, batch.key_offsets,
                                    key_width)
        lanes = matrix_to_lanes(kmat)
        vwords = _encode_values(batch, value_width)
        with self.lock:
            st = self.edges.setdefault(
                edge_id, _EdgeState(num_producers, num_consumers, edge_id))
            if max_rows_per_round:
                st.max_rows_per_round = int(max_rows_per_round)
            if engine:
                st.engine = engine
            if coded:
                st.coded = coded
            if split_after is not None:
                st.split_after = int(split_after)
            if counters is not None:
                st.counters = counters
            st.spans[task_index] = (lanes,
                                    klens.astype(np.uint32),
                                    vwords)
            if isinstance(st.error, TimeoutError):
                # a straggler poisoned the edge, and here it is: the edge
                # is viable again — consumer RETRIES must see a fresh
                # barrier, not the stale poison
                st.error = None
            if st.results is not None:
                # a producer RE-RAN after the exchange: invalidate and
                # re-exchange with the replacement span (consumers that
                # already read the old result fail on their
                # InputFailedEvent and re-run against the fresh one)
                log.warning("mesh edge %s: producer %d re-registered after "
                            "the exchange; re-running it", edge_id,
                            task_index)
                st.results = None
            if st.executing:
                st.dirty = True    # the in-flight run is stale; rerun after
                return
            ready = len(st.spans) >= st.num_producers
            if ready:
                st.executing = True
        if not ready:
            return
        while True:
            try:
                results = self._execute(st)
            except BaseException as e:  # noqa: BLE001 — consumers must wake
                with self.lock:
                    st.error = e
                    st.executing = False
                    self.lock.notify_all()
                raise
            with self.lock:
                if st.dirty:
                    st.dirty = False
                    continue           # spans changed mid-run: go again
                st.results = results
                st.error = None
                st.executing = False
                self.lock.notify_all()
                return

    # ------------------------------------------------------------- consumers
    def wait_consumer(self, edge_id: str, consumer_index: int,
                      num_producers: int, num_consumers: int,
                      timeout: Optional[float] = None,
                      progress=None) -> KVBatch:
        """Block until the edge's exchange lands.  `timeout` is the
        straggler defense for the gang barrier (VERDICT r3 item 7): a
        producer that never registers would otherwise stall every consumer
        forever.  On expiry the whole edge is POISONED (st.error) naming
        the missing producers, so sibling consumers fail fast instead of
        each burning its own full deadline — the actionable failure path;
        the AM's task retry / failure blame takes it from there (reference
        analog: the fetch penalty box + ShuffleScheduler.java:179
        too-long-stalled escape)."""
        import time
        deadline = None if timeout is None else time.monotonic() + timeout
        with self.lock:
            st = self.edges.setdefault(
                edge_id, _EdgeState(num_producers, num_consumers, edge_id))
            while st.results is None and st.error is None:
                # the deadline guards the PRODUCER barrier only: once every
                # span is in (or an exchange is in flight), a slow exchange
                # is compute, not a straggler — AM task-level failure
                # detection owns hung exchanges
                barrier_open = len(st.spans) < st.num_producers and \
                    not st.executing
                if deadline is not None and barrier_open and \
                        time.monotonic() > deadline:
                    missing = sorted(set(range(st.num_producers)) -
                                     set(st.spans))
                    err = TimeoutError(
                        f"mesh exchange {edge_id}: "
                        f"{len(st.spans)}/{st.num_producers} producers "
                        f"after {timeout:.0f}s; missing producer task "
                        f"indices {missing[:16]}"
                        f"{'...' if len(missing) > 16 else ''}")
                    # the edge cannot complete without the missing spans —
                    # poison it so sibling consumers fail fast
                    st.error = err
                    self.lock.notify_all()
                    raise err
                self.lock.wait(0.2)
                if progress is not None:
                    progress()
            if st.error is not None:
                raise RuntimeError(
                    f"mesh exchange {edge_id} failed") from st.error
            return st.results[consumer_index]

    def cleanup_edge(self, edge_id: str) -> None:
        with self.lock:
            self.edges.pop(edge_id, None)

    def cleanup_dag(self, dag_id_prefix: str) -> int:
        """Deletion tracking (reference: DeletionTracker/DagDeleteRunnable):
        drop every edge of a finished DAG — spans and materialized results."""
        with self.lock:
            doomed = [e for e in self.edges if e.startswith(dag_id_prefix)]
            for e in doomed:
                del self.edges[e]
            return len(doomed)

    # -------------------------------------------------------------- exchange
    def _compiled_fn(self, mesh, num_lanes: int, rows_per_worker: int,
                     cap: int, value_words: int, ragged: bool = False):
        from tez_tpu.parallel.exchange import build_distributed_shuffle
        key = (mesh.devices.size, num_lanes, rows_per_worker, cap,
               value_words, ragged)
        fn = self._compiled.get(key)
        if fn is None:
            # always explicit_dests: the coordinator owns routing (splitter
            # re-targets, coded duplicates) — the kernel must not re-derive
            # destinations from the key hash
            fn = build_distributed_shuffle(mesh, num_lanes, rows_per_worker,
                                           cap, value_words=value_words,
                                           ragged=ragged,
                                           explicit_dests=True)
            self._compiled[key] = fn
        return fn

    def _read_shards(self, arrs, mesh, edge_id: str, round_idx: int):
        """Materialize the exchange outputs one device at a time, each on
        its own daemon reader thread.  Every reader fires the
        ``mesh.exchange.delay`` fault point (detail
        ``<edge>:round=<r>:device=<d>``) before touching its shard — the
        chaos lever that turns one chip into a readback straggler, since
        the jitted SPMD body itself is not instrumentable.  Returns
        (events, results, any_done); results[d] becomes the device's
        (lanes, klens, vwords, valid) tuple, or the exception its reader
        hit (a faulted chip), once events[d] is set."""
        D = mesh.devices.size
        pos = {dev: i for i, dev in enumerate(mesh.devices.flat)}
        shard_maps = []
        for a in arrs:
            shard_maps.append(
                {pos[s.device]: s.data for s in a.addressable_shards})
        events = [threading.Event() for _ in range(D)]
        results: List[object] = [None] * D
        any_done = threading.Event()

        def _read(d: int) -> None:
            try:
                faults.fire("mesh.exchange.delay",
                            detail=f"{edge_id}:round={round_idx}:device={d}")
                results[d] = tuple(np.asarray(m[d]) for m in shard_maps)
            except BaseException as e:  # noqa: BLE001 — surfaced by reader
                results[d] = e
            finally:
                events[d].set()
                any_done.set()

        for d in range(D):
            # daemon: a delayed/hung reader is ABANDONED once its buddy's
            # copy wins — it must never pin process exit
            threading.Thread(target=_read, args=(d,), daemon=True,
                             name=f"mesh-exchange-read-{d}").start()
        return events, results, any_done

    def _select_coded(self, events, results, any_done,
                      num_devices: int) -> Tuple[Dict[int, int], int]:
        """First-complete-copy selection for coded r2: partition p is
        served by whichever of (primary p, buddy (p+1)%D) materializes
        first; ties prefer the primary so buddy wins are a true straggler
        signal.  A reader that FAILED (fault, not delay) is skipped — the
        surviving copy masks faulted chips too; only both copies failing
        surfaces an error."""
        from tez_tpu.parallel.mesh import coded_buddy
        remaining = set(range(num_devices))
        chosen: Dict[int, int] = {}
        wins = 0
        while remaining:
            progressed = False
            for p in sorted(remaining):
                cands = (p, coded_buddy(p, num_devices))
                done = [d for d in cands if events[d].is_set() and
                        not isinstance(results[d], BaseException)]
                if done:
                    chosen[p] = done[0]
                    wins += int(done[0] != p)
                    remaining.discard(p)
                    progressed = True
                elif all(events[d].is_set() for d in cands):
                    err = next(results[d] for d in cands
                               if isinstance(results[d], BaseException))
                    raise RuntimeError(
                        f"mesh exchange: both copies of partition {p} "
                        f"failed under coded r2") from err
            if remaining and not progressed:
                any_done.wait(0.05)
                any_done.clear()
        return chosen, wins

    def _execute(self, st: _EdgeState) -> List[KVBatch]:
        """Run the SPMD exchange for a complete edge.  CAP comes from exact
        host-side partition counts (fnv_rows_host == the kernel's
        partitioner), so the padded all-to-all cannot overflow; when the
        biggest partition exceeds max_rows_per_round the exchange runs in
        rank-sliced rounds and each consumer's rounds merge at the end.
        See the module docstring for the skew levers layered on top
        (histogram round sizing, the splitter, coded r2)."""
        import time

        from tez_tpu.common import metrics
        from tez_tpu.ops.host_sort import fnv_rows_host
        from tez_tpu.ops.sorter import merge_sorted_runs
        from tez_tpu.ops.runformat import Run
        from tez_tpu.parallel.exchange import resolve_engine

        # host-level seam: the jitted SPMD body is not instrumentable, so
        # chaos hits the exchange at entry (the caller's error path turns
        # this into the edge-wide failure consumers see)
        faults.fire("mesh.exchange", detail=st.edge_id)
        W = st.num_consumers
        D = self.devices_for(W)     # devices carrying the exchange; each
        mesh = self.mesh_for(D)     # holds W/D consumer partitions
        with self.lock:
            spans = [st.spans[i] for i in sorted(st.spans)]
        # harmonize widths: spans auto-widened independently — zero-pad
        # narrow ones (zero lanes/words == absent bytes; order unaffected)
        max_lanes = max((s[0].shape[1] for s in spans), default=1)
        max_vw = max((s[2].shape[1] for s in spans), default=1)

        def _widen(a: np.ndarray, width: int) -> np.ndarray:
            if a.shape[1] == width:
                return a
            return np.pad(a, ((0, 0), (0, width - a.shape[1])))

        lanes = np.concatenate([_widen(s[0], max_lanes) for s in spans]) \
            if spans else np.zeros((0, 1), np.uint32)
        klens = np.concatenate([s[1] for s in spans]) \
            if spans else np.zeros((0,), np.uint32)
        vwords = np.concatenate([_widen(s[2], max_vw) for s in spans]) \
            if spans else np.zeros((0, 1), np.uint32)
        total = lanes.shape[0]
        num_lanes = lanes.shape[1]
        value_words = vwords.shape[1]
        if total == 0:
            return [KVBatch.empty() for _ in range(W)]

        # exact routing on host: byte-masked FNV over the padded key matrix
        # (reconstruct the byte matrix from lanes — cheap, vectorized).
        # Routing is hash % D; with D | W that equals (hash % W) % D, so
        # device d receives exactly the rows of consumer partitions
        # {c : c % D == d} (split apart after the exchange).
        from tez_tpu.ops.device import _bucket
        from tez_tpu.ops.keycodec import lanes_to_matrix
        kmat = lanes_to_matrix(lanes)
        hashes = fnv_rows_host(kmat, klens.astype(np.int64))
        rdest = (hashes % np.uint32(D)).astype(np.int64)
        counts = np.bincount(rdest, minlength=D)
        per_round = st.max_rows_per_round or self.max_rows_per_round

        # ---- fair-shuffle splitter: an edge whose largest partition has
        # exceeded the round budget split_after times IN A ROW (recurring
        # runs share the id suffix; the dag prefix changes per run) gets
        # each hot destination re-partitioned across d_sub sub-destinations
        # in contiguous arrival blocks.  Routing stops being key-derivable
        # for those rows, but the CONSUMER identity (hash % W) still is —
        # the merge-side recombine below reassembles split partitions.
        skew_key = st.edge_id.split("/", 1)[-1] or st.edge_id
        over_budget = int(counts.max()) > per_round
        with self.lock:
            if over_budget:
                streak = self._skew_history.get(skew_key, 0) + 1
                self._skew_history[skew_key] = streak
            else:
                self._skew_history.pop(skew_key, None)
                streak = 0
        split_after = st.split_after if st.split_after is not None \
            else self.split_after
        splits = 0
        if over_budget and D > 1 and split_after > 0 and \
                streak >= split_after:
            hot = np.flatnonzero(counts > per_round)
            load = counts.astype(np.int64).copy()
            load[hot] = per_round      # each hot dest keeps a full round
            # split every hot dest against the ORIGINAL routing snapshot:
            # rows an earlier split re-homed INTO d are not d's to re-split
            orig_rdest = rdest.copy()
            # biggest partition gets first pick of the headroom
            for d in hot[np.argsort(-counts[hot], kind="stable")]:
                n_d = int(counts[d])
                amounts = np.zeros(D, dtype=np.int64)
                amounts[d] = per_round
                remaining = n_d - per_round
                # fill other destinations' headroom, least-loaded first:
                # whenever the total fits in D*per_round at all, the
                # exchange comes out single-round
                for t in np.argsort(load, kind="stable"):
                    if remaining == 0:
                        break
                    if t == d or load[t] >= per_round:
                        continue
                    take = min(int(per_round - load[t]), remaining)
                    amounts[t] += take
                    load[t] += take
                    remaining -= take
                if remaining:
                    # no headroom left: multi-round is inevitable; spread
                    # the rest evenly so no destination re-rounds alone
                    base, extra = divmod(remaining, D)
                    add = np.full(D, base, dtype=np.int64)
                    add[:extra] += 1
                    amounts += add
                    load += add
                # carve d's arrival-ordered rows into contiguous blocks
                # handed to destinations in ASCENDING device index: the
                # consumer-side recombine merges runs in device order, so
                # ascending blocks reconstruct arrival order exactly
                # (deterministic equal-key ties, same as the unsplit path)
                rows = np.flatnonzero(orig_rdest == d)  # ascending==arrival
                rdest[rows] = np.repeat(np.arange(D), amounts)
                splits += 1
            counts = np.bincount(rdest, minlength=D)
            with self.lock:
                self.partition_splits += splits
            log.info("mesh exchange %s: splitter engaged after %d "
                     "over-budget exchange(s); %d hot partition(s) "
                     "re-partitioned", st.edge_id, streak, splits)

        engine, engine_reason = resolve_engine(st.engine or self.engine,
                                               mesh)
        self.last_engine = engine
        log.debug("mesh exchange %s: engine=%s (%s)", st.edge_id, engine,
                  engine_reason)
        coded = (st.coded or "off") == "r2" and D > 1
        plan = plan_rounds(counts, per_round, D, legacy=self.legacy_sizing)
        _flight.record(_flight.EXCHANGE, "plan", st.edge_id,
                       a=len(plan), b=total)

        # rank of each row within its routing partition (arrival order)
        order = np.argsort(rdest, kind="stable")
        ranks = np.empty(total, dtype=np.int64)
        starts = np.zeros(D + 1, dtype=np.int64)
        np.cumsum(counts, out=starts[1:])
        ranks[order] = np.arange(total, dtype=np.int64) - \
            np.repeat(starts[:-1], counts)

        row_words = num_lanes + 1 + value_words   # lanes + klen + vwords
        sent_rows = dup_rows = buddy_wins = rounds_run = 0
        lane_counts = np.zeros(D, dtype=np.int64)
        per_round_results: List[List[KVBatch]] = []
        for r, (quota, cap) in enumerate(plan):
            lo = r * per_round
            sel = np.flatnonzero((ranks >= lo) & (ranks < lo + per_round))
            n_round = sel.size
            if n_round == 0:
                continue
            t_round = time.perf_counter()
            rows_idx = sel
            dests_all = rdest[sel]
            rtag = None
            if coded:
                # r2: every row ALSO goes to its destination's rotation
                # buddy.  An extra value word carries the routing partition
                # (same on both copies) so each shard can tell its primary
                # rows from buddy copies — not derivable from the key once
                # the splitter has re-routed rows.
                rows_idx = np.concatenate([sel, sel])
                rtag = np.concatenate([dests_all, dests_all]) \
                    .astype(np.uint32)
                dests_all = np.concatenate(
                    [dests_all, (dests_all + 1) % D])
                dup_rows += n_round
            qc = np.bincount(dests_all, minlength=D)
            lane_counts += qc
            if coded:
                # duplication doubled the quotas; re-derive the balanced
                # cap from the combined histogram (coded always uses
                # balanced placement — legacy tail-packing could put a
                # whole destination's copies on one sender)
                cap = min(_bucket(max(1, -(-int(qc.max()) // D))),
                          per_round)
            balanced = coded or not self.legacy_sizing
            if balanced:
                # balanced blocked placement: destination d's rows split
                # into <= D contiguous arrival-order chunks, chunk j ->
                # sender j, so no (sender, dest) pair exceeds
                # ceil(quota_d / D) <= cap.  Contiguous chunks + the
                # receiver's stable sender-major merge preserve global
                # arrival order for equal keys.
                qorder = np.argsort(dests_all, kind="stable")
                lrank = np.empty(dests_all.size, dtype=np.int64)
                qstarts = np.zeros(D + 1, dtype=np.int64)
                np.cumsum(qc, out=qstarts[1:])
                lrank[qorder] = np.arange(dests_all.size, dtype=np.int64) \
                    - np.repeat(qstarts[:-1], qc)
                chunk_d = np.maximum(1, -(-qc // D))
                senders = lrank // chunk_d[dests_all]
                loads = np.bincount(senders, minlength=D)
                N = _bucket(int(loads.max()))
                place = np.argsort(senders, kind="stable")
                within = np.empty(senders.size, dtype=np.int64)
                lstarts = np.zeros(D + 1, dtype=np.int64)
                np.cumsum(loads, out=lstarts[1:])
                within[place] = \
                    np.arange(senders.size, dtype=np.int64) - \
                    np.repeat(lstarts[:-1], loads)
                pos = senders * N + within
            else:
                # legacy layout: rows in arrival order, zero tail pad
                N = _bucket(-(-dests_all.size // D))
                pos = np.arange(dests_all.size, dtype=np.int64)
            vw = value_words + (1 if coded else 0)
            r_lanes = np.zeros((D * N, num_lanes), np.uint32)
            r_klens = np.zeros(D * N, np.uint32)
            r_vwords = np.zeros((D * N, vw), np.uint32)
            r_valid = np.zeros(D * N, bool)
            r_dests = np.zeros(D * N, np.uint32)
            r_lanes[pos] = lanes[rows_idx]
            r_klens[pos] = klens[rows_idx]
            r_vwords[pos, :value_words] = vwords[rows_idx]
            if coded:
                r_vwords[pos, value_words] = rtag
            r_valid[pos] = True
            r_dests[pos] = dests_all.astype(np.uint32)
            fn = self._compiled_fn(mesh, num_lanes, N, cap, vw,
                                   ragged=(engine == "ragged"))
            out_lanes, out_klens, out_vwords, out_valid, dropped = \
                fn(r_lanes, r_klens, r_vwords, r_valid, r_dests)
            # the dropped flag is a tiny replicated array: reading it does
            # not serialize the per-device readback below (the delay fault
            # stalls our reader threads, not device compute)
            dropped_total = int(np.asarray(dropped).sum())
            if dropped_total:
                raise MeshCapacityError(
                    f"mesh exchange overflow: {dropped_total} rows dropped "
                    f"(cap {cap}, round {r}) — capacity accounting bug")
            events, results, any_done = self._read_shards(
                (out_lanes, out_klens, out_vwords, out_valid), mesh,
                st.edge_id, r)
            round_parts: List[KVBatch] = []
            if coded:
                chosen, wins = self._select_coded(events, results,
                                                  any_done, D)
                buddy_wins += wins
                for p in range(D):
                    dl, dk, dv, dval = results[chosen[p]]
                    keep = dval.astype(bool) & (dv[:, value_words] == p)
                    round_parts.append(_decode_rows(
                        dl, dk,
                        np.ascontiguousarray(dv[:, :value_words]), keep))
            else:
                for d in range(D):
                    events[d].wait()
                    if isinstance(results[d], BaseException):
                        raise results[d]
                    dl, dk, dv, dval = results[d]
                    round_parts.append(
                        _decode_rows(dl, dk, dv, dval.astype(bool)))
            per_round_results.append(round_parts)
            metrics.observe("mesh.exchange.round",
                            (time.perf_counter() - t_round) * 1000.0,
                            st.counters)
            _flight.record(_flight.EXCHANGE, "round", st.edge_id,
                           a=r, b=n_round)
            sent_rows += n_round
            rounds_run += 1
            with self.lock:
                self.rows_exchanged += n_round
        with self.lock:
            self.exchanges_run += 1
            self.coded_buddy_wins += buddy_wins
            if rounds_run > 1:
                self.multi_round_exchanges += 1
            for d in range(D):
                self.lane_rows[d] = \
                    self.lane_rows.get(d, 0) + int(lane_counts[d])
        if st.counters is not None:
            g = st.counters.group(MESH_EXCHANGE_GROUP)
            g.find_counter("exchange.rows.sent").increment(sent_rows)
            g.find_counter("exchange.bytes.sent").increment(
                sent_rows * row_words * 4)
            g.find_counter("exchange.rounds").increment(rounds_run)
            g.find_counter("exchange.splits").increment(splits)
            g.find_counter("exchange.coded.duplicate.bytes").increment(
                dup_rows * row_words * 4)
            g.find_counter("exchange.coded.buddy.wins").increment(
                buddy_wins)

        if len(per_round_results) == 1:
            per_device = per_round_results[0]
        else:
            per_device = []
            for w in range(D):
                runs = [Run(res[w],
                            np.array([0, res[w].num_records],
                                     dtype=np.int64))
                        for res in per_round_results
                        if res[w].num_records > 0]
                if not runs:
                    per_device.append(KVBatch.empty())
                elif len(runs) == 1:
                    per_device.append(runs[0].batch)
                else:
                    per_device.append(merge_sorted_runs(
                        runs, 1, num_lanes * 4, engine="host").batch)
        if W == D and splits == 0:
            return per_device
        # general consumer assembly, covering both W > D (device d holds
        # consumer partitions {c : c % D == d} key-sorted) and the
        # splitter's merge-side recombine (a split consumer's rows landed
        # on several devices; each device's slice is key-sorted, so a
        # stable host merge in device order reassembles the partition with
        # arrival-order ties).  The TRUE consumer hash (fnv % W) is always
        # key-derivable, even for re-routed rows.
        runs_per_consumer: List[List[KVBatch]] = [[] for _ in range(W)]
        for d in range(D):
            batch = per_device[d]
            if batch.num_records == 0:
                continue
            bmat, blens = pad_to_matrix(batch.key_bytes, batch.key_offsets,
                                        num_lanes * 4)
            c_part = (fnv_rows_host(bmat, blens.astype(np.int64)) %
                      np.uint32(W)).astype(np.int64)
            for c in np.unique(c_part):
                csel = np.flatnonzero(c_part == c)
                runs_per_consumer[int(c)].append(batch.take(csel))
        results_out: List[KVBatch] = []
        for c in range(W):
            runs = runs_per_consumer[c]
            if not runs:
                results_out.append(KVBatch.empty())
            elif len(runs) == 1:
                results_out.append(runs[0])
            else:
                results_out.append(merge_sorted_runs(
                    [Run(b, np.array([0, b.num_records], dtype=np.int64))
                     for b in runs], 1, num_lanes * 4,
                    engine="host").batch)
        return results_out


_coordinator: Optional[MeshExchangeCoordinator] = None
_coordinator_lock = threading.Lock()


def mesh_coordinator() -> MeshExchangeCoordinator:
    global _coordinator
    with _coordinator_lock:
        if _coordinator is None:
            import os
            _coordinator = MeshExchangeCoordinator(
                max_rows_per_round=int(os.environ.get(
                    "TEZ_TPU_MESH_MAX_ROWS_PER_ROUND", 1 << 20)))
        return _coordinator


def reset_coordinator() -> None:
    """Test hook: drop all edge state (fresh process semantics)."""
    global _coordinator
    with _coordinator_lock:
        _coordinator = None


def telemetry_collector() -> Dict[str, float]:
    """Live-telemetry hook (obs/timeseries registry): per-device-lane
    exchange occupancy gauges — each lane's cumulative landed rows and
    its share of all landed rows, so ``graft top`` shows a skewed mesh as
    one hot lane instead of an averaged-away total.  Never *creates* the
    coordinator: an AM that ran no exchange reports nothing."""
    with _coordinator_lock:
        coord = _coordinator
    if coord is None:
        return {}
    with coord.lock:
        lane_rows = dict(coord.lane_rows)
    total = float(sum(lane_rows.values()))
    out: Dict[str, float] = {}
    for d, rows in lane_rows.items():
        out[f"mesh.lane.{d}.rows"] = float(rows)
        out[f"mesh.lane.{d}.occupancy"] = \
            round(rows / total, 6) if total else 0.0
    return out
