"""Mesh exchange coordinator: SCATTER_GATHER edges over ICI collectives.

This is the framework seam that turns a DAG edge into ONE SPMD program
(reference roles replaced: ShuffleHandler.java:159 server + Fetcher.java:79
clients + MergeManager's final merge all collapse into the jitted
all-to-all exchange of parallel/exchange.py).  Producer tasks register
their encoded spans; when the last producer lands, the coordinator sizes
the exchange from EXACT per-partition counts (so the padded kernel can
never overflow), runs it over the device mesh — multi-round when one round
would exceed the per-device row budget (SURVEY.md §5.7 multi-pass analog)
— and consumer tasks block on their sorted partition.

Single-controller topology: every runner in this process shares one
coordinator (the analog of local_shuffle_service); a multi-host deployment
runs one coordinator per host participating in a global jax mesh, with the
same register/wait surface.
"""
from __future__ import annotations

import logging
import threading
from typing import Dict, List, Optional, Tuple

import numpy as np

from tez_tpu.common import faults
from tez_tpu.ops.keycodec import matrix_to_lanes, pad_to_matrix
from tez_tpu.ops.runformat import KVBatch

log = logging.getLogger(__name__)


class MeshCapacityError(RuntimeError):
    """A single partition exceeds what the mesh exchange can carry even
    multi-round; callers fall back to the fair-shuffle split path."""


def _encode_values(batch: KVBatch, value_width: int) -> np.ndarray:
    """Values -> u32[N, 1 + value_width/4]: word 0 is the true byte length,
    the rest the zero-padded value bytes as big-endian words."""
    vmat, vlens = pad_to_matrix(batch.val_bytes, batch.val_offsets,
                                value_width)
    words = matrix_to_lanes(vmat)
    return np.concatenate([vlens.astype(np.uint32)[:, None],
                           words.astype(np.uint32)], axis=1)


def _decode_rows(lanes: np.ndarray, lengths: np.ndarray, values: np.ndarray,
                 valid: np.ndarray) -> KVBatch:
    """Exchange output -> KVBatch (vectorized byte reconstruction)."""
    from tez_tpu.ops.keycodec import lanes_to_matrix
    sel = np.flatnonzero(valid)
    if sel.size == 0:
        return KVBatch.empty()
    lanes = lanes[sel]
    klens = lengths[sel].astype(np.int64)
    vwords = values[sel]
    n, L = lanes.shape
    kmat = lanes_to_matrix(lanes)
    kmask = np.arange(L * 4)[None, :] < klens[:, None]
    key_bytes = kmat[kmask]
    key_offsets = np.zeros(n + 1, dtype=np.int64)
    np.cumsum(klens, out=key_offsets[1:])

    vlens = vwords[:, 0].astype(np.int64)
    vmat = lanes_to_matrix(np.ascontiguousarray(vwords[:, 1:]))
    vmask = np.arange(vmat.shape[1])[None, :] < vlens[:, None]
    val_bytes = vmat[vmask]
    val_offsets = np.zeros(n + 1, dtype=np.int64)
    np.cumsum(vlens, out=val_offsets[1:])
    return KVBatch(key_bytes, key_offsets, val_bytes, val_offsets)


class _EdgeState:
    def __init__(self, num_producers: int, num_consumers: int,
                 edge_id: str = ""):
        self.edge_id = edge_id
        self.num_producers = num_producers
        self.num_consumers = num_consumers
        self.max_rows_per_round: Optional[int] = None   # per-edge conf
        self.spans: Dict[int, Tuple[np.ndarray, np.ndarray, np.ndarray]] = {}
        self.results: Optional[List[KVBatch]] = None
        self.error: Optional[BaseException] = None
        self.executing = False     # an _execute is in flight on some thread
        self.dirty = False         # spans changed while executing: re-run


class MeshExchangeCoordinator:
    """Per-process exchange coordinator (one per runner host)."""

    def __init__(self, mesh=None, max_rows_per_round: int = 1 << 20):
        self._mesh = mesh
        self.max_rows_per_round = max_rows_per_round
        self.lock = threading.Condition()
        self.edges: Dict[str, _EdgeState] = {}
        # compiled exchange programs keyed by (devices, shape...) — meshes
        # are cached per size below so these keys are stable across edges
        self._compiled: Dict[Tuple[int, int, int, int], object] = {}
        self._meshes: Dict[int, object] = {}
        self.exchanges_run = 0
        self.rows_exchanged = 0
        self.multi_round_exchanges = 0

    # ------------------------------------------------------------------ mesh
    def devices_for(self, num_consumers: int) -> int:
        """How many devices carry a W-consumer exchange: the largest device
        count d <= |devices| with W % d == 0.  d < W means each device
        carries W/d consumer partitions (routing hash%d is consistent with
        consumer partition hash%W exactly when d divides W), split apart on
        host after the exchange."""
        import jax
        avail = len(jax.devices())
        d = min(avail, num_consumers)
        while num_consumers % d != 0:
            d -= 1
        if d * 2 <= min(avail, num_consumers):
            # e.g. W=7 consumers on 4 devices -> d=1: the whole exchange
            # funnels through one device and splits on host.  Legal but
            # quietly wasteful — surface it so the operator can pick a
            # consumer count that divides (or is a multiple of) the mesh.
            log.warning(
                "mesh exchange: %d consumers on %d devices routes through "
                "only %d device(s) (largest divisor); consider a consumer "
                "parallelism divisible by the device count", num_consumers,
                avail, d)
        return d

    def mesh_for(self, num_devices: int):
        from tez_tpu.parallel.mesh import make_mesh
        if self._mesh is not None and \
                self._mesh.devices.size == num_devices:
            return self._mesh
        cached = self._meshes.get(num_devices)
        if cached is not None:
            return cached
        mesh = make_mesh(n_devices=num_devices)
        self._meshes[num_devices] = mesh
        return mesh

    # ------------------------------------------------------------- producers
    def register_producer(self, edge_id: str, task_index: int,
                          num_producers: int, num_consumers: int,
                          batch: KVBatch, key_width: int,
                          value_width: int,
                          max_rows_per_round: Optional[int] = None,
                          max_key_bytes: int = 256,
                          max_value_bytes: int = 1024) -> None:
        """Record one producer span (encoded).  The LAST registration runs
        the exchange inline on that producer's thread — the gang barrier:
        by then every producer's data is resident, which is exactly the
        gang-scheduling condition CONCURRENT edges declare.

        Widths AUTO-WIDEN to the span's actual max key/value (rounded to
        whole u32 lanes) up to the hard caps — the configured widths are
        slot-size hints, not limits (VERDICT r2 item 5; reference carries
        arbitrary KV, IFile.java:67).  Spans with different widths zero-pad
        to the edge max at exchange time (zero lanes == absent bytes, so
        ordering is unaffected).  Beyond the caps the record belongs on the
        host shuffle edge — HBM slots are per-row, so a single huge record
        would tax every row."""
        if len(batch.key_offsets) > 1:
            max_key = int(np.max(np.diff(batch.key_offsets)))
            if max_key > max_key_bytes:
                raise MeshCapacityError(
                    f"mesh edge carries keys up to "
                    f"tez.runtime.tpu.mesh.max.key.bytes={max_key_bytes}B, "
                    f"found {max_key}B; use the host shuffle edge for "
                    f"records this large")
            key_width = max(key_width, ((max_key + 3) // 4) * 4)
            max_val = int(np.max(np.diff(batch.val_offsets)))
            if max_val > max_value_bytes:
                raise MeshCapacityError(
                    f"mesh edge carries values up to "
                    f"tez.runtime.tpu.mesh.max.value.bytes="
                    f"{max_value_bytes}B, found {max_val}B; use the host "
                    f"shuffle edge for records this large")
            value_width = max(value_width, ((max_val + 3) // 4) * 4)
        kmat, klens = pad_to_matrix(batch.key_bytes, batch.key_offsets,
                                    key_width)
        lanes = matrix_to_lanes(kmat)
        vwords = _encode_values(batch, value_width)
        with self.lock:
            st = self.edges.setdefault(
                edge_id, _EdgeState(num_producers, num_consumers, edge_id))
            if max_rows_per_round:
                st.max_rows_per_round = int(max_rows_per_round)
            st.spans[task_index] = (lanes,
                                    klens.astype(np.uint32),
                                    vwords)
            if isinstance(st.error, TimeoutError):
                # a straggler poisoned the edge, and here it is: the edge
                # is viable again — consumer RETRIES must see a fresh
                # barrier, not the stale poison
                st.error = None
            if st.results is not None:
                # a producer RE-RAN after the exchange: invalidate and
                # re-exchange with the replacement span (consumers that
                # already read the old result fail on their
                # InputFailedEvent and re-run against the fresh one)
                log.warning("mesh edge %s: producer %d re-registered after "
                            "the exchange; re-running it", edge_id,
                            task_index)
                st.results = None
            if st.executing:
                st.dirty = True    # the in-flight run is stale; rerun after
                return
            ready = len(st.spans) >= st.num_producers
            if ready:
                st.executing = True
        if not ready:
            return
        while True:
            try:
                results = self._execute(st)
            except BaseException as e:  # noqa: BLE001 — consumers must wake
                with self.lock:
                    st.error = e
                    st.executing = False
                    self.lock.notify_all()
                raise
            with self.lock:
                if st.dirty:
                    st.dirty = False
                    continue           # spans changed mid-run: go again
                st.results = results
                st.error = None
                st.executing = False
                self.lock.notify_all()
                return

    # ------------------------------------------------------------- consumers
    def wait_consumer(self, edge_id: str, consumer_index: int,
                      num_producers: int, num_consumers: int,
                      timeout: Optional[float] = None,
                      progress=None) -> KVBatch:
        """Block until the edge's exchange lands.  `timeout` is the
        straggler defense for the gang barrier (VERDICT r3 item 7): a
        producer that never registers would otherwise stall every consumer
        forever.  On expiry the whole edge is POISONED (st.error) naming
        the missing producers, so sibling consumers fail fast instead of
        each burning its own full deadline — the actionable failure path;
        the AM's task retry / failure blame takes it from there (reference
        analog: the fetch penalty box + ShuffleScheduler.java:179
        too-long-stalled escape)."""
        import time
        deadline = None if timeout is None else time.monotonic() + timeout
        with self.lock:
            st = self.edges.setdefault(
                edge_id, _EdgeState(num_producers, num_consumers, edge_id))
            while st.results is None and st.error is None:
                # the deadline guards the PRODUCER barrier only: once every
                # span is in (or an exchange is in flight), a slow exchange
                # is compute, not a straggler — AM task-level failure
                # detection owns hung exchanges
                barrier_open = len(st.spans) < st.num_producers and \
                    not st.executing
                if deadline is not None and barrier_open and \
                        time.monotonic() > deadline:
                    missing = sorted(set(range(st.num_producers)) -
                                     set(st.spans))
                    err = TimeoutError(
                        f"mesh exchange {edge_id}: "
                        f"{len(st.spans)}/{st.num_producers} producers "
                        f"after {timeout:.0f}s; missing producer task "
                        f"indices {missing[:16]}"
                        f"{'...' if len(missing) > 16 else ''}")
                    # the edge cannot complete without the missing spans —
                    # poison it so sibling consumers fail fast
                    st.error = err
                    self.lock.notify_all()
                    raise err
                self.lock.wait(0.2)
                if progress is not None:
                    progress()
            if st.error is not None:
                raise RuntimeError(
                    f"mesh exchange {edge_id} failed") from st.error
            return st.results[consumer_index]

    def cleanup_edge(self, edge_id: str) -> None:
        with self.lock:
            self.edges.pop(edge_id, None)

    def cleanup_dag(self, dag_id_prefix: str) -> int:
        """Deletion tracking (reference: DeletionTracker/DagDeleteRunnable):
        drop every edge of a finished DAG — spans and materialized results."""
        with self.lock:
            doomed = [e for e in self.edges if e.startswith(dag_id_prefix)]
            for e in doomed:
                del self.edges[e]
            return len(doomed)

    # -------------------------------------------------------------- exchange
    def _compiled_fn(self, mesh, num_lanes: int, rows_per_worker: int,
                     cap: int, value_words: int):
        from tez_tpu.parallel.exchange import build_distributed_shuffle
        key = (mesh.devices.size, num_lanes, rows_per_worker, cap,
               value_words)
        fn = self._compiled.get(key)
        if fn is None:
            fn = build_distributed_shuffle(mesh, num_lanes, rows_per_worker,
                                           cap, value_words=value_words)
            self._compiled[key] = fn
        return fn

    def _execute(self, st: _EdgeState) -> List[KVBatch]:
        """Run the SPMD exchange for a complete edge.  CAP comes from exact
        host-side partition counts (fnv_rows_host == the kernel's
        partitioner), so the padded all-to-all cannot overflow; when the
        biggest partition exceeds max_rows_per_round the exchange runs in
        rank-sliced rounds and each consumer's rounds merge at the end."""
        from tez_tpu.ops.host_sort import fnv_rows_host
        from tez_tpu.ops.sorter import merge_sorted_runs
        from tez_tpu.ops.runformat import Run

        # host-level seam: the jitted SPMD body is not instrumentable, so
        # chaos hits the exchange at entry (the caller's error path turns
        # this into the edge-wide failure consumers see)
        faults.fire("mesh.exchange", detail=st.edge_id)
        W = st.num_consumers
        D = self.devices_for(W)     # devices carrying the exchange; each
        mesh = self.mesh_for(D)     # holds W/D consumer partitions
        with self.lock:
            spans = [st.spans[i] for i in sorted(st.spans)]
        # harmonize widths: spans auto-widened independently — zero-pad
        # narrow ones (zero lanes/words == absent bytes; order unaffected)
        max_lanes = max((s[0].shape[1] for s in spans), default=1)
        max_vw = max((s[2].shape[1] for s in spans), default=1)

        def _widen(a: np.ndarray, width: int) -> np.ndarray:
            if a.shape[1] == width:
                return a
            return np.pad(a, ((0, 0), (0, width - a.shape[1])))

        lanes = np.concatenate([_widen(s[0], max_lanes) for s in spans]) \
            if spans else np.zeros((0, 1), np.uint32)
        klens = np.concatenate([s[1] for s in spans]) \
            if spans else np.zeros((0,), np.uint32)
        vwords = np.concatenate([_widen(s[2], max_vw) for s in spans]) \
            if spans else np.zeros((0, 1), np.uint32)
        total = lanes.shape[0]
        num_lanes = lanes.shape[1]
        value_words = vwords.shape[1]
        if total == 0:
            return [KVBatch.empty() for _ in range(W)]

        # exact routing on host: byte-masked FNV over the padded key matrix
        # (reconstruct the byte matrix from lanes — cheap, vectorized).
        # Routing is hash % D; with D | W that equals (hash % W) % D, so
        # device d receives exactly the rows of consumer partitions
        # {c : c % D == d} (split apart after the exchange).
        from tez_tpu.ops.device import _bucket
        from tez_tpu.ops.keycodec import lanes_to_matrix
        kmat = lanes_to_matrix(lanes)
        hashes = fnv_rows_host(kmat, klens.astype(np.int64))
        part = (hashes % np.uint32(D)).astype(np.int64)
        counts = np.bincount(part, minlength=D)
        max_part = int(counts.max())
        per_round = st.max_rows_per_round or self.max_rows_per_round
        rounds = max(1, -(-max_part // per_round))
        # power-of-two bucketing keeps the compiled-program cache keys
        # stable across runs with slightly different cardinalities (the
        # kernel tolerates extra capacity as padding)
        cap = min(_bucket(min(max_part, per_round)), per_round)

        # rank of each row within its partition (stable arrival order)
        order = np.argsort(part, kind="stable")
        ranks = np.empty(total, dtype=np.int64)
        starts = np.zeros(D + 1, dtype=np.int64)
        np.cumsum(counts, out=starts[1:])
        ranks[order] = np.arange(total, dtype=np.int64) - \
            np.repeat(starts[:-1], counts)

        per_round_results: List[List[KVBatch]] = []
        for r in range(rounds):
            lo, hi = r * cap, (r + 1) * cap
            sel = np.flatnonzero((ranks >= lo) & (ranks < hi))
            n_round = sel.size
            if n_round == 0:
                continue
            # rows per worker, padded AND bucketed (stable compile keys)
            N = _bucket(-(-n_round // D))
            pad = D * N - n_round
            r_lanes = np.concatenate(
                [lanes[sel],
                 np.zeros((pad, num_lanes), np.uint32)])
            r_klens = np.concatenate([klens[sel],
                                      np.zeros(pad, np.uint32)])
            r_vwords = np.concatenate(
                [vwords[sel], np.zeros((pad, value_words), np.uint32)])
            r_valid = np.concatenate([np.ones(n_round, bool),
                                      np.zeros(pad, bool)])
            fn = self._compiled_fn(mesh, num_lanes, N, cap, value_words)
            out_lanes, out_klens, out_vwords, out_valid, dropped = \
                fn(r_lanes, r_klens, r_vwords, r_valid)
            dropped_total = int(np.asarray(dropped).sum())
            if dropped_total:
                raise MeshCapacityError(
                    f"mesh exchange overflow: {dropped_total} rows dropped "
                    f"(cap {cap}, round {r}) — capacity accounting bug")
            out_lanes = np.asarray(out_lanes).reshape(D, -1, num_lanes)
            out_klens = np.asarray(out_klens).reshape(D, -1)
            out_vwords = np.asarray(out_vwords).reshape(D, -1, value_words)
            out_valid = np.asarray(out_valid).reshape(D, -1)
            per_round_results.append([
                _decode_rows(out_lanes[w], out_klens[w], out_vwords[w],
                             out_valid[w]) for w in range(D)])
            with self.lock:
                self.rows_exchanged += n_round
        with self.lock:
            self.exchanges_run += 1
            if rounds > 1:
                self.multi_round_exchanges += 1

        if len(per_round_results) == 1:
            per_device = per_round_results[0]
        else:
            per_device = []
            for w in range(D):
                runs = [Run(res[w],
                            np.array([0, res[w].num_records],
                                     dtype=np.int64))
                        for res in per_round_results
                        if res[w].num_records > 0]
                if not runs:
                    per_device.append(KVBatch.empty())
                elif len(runs) == 1:
                    per_device.append(runs[0].batch)
                else:
                    per_device.append(merge_sorted_runs(
                        runs, 1, num_lanes * 4, engine="host").batch)
        if W == D:
            return per_device
        # consumers exceed devices: device d holds partitions
        # {c : c % D == d} key-sorted; split them apart (stable selection
        # from a key-sorted stream stays key-sorted)
        results: List[Optional[KVBatch]] = [None] * W
        for d in range(D):
            batch = per_device[d]
            if batch.num_records == 0:
                for c in range(d, W, D):
                    results[c] = KVBatch.empty()
                continue
            bmat, blens = pad_to_matrix(batch.key_bytes, batch.key_offsets,
                                        num_lanes * 4)
            c_part = (fnv_rows_host(bmat, blens.astype(np.int64)) %
                      np.uint32(W)).astype(np.int64)
            for c in range(d, W, D):
                sel = np.flatnonzero(c_part == c)
                results[c] = batch.take(sel) if sel.size else \
                    KVBatch.empty()
        return results    # type: ignore[return-value]


_coordinator: Optional[MeshExchangeCoordinator] = None
_coordinator_lock = threading.Lock()


def mesh_coordinator() -> MeshExchangeCoordinator:
    global _coordinator
    with _coordinator_lock:
        if _coordinator is None:
            import os
            _coordinator = MeshExchangeCoordinator(
                max_rows_per_round=int(os.environ.get(
                    "TEZ_TPU_MESH_MAX_ROWS_PER_ROUND", 1 << 20)))
        return _coordinator


def reset_coordinator() -> None:
    """Test hook: drop all edge state (fresh process semantics)."""
    global _coordinator
    with _coordinator_lock:
        _coordinator = None
