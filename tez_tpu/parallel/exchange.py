"""Distributed scatter-gather shuffle: the ICI all-to-all data plane.

This is the multi-chip replacement for the reference's fetch-based shuffle
(ShuffleHandler + Fetcher, SURVEY.md §2.10): instead of N^2 HTTP fetches, the
whole exchange is ONE jitted SPMD program over a device mesh —

    per worker:  hash-partition -> local segmented sort ->
    all-to-all over ICI          (partition p's rows land on worker p) ->
    local k-way merge (stable sort of concatenation)

Row payload is fully general KV: `lanes` carry the key bytes as big-endian
u32 words (keycodec packing, so lane order == byte order), `lengths` the
true key length (the tie-break that makes zero-padded short keys sort
exactly like raw bytes: "ab" < "ab\\x00"), and `values` V u32 words per row
(fixed-width value slots; the mesh edge layer enforces the width).

Everything is static-shape: each worker holds up to N rows (padding rows
carry partition = P_MAX so they sort to the tail and exchange as slack), and
the all-to-all moves a fixed [W, CAP] send buffer per worker — the padded
formulation of a ragged all-to-all.  Skew beyond CAP is handled above this
kernel: the mesh exchange coordinator sizes CAP from exact partition counts
and falls back to a multi-round exchange when one round would exceed the
device budget (SURVEY.md §5.7), with fair-shuffle splitting for persistent
skew.

Two first-class engines share the choreography (docs/exchange.md):

- ``padded`` — the portable default: a fixed [W, CAP] send buffer per
  worker moves over ``jax.lax.all_to_all``; padding slots cross ICI as
  slack.
- ``ragged`` — ``jax.lax.ragged_all_to_all``: only real rows cross ICI.
  TPU-only today (XLA:CPU lacks the thunk); ``probe_ragged_support``
  detects availability at runtime and ``resolve_engine`` maps the
  ``tez.runtime.mesh.exchange.engine`` knob (auto|padded|ragged) onto a
  bit-exact choice for this backend.

Routing is normally the on-device FNV-1a of each key, but callers may pass
EXPLICIT per-row destinations (``explicit_dests=True``): the coordinator
already computes the exact host-side histogram with the same hash, and
explicit routing is what lets it re-partition persistently hot keys across
sub-partitions (fair-shuffle splitter) and send coded duplicate rows to a
rotation-offset buddy device (Coded TeraSort r2) — neither destination is
derivable from the key alone.
"""
from __future__ import annotations

import functools
import logging
import threading
from typing import Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from tez_tpu.parallel.mesh import WORKER_AXIS

log = logging.getLogger(__name__)

INVALID = jnp.uint32(0xFFFFFFFF)

EXCHANGE_ENGINES = ("auto", "padded", "ragged")


def _fnv_lanes(lanes: jnp.ndarray, lengths: jnp.ndarray) -> jnp.ndarray:
    """FNV-1a over each row's key BYTES (big-endian expansion of the lanes,
    truncated to the true length) — the distributed kernel's partitioner,
    byte-for-byte the same hash a host HashPartitioner computes over the
    raw key, so mesh and host shuffles route identically."""
    n, num_lanes = lanes.shape
    h = jnp.full((n,), 2166136261, dtype=jnp.uint32)
    for i in range(num_lanes):
        word = lanes[:, i]
        for shift in (24, 16, 8, 0):
            byte_index = i * 4 + (3 - shift // 8)
            byte = (word >> shift) & jnp.uint32(0xFF)
            live = byte_index < lengths
            h = jnp.where(
                live, ((h ^ byte) * jnp.uint32(16777619)).astype(jnp.uint32),
                h)
    return h


def _stable_sort_rows(keys_cols, payload_cols):
    """Stable lexicographic sort by `keys_cols` (list of u32[N] arrays),
    implemented as LSD passes of single-key sorts (same trick as
    ops.device.sort_run: cheap to compile, fast on TPU)."""
    n = keys_cols[0].shape[0]
    perm = jnp.arange(n, dtype=jnp.int32)
    for col in reversed(keys_cols):
        gathered = col[perm]
        _, perm = jax.lax.sort((gathered, perm), dimension=0, is_stable=True,
                               num_keys=1)
    return [c[perm] for c in keys_cols], [p[perm] for p in payload_cols], perm


def _partition_sort(lanes, lengths, values, valid, num_workers, dests=None):
    """Shared prologue: partition + stable local sort by
    (partition, key lanes, key length); invalid rows carry partition ==
    num_workers so they sort to the tail.  ``dests`` (u32[N], < num_workers)
    overrides the on-device hash routing with explicit destinations —
    the splitter/coded seam (module docstring)."""
    n, num_lanes = lanes.shape
    if dests is None:
        route = _fnv_lanes(lanes, lengths) % num_workers
    else:
        # clamp defensively: an out-of-range dest must never index past the
        # send buffer (the coordinator always passes values < num_workers)
        route = jnp.minimum(dests.astype(jnp.uint32),
                            jnp.uint32(num_workers - 1))
    part = jnp.where(valid, route, jnp.uint32(num_workers))
    key_cols = [part.astype(jnp.uint32)] + \
        [lanes[:, i] for i in range(num_lanes)] + [lengths.astype(jnp.uint32)]
    sorted_keys, sorted_payload, _ = _stable_sort_rows(
        key_cols, [values, valid.astype(jnp.uint32)])
    spart = sorted_keys[0]
    slanes = jnp.stack(sorted_keys[1:1 + num_lanes], axis=1) if num_lanes \
        else jnp.zeros((n, 0), jnp.uint32)
    slengths = sorted_keys[-1]
    svalues, svalid = sorted_payload
    return spart, slanes, slengths, svalues, svalid


def _merge_received(rlanes, rlengths, rvals, rvalid):
    """Shared epilogue: stable sort of the received concatenation by
    (key lanes, key length), validity-major (invalid rows to the tail)."""
    num_lanes = rlanes.shape[1]
    key_cols = [jnp.where(rvalid > 0, jnp.uint32(0), jnp.uint32(1))] + \
        [rlanes[:, i] for i in range(num_lanes)] + \
        [rlengths.astype(jnp.uint32)]
    sorted_keys, sorted_payload, _ = _stable_sort_rows(
        key_cols, [rvals, rvalid])
    out_lanes = jnp.stack(sorted_keys[1:1 + num_lanes], axis=1) \
        if num_lanes else rlanes
    out_lengths = sorted_keys[-1]
    out_vals, out_valid = sorted_payload
    return out_lanes, out_lengths, out_vals, out_valid


def _shuffle_step_local(lanes: jnp.ndarray, lengths: jnp.ndarray,
                        values: jnp.ndarray, valid: jnp.ndarray,
                        dests: jnp.ndarray = None,
                        *, num_workers: int,
                        cap: int) -> Tuple[jnp.ndarray, ...]:
    """Per-worker body run under shard_map.  lanes: u32[N, L]; lengths:
    u32[N]; values: u32[N, V]; valid: bool[N]; dests: optional u32[N]
    explicit routing.  Returns (lanes', lengths', values', valid', dropped)
    holding this worker's partition, key-sorted, padded to [W*cap], plus a
    per-worker count of rows lost to capacity overflow (must be zero)."""
    n, num_lanes = lanes.shape
    num_vwords = values.shape[1]
    spart, slanes, slengths, svalues, svalid = _partition_sort(
        lanes, lengths, values, valid, num_workers, dests)

    # scatter rows into the fixed [W, cap] send buffer: row i of partition p
    # goes to slot (p, rank_within_partition(i))
    ranks = jnp.arange(n, dtype=jnp.int32) - \
        jnp.searchsorted(spart, spart, side="left").astype(jnp.int32)
    in_range = (spart < num_workers) & (ranks < cap) & (svalid > 0)
    # out-of-range rows scatter to a sacrificial trailing slot (sliced off)
    # so they can never clobber slot 0
    dump = num_workers * cap
    flat_slot = jnp.where(in_range, spart.astype(jnp.int32) * cap + ranks,
                          dump)

    send_lanes = jnp.full((dump + 1, num_lanes), INVALID, dtype=jnp.uint32)
    send_lengths = jnp.zeros((dump + 1,), dtype=jnp.uint32)
    send_vals = jnp.zeros((dump + 1, num_vwords), dtype=jnp.uint32)
    send_valid = jnp.zeros((dump + 1,), dtype=jnp.uint32)
    send_lanes = send_lanes.at[flat_slot].set(slanes)
    send_lengths = send_lengths.at[flat_slot].set(slengths)
    send_vals = send_vals.at[flat_slot].set(svalues)
    send_valid = send_valid.at[flat_slot].set(jnp.uint32(1))

    # ICI all-to-all: block w of my send buffer -> worker w
    recv_lanes = jax.lax.all_to_all(
        send_lanes[:dump].reshape(num_workers, cap, num_lanes),
        WORKER_AXIS, 0, 0, tiled=False)
    recv_lengths = jax.lax.all_to_all(
        send_lengths[:dump].reshape(num_workers, cap),
        WORKER_AXIS, 0, 0, tiled=False)
    recv_vals = jax.lax.all_to_all(
        send_vals[:dump].reshape(num_workers, cap, num_vwords),
        WORKER_AXIS, 0, 0, tiled=False)
    recv_valid = jax.lax.all_to_all(
        send_valid[:dump].reshape(num_workers, cap),
        WORKER_AXIS, 0, 0, tiled=False)

    # local merge: stable sort of the received concatenation by key lanes
    # (invalid rows carry INVALID lanes -> tail)
    m = num_workers * cap
    out_lanes, out_lengths, out_vals, out_valid = _merge_received(
        recv_lanes.reshape(m, num_lanes), recv_lengths.reshape(m),
        recv_vals.reshape(m, num_vwords), recv_valid.reshape(m))
    # overflow signal: valid rows this worker could NOT send (rank >= cap).
    # Zero in correct operation; the caller MUST check it — capacity
    # overflow otherwise means silent data loss (the coordinator re-runs
    # with more rounds or splits the partition).
    dropped = jnp.sum((svalid > 0) & ~in_range).astype(jnp.int32)
    return out_lanes, out_lengths, out_vals, out_valid.astype(jnp.bool_), \
        dropped[None]


def _shuffle_step_local_ragged(lanes: jnp.ndarray, lengths: jnp.ndarray,
                               values: jnp.ndarray, valid: jnp.ndarray,
                               dests: jnp.ndarray = None,
                               *, num_workers: int,
                               out_cap: int) -> Tuple[jnp.ndarray, ...]:
    """Ragged variant: only real rows cross ICI (jax.lax.ragged_all_to_all).

    Offsets choreography: senders lay rows out destination-contiguously
    (the partition sort), sizes are exchanged with a [W]-int all_to_all,
    receivers compute exclusive output offsets and send them BACK so each
    sender knows where its block lands.  TPU-only today (XLA:CPU lacks the
    ragged-all-to-all thunk), so the padded formulation stays the portable
    default.
    """
    n, num_lanes = lanes.shape
    num_vwords = values.shape[1]
    spart, slanes, slengths, svalues, _ = _partition_sort(
        lanes, lengths, values, valid, num_workers, dests)

    raw_sizes = jnp.bincount(
        jnp.minimum(spart, num_workers).astype(jnp.int32),
        length=num_workers + 1)[:num_workers].astype(jnp.int32)
    input_offsets = jnp.concatenate(
        [jnp.zeros(1, jnp.int32),
         jnp.cumsum(raw_sizes)[:-1].astype(jnp.int32)])
    raw_recv = jax.lax.all_to_all(
        raw_sizes.reshape(num_workers, 1), WORKER_AXIS, 0, 0
    ).reshape(num_workers).astype(jnp.int32)
    excl = jnp.concatenate(
        [jnp.zeros(1, jnp.int32),
         jnp.cumsum(raw_recv)[:-1].astype(jnp.int32)])
    output_offsets = jax.lax.all_to_all(
        excl.reshape(num_workers, 1), WORKER_AXIS, 0, 0
    ).reshape(num_workers).astype(jnp.int32)
    # Sender-side overflow clamp: never write past the receiver's out_cap.
    # Each sender keeps the prefix of its block that fits below the cap
    # (offsets are the unclamped cumulative, so prefixes tile exactly);
    # clamped counts are re-exchanged so recv_sizes matches what is sent.
    send_sizes = jnp.clip(out_cap - output_offsets, 0, raw_sizes)
    recv_sizes = jax.lax.all_to_all(
        send_sizes.reshape(num_workers, 1), WORKER_AXIS, 0, 0
    ).reshape(num_workers).astype(jnp.int32)

    out_lanes = jnp.full((out_cap, num_lanes), INVALID, dtype=jnp.uint32)
    out_lengths = jnp.zeros((out_cap,), dtype=jnp.uint32)
    out_vals = jnp.zeros((out_cap, num_vwords), dtype=jnp.uint32)
    out_lanes = jax.lax.ragged_all_to_all(
        slanes, out_lanes, input_offsets, send_sizes, output_offsets,
        recv_sizes, axis_name=WORKER_AXIS)
    out_lengths = jax.lax.ragged_all_to_all(
        slengths, out_lengths, input_offsets, send_sizes, output_offsets,
        recv_sizes, axis_name=WORKER_AXIS)
    out_vals = jax.lax.ragged_all_to_all(
        svalues, out_vals, input_offsets, send_sizes, output_offsets,
        recv_sizes, axis_name=WORKER_AXIS)
    n_recv = jnp.sum(recv_sizes)
    rvalid = (jnp.arange(out_cap) < n_recv).astype(jnp.uint32)

    final_lanes, final_lengths, final_vals, final_valid = _merge_received(
        out_lanes, out_lengths, out_vals, rvalid)
    # overflow signal: rows this worker could not SEND (receiver cap hit)
    dropped = jnp.sum(raw_sizes - send_sizes).astype(jnp.int32)
    return final_lanes, final_lengths, final_vals, \
        final_valid.astype(jnp.bool_), dropped[None]


def build_distributed_shuffle(mesh, num_lanes: int, rows_per_worker: int,
                              cap_per_pair: int, value_words: int = 1,
                              ragged: bool = False,
                              explicit_dests: bool = False):
    """Compile the SPMD shuffle step for a mesh.  Returns a jitted function
    f(lanes u32[W*N, L], lengths u32[W*N], values u32[W*N, V],
      valid bool[W*N][, dests u32[W*N]]) -> per-worker sorted partitions,
    sharded over the mesh.  ``explicit_dests`` adds the dests input and
    routes by it instead of the on-device key hash (coordinator splitter /
    coded-buddy seam)."""
    try:
        from jax import shard_map          # jax >= 0.8
    except ImportError:                    # pragma: no cover — older jax
        from jax.experimental.shard_map import shard_map
    num_workers = mesh.devices.size

    if ragged:
        body = functools.partial(_shuffle_step_local_ragged,
                                 num_workers=num_workers,
                                 out_cap=num_workers * cap_per_pair)
    else:
        body = functools.partial(_shuffle_step_local,
                                 num_workers=num_workers, cap=cap_per_pair)
    import inspect
    # replication-check kwarg was renamed check_rep -> check_vma in jax 0.8
    check_kw = "check_vma" if "check_vma" in \
        inspect.signature(shard_map).parameters else "check_rep"
    n_in = 5 if explicit_dests else 4
    smapped = shard_map(
        body, mesh=mesh,
        in_specs=tuple(P(WORKER_AXIS) for _ in range(n_in)),
        out_specs=(P(WORKER_AXIS), P(WORKER_AXIS), P(WORKER_AXIS),
                   P(WORKER_AXIS), P(WORKER_AXIS)),
        **{check_kw: False})
    return jax.jit(smapped)


# ---------------------------------------------------------------------------
# Engine selection: capability probe + knob resolution
# ---------------------------------------------------------------------------

_probe_lock = threading.Lock()
_RAGGED_PROBE: Dict[Tuple[int, str], Tuple[bool, str]] = {}


def _ragged_unsupported_reason(e: BaseException, platform: str) -> str:
    """Classify a probe failure as 'backend lacks it' vs a real bug; the
    same triage the guarded parity test used before the probe existed."""
    if "UNIMPLEMENTED" in str(e) or isinstance(e, NotImplementedError) or \
            (isinstance(e, AttributeError) and "ragged_all_to_all" in str(e)):
        return (f"{platform} backend lacks the ragged-all-to-all thunk "
                f"({type(e).__name__})")
    raise e


def probe_ragged_support(mesh) -> Tuple[bool, str]:
    """(supported, reason) for ``jax.lax.ragged_all_to_all`` on this mesh's
    backend — compiled AND executed once on a tiny shape, cached per
    (device count, platform).  A probe failure that is not the known
    missing-thunk signature re-raises: masking a real compile bug as
    'unsupported' would silently pin every exchange to the padded engine."""
    platform = mesh.devices.flat[0].platform
    key = (mesh.devices.size, platform)
    with _probe_lock:
        cached = _RAGGED_PROBE.get(key)
    if cached is not None:
        return cached
    if not hasattr(jax.lax, "ragged_all_to_all"):
        result = (False, "this jax has no jax.lax.ragged_all_to_all")
    else:
        W = mesh.devices.size
        try:
            fn = build_distributed_shuffle(mesh, 1, 1, 1, value_words=1,
                                           ragged=True)
            jax.device_get(fn(np.zeros((W, 1), np.uint32),
                              np.ones(W, np.uint32),
                              np.zeros((W, 1), np.uint32),
                              np.ones(W, bool)))
            result = (True, f"ragged_all_to_all available on {platform}")
        except Exception as e:  # noqa: BLE001 — classified, re-raised if real
            result = (False, _ragged_unsupported_reason(e, platform))
    with _probe_lock:
        _RAGGED_PROBE[key] = result
    return result


def resolve_engine(requested: str, mesh) -> Tuple[str, str]:
    """Map the ``tez.runtime.mesh.exchange.engine`` knob onto the engine
    this backend can actually run, bit-exact either way.  Returns
    (engine, reason): 'auto' takes ragged when the probe passes; an
    explicit 'ragged' on a backend without it falls back to padded with a
    loud warning (never an error — the padded formulation computes the
    identical result)."""
    if requested not in EXCHANGE_ENGINES:
        raise ValueError(
            f"tez.runtime.mesh.exchange.engine={requested!r}: expected one "
            f"of {'|'.join(EXCHANGE_ENGINES)}")
    if requested == "padded":
        return "padded", "engine=padded requested"
    ok, reason = probe_ragged_support(mesh)
    if ok:
        return "ragged", reason
    if requested == "ragged":
        log.warning("mesh exchange: engine=ragged requested but %s; "
                    "falling back to the bit-exact padded engine", reason)
    return "padded", reason


def fnv_bytes_host(key: bytes) -> int:
    """Host reference of the kernel's byte-wise FNV-1a partitioner."""
    h = 2166136261
    for b in key:
        h = ((h ^ b) * 16777619) & 0xFFFFFFFF
    return h


def distributed_shuffle_reference(lanes: np.ndarray, lengths: np.ndarray,
                                  values: np.ndarray, valid: np.ndarray,
                                  num_workers: int) -> list:
    """Host golden: what each worker should hold after the exchange."""

    def row_key_bytes(i: int) -> bytes:
        raw = b"".join(int(w).to_bytes(4, "big") for w in lanes[i])
        return raw[: int(lengths[i])]

    out = [[] for _ in range(num_workers)]
    for i in range(len(valid)):
        if not valid[i]:
            continue
        kb = row_key_bytes(i)
        w = fnv_bytes_host(kb) % num_workers
        out[w].append((tuple(lanes[i].tolist()), int(lengths[i]),
                       tuple(np.atleast_1d(values[i]).tolist())))
    for part in out:
        part.sort(key=lambda t: (t[0], t[1]))
    return out
