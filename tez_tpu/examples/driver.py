"""Example program driver: one CLI dispatching every shipped example.

Reference role: ExampleDriver
(tez-examples/src/main/java/org/apache/tez/examples/ExampleDriver.java:33),
which registers each example under a short name with Hadoop's
ProgramDriver.  `tez-examples <name> <args...>` here, `hadoop jar
tez-examples.jar <name> <args...>` there.
"""
from __future__ import annotations

import sys

from tez_tpu.examples import (cartesian_product, hash_join, mrr,
                              ordered_wordcount, simple_session,
                              sort_merge_join, wordcount)


def _two_arg(run):
    def go(argv):
        if len(argv) < 2:
            return None
        return run(argv[:-1], argv[-1])
    return go


def _three_arg(run):
    def go(argv):
        if len(argv) != 3:
            return None
        return run([argv[0]], [argv[1]], argv[2])
    return go


_PROGRAMS = {
    "wordcount": (
        _two_arg(wordcount.run), "<input...> <output_dir>",
        "hash-partitioned (unordered) word count"),
    "orderedwordcount": (
        _two_arg(ordered_wordcount.run), "<input...> <output_dir>",
        "word count with counts sorted via a second ordered edge"),
    "mrr": (
        _two_arg(mrr.run), "<input...> <output_dir>",
        "map -> reduce -> reduce chained-shuffle DAG"),
    "sortmergejoin": (
        _three_arg(sort_merge_join.run), "<left> <right> <output_dir>",
        "two ordered edges merged in one joiner vertex"),
    "hashjoin": (
        _three_arg(hash_join.run), "<stream> <hash> <output_dir>",
        "broadcast-edge hash join (small side replicated)"),
    "cartesianproduct": (
        _three_arg(cartesian_product.run), "<left> <right> <output_dir>",
        "cross product via the CUSTOM cartesian-product edge"),
    "simplesessionexample": (
        _two_arg(simple_session.run), "<input...> <output_dir>",
        "several DAGs through one session with runner reuse"),
}


def _usage() -> int:
    print("usage: tez-examples <program> <args...>\n\nprograms:")
    for name, (_, args, desc) in sorted(_PROGRAMS.items()):
        print(f"  {name:18s} {args}\n  {'':18s}   {desc}")
    return 2


def main() -> int:
    argv = sys.argv[1:]
    if argv and argv[0] in ("-h", "--help"):
        _usage()
        return 0
    if not argv or argv[0] not in _PROGRAMS:
        return _usage()
    run, args_help, _ = _PROGRAMS[argv[0]]
    state = run(argv[1:])
    if state is None:
        print(f"usage: tez-examples {argv[0]} {args_help}")
        return 2
    print(state)
    return 0 if state == "SUCCEEDED" else 1


if __name__ == "__main__":
    sys.exit(main())
