"""OrderedWordCount: the reference's flagship example and the north-star
benchmark workload.

Reference parity: tez-examples/.../OrderedWordCount.java:56 (DAG at :124):
tokenizer --(word,1 sorted+combined)--> summation --(count,word sorted)-->
sorter, writing words ordered by count.  Both edges are sorted scatter-gather
running on the TPU DeviceSorter; the count key uses the order-preserving
big-endian long serde so numeric order == byte order.
"""
from __future__ import annotations

import sys
from typing import Dict

from tez_tpu.api.runtime import LogicalInput, LogicalOutput
from tez_tpu.client.tez_client import TezClient
from tez_tpu.common.payload import (InputDescriptor,
                                    InputInitializerDescriptor,
                                    OutputCommitterDescriptor,
                                    OutputDescriptor, ProcessorDescriptor)
from tez_tpu.dag.dag import (DAG, DataSinkDescriptor, DataSourceDescriptor,
                             Edge, Vertex)
from tez_tpu.library.conf import OrderedPartitionedKVEdgeConfig
from tez_tpu.library.processors import SimpleProcessor

class TokenProcessor(SimpleProcessor):
    """Split lines into words, emit (word, 1)."""

    def run(self, inputs: Dict[str, LogicalInput],
            outputs: Dict[str, LogicalOutput]) -> None:
        reader = inputs["input"].get_reader()
        writer = outputs["summation"].get_writer()
        for _offset, line in reader:
            for word in line.split():
                writer.write(word, 1)


class VectorTokenProcessor(SimpleProcessor):
    """Batch-first tokenizer: numpy whitespace split over large line-aligned
    chunks, records shipped as pre-serialized KVBatches (write_batch) — no
    per-record Python on the hot path.  This is the TPU-native shape of the
    reference's per-record map loop (the bench path)."""

    def run(self, inputs: Dict[str, LogicalInput],
            outputs: Dict[str, LogicalOutput]) -> None:
        import numpy as np
        from tez_tpu.ops.runformat import KVBatch
        from tez_tpu.ops.serde import VarLongSerde

        one = VarLongSerde().to_bytes(1)
        reader = inputs["input"].get_reader()
        writer = outputs["summation"].get_writer()

        # fused native tokenize+count when the edge carries a sum combiner:
        # one C pass replaces tokenize -> 4M-record batch -> combine (the
        # map task becomes emit-of-partial-counts, which is exactly what
        # the combiner would have produced)
        out = outputs["summation"]
        sorter = getattr(out, "sorter", None)
        from tez_tpu.ops.sorter import sum_long_combiner
        if sorter is not None and sorter.combiner is sum_long_combiner:
            from tez_tpu.ops.native import WordCountAggregator
            agg = WordCountAggregator.create()
            if agg is not None:
                try:
                    for chunk in reader.iter_chunks():
                        agg.feed(bytes(chunk))
                    key_bytes, key_offsets, counts = agg.emit()
                finally:
                    agg.close()
                enc = (counts.view(np.uint64)
                       ^ np.uint64(1 << 63)).astype(">u8")
                val_bytes = np.frombuffer(enc.tobytes(),
                                          dtype=np.uint8).copy()
                val_offsets = np.arange(len(counts) + 1,
                                        dtype=np.int64) * 8
                # keys are already unique (one row per distinct word):
                # pre_combined lets a single-span sort skip its redundant
                # pre-sort hash combine pass
                writer.write_batch(KVBatch(key_bytes, key_offsets,
                                           val_bytes, val_offsets,
                                           pre_combined=True))
                return

        from tez_tpu.ops.native import split_ws_native
        for chunk in reader.iter_chunks():
            native = split_ws_native(bytes(chunk))
            if native is not None:
                # one C pass (GIL released): compacted word bytes + offsets
                key_bytes, key_offsets = native
                n = len(key_offsets) - 1
                if n == 0:
                    continue
            else:
                data = np.frombuffer(chunk, dtype=np.uint8)
                # full bytes.split() whitespace set: space \t \n \v \f \r
                ws = (data == 32) | ((data >= 9) & (data <= 13))
                sel = ~ws
                if not sel.any():
                    continue
                key_bytes = data[sel].copy()
                starts_mask = sel & np.concatenate(([True], ws[:-1]))
                run_id = np.cumsum(starts_mask)[sel]    # 1-based word id
                lengths = np.bincount(run_id - 1)
                n = len(lengths)
                key_offsets = np.zeros(n + 1, np.int64)
                np.cumsum(lengths, out=key_offsets[1:])
            val_bytes = np.frombuffer(one * n, dtype=np.uint8).copy()
            val_offsets = np.arange(n + 1, dtype=np.int64) * len(one)
            writer.write_batch(KVBatch(key_bytes, key_offsets,
                                       val_bytes, val_offsets))


class SumProcessor(SimpleProcessor):
    """Sum counts per word, emit (count, word) toward the sorter.

    Batch-first when the reader supports it: per-group sums via one
    np.add.reduceat, output shipped as a single pre-serialized KVBatch."""

    def run(self, inputs: Dict[str, LogicalInput],
            outputs: Dict[str, LogicalOutput]) -> None:
        import numpy as np
        reader = inputs["tokenizer"].get_reader()
        writer = outputs["sorter"].get_writer()
        # probe the writer config BEFORE consuming the reader: a custom
        # Partitioner rejects write_batch, and falling back mid-stream
        # would lose already-consumed groups
        if hasattr(reader, "grouped_blocks") and \
                getattr(writer, "supports_batch", False):
            from tez_tpu.ops.runformat import KVBatch, gather_ragged
            from tez_tpu.ops.serde import decode_longs_be, encode_longs_be
            for batch, starts in reader.grouped_blocks():
                n = batch.num_records
                if n == 0:
                    continue
                if bool(np.all(np.diff(batch.val_offsets) == 8)):
                    decoded = decode_longs_be(batch.val_bytes, n)
                    sums = np.add.reduceat(decoded, starts)
                    words_b, words_o = gather_ragged(
                        batch.key_bytes, batch.key_offsets, starts)
                    key_bytes = encode_longs_be(sums)
                    key_offsets = np.arange(len(sums) + 1,
                                            dtype=np.int64) * 8
                    writer.write_batch(KVBatch(key_bytes, key_offsets,
                                               words_b, words_o))
                else:
                    # mixed-width values (non-long serde): per-record via
                    # the reader's OWN serdes for this block only — groups
                    # are complete per block, so correctness is unaffected
                    bounds = np.append(starts, n)
                    for s, e in zip(bounds[:-1], bounds[1:]):
                        word = reader.key_serde.from_bytes(batch.key(int(s)))
                        total = sum(
                            reader.val_serde.from_bytes(batch.value(i))
                            for i in range(int(s), int(e)))
                        writer.write(total, word)
            return
        for word, counts in reader:
            writer.write(sum(counts), word)


class NoOpSorterProcessor(SimpleProcessor):
    """Write the (count, word) stream — already globally count-ordered when
    sorter parallelism is 1 (reference: OrderedWordCount NoOpSorter).

    Batch-first when reader and writer support it: output lines assemble
    via ONE ragged gather over a pool of [word rows + per-group
    '\\t<count>\\n' tails] (zero per-record Python)."""

    def run(self, inputs: Dict[str, LogicalInput],
            outputs: Dict[str, LogicalOutput]) -> None:
        import numpy as np
        reader = inputs["summation"].get_reader()
        writer = outputs["output"].get_writer()
        if hasattr(reader, "grouped_blocks") and hasattr(writer, "write_raw"):
            from tez_tpu.ops.runformat import gather_ragged
            from tez_tpu.ops.serde import decode_longs_be
            # honor a configured output separator (the iterator path writes
            # through _PartWriter, which does) — format the same bytes here
            sep = getattr(writer, "sep", b"\t")
            for batch, starts in reader.grouped_blocks():
                n = batch.num_records
                if n == 0:
                    continue
                if not bool(np.all(np.diff(batch.key_offsets) == 8)):
                    # non-long count keys: per-record for this block only
                    bounds = np.append(starts, n)
                    for s, e in zip(bounds[:-1], bounds[1:]):
                        count = reader.key_serde.from_bytes(batch.key(int(s)))
                        for i in range(int(s), int(e)):
                            word = reader.val_serde.from_bytes(batch.value(i))
                            writer.write(word, str(count))
                    continue
                counts = decode_longs_be(batch.key_bytes, n)
                tails = [sep + b"%d\n" % int(counts[s]) for s in starts]
                tail_bytes = np.frombuffer(b"".join(tails), dtype=np.uint8)
                tail_lens = np.array([len(t) for t in tails],
                                     dtype=np.int64)
                pool_bytes = np.concatenate([batch.val_bytes, tail_bytes])
                pool_offsets = np.concatenate([
                    batch.val_offsets,
                    batch.val_offsets[-1] + np.cumsum(tail_lens)])
                # record i -> rows (word_i, tail_of_group(i))
                group_of = np.zeros(n, dtype=np.int64)
                group_of[starts[1:]] = 1
                group_of = np.cumsum(group_of)
                perm = np.empty(2 * n, dtype=np.int64)
                perm[0::2] = np.arange(n)
                perm[1::2] = n + group_of
                lines, _ = gather_ragged(pool_bytes, pool_offsets, perm)
                writer.write_raw(lines.tobytes(), n)
            return
        for count, words in reader:
            for word in words:
                writer.write(word, str(count))


def build_dag(input_paths, output_path: str, tokenizer_parallelism: int = -1,
              summation_parallelism: int = 2, sorter_parallelism: int = 1,
              combine: bool = True, pipelined: bool = False,
              exchange: str = "host", tokenizer_mode: str = "simple") -> DAG:
    """exchange="mesh" moves the tokenizer->summation shuffle onto the ICI
    mesh exchange (one SPMD all-to-all program instead of spill+fetch;
    needs one device per summation task).  tokenizer_mode="vector" uses the
    batch-first numpy tokenizer (the bench path)."""
    tok_cls = VectorTokenProcessor if tokenizer_mode == "vector" \
        else TokenProcessor
    tokenizer = Vertex.create("tokenizer", ProcessorDescriptor.create(
        tok_cls), tokenizer_parallelism)
    tokenizer.add_data_source("input", DataSourceDescriptor.create(
        InputDescriptor.create("tez_tpu.io.text:TextInput"),
        InputInitializerDescriptor.create(
            "tez_tpu.io.text:TextSplitGenerator",
            payload={"paths": list(input_paths),
                     "desired_splits": tokenizer_parallelism}),
    ))
    summation = Vertex.create("summation", ProcessorDescriptor.create(
        SumProcessor), summation_parallelism)
    sorter = Vertex.create("sorter", ProcessorDescriptor.create(
        NoOpSorterProcessor), sorter_parallelism)
    sorter.add_data_sink("output", DataSinkDescriptor.create(
        OutputDescriptor.create("tez_tpu.io.file_output:FileOutput",
                                payload={"path": output_path,
                                         "key_serde": "text",
                                         "value_serde": "text"}),
        OutputCommitterDescriptor.create(
            "tez_tpu.io.file_output:FileOutputCommitter",
            payload={"path": output_path})))

    if exchange == "mesh":
        from tez_tpu.library.conf import MeshOrderedPartitionedKVEdgeConfig
        # the mesh edge has no separate combine phase: the exchange's merge
        # epilogue lands every equal key on one worker already
        e1 = MeshOrderedPartitionedKVEdgeConfig.new_builder("bytes", "long")\
            .set_value_width(8).build()
    else:
        e1_builder = OrderedPartitionedKVEdgeConfig.new_builder("bytes",
                                                                "long")
        if combine:
            e1_builder.set_combiner("sum_long")
        if pipelined:
            e1_builder.set_pipelined(True)
        e1 = e1_builder.build()
    e2 = OrderedPartitionedKVEdgeConfig.new_builder("long", "bytes").build()

    dag = DAG.create("OrderedWordCount")
    dag.add_vertex(tokenizer).add_vertex(summation).add_vertex(sorter)
    dag.add_edge(Edge.create(tokenizer, summation,
                             e1.create_default_edge_property()))
    dag.add_edge(Edge.create(summation, sorter,
                             e2.create_default_edge_property()))
    return dag


def run(input_paths, output_path: str, conf=None, **kw) -> str:
    with TezClient.create("OrderedWordCount", conf or {}) as client:
        dag = build_dag(input_paths, output_path, **kw)
        status = client.submit_dag(dag).wait_for_completion()
        return status.state.name


def main() -> int:
    if len(sys.argv) < 3:
        print("usage: ordered_wordcount <input...> <output_dir>")
        return 2
    state = run(sys.argv[1:-1], sys.argv[-1])
    print(state)
    return 0 if state == "SUCCEEDED" else 1


if __name__ == "__main__":
    sys.exit(main())
