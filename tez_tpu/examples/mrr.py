"""MRR: 3-stage map -> reduce -> reduce chain over TeraSort-style records.

Reference parity: tez-tests mapreduce examples (TestOrderedWordCount /
MRRSleepJob — benchmark workload 4, BASELINE.md): two chained sorted
shuffles.  Stage 1 tokenizes key:value lines, stage 2 aggregates per key,
stage 3 re-keys by aggregate and writes globally ordered output.
"""
from __future__ import annotations

import sys
from typing import Dict

from tez_tpu.api.runtime import LogicalInput, LogicalOutput
from tez_tpu.client.tez_client import TezClient
from tez_tpu.common.payload import (InputDescriptor,
                                    InputInitializerDescriptor,
                                    OutputCommitterDescriptor,
                                    OutputDescriptor, ProcessorDescriptor)
from tez_tpu.dag.dag import (DAG, DataSinkDescriptor, DataSourceDescriptor,
                             Edge, Vertex)
from tez_tpu.library.conf import OrderedPartitionedKVEdgeConfig
from tez_tpu.library.processors import SimpleProcessor


class Stage1Map(SimpleProcessor):
    """line 'key<TAB>value' -> (key, value-length) pairs."""

    def run(self, inputs: Dict[str, LogicalInput],
            outputs: Dict[str, LogicalOutput]) -> None:
        writer = outputs["r1"].get_writer()
        for _off, line in inputs["input"].get_reader():
            key, _, value = line.partition(b"\t")
            writer.write(key, len(value))


class Stage2Reduce(SimpleProcessor):
    """(key, lengths) -> (total_length, key): re-key by aggregate."""

    def run(self, inputs: Dict[str, LogicalInput],
            outputs: Dict[str, LogicalOutput]) -> None:
        writer = outputs["r2"].get_writer()
        for key, lengths in inputs["m"].get_reader():
            writer.write(sum(lengths), key)


class Stage3Reduce(SimpleProcessor):
    """Globally ordered (total, keys) -> output lines."""

    def run(self, inputs: Dict[str, LogicalInput],
            outputs: Dict[str, LogicalOutput]) -> None:
        writer = outputs["output"].get_writer()
        for total, keys in inputs["r1"].get_reader():
            for key in keys:
                writer.write(key, str(total))


def build_dag(input_paths, output_path: str, map_parallelism: int = -1,
              r1_parallelism: int = 2, r2_parallelism: int = 1) -> DAG:
    m = Vertex.create("m", ProcessorDescriptor.create(Stage1Map),
                      map_parallelism)
    m.add_data_source("input", DataSourceDescriptor.create(
        InputDescriptor.create("tez_tpu.io.text:TextInput"),
        InputInitializerDescriptor.create(
            "tez_tpu.io.text:TextSplitGenerator",
            payload={"paths": list(input_paths),
                     "desired_splits": map_parallelism})))
    r1 = Vertex.create("r1", ProcessorDescriptor.create(Stage2Reduce),
                       r1_parallelism)
    r2 = Vertex.create("r2", ProcessorDescriptor.create(Stage3Reduce),
                       r2_parallelism)
    r2.add_data_sink("output", DataSinkDescriptor.create(
        OutputDescriptor.create("tez_tpu.io.file_output:FileOutput",
                                payload={"path": output_path,
                                         "key_serde": "text",
                                         "value_serde": "text"}),
        OutputCommitterDescriptor.create(
            "tez_tpu.io.file_output:FileOutputCommitter",
            payload={"path": output_path})))
    e1 = OrderedPartitionedKVEdgeConfig.new_builder("bytes", "long").build()
    e2 = OrderedPartitionedKVEdgeConfig.new_builder("long", "bytes").build()
    dag = DAG.create("MRR")
    for v in (m, r1, r2):
        dag.add_vertex(v)
    dag.add_edge(Edge.create(m, r1, e1.create_default_edge_property()))
    dag.add_edge(Edge.create(r1, r2, e2.create_default_edge_property()))
    return dag


def run(input_paths, output_path: str, conf=None, **kw) -> str:
    with TezClient.create("MRR", conf or {}) as client:
        status = client.submit_dag(
            build_dag(input_paths, output_path, **kw)).wait_for_completion()
        return status.state.name


if __name__ == "__main__":
    if len(sys.argv) < 3:
        print("usage: mrr <input...> <output_dir>")
        sys.exit(2)
    print(run(sys.argv[1:-1], sys.argv[-1]))
