"""Broadcast hash join: small dim table broadcast to every fact task.

Reference parity: tez-examples/.../HashJoinExample.java:74 (benchmark
workload 5, BASELINE.md): the small side ships over a BROADCAST edge with
UnorderedKVOutput; each streaming (fact) task builds a hash set/table and
joins its split of the big side.

This example is a thin shim over the relational query layer
(tez_tpu/query/, docs/query.md): the whole workload is one logical plan —
``stream SEMI JOIN hash_side`` with the join strategy pinned to broadcast
— lowered by the planner onto exactly the DAG shape the hand-built
original used (a 1-task build vertex over a broadcast UnorderedKVEdge
into a fused scan+hash_join probe vertex with a FileOutput sink).  The
output is bit-exact with the pre-query-layer example: one
``(word, "1")`` record per stream occurrence whose word appears in the
hash side.
"""
from __future__ import annotations

import sys

from tez_tpu.query import Table, plan_query
from tez_tpu.client.tez_client import TezClient


def build_plan(stream_paths, hash_paths) -> Table:
    stream = Table.scan("stream", list(stream_paths), ["word"],
                        mode="lines")
    hash_side = Table.scan("hashside", list(hash_paths), ["word"],
                           mode="lines")
    # semi join: keep every stream occurrence whose word is in the hash
    # side; hash_join pins the broadcast strategy the example is about
    return stream.hash_join(hash_side, "word", how="semi")


def build_dag(stream_paths, hash_paths, output_path: str,
              num_joiners: int = 2, conf=None):
    merged = {"tez.query.scan.splits": num_joiners, **(conf or {})}
    planned = plan_query(build_plan(stream_paths, hash_paths), merged,
                         output_path, dag_name="HashJoin",
                         sink={"key_col": "word", "literal": "1"})
    return planned.dag


def run(stream_paths, hash_paths, output_path: str, conf=None, **kw) -> str:
    with TezClient.create("HashJoin", conf or {}) as client:
        dag = build_dag(stream_paths, hash_paths, output_path,
                        conf=conf, **kw)
        status = client.submit_dag(dag).wait_for_completion()
        return status.state.name


if __name__ == "__main__":
    if len(sys.argv) != 4:
        print("usage: hash_join <stream_file> <hash_file> <output_dir>")
        sys.exit(2)
    print(run([sys.argv[1]], [sys.argv[2]], sys.argv[3]))
