"""Broadcast hash join: small dim table broadcast to every fact task.

Reference parity: tez-examples/.../HashJoinExample.java:74 (benchmark
workload 5, BASELINE.md): the small side ships over a BROADCAST edge with
UnorderedKVOutput; each streaming (fact) task builds a hash set/table and
joins its split of the big side.
"""
from __future__ import annotations

import sys
from typing import Dict

from tez_tpu.api.runtime import LogicalInput, LogicalOutput
from tez_tpu.client.tez_client import TezClient
from tez_tpu.common.payload import (InputDescriptor,
                                    InputInitializerDescriptor,
                                    OutputCommitterDescriptor,
                                    OutputDescriptor, ProcessorDescriptor)
from tez_tpu.dag.dag import (DAG, DataSinkDescriptor, DataSourceDescriptor,
                             Edge, Vertex)
from tez_tpu.library.conf import UnorderedKVEdgeConfig
from tez_tpu.library.processors import SimpleProcessor


class ForwardProcessor(SimpleProcessor):
    """Reads the (small) hash side and forwards keys downstream."""

    def run(self, inputs: Dict[str, LogicalInput],
            outputs: Dict[str, LogicalOutput]) -> None:
        reader = inputs["input"].get_reader()
        writer = outputs["joiner"].get_writer()
        for _offset, line in reader:
            key = line.strip()
            if key:
                writer.write(key, b"")


class HashJoinProcessor(SimpleProcessor):
    """Builds the broadcast hash set, streams the big side, emits matches
    (reference: HashJoinExample.HashJoinProcessor)."""

    def run(self, inputs: Dict[str, LogicalInput],
            outputs: Dict[str, LogicalOutput]) -> None:
        hash_side = inputs["hashside"].get_reader()
        keys = {k for k, _ in hash_side}
        stream = inputs["input"].get_reader()
        writer = outputs["output"].get_writer()
        for _offset, line in stream:
            word = line.strip()
            if word in keys:
                writer.write(word, "1")


def build_dag(stream_paths, hash_paths, output_path: str,
              num_joiners: int = 2) -> DAG:
    hash_side = Vertex.create("hashside", ProcessorDescriptor.create(
        ForwardProcessor), 1)
    hash_side.add_data_source("input", DataSourceDescriptor.create(
        InputDescriptor.create("tez_tpu.io.text:TextInput"),
        InputInitializerDescriptor.create(
            "tez_tpu.io.text:TextSplitGenerator",
            payload={"paths": list(hash_paths), "desired_splits": 1})))
    joiner = Vertex.create("joiner", ProcessorDescriptor.create(
        HashJoinProcessor), num_joiners)
    joiner.add_data_source("input", DataSourceDescriptor.create(
        InputDescriptor.create("tez_tpu.io.text:TextInput"),
        InputInitializerDescriptor.create(
            "tez_tpu.io.text:TextSplitGenerator",
            payload={"paths": list(stream_paths),
                     "desired_splits": num_joiners})))
    joiner.add_data_sink("output", DataSinkDescriptor.create(
        OutputDescriptor.create("tez_tpu.io.file_output:FileOutput",
                                payload={"path": output_path,
                                         "key_serde": "text",
                                         "value_serde": "text"}),
        OutputCommitterDescriptor.create(
            "tez_tpu.io.file_output:FileOutputCommitter",
            payload={"path": output_path})))
    edge = UnorderedKVEdgeConfig.new_builder("bytes", "bytes").build()
    dag = DAG.create("HashJoin").add_vertex(hash_side).add_vertex(joiner)
    # rename edge output key: hash_side -> joiner under input name "hashside"
    dag.add_edge(Edge.create(hash_side, joiner,
                             edge.create_default_broadcast_edge_property()))
    return dag


def run(stream_paths, hash_paths, output_path: str, conf=None, **kw) -> str:
    with TezClient.create("HashJoin", conf or {}) as client:
        status = client.submit_dag(build_dag(
            stream_paths, hash_paths, output_path, **kw)).wait_for_completion()
        return status.state.name


if __name__ == "__main__":
    if len(sys.argv) != 4:
        print("usage: hash_join <stream_file> <hash_file> <output_dir>")
        sys.exit(2)
    print(run([sys.argv[1]], [sys.argv[2]], sys.argv[3]))
