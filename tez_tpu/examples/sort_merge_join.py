"""Sort-merge join: two sorted scatter-gather edges into one joiner.

Reference parity: tez-examples/.../SortMergeJoinExample.java:72 (benchmark
workload 3, BASELINE.md): both sides shuffle sorted on the join key to the
same partition space; the joiner walks the two grouped iterators in lockstep.

This example is a thin shim over the relational query layer
(tez_tpu/query/, docs/query.md): the whole workload is one logical plan —
``left SEMI-DISTINCT JOIN right`` on the tokenized word — whose
semi_distinct join REQUIRES the repartition strategy, so the planner
lowers it onto exactly the DAG shape the hand-built original used (both
scan sides terminating into key-partitioned OrderedPartitionedKVEdges
feeding a lockstep sort-merge joiner).  The output is bit-exact with the
pre-query-layer example: one ``(word, "1")`` record per distinct word
present on both sides.
"""
from __future__ import annotations

import sys

from tez_tpu.query import Table, plan_query
from tez_tpu.client.tez_client import TezClient


def build_plan(left_paths, right_paths) -> Table:
    left = Table.scan("left", list(left_paths), ["word"], mode="words")
    right = Table.scan("right", list(right_paths), ["word"], mode="words")
    # semi_distinct: one row per distinct key on both sides — the
    # lockstep emit-once-per-matching-key the original joiner performed
    return left.join(right, "word", how="semi_distinct")


def build_dag(left_paths, right_paths, output_path: str,
              num_joiners: int = 2, side_parallelism: int = 2, conf=None):
    merged = {"tez.query.reducers": num_joiners,
              "tez.query.scan.splits": side_parallelism, **(conf or {})}
    planned = plan_query(build_plan(left_paths, right_paths), merged,
                         output_path, dag_name="SortMergeJoin",
                         sink={"key_col": "word", "literal": "1"})
    return planned.dag


def run(left_paths, right_paths, output_path: str, conf=None, **kw) -> str:
    with TezClient.create("SortMergeJoin", conf or {}) as client:
        dag = build_dag(left_paths, right_paths, output_path,
                        conf=conf, **kw)
        status = client.submit_dag(dag).wait_for_completion()
        return status.state.name


if __name__ == "__main__":
    if len(sys.argv) != 4:
        print("usage: sort_merge_join <left_file> <right_file> <output_dir>")
        sys.exit(2)
    print(run([sys.argv[1]], [sys.argv[2]], sys.argv[3]))
