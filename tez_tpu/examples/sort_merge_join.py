"""Sort-merge join: two sorted scatter-gather edges into one joiner.

Reference parity: tez-examples/.../SortMergeJoinExample.java:72 (benchmark
workload 3, BASELINE.md): both sides shuffle sorted on the join key to the
same partition space; the joiner walks the two grouped iterators in lockstep.
"""
from __future__ import annotations

import sys
from typing import Dict

from tez_tpu.api.runtime import LogicalInput, LogicalOutput
from tez_tpu.client.tez_client import TezClient
from tez_tpu.common.payload import (InputDescriptor,
                                    InputInitializerDescriptor,
                                    OutputCommitterDescriptor,
                                    OutputDescriptor, ProcessorDescriptor)
from tez_tpu.dag.dag import (DAG, DataSinkDescriptor, DataSourceDescriptor,
                             Edge, Vertex)
from tez_tpu.library.conf import OrderedPartitionedKVEdgeConfig
from tez_tpu.library.processors import SimpleProcessor


class PrepareProcessor(SimpleProcessor):
    """Tokenize a side into (key, "") sorted output."""

    def run(self, inputs: Dict[str, LogicalInput],
            outputs: Dict[str, LogicalOutput]) -> None:
        reader = inputs["input"].get_reader()
        writer = outputs["joiner"].get_writer()
        for _offset, line in reader:
            for word in line.split():
                writer.write(word, b"")


class SortMergeJoinProcessor(SimpleProcessor):
    """Lockstep merge of two key-sorted grouped inputs (inner join)."""

    def run(self, inputs: Dict[str, LogicalInput],
            outputs: Dict[str, LogicalOutput]) -> None:
        left = iter(inputs["left"].get_reader())
        right = iter(inputs["right"].get_reader())
        writer = outputs["output"].get_writer()

        def nxt(it):
            try:
                k, vs = next(it)
                return k, vs
            except StopIteration:
                return None, None

        lk, lv = nxt(left)
        rk, rv = nxt(right)
        while lk is not None and rk is not None:
            if lk == rk:
                writer.write(lk, "1")
                lk, lv = nxt(left)
                rk, rv = nxt(right)
            elif lk < rk:
                lk, lv = nxt(left)
            else:
                rk, rv = nxt(right)


def _side(name: str, paths, parallelism: int) -> Vertex:
    v = Vertex.create(name, ProcessorDescriptor.create(PrepareProcessor),
                      parallelism)
    v.add_data_source("input", DataSourceDescriptor.create(
        InputDescriptor.create("tez_tpu.io.text:TextInput"),
        InputInitializerDescriptor.create(
            "tez_tpu.io.text:TextSplitGenerator",
            payload={"paths": list(paths), "desired_splits": parallelism})))
    return v


def build_dag(left_paths, right_paths, output_path: str,
              num_joiners: int = 2, side_parallelism: int = 2) -> DAG:
    left = _side("left", left_paths, side_parallelism)
    right = _side("right", right_paths, side_parallelism)
    joiner = Vertex.create("joiner", ProcessorDescriptor.create(
        SortMergeJoinProcessor), num_joiners)
    joiner.add_data_sink("output", DataSinkDescriptor.create(
        OutputDescriptor.create("tez_tpu.io.file_output:FileOutput",
                                payload={"path": output_path,
                                         "key_serde": "text",
                                         "value_serde": "text"}),
        OutputCommitterDescriptor.create(
            "tez_tpu.io.file_output:FileOutputCommitter",
            payload={"path": output_path})))
    edge = OrderedPartitionedKVEdgeConfig.new_builder("bytes", "bytes")
    dag = DAG.create("SortMergeJoin")
    for v in (left, right, joiner):
        dag.add_vertex(v)
    dag.add_edge(Edge.create(left, joiner,
                             edge.build().create_default_edge_property()))
    dag.add_edge(Edge.create(right, joiner,
                             edge.build().create_default_edge_property()))
    return dag


def run(left_paths, right_paths, output_path: str, conf=None, **kw) -> str:
    with TezClient.create("SortMergeJoin", conf or {}) as client:
        status = client.submit_dag(build_dag(
            left_paths, right_paths, output_path, **kw)).wait_for_completion()
        return status.state.name


if __name__ == "__main__":
    if len(sys.argv) != 4:
        print("usage: sort_merge_join <left_file> <right_file> <output_dir>")
        sys.exit(2)
    print(run([sys.argv[1]], [sys.argv[2]], sys.argv[3]))
