"""SimpleSessionExample: several DAGs through ONE session, reusing runners.

Reference parity: tez-examples/.../SimpleSessionExample.java (one TezClient
session submits a DAG per input, containers are reused across DAGs instead
of being torn down between jobs).
"""
from __future__ import annotations

import os
import sys

from tez_tpu.client.tez_client import TezClient
from tez_tpu.examples import wordcount


def run(input_paths, output_dir: str, conf=None) -> str:
    """One word-count DAG per input file, all inside one session.
    Returns the final state of the last DAG ('SUCCEEDED' if all did)."""
    conf = dict(conf or {})
    conf.setdefault("tez.session.mode", True)
    last = "SUCCEEDED"
    with TezClient.create("SimpleSession", conf) as client:
        client.pre_warm()
        for i, path in enumerate(input_paths):
            dag = wordcount.build_dag(
                [path], os.path.join(output_dir, f"dag{i}"))
            # unique per-session DAG names (reference: session rejects dups)
            dag.name = f"wordcount-{i}"
            status = client.submit_dag(dag).wait_for_completion()
            last = status.state.name
            if last != "SUCCEEDED":
                return last
    return last


if __name__ == "__main__":
    if len(sys.argv) < 3:
        print("usage: simple_session <input...> <output_dir>")
        sys.exit(2)
    state = run(sys.argv[1:-1], sys.argv[-1])
    print(state)
    sys.exit(0 if state == "SUCCEEDED" else 1)
