"""CartesianProduct example: cross product of two datasets via the
first-class CUSTOM edge.

Reference parity: tez-examples/.../CartesianProduct.java (two source
vertices cross-joined into one consumer through
CartesianProductVertexManager + CartesianProductEdgeManager; output is
every (left, right) token pair).
"""
from __future__ import annotations

import sys
from typing import Dict

from tez_tpu.api.runtime import LogicalInput, LogicalOutput
from tez_tpu.client.tez_client import TezClient
from tez_tpu.common.payload import (EdgeManagerPluginDescriptor,
                                    InputDescriptor,
                                    InputInitializerDescriptor,
                                    OutputCommitterDescriptor,
                                    OutputDescriptor, ProcessorDescriptor,
                                    VertexManagerPluginDescriptor)
from tez_tpu.dag.dag import (DAG, DataSinkDescriptor, DataSourceDescriptor,
                             Edge, Vertex)
from tez_tpu.dag.edge_property import DataSourceType, EdgeProperty
from tez_tpu.library.processors import SimpleProcessor


class TokenForwardProcessor(SimpleProcessor):
    """Reads this task's text split, forwards each token downstream."""

    def run(self, inputs: Dict[str, LogicalInput],
            outputs: Dict[str, LogicalOutput]) -> None:
        writer = next(iter(outputs.values())).get_writer()
        for _off, line in inputs["input"].get_reader():
            for token in line.split():
                writer.write(token, b"")


class PairWriterProcessor(SimpleProcessor):
    """Emits 'left|right' for every pair of tokens across its two inputs."""

    def run(self, inputs: Dict[str, LogicalInput],
            outputs: Dict[str, LogicalOutput]) -> None:
        writer = outputs["output"].get_writer()
        left = [k for k, _v in inputs["left"].get_reader()]
        right = [k for k, _v in inputs["right"].get_reader()]
        for a in left:
            for b in right:
                writer.write(a + b"|" + b, b"1")


def _source_vertex(name: str, path: str, parallelism: int) -> Vertex:
    v = Vertex.create(name, ProcessorDescriptor.create(
        TokenForwardProcessor), parallelism)
    v.add_data_source("input", DataSourceDescriptor.create(
        InputDescriptor.create("tez_tpu.io.formats:MRInput",
                               payload={"format": "text"}),
        InputInitializerDescriptor.create(
            "tez_tpu.io.formats:MRSplitGenerator",
            payload={"paths": [path], "desired_splits": parallelism,
                     "format": "text"})))
    return v


def build_dag(left_path: str, right_path: str, output_path: str,
              source_parallelism: int = 2,
              joiner_parallelism: int = 4) -> DAG:
    left = _source_vertex("left", left_path, source_parallelism)
    right = _source_vertex("right", right_path, source_parallelism)
    joiner = Vertex.create("joiner", ProcessorDescriptor.create(
        PairWriterProcessor), joiner_parallelism)
    joiner.add_data_sink("output", DataSinkDescriptor.create(
        OutputDescriptor.create("tez_tpu.io.file_output:FileOutput",
                                payload={"path": output_path,
                                         "key_serde": "text",
                                         "value_serde": "text"}),
        OutputCommitterDescriptor.create(
            "tez_tpu.io.file_output:FileOutputCommitter",
            payload={"path": output_path})))
    joiner.set_vertex_manager_plugin(VertexManagerPluginDescriptor.create(
        "tez_tpu.library.cartesian_product:CartesianProductVertexManager",
        payload={"sources": ["left", "right"]}))
    conf = {"tez.runtime.key.class": "bytes",
            "tez.runtime.value.class": "bytes"}

    def cp_edge() -> EdgeProperty:
        return EdgeProperty.create_custom(
            EdgeManagerPluginDescriptor.create(
                "tez_tpu.library.cartesian_product:"
                "CartesianProductEdgeManager", payload={}),
            DataSourceType.PERSISTED,
            OutputDescriptor.create(
                "tez_tpu.library.unordered:UnorderedKVOutput", payload=conf),
            InputDescriptor.create(
                "tez_tpu.library.unordered:UnorderedKVInput", payload=conf))

    dag = DAG.create("CartesianProduct")
    for v in (left, right, joiner):
        dag.add_vertex(v)
    dag.add_edge(Edge.create(left, joiner, cp_edge()))
    dag.add_edge(Edge.create(right, joiner, cp_edge()))
    return dag


def run(left_path, right_path, output_path: str, conf=None, **kw) -> str:
    if isinstance(left_path, (list, tuple)):
        left_path = left_path[0]
    if isinstance(right_path, (list, tuple)):
        right_path = right_path[0]
    with TezClient.create("CartesianProduct", conf or {}) as client:
        status = client.submit_dag(build_dag(
            left_path, right_path, output_path, **kw)).wait_for_completion()
        return status.state.name


if __name__ == "__main__":
    if len(sys.argv) != 4:
        print("usage: cartesian_product <left_file> <right_file> "
              "<output_dir>")
        sys.exit(2)
    print(run(sys.argv[1], sys.argv[2], sys.argv[3]))
