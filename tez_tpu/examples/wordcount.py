"""WordCount over the unordered (hash-partition only) path.

Reference parity: tez-examples/.../WordCount.java:58 — tokenizer
--(UnorderedPartitionedKVOutput)--> summation, which aggregates with a hash
map and writes counts (benchmark workload 2, BASELINE.md).
"""
from __future__ import annotations

import sys
from collections import Counter
from typing import Dict

from tez_tpu.api.runtime import LogicalInput, LogicalOutput
from tez_tpu.client.tez_client import TezClient
from tez_tpu.common.payload import (InputDescriptor,
                                    InputInitializerDescriptor,
                                    OutputCommitterDescriptor,
                                    OutputDescriptor, ProcessorDescriptor)
from tez_tpu.dag.dag import (DAG, DataSinkDescriptor, DataSourceDescriptor,
                             Edge, Vertex)
from tez_tpu.library.conf import UnorderedPartitionedKVEdgeConfig
from tez_tpu.library.processors import SimpleProcessor


class TokenProcessor(SimpleProcessor):
    def run(self, inputs: Dict[str, LogicalInput],
            outputs: Dict[str, LogicalOutput]) -> None:
        reader = inputs["input"].get_reader()
        writer = outputs["summation"].get_writer()
        for _offset, line in reader:
            for word in line.split():
                writer.write(word, 1)


class SumProcessor(SimpleProcessor):
    def run(self, inputs: Dict[str, LogicalInput],
            outputs: Dict[str, LogicalOutput]) -> None:
        reader = inputs["tokenizer"].get_reader()
        writer = outputs["output"].get_writer()
        counts: Counter = Counter()
        for word, one in reader:
            counts[word] += one
        for word, count in sorted(counts.items()):
            writer.write(word, str(count))


def build_dag(input_paths, output_path: str, tokenizer_parallelism: int = -1,
              summation_parallelism: int = 2) -> DAG:
    tokenizer = Vertex.create("tokenizer", ProcessorDescriptor.create(
        TokenProcessor), tokenizer_parallelism)
    tokenizer.add_data_source("input", DataSourceDescriptor.create(
        InputDescriptor.create("tez_tpu.io.text:TextInput"),
        InputInitializerDescriptor.create(
            "tez_tpu.io.text:TextSplitGenerator",
            payload={"paths": list(input_paths),
                     "desired_splits": tokenizer_parallelism})))
    summation = Vertex.create("summation", ProcessorDescriptor.create(
        SumProcessor), summation_parallelism)
    summation.add_data_sink("output", DataSinkDescriptor.create(
        OutputDescriptor.create("tez_tpu.io.file_output:FileOutput",
                                payload={"path": output_path,
                                         "key_serde": "text",
                                         "value_serde": "text"}),
        OutputCommitterDescriptor.create(
            "tez_tpu.io.file_output:FileOutputCommitter",
            payload={"path": output_path})))
    edge = UnorderedPartitionedKVEdgeConfig.new_builder(
        "bytes", "pickle").build()
    dag = DAG.create("WordCount").add_vertex(tokenizer).add_vertex(summation)
    dag.add_edge(Edge.create(tokenizer, summation,
                             edge.create_default_edge_property()))
    return dag


def run(input_paths, output_path: str, conf=None, **kw) -> str:
    with TezClient.create("WordCount", conf or {}) as client:
        status = client.submit_dag(
            build_dag(input_paths, output_path, **kw)).wait_for_completion()
        return status.state.name


if __name__ == "__main__":
    if len(sys.argv) < 3:
        print("usage: wordcount <input...> <output_dir>")
        sys.exit(2)
    print(run(sys.argv[1:-1], sys.argv[-1]))
