"""Retry/backoff policy shared by DCN-facing clients.

Reference role: the exponential penalty schedule the shuffle clients apply
between attempts (ShuffleScheduler's Penalty DelayQueue,
tez-runtime-library .../orderedgrouped/ShuffleScheduler.java:179, and the
fetcher retry loops in Fetcher.java:79).

Full jitter: the plain `base * 2^attempt` schedule keeps every client
penalized by one bad host in lockstep — when the penalty expires they all
reconnect in the same instant and knock the host over again (thundering
herd).  With ``jitter=True`` each delay is drawn uniformly from
``[0, min(cap, base * 2^attempt)]`` (the AWS "full jitter" scheme), which
decorrelates the herd while keeping the same expected envelope.  The RNG is
injectable so tests can pin the draw.
"""
from __future__ import annotations

import random
import time
from typing import Callable, Optional, Tuple, Type

_module_rng = random.Random()


class ExponentialBackoff:
    """base * 2^attempt, capped; attempt counter owned by the caller.

    With ``jitter=True``, draws uniformly from [0, that envelope] per call
    (full jitter).  ``rng`` pins the stream for deterministic tests."""

    def __init__(self, base: float = 0.2, cap: float = 10.0,
                 jitter: bool = False,
                 rng: Optional[random.Random] = None):
        self.base = base
        self.cap = cap
        self.jitter = jitter
        self.rng = rng

    def delay(self, attempt: int) -> float:
        d = min(self.cap, self.base * (2 ** attempt))
        if not self.jitter:
            return d
        return (self.rng or _module_rng).uniform(0.0, d)

    def sleep(self, attempt: int) -> None:
        time.sleep(self.delay(attempt))


def retry_call(fn: Callable, retries: int,
               retryable: Tuple[Type[BaseException], ...],
               backoff: Optional[ExponentialBackoff] = None,
               fatal: Tuple[Type[BaseException], ...] = ()):
    """Run fn() up to `retries` times, sleeping the policy's delay between
    retryable failures.  `fatal` exception types propagate immediately
    (definitive misses must not be retried — e.g. ShuffleDataNotFound
    drives the InputReadErrorEvent path instead)."""
    if retries < 1:
        raise ValueError(f"retries must be >= 1, got {retries}")
    policy = backoff or ExponentialBackoff(jitter=True)
    last: Optional[BaseException] = None
    for attempt in range(retries):
        try:
            return fn()
        except fatal:
            raise
        except retryable as e:
            last = e
            if attempt < retries - 1:
                policy.sleep(attempt)
    assert last is not None
    raise last
