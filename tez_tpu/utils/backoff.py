"""Retry/backoff policy shared by DCN-facing clients.

Reference role: the exponential penalty schedule the shuffle clients apply
between attempts (ShuffleScheduler's Penalty DelayQueue,
tez-runtime-library .../orderedgrouped/ShuffleScheduler.java:179, and the
fetcher retry loops in Fetcher.java:79).
"""
from __future__ import annotations

import time
from typing import Callable, Optional, Tuple, Type


class ExponentialBackoff:
    """base * 2^attempt, capped; attempt counter owned by the caller."""

    def __init__(self, base: float = 0.2, cap: float = 10.0):
        self.base = base
        self.cap = cap

    def delay(self, attempt: int) -> float:
        return min(self.cap, self.base * (2 ** attempt))

    def sleep(self, attempt: int) -> None:
        time.sleep(self.delay(attempt))


def retry_call(fn: Callable, retries: int,
               retryable: Tuple[Type[BaseException], ...],
               backoff: Optional[ExponentialBackoff] = None,
               fatal: Tuple[Type[BaseException], ...] = ()):
    """Run fn() up to `retries` times, sleeping the policy's delay between
    retryable failures.  `fatal` exception types propagate immediately
    (definitive misses must not be retried — e.g. ShuffleDataNotFound
    drives the InputReadErrorEvent path instead)."""
    if retries < 1:
        raise ValueError(f"retries must be >= 1, got {retries}")
    policy = backoff or ExponentialBackoff()
    last: Optional[BaseException] = None
    for attempt in range(retries):
        try:
            return fn()
        except fatal:
            raise
        except retryable as e:
            last = e
            if attempt < retries - 1:
                policy.sleep(attempt)
    assert last is not None
    raise last
