"""Cross-cutting utilities with no framework dependencies.

(Per-subsystem helpers live next to their subsystem — e.g.
tez_tpu/library/util.py for the IO library's config resolution; this
package is for policies shared across transport clients and tools.)
"""

from tez_tpu.utils.backoff import ExponentialBackoff, retry_call

__all__ = ["ExponentialBackoff", "retry_call"]
