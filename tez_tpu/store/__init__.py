"""Tiered shuffle buffer store (docs/store.md).

``local_buffer_store()`` is the per-process singleton, mirroring
``local_shuffle_service()``: producers publish through the shuffle
service's delegation seam, consumers short-circuit fetches, and the AM
seals lineage keys on DAG commit.  ``ensure_store(conf)`` creates it from
the ``tez.runtime.store.*`` knobs on first use and attaches it to the
local shuffle service; it returns None while the store is disabled so
every call site stays zero-cost on the historical path.
"""
from __future__ import annotations

import threading
from typing import Any, Optional

from tez_tpu.store.buffer_store import (COUNTER_GROUP, DEVICE, DISK, HOST,
                                        LINEAGE_PREFIX, ShuffleBufferStore,
                                        StoreKeyNotFound,
                                        StoreQuotaExceeded)

__all__ = ["ShuffleBufferStore", "StoreKeyNotFound", "StoreQuotaExceeded",
           "local_buffer_store",
           "ensure_store", "reset_store", "COUNTER_GROUP", "LINEAGE_PREFIX",
           "DEVICE", "HOST", "DISK"]

_lock = threading.Lock()
_store: Optional[ShuffleBufferStore] = None


def local_buffer_store() -> Optional[ShuffleBufferStore]:
    """The process store, or None when no DAG enabled it yet."""
    return _store


def ensure_store(conf: Any) -> Optional[ShuffleBufferStore]:
    """Create (once) and return the process store when the conf enables
    it; None otherwise.  ``conf`` is anything with a dict-style ``get``
    carrying tez.runtime.store.* keys."""
    from tez_tpu.common import config as C

    def _get(key):
        v = conf.get(key.name) if hasattr(conf, "get") else None
        return key.default if v is None else v

    enabled = _get(C.STORE_ENABLED)
    if isinstance(enabled, str):
        enabled = enabled.lower() in ("1", "true", "yes")
    if not enabled:
        return None
    global _store
    with _lock:
        if _store is None:
            # fractional MB accepted (chaos/test scenarios shrink a tier
            # below 1MB to force eviction storms on tiny datasets)
            mb = float(1 << 20)
            _store = ShuffleBufferStore(
                device_capacity=int(float(_get(
                    C.STORE_DEVICE_CAPACITY_MB)) * mb),
                host_capacity=int(float(_get(
                    C.STORE_HOST_CAPACITY_MB)) * mb),
                disk_capacity=int(float(_get(
                    C.STORE_DISK_CAPACITY_MB)) * mb),
                disk_dir=str(_get(C.STORE_DIR) or ""),
                high_watermark=float(_get(C.STORE_HIGH_WATERMARK)),
                low_watermark=float(_get(C.STORE_LOW_WATERMARK)),
                tenant_device_quota=int(float(_get(
                    C.STORE_TENANT_DEVICE_QUOTA_MB)) * mb),
                tenant_host_quota=int(float(_get(
                    C.STORE_TENANT_HOST_QUOTA_MB)) * mb),
                tenant_disk_quota=int(float(_get(
                    C.STORE_TENANT_DISK_QUOTA_MB)) * mb),
                result_cache_ttl=float(_get(C.STORE_RESULT_CACHE_TTL_SECS)),
                result_cache_bytes=int(float(_get(
                    C.STORE_RESULT_CACHE_MB)) * mb),
                result_cache_admit=str(_get(C.STORE_RESULT_CACHE_ADMIT)
                                       or "always"))
            from tez_tpu.shuffle.service import local_shuffle_service
            local_shuffle_service().attach_buffer_store(_store)
            from tez_tpu.ops import async_stage
            store = _store
            async_stage.register_pressure_hook(
                store.relieve_device_pressure)
        push_on = _get(C.PUSH_ENABLED)
        if isinstance(push_on, str):
            push_on = push_on.lower() in ("1", "true", "yes")
        if push_on:
            # eager-push landing zone: attach the admission controller the
            # first push-enabled DAG configures (idempotent per process,
            # like the store itself; reset_store detaches it)
            from tez_tpu.shuffle.service import local_shuffle_service
            svc = local_shuffle_service()
            if svc.push_admission() is None:
                from tez_tpu.shuffle.push import PushAdmissionController
                svc.attach_push_admission(PushAdmissionController(
                    local_buffer_store,
                    source_quota_bytes=int(float(_get(
                        C.PUSH_SOURCE_QUOTA_MB)) * (1 << 20)),
                    admit_watermark=float(_get(C.PUSH_ADMIT_WATERMARK)),
                    retry_after_ms=float(_get(C.PUSH_RETRY_AFTER_MS))))
        return _store


def reset_store() -> None:
    """Tear down the process store (tests / session teardown)."""
    global _store
    with _lock:
        store, _store = _store, None
    if store is not None:
        from tez_tpu.shuffle.service import local_shuffle_service
        local_shuffle_service().attach_buffer_store(None)
        local_shuffle_service().attach_push_admission(None)
        from tez_tpu.ops import async_stage
        async_stage.clear_pressure_hooks()
        store.close()
