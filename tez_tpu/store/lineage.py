"""Lineage keys for cross-DAG output reuse (session mode).

A vertex's lineage hash is a content address for "the output this vertex
will produce": its processor descriptor (class + payload bytes), its
parallelism, its root inputs, its in-edge plumbing (movement type +
edge IO descriptors), the vertex conf, and — topologically — the lineage
of every upstream vertex.  Two DAGs submitted to one session that agree
on a vertex's hash would compute byte-identical output for it, so the
store can serve the sealed runs of the first DAG to the second
(``ShuffleBufferStore.seal_lineage`` / ``republish_lineage``).

The hash deliberately excludes the DAG name and id (recurring DAGs get
fresh names) and anything scheduling-only (locality hints, container
counts).  Leaf outputs are INCLUDED: a vertex that also publishes to an
external sink must re-run so the sink sees its side effects.

Per-task lineage keys are ``<vertex_hash>/<task_index>/<dest_vertex>`` —
the task index pins the partition range, the destination vertex pins
which edge output the segment feeds.

Streaming mode: a per-window DAG plan carries ``tez.runtime.stream.id``
and ``tez.runtime.stream.window-id`` in its DAG conf, and both are folded
into every vertex hash — the lineage key is effectively
``(lineage, window_id)``, so window N's sealed runs can never be served
as a cache hit to window M, while a window-exact REPLAY of window N
(same stream, same window id, same spool) hits and skips recomputation.
"""
from __future__ import annotations

import hashlib
from typing import Any, Dict

from tez_tpu.common.payload import EntityDescriptor


def _descriptor_bytes(d: Any) -> bytes:
    if d is None:
        return b"-"
    if isinstance(d, EntityDescriptor):
        payload = d.payload.data if d.payload is not None else b""
        return d.class_name.encode() + b"\x00" + payload
    return repr(d).encode()


def _conf_bytes(conf: Any) -> bytes:
    if not conf:
        return b"-"
    items = sorted((str(k), str(v)) for k, v in dict(conf).items())
    return b";".join(f"{k}={v}".encode() for k, v in items)


def vertex_lineage_hashes(plan: Any) -> Dict[str, str]:
    """{vertex_name: lineage_hash} for every vertex in a DAGPlan,
    computed topologically so a hash transitively covers the whole
    upstream subgraph (input signature)."""
    hashes: Dict[str, str] = {}
    # window-scoped lineage: the stream/window coordinates (DAG-level conf)
    # salt every vertex hash so sealed entries are keyed (lineage, window)
    dag_conf = dict(getattr(plan, "dag_conf", None) or {})
    stream = str(dag_conf.get("tez.runtime.stream.id", "") or "")
    window_salt = b""
    if stream:
        window_salt = (
            f"|stream:{stream}"
            f"/w{dag_conf.get('tez.runtime.stream.window-id', 0)}"
        ).encode()
    pending = {v.name: v for v in plan.vertices}
    edges_in: Dict[str, list] = {v.name: [] for v in plan.vertices}
    for e in plan.edges:
        edges_in.setdefault(e.output_vertex, []).append(e)
    guard = 0
    while pending and guard <= len(plan.vertices):
        guard += 1
        for name in list(pending):
            ins = edges_in.get(name, [])
            if any(e.input_vertex not in hashes for e in ins):
                continue
            v = pending.pop(name)
            h = hashlib.sha256()
            h.update(v.name.encode() + b"\x00")
            h.update(_descriptor_bytes(v.processor))
            h.update(b"|par=%d" % int(v.parallelism))
            for ri in v.root_inputs:
                h.update(b"|root:" + _descriptor_bytes(
                    getattr(ri, "descriptor", ri)))
            for lo in v.leaf_outputs:
                h.update(b"|leaf:" + _descriptor_bytes(
                    getattr(lo, "descriptor", lo)))
            h.update(b"|conf:" + _conf_bytes(v.conf))
            h.update(window_salt)
            for e in sorted(ins, key=lambda e: e.id):
                p = e.edge_property
                h.update(b"|edge:" + str(p.data_movement_type).encode())
                h.update(_descriptor_bytes(p.edge_source))
                h.update(_descriptor_bytes(p.edge_destination))
                h.update(b"<" + hashes[e.input_vertex].encode())
            hashes[name] = h.hexdigest()[:24]
    # a cycle (never valid in a verified DAG) leaves vertices unpinned:
    # give them no lineage rather than a wrong one
    for name in pending:
        hashes[name] = ""
    return hashes


def task_lineage(vertex_hash: str, task_index: int,
                 dest_vertex: str) -> str:
    """The store key tag for one task's output toward one edge."""
    if not vertex_hash:
        return ""
    return f"{vertex_hash}/{task_index}/{dest_vertex}"
